"""Fault model: retry/hedge/quarantine policy + deterministic chaos injection.

The reference gets partition-level fault tolerance for free from Spark
(failed tasks are retried, stragglers are speculatively re-executed); the
host-side substrate (parallel/executor.py) replaced the Spark driver with a
bare pool, so until this layer existed a single transient I/O error killed
an entire multi-hour load. Two halves live here:

- ``FaultPolicy`` — what the resilient executor is allowed to do about a
  failing partition: bounded retries with jittered exponential backoff, a
  per-attempt deadline, speculative re-execution of stragglers ("hedging",
  the Spark-speculation analog), and the ``strict`` | ``tolerant``
  degradation mode (raise vs quarantine-and-continue). Parseable from a
  compact ``k=v,...`` spec so it threads through config/env/CLI unchanged
  (``Config.faults`` / ``SPARK_BAM_FAULTS`` / ``--faults``).

- ``ChaosChannel`` — a seeded, deterministic ``ByteChannel`` wrapper that
  injects transient ``IOError``s, latency spikes, short reads, and byte
  corruption, each decided by an offset-keyed splitmix64 hash so the fault
  *set* is reproducible across runs (same seed ⇒ same faulty offsets ⇒ same
  recovery story). Transient faults fire once per offset (shared across all
  channels of one installation), so a partition retry makes progress the
  way a real transient blip does. ``install_chaos("SEED:SPEC")`` wraps
  every channel ``open_channel`` hands out (the ``--chaos`` CLI flag).

Proofs live in tests/test_faults.py; semantics in docs/robustness.md.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass
from functools import lru_cache

from spark_bam_tpu import obs
from spark_bam_tpu.core import channel as _channel
from spark_bam_tpu.core.channel import ByteChannel


class Unrecoverable:
    """Marker mixin: errors that retrying can never fix (corruption, parse
    failures with deterministic inputs). The resilient executor fails such
    attempts immediately instead of burning its retry budget."""


#: OSError subclasses that are deterministic in practice — retrying a
#: missing file three times only delays the real error.
_NONRETRYABLE_OS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def retryable(exc: BaseException) -> bool:
    """Is this exception worth a fresh attempt? Transient transport errors
    (the OSError family, timeouts) are; corruption (``Unrecoverable``),
    deterministic filesystem errors, and everything else are not."""
    if isinstance(exc, Unrecoverable):
        return False
    if isinstance(exc, _NONRETRYABLE_OS):
        return False
    return isinstance(exc, (OSError, TimeoutError))


class ShortReadError(IOError):
    """Mid-file byte loss: the channel reported more bytes than it
    delivered (EOF before ``channel.size``). Retryable — the transient-
    short-read signature; a genuinely truncated file EOFs *at* its size
    and keeps the historical clean-truncation semantics instead."""


class BlockCorruptionError(IOError, Unrecoverable):
    """A BGZF block failed CRC32/inflate — deterministic damage that no
    retry fixes. Strict mode raises it; tolerant mode quarantines."""


class BlockGapError(IOError, Unrecoverable):
    """Tolerant-mode resync marker: the block at ``damaged_start`` was
    unreadable and the stream's next sound block starts at ``resync``
    (``None`` when no further block header chains — damage runs to EOF).
    Raised by a tolerant ``BlockStream`` so the record layer can re-find a
    record boundary past the gap and continue (load/api.py)."""

    def __init__(self, damaged_start: int, resync: int | None, reason: str):
        super().__init__(
            f"unreadable BGZF block at {damaged_start} "
            f"(resync at {resync}): {reason}"
        )
        self.damaged_start = damaged_start
        self.resync = resync
        self.reason = reason


# ------------------------------------------------------------------ policy
@dataclass(frozen=True)
class FaultPolicy:
    """What the resilient executor may do about a failing/straggling
    partition. The default is production-lenient on transients (3 retries)
    and strict on outcomes (exhausted retries raise)."""

    max_retries: int = 3        # retries beyond the first attempt
    backoff_base: float = 0.05  # s; doubles per retry
    backoff_max: float = 5.0    # s; backoff ceiling
    jitter: float = 0.5         # fraction of each delay randomized away
    deadline: float | None = None     # s per attempt; None = unbounded
    hedge_after: float | None = None  # launch a twin at N× median latency
    mode: str = "strict"        # strict (raise) | tolerant (quarantine)

    MODES = ("strict", "tolerant")

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise ValueError(
                f"Unknown fault mode {self.mode!r}: expected one of "
                f"{', '.join(self.MODES)}"
            )
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0: {self.max_retries}")

    @property
    def tolerant(self) -> bool:
        return self.mode == "tolerant"

    def backoff_delay(self, attempt: int, rng=random) -> float:
        """Jittered exponential backoff before retry ``attempt + 1``."""
        d = min(self.backoff_max, self.backoff_base * (2 ** attempt))
        return d * (1 - self.jitter + self.jitter * rng.random())

    _KEYS = {
        "retries": "max_retries",
        "max_retries": "max_retries",
        "backoff": "backoff_base",
        "backoff_base": "backoff_base",
        "backoff_max": "backoff_max",
        "jitter": "jitter",
        "deadline": "deadline",
        "hedge": "hedge_after",
        "hedge_after": "hedge_after",
        "mode": "mode",
    }

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "FaultPolicy":
        """``"retries=3,backoff=0.05,deadline=60,hedge=2,mode=tolerant"``
        (any subset; ``""`` ⇒ defaults). ``hedge``/``deadline`` accept
        ``off``/``none`` to disable explicitly."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad fault-policy entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            field = FaultPolicy._KEYS.get(key.replace("-", "_"))
            if field is None:
                raise ValueError(
                    f"Unknown fault-policy key {key!r}: expected one of "
                    f"{', '.join(sorted(set(FaultPolicy._KEYS)))}"
                )
            if field == "mode":
                kw[field] = value
            elif field == "max_retries":
                kw[field] = int(value)
            elif field in ("deadline", "hedge_after") and value.lower() in (
                "off", "none", ""
            ):
                kw[field] = None
            else:
                kw[field] = float(value)
        return FaultPolicy(**kw)

    @staticmethod
    def from_env(env=None) -> "FaultPolicy":
        import os

        return FaultPolicy.parse((env or os.environ).get("SPARK_BAM_FAULTS", ""))


def with_retries(fn, policy: "FaultPolicy", what: str = "operation"):
    """Run a driver-side callable under the policy's retry schedule.

    The executor covers partition work; this covers the small driver-level
    reads that precede it (header parse, split planning) so a transient
    fault there doesn't kill the job either. Returns ``fn()``'s value;
    exhausted retries re-raise the last error (driver reads have no
    quarantine analog — nothing downstream exists without them)."""
    last: BaseException | None = None
    for attempt in range(policy.max_retries + 1):
        try:
            return fn()
        except Exception as e:
            last = e
            if not retryable(e) or attempt == policy.max_retries:
                raise
            obs.count("faults.retries")
            time.sleep(policy.backoff_delay(attempt))
    raise last  # unreachable; satisfies control-flow analysis


# --------------------------------------------------------------- latency
class LatencyTracker:
    """Sliding-window latency stats for hedging decisions.

    The executor hedges partitions at N× the median completed-attempt
    latency; the remote data plane (core/remote_plan.py) hedges individual
    GETs the same way. Both need a thread-safe rolling median that refuses
    to guess before it has seen enough samples (``MIN_SAMPLES``, matching
    the executor's ``_HEDGE_MIN_SAMPLES``)."""

    MIN_SAMPLES = 3

    def __init__(self, window: int = 64):
        from collections import deque

        self._samples: "deque[float]" = deque(maxlen=window)
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        with self._lock:
            self._samples.append(ms)

    def median(self) -> float | None:
        """Median of the recent window, or None below ``MIN_SAMPLES``."""
        import statistics

        with self._lock:
            if len(self._samples) < self.MIN_SAMPLES:
                return None
            return statistics.median(self._samples)


# ------------------------------------------------------------------- chaos
class ChaosError(IOError):
    """Injected transient I/O failure (retryable by design)."""


_M64 = (1 << 64) - 1
# Distinct streams per fault kind so the same offset rolls independently.
_K_IO, _K_LATENCY, _K_SHORT, _K_CORRUPT = 1, 2, 3, 4


def _mix(seed: int, kind: int, x: int) -> int:
    """splitmix64 finalizer over (seed, kind, offset) — the deterministic
    per-offset randomness source (reproducible across runs/platforms)."""
    z = (x + seed * 0x9E3779B97F4A7C15 + kind * 0xD1B54A32D192ED03) & _M64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return z ^ (z >> 31)


def _roll(seed: int, kind: int, x: int, rate: float) -> bool:
    return rate > 0 and (_mix(seed, kind, x) >> 11) < rate * (1 << 53)


@dataclass(frozen=True)
class ChaosSpec:
    """Which faults to inject and how often. Rates are per *read request*
    (keyed by its byte offset), except ``corrupt`` which is per byte."""

    io: float = 0.0        # transient IOError rate
    latency: float = 0.0   # latency-spike rate
    latency_ms: float = 10.0
    short: float = 0.0     # short-read rate
    corrupt: float = 0.0   # per-byte corruption rate

    @staticmethod
    def parse(spec: str) -> "ChaosSpec":
        """``"io=0.1,latency=0.05x10,short=0.02,corrupt=1e-6"`` — latency's
        optional ``xMS`` suffix sets the spike length (default 10 ms)."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad chaos entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            if key == "latency":
                if "x" in value:
                    rate, ms = value.split("x", 1)
                    kw["latency"], kw["latency_ms"] = float(rate), float(ms)
                else:
                    kw["latency"] = float(value)
            elif key in ("io", "short", "corrupt"):
                kw[key] = float(value)
            else:
                raise ValueError(
                    f"Unknown chaos key {key!r}: expected io, latency, "
                    f"short, or corrupt"
                )
        return ChaosSpec(**kw)


def parse_chaos(arg: str) -> tuple[int, ChaosSpec]:
    """``"SEED:SPEC"`` (the ``--chaos`` argument shape)."""
    seed, _, spec = arg.partition(":")
    try:
        seed_i = int(seed)
    except ValueError:
        raise ValueError(f"Bad chaos seed {seed!r} in {arg!r} (want SEED:SPEC)")
    return seed_i, ChaosSpec.parse(spec)


#: Transient-fault blast radius: one fired fault suppresses further
#: transient faults of its kind within this many bytes (aligned region).
#: Models a time/locality-correlated blip — the retry that re-reads the
#: neighborhood succeeds, the way a real hiccup clears — so recovery cost
#: scales with damaged *regions*, not with every unlucky request offset.
_TRANSIENT_RADIUS_BITS = 12  # 4 KiB


class ChaosState:
    """Shared across every ChaosChannel of one installation: transient-
    fault consumption (a fault fires once per 4 KiB region, so a partition
    retry that re-reads the file makes progress) and injected-fault tallies
    for assertions/reporting."""

    def __init__(self, seed: int, spec: ChaosSpec):
        self.seed = seed
        self.spec = spec
        self.lock = threading.Lock()
        self.consumed: set[tuple[int, int]] = set()
        self.injected: dict[str, int] = {
            "io": 0, "latency": 0, "short": 0, "corrupt": 0
        }

    def _note(self, kind: str, n: int = 1) -> None:
        with self.lock:
            self.injected[kind] += n

    def _consume_once(self, kind: int, pos: int) -> bool:
        """True the first time a (kind, region) fault fires."""
        key = (kind, pos >> _TRANSIENT_RADIUS_BITS)
        with self.lock:
            if key in self.consumed:
                return False
            self.consumed.add(key)
            return True


class ChaosChannel(ByteChannel):
    """Deterministic fault-injecting wrapper around any ``ByteChannel``.

    Fault decisions are pure functions of (seed, kind, offset); transient
    kinds (io, short) additionally fire only on the offset's first access
    (shared ``ChaosState``), so retries recover the way they would from a
    real transient blip while the fault set stays replayable. Corruption is
    a pure per-byte function — persistent damage, the quarantine test case.
    """

    def __init__(self, inner: ByteChannel, seed: int, spec: ChaosSpec,
                 state: ChaosState | None = None):
        super().__init__()
        self.inner = inner
        self.state = state or ChaosState(seed, spec)
        self.seed = self.state.seed
        self.spec = self.state.spec

    def _read_at(self, pos: int, n: int) -> bytes:
        if n <= 0:
            return self.inner.read_at(pos, n)
        seed, spec, state = self.seed, self.spec, self.state
        if _roll(seed, _K_LATENCY, pos, spec.latency):
            state._note("latency")
            obs.count("chaos.latency_spikes")
            time.sleep(spec.latency_ms / 1e3)
        if _roll(seed, _K_IO, pos, spec.io) and state._consume_once(_K_IO, pos):
            state._note("io")
            obs.count("chaos.io_errors")
            raise ChaosError(
                f"chaos(seed={seed}): injected transient IOError at "
                f"offset {pos}"
            )
        data = self.inner.read_at(pos, n)
        if (
            len(data) > 1
            and _roll(seed, _K_SHORT, pos, spec.short)
            and state._consume_once(_K_SHORT, pos)
        ):
            state._note("short")
            obs.count("chaos.short_reads")
            data = data[: len(data) // 2]
        if spec.corrupt > 0 and data:
            data = self._corrupt(pos, data)
        return data

    def _corrupt(self, pos: int, data: bytes) -> bytes:
        import numpy as np

        offs = np.arange(pos, pos + len(data), dtype=np.uint64)
        z = (
            offs
            + np.uint64((self.seed * 0x9E3779B97F4A7C15) & _M64)
            + np.uint64((_K_CORRUPT * 0xD1B54A32D192ED03) & _M64)
        )
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
        mask = (z >> np.uint64(11)) < np.uint64(int(self.spec.corrupt * (1 << 53)))
        if not mask.any():
            return data
        out = np.frombuffer(data, dtype=np.uint8).copy()
        # Nonzero flip so a "corrupted" byte always actually changes.
        out[mask] ^= (z[mask] & np.uint64(0xFF)).astype(np.uint8) | np.uint8(1)
        hits = int(mask.sum())
        self.state._note("corrupt", hits)
        obs.count("chaos.corrupted_bytes", hits)
        return out.tobytes()

    @property
    def size(self) -> int:
        return self.inner.size

    def close(self) -> None:
        self.inner.close()


# ------------------------------------------------- process-wide installation
_installed: ChaosState | None = None


def install_chaos(arg: str | tuple[int, ChaosSpec]) -> ChaosState:
    """Wrap every channel ``open_channel`` hands out from now on in a
    ``ChaosChannel`` sharing one ``ChaosState`` (the ``--chaos`` flag).
    Returns the state for fault-tally inspection."""
    global _installed
    seed, spec = parse_chaos(arg) if isinstance(arg, str) else arg
    state = ChaosState(seed, spec)
    _installed = state
    _channel.set_chaos_wrapper(
        lambda ch, path: ChaosChannel(ch, seed, spec, state=state)
    )
    # Stamp the seed into the flight-recorder dump context: any crash or
    # postmortem artifact from a chaos run reproduces the run by itself.
    from spark_bam_tpu.obs import flight
    flight.set_context(
        chaos_seed=seed,
        chaos_spec=arg if isinstance(arg, str) else f"{seed}:{spec}",
    )
    return state


def uninstall_chaos() -> None:
    global _installed
    _installed = None
    _channel.set_chaos_wrapper(None)
    from spark_bam_tpu.obs import flight
    flight.clear_context("chaos_seed", "chaos_spec")


def installed_chaos() -> ChaosState | None:
    return _installed


@contextlib.contextmanager
def chaos(arg: str | tuple[int, ChaosSpec]):
    """``with chaos("7:io=0.1"): ...`` — scoped installation for tests."""
    state = install_chaos(arg)
    try:
        yield state
    finally:
        uninstall_chaos()


# -------------------------------------------------------------- disk chaos
# Filesystem-seam fault injection (the write-side mirror of ChaosChannel):
# the durable-job plane (jobs/) and AtomicFile route their writes, fsyncs
# and renames through these hooks, so ENOSPC mid-segment, a torn journal
# append or a failed commit rename are all reproducible from one seed.
# Decisions are op-indexed (the Nth write/rename of the process rolls
# kind-keyed splitmix64), not offset-keyed: write streams have no stable
# offsets the way read requests do.
_K_ENOSPC, _K_EIO, _K_SHORTW, _K_TORN, _K_RENAME = 21, 22, 23, 24, 25


@dataclass(frozen=True)
class DiskChaosSpec:
    """Which filesystem faults to inject and how often (rates are per
    operation: write calls for the first four kinds, renames for the
    last)."""

    enospc: float = 0.0   # raise ENOSPC before writing anything
    eio: float = 0.0      # raise EIO before writing anything
    short: float = 0.0    # write a prefix, then raise EIO ("failed mid-write")
    torn: float = 0.0     # write a prefix, report success (power-loss tail)
    rename: float = 0.0   # os.replace raises EIO

    _KINDS = {
        "enospc": _K_ENOSPC, "eio": _K_EIO, "short": _K_SHORTW,
        "torn": _K_TORN, "rename": _K_RENAME,
    }

    @staticmethod
    def parse(spec: str) -> "DiskChaosSpec":
        """``"enospc=0.05+eio=0.02+short=0.02+torn=0.01+rename=0.1"`` —
        ``+``-separated like the fabric chaos grammar, so the whole spec
        embeds in ``,``-separated config strings (``disk=SEED:SPEC``)."""
        kw: dict = {}
        for part in (spec or "").split("+"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad disk-chaos entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            if key not in DiskChaosSpec._KINDS:
                raise ValueError(
                    f"Unknown disk-chaos key {key!r}: expected one of "
                    f"{', '.join(sorted(DiskChaosSpec._KINDS))}"
                )
            kw[key] = float(value)
        return DiskChaosSpec(**kw)


def parse_disk_chaos(arg: str) -> "tuple[int, DiskChaosSpec]":
    """``"SEED:SPEC"`` (the ``--disk-chaos`` argument / ``disk=`` key)."""
    seed, _, spec = arg.partition(":")
    try:
        seed_i = int(seed)
    except ValueError:
        raise ValueError(
            f"Bad disk-chaos seed {seed!r} in {arg!r} (want SEED:SPEC)"
        )
    return seed_i, DiskChaosSpec.parse(spec)


class DiskChaosState:
    """One installation's decision state: a monotone per-kind op counter
    (so the fault schedule is a pure function of the seed and the
    process's operation order) plus injected tallies for assertions."""

    def __init__(self, seed: int, spec: DiskChaosSpec):
        self.seed = seed
        self.spec = spec
        self.lock = threading.Lock()
        self._n = {k: 0 for k in DiskChaosSpec._KINDS.values()}
        self.injected: dict[str, int] = {k: 0 for k in DiskChaosSpec._KINDS}

    def roll(self, name: str) -> bool:
        rate = getattr(self.spec, name)
        kind = DiskChaosSpec._KINDS[name]
        with self.lock:
            n = self._n[kind]
            self._n[kind] = n + 1
        if not _roll(self.seed, kind, n, rate):
            return False
        with self.lock:
            self.injected[name] += 1
        return True


_disk: DiskChaosState | None = None


def install_disk_chaos(arg: "str | tuple[int, DiskChaosSpec]") -> DiskChaosState:
    global _disk
    seed, spec = parse_disk_chaos(arg) if isinstance(arg, str) else arg
    _disk = DiskChaosState(seed, spec)
    from spark_bam_tpu.obs import flight
    flight.set_context(
        disk_chaos_seed=seed,
        disk_chaos_spec=arg if isinstance(arg, str) else f"{seed}:{spec}",
    )
    return _disk


def uninstall_disk_chaos() -> None:
    global _disk
    _disk = None
    from spark_bam_tpu.obs import flight
    flight.clear_context("disk_chaos_seed", "disk_chaos_spec")


def installed_disk_chaos() -> DiskChaosState | None:
    return _disk


def maybe_install_disk_chaos_from_env(env=None) -> DiskChaosState | None:
    """Install from ``SPARK_BAM_DISK_CHAOS`` when set (how fabric workers
    inherit the seam from the pool's environment); no-op otherwise."""
    import os

    arg = (env or os.environ).get("SPARK_BAM_DISK_CHAOS", "")
    return install_disk_chaos(arg) if arg else None


@contextlib.contextmanager
def disk_chaos(arg: "str | tuple[int, DiskChaosSpec]"):
    """``with disk_chaos("7:enospc=0.1"): ...`` — scoped, for tests."""
    state = install_disk_chaos(arg)
    try:
        yield state
    finally:
        uninstall_disk_chaos()


class _DiskChaosFile:
    """Write-through wrapper applying the installed disk faults to one
    file object. Only constructed when chaos is installed (``wrap_disk``)
    — the unconfigured write path keeps zero chaos branches."""

    def __init__(self, f, state: DiskChaosState):
        self._f = f
        self._state = state

    def write(self, data) -> int:
        import errno as _errno

        state = self._state
        n = len(data)
        if n and state.roll("enospc"):
            obs.count("chaos.disk_enospc")
            raise OSError(
                _errno.ENOSPC,
                f"disk chaos(seed={state.seed}): injected ENOSPC",
            )
        if n and state.roll("eio"):
            obs.count("chaos.disk_eio")
            raise OSError(
                _errno.EIO, f"disk chaos(seed={state.seed}): injected EIO"
            )
        if n > 1 and state.roll("short"):
            obs.count("chaos.disk_short_writes")
            self._f.write(data[: n // 2])
            raise OSError(
                _errno.EIO,
                f"disk chaos(seed={state.seed}): write failed after "
                f"{n // 2}/{n} bytes",
            )
        if n > 1 and state.roll("torn"):
            # The power-loss signature: the call "succeeds" but only a
            # prefix is durable. Only recovery-time CRC/size validation
            # (journal framing, segment-length checks) can see it.
            obs.count("chaos.disk_torn_writes")
            self._f.write(data[: n // 2])
            return n
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()


def wrap_disk(f):
    """Wrap a binary file object in the installed disk-chaos seam; the
    identity function when no disk chaos is installed."""
    return f if _disk is None else _DiskChaosFile(f, _disk)


def disk_replace(src, dst) -> None:
    """``os.replace`` through the rename-fail seam."""
    import os

    if _disk is not None and _disk.roll("rename"):
        import errno as _errno

        obs.count("chaos.disk_rename_fails")
        raise OSError(
            _errno.EIO,
            f"disk chaos(seed={_disk.seed}): injected rename failure "
            f"({src} -> {dst})",
        )
    os.replace(src, dst)
