"""Typed configuration surface.

The reference exposes value-class knobs with implicit defaults
(bgzf/.../block/package.scala:20-22, check/.../package.scala:36-58,
bgzf/.../EstimatedCompressionRatio.scala:5-14) plus a ``spark.bam.*``-style
config namespace. Here the same knobs live on one explicit dataclass; every
API/CLI entry point threads a ``Config`` instead of Scala implicits.

Keys may also be supplied as a flat ``{"spark.bam.<knob>": value}`` mapping
(``Config.from_dict``) for parity with the reference's config-surface contract
(BASELINE.json: "gated behind the existing Checker plugin and spark.bam.*
config surface").
"""

from __future__ import annotations

import dataclasses
import os
import re
from dataclasses import dataclass

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kKmMgGtTpP]?)i?[bB]?\s*$")

_SIZE_FACTORS = {
    "": 1,
    "k": 1 << 10,
    "m": 1 << 20,
    "g": 1 << 30,
    "t": 1 << 40,
    "p": 1 << 50,
}


def parse_bytes(s) -> int:
    """Parse byte-size shorthand: ``"2MB"``, ``"32m"``, ``"100KB"``, ``1024``.

    Mirrors the reference's ``hammerlab.bytes`` shorthand accepted by
    ``SplitSize.Args`` (check/.../args/SplitSize.scala:9-32).
    """
    if isinstance(s, int):
        return s
    m = _SIZE_RE.match(str(s))
    if not m:
        raise ValueError(f"Bad byte-size: {s!r}")
    value, unit = m.groups()
    return int(float(value) * _SIZE_FACTORS[unit.lower()])


def format_bytes(n: int) -> str:
    for unit, shift in (("PB", 50), ("TB", 40), ("GB", 30), ("MB", 20), ("KB", 10)):
        if n >= (1 << shift) and n % (1 << shift) == 0:
            return f"{n >> shift}{unit}"
    for unit, shift in (("PB", 50), ("TB", 40), ("GB", 30), ("MB", 20), ("KB", 10)):
        if n >= (1 << shift):
            return f"{n / (1 << shift):.1f}{unit}"
    return f"{n}B"


@dataclass(frozen=True)
class Config:
    # --- BGZF block search (bgzf/.../block/package.scala:20-22) ---
    bgzf_blocks_to_check: int = 5       # consecutive headers a block-start must chain
    # --- record checking (check/.../package.scala:36-58) ---
    reads_to_check: int = 10            # consecutive records a boundary must chain
    max_read_size: int = 10_000_000     # byte budget for a boundary scan
    # --- split planning ---
    split_size: int | None = None       # bytes; None → context default (2MB check path)
    estimated_compression_ratio: float = 3.0
    # --- backend selection: the Checker plugin surface ---
    checker: str = "eager"              # eager | full | indexed | seqdoop
    backend: str = "auto"               # auto | tpu | pallas | numpy | python | native
    # --- TPU execution shape ---
    # Uncompressed bytes checked per device window. The streaming path
    # rounds (window + carry) up to a power of two for the kernel shape, so
    # 24 MB + the 4 MB halo stays within a 32 MB kernel — the largest that
    # fits a 16 GB-HBM chip (64 MB windows OOM at compile time).
    window_size: int = 24 << 20
    halo_size: int = 4 << 20            # extra trailing bytes so chains can complete
    # Two-phase device inflate (host entropy decode + on-device LZ77
    # resolution, tpu/inflate.py). ``None`` = auto: on the TPU backend with
    # the native tokenizer built it resolves True (the production default —
    # the LZ77 copy phase, inflate's memory-bandwidth half, belongs on HBM);
    # anywhere else False. Tokens cost ~3x the uncompressed bytes on the
    # wire, so hosts whose device link is the constraint should pin False;
    # either way the pipeline demotes to host zlib per window on failure.
    device_inflate: bool | None = None
    # Resident-scan counting (tpu/stream_check.count_reads_resident):
    # windows packed into HBM-resident chunks, ONE dispatch per chunk via
    # checker.count_scan. Amortizes per-dispatch round-trip latency —
    # decisive on remote/tunnelled devices (measured ~5 s/dispatch there)
    # and harmless on-host. Opt-in: the streaming loop stays the default
    # because resident chunks hold ~1 GiB of HBM and the count is the only
    # projection the scan kernel serves.
    resident_scan: bool = False
    # HBM budget for one resident-scan chunk, bytes (clamped to ≤ 1 GiB —
    # the int32-offset ceiling — and to ≥ one window row). BENCH_r05's
    # resident leg crashed the TPU worker at the old hardwired 1 GiB: two
    # chunks in flight plus the scan body's window intermediates exceed a
    # 16 GiB part at 32 MB windows. 256 MiB keeps the dispatch
    # amortization (hundreds of windows per round-trip) with headroom.
    resident_chunk_bytes: int = 256 << 20
    # Fully device-resident count path (stream_check._count_reads_fused):
    # ship packed LZ77 tokens, resolve + assemble + funnel + walk in one
    # XLA program per window, carry chained in HBM. ``None`` = auto:
    # follows the resolved ``device_inflate`` state (the two share the
    # tokenizer prerequisite); demotes to the classic streaming loop
    # whenever the tokenizer or kernel geometry can't serve a file.
    fused_count: bool | None = None
    # --- fault tolerance (core/faults.py; docs/robustness.md) ---
    # Compact FaultPolicy spec ("retries=3,deadline=60,mode=tolerant"; "" =
    # defaults). Kept as the string form so the frozen dataclass stays
    # hashable/env-roundtrippable; ``fault_policy`` parses it (cached).
    faults: str = ""
    # --- split-index cache (sbi/; docs/caching.md) ---
    # "off | read | write | readwrite" with optional ",strict" suffix
    # ("" = off). Same string-spec pattern as ``faults``; ``cache_mode``
    # parses it. Sidecar location/budget come from SPARK_BAM_CACHE_DIR /
    # SPARK_BAM_CACHE_BUDGET (store-level, not Config knobs).
    cache: str = ""
    # --- decode limits (core/guard.py; docs/robustness.md) ---
    # Compact DecodeLimits spec ("record=32MB,refs=1000"; "" = defaults).
    # Same string-spec pattern; ``decode_limits`` parses it (cached).
    limits: str = ""
    # --- remote data plane (core/remote_plan.py; docs/remote.md) ---
    # Compact RemoteConfig spec ("mode=plan,depth=8,gap=128KB,hedge=3";
    # "" = defaults: plan-driven, adaptive depth). Same string-spec
    # pattern; ``remote_config`` parses it (cached).
    remote: str = ""
    # --- serving daemon (serve/; docs/serving.md) ---
    # Compact ServeConfig spec ("batch=16,tick=2,scan_queue=128,window=1MB";
    # "" = defaults). Same string-spec pattern; ``serve_config`` parses it
    # (cached). Governs the long-running split/record service's batching,
    # admission limits, and resident-cache budgets.
    serve: str = ""
    # --- columnar analytics plane (columnar/; docs/analytics.md) ---
    # Compact ColumnarConfig spec ("rows=8192,codec=zlib,level=6,
    # columns=flag+pos+name"; "" = defaults). Same string-spec pattern;
    # ``columnar_config`` parses it (cached). Governs record-batch row
    # counts, native-container compression, and the default projection
    # for the export sinks and the serve ``batch`` op.
    columnar: str = ""
    # --- read-path device inflate (tpu/inflate.py; docs/design.md) ---
    # Compact InflateConfig spec ("tokenize=device,kernel=auto,
    # donate=on"; "" = defaults: tokenize=auto). Same string-spec
    # pattern; ``inflate_config`` parses it (cached). Governs where the
    # DEFLATE entropy phase runs (host native tokenizer vs the device
    # bit-reader kernel), the device kernel engine (pallas/xla), and
    # window-ring buffer donation. Orthogonal to ``device_inflate``
    # (whether the two-phase device path runs at all).
    inflate: str = ""
    # --- write-path compression (compress/; docs/design.md) ---
    # Compact DeflateConfig spec ("mode=fixed,level=6,lanes=16,
    # device=auto"; "" = defaults: host zlib). Same string-spec pattern;
    # ``deflate_config`` parses it (cached). Governs the block codec
    # behind write_bam/htsjdk-rewrite/the serve ``rewrite`` op: stored /
    # fixed-Huffman members batch-compressed on device with per-window
    # demote-to-host, or the seed host-zlib path when off.
    deflate: str = ""
    # --- serve fabric control plane (fabric/; docs/fabric.md) ---
    # Compact FabricConfig spec ("workers=3,slo=200,probe=500,spill=8";
    # "" = defaults). Same string-spec pattern; ``fabric_config`` parses
    # it (cached). Governs the router's worker pool, affinity spillover,
    # health probe/eject pacing, and the SLO autoscaler's target and
    # actuation floors/ceilings.
    fabric: str = ""
    # --- durable job plane (jobs/; docs/robustness.md) ---
    # Compact JobsConfig spec ("dir=/var/jobs,checkpoint=5000,frames=8,
    # mem=0.92,max=2"; "" = defaults). Same string-spec pattern;
    # ``jobs_config`` parses it. Governs the WAL job directory, the
    # checkpoint cadence for journaled rewrite/export/transcode, and the
    # manager's admission watermarks (max concurrent jobs, host-memory
    # fraction above which submits defer).
    jobs: str = ""
    # --- disk-fault chaos seam (core/faults.py; docs/robustness.md) ---
    # "SEED:SPEC" (e.g. "9:enospc=0.05+torn=0.01"; "" = off). Carried as
    # a Config knob so SPARK_BAM_DISK_CHAOS round-trips through
    # ``Config.from_env`` into pool workers; installation itself happens
    # at process entry (``maybe_install_disk_chaos_from_env`` /
    # ``--disk-chaos``), not lazily — a seam that appears mid-run would
    # make the seeded fault schedule depend on call order.
    disk_chaos: str = ""
    # --- on-device aggregation plane (agg/; docs/analytics.md) ---
    # Compact AggConfig spec ("coverage:bin=1000,bins=512;flagstat;mapq;
    # tlen:max=2000;count"; "" = every metric at defaults). Same
    # string-spec pattern; ``agg_config`` parses it (cached). Governs
    # the default metric plan behind the serve ``aggregate`` op, the
    # ``aggregate`` CLI subcommand and ``load.api.aggregate``; requests
    # may override it per call.
    agg: str = ""
    # --- SLO objectives + burn-rate alerting (obs/slo.py) ---
    # Compact SloConfig spec ("serve.latency:p99<1500ms@5m;
    # serve.errors:ratio<0.1%@1h;sample=0.1"; "" = disabled). Same
    # string-spec pattern; ``slo_config`` parses it (cached). Governs the
    # serve-side SLO engine's objectives, alerting windows/threshold, and
    # the tail sampler's keep fraction/seed (docs/observability.md).
    slo: str = ""
    # --- candidate funnel (tpu/checker.py; docs/design.md) ---
    # Two-stage checker hot path: cheap fixed-block prefilter over every
    # position, full 19-flag pass only on survivors. "auto" (default)
    # funnels verdict projections (spans/count/check-bam) and keeps the
    # single-pass kernel wherever full per-position flag masks are the
    # product (full-check forensics) — the funnel's masks are only
    # verdict-faithful. "on" behaves like auto (mask projections always
    # take the exact path); "off" disables it everywhere.
    funnel: str = "auto"                # on | off | auto
    # --- device pacing (tpu/stream_check.py) ---
    # Device→host flush interval for the fused count path, in windows.
    # None → auto: ≤ 2^30 positions between flushes so the on-device
    # int32 accumulators cannot overflow (the auto cap still bounds
    # explicit values).
    flush_every: int | None = None
    # Windows whose device scalars may remain un-synced in the fused
    # count ring (the two-in-flight pipeline's pacing depth).
    ring_depth: int = 2
    # --- misc ---
    warn: bool = False                  # root log-level toggle (args/LogArgs.scala:30-33)
    # Accepted for config-surface parity (PostPartitionArgs -p, default
    # 100000, args/PostPartitionArgs.scala:38-43) but intentionally inert:
    # the reference repartitions its filtered-calls RDD so annotation work
    # balances across executors; here disagreement positions are a host
    # array and annotation is vectorized, so there is no partition count to
    # tune. Kept so reference invocations parse unchanged.
    post_partition_size: int = 100_000

    CHECK_SPLIT_SIZE_DEFAULT = 2 << 20  # Blocks.scala:64
    LOAD_SPLIT_SIZE_DEFAULT = 32 << 20  # hadoop FileSplits default in the load path

    @property
    def flags_impl(self) -> str:
        """Which flag-pass kernel the device engines run ("pallas" when
        ``backend=pallas``, else the XLA pass) — the single mapping every
        tier consults (StreamChecker, the CLI, the mesh steps)."""
        return "pallas" if self.backend == "pallas" else "xla"

    @property
    def fault_policy(self):
        """The parsed ``FaultPolicy`` for this config's ``faults`` spec."""
        from spark_bam_tpu.core.faults import FaultPolicy

        return FaultPolicy.parse(self.faults)

    @property
    def cache_mode(self):
        """The parsed ``CacheMode`` for this config's ``cache`` spec."""
        from spark_bam_tpu.sbi.store import CacheMode

        return CacheMode.parse(self.cache)

    @property
    def decode_limits(self):
        """The parsed ``DecodeLimits`` for this config's ``limits`` spec."""
        from spark_bam_tpu.core.guard import DecodeLimits

        return DecodeLimits.parse(self.limits)

    @property
    def remote_config(self):
        """The parsed ``RemoteConfig`` for this config's ``remote`` spec."""
        from spark_bam_tpu.core.remote_plan import RemoteConfig

        return RemoteConfig.parse(self.remote)

    @property
    def serve_config(self):
        """The parsed ``ServeConfig`` for this config's ``serve`` spec."""
        from spark_bam_tpu.serve.config import ServeConfig

        return ServeConfig.parse(self.serve)

    @property
    def columnar_config(self):
        """The parsed ``ColumnarConfig`` for this config's ``columnar`` spec."""
        from spark_bam_tpu.columnar.config import ColumnarConfig

        return ColumnarConfig.parse(self.columnar)

    @property
    def inflate_config(self):
        """The parsed ``InflateConfig`` for this config's ``inflate`` spec."""
        from spark_bam_tpu.core.inflate_config import InflateConfig

        return InflateConfig.parse(self.inflate)

    @property
    def deflate_config(self):
        """The parsed ``DeflateConfig`` for this config's ``deflate`` spec."""
        from spark_bam_tpu.compress.config import DeflateConfig

        return DeflateConfig.parse(self.deflate)

    @property
    def fabric_config(self):
        """The parsed ``FabricConfig`` for this config's ``fabric`` spec."""
        from spark_bam_tpu.fabric.config import FabricConfig

        return FabricConfig.parse(self.fabric)

    @property
    def jobs_config(self):
        """The parsed ``JobsConfig`` for this config's ``jobs`` spec."""
        from spark_bam_tpu.jobs.manager import JobsConfig

        return JobsConfig.parse(self.jobs)

    @property
    def disk_chaos_config(self):
        """The parsed ``(seed, DiskChaosSpec)`` for this config's
        ``disk_chaos`` spec, or ``None`` when off."""
        from spark_bam_tpu.core.faults import parse_disk_chaos

        return parse_disk_chaos(self.disk_chaos) if self.disk_chaos else None

    @property
    def agg_config(self):
        """The parsed ``AggConfig`` for this config's ``agg`` spec."""
        from spark_bam_tpu.agg.plan import AggConfig

        return AggConfig.parse(self.agg)

    @property
    def slo_config(self):
        """The parsed ``SloConfig`` for this config's ``slo`` spec."""
        from spark_bam_tpu.obs.slo import SloConfig

        return SloConfig.parse(self.slo)

    def funnel_enabled(self, full_masks: bool = False) -> bool:
        """Whether a projection should run the two-stage candidate funnel.

        ``full_masks=True`` marks projections whose *product* is the
        per-position flag mask (full-check forensics): those always take
        the exact single-pass kernel — the funnel's masks carry only
        prefilter bits at rejected positions, so they are verdict-faithful
        but not mask-faithful.
        """
        mode = self.funnel
        if mode not in ("on", "off", "auto"):
            raise ValueError(
                f"Bad funnel mode: {mode!r} (expected on | off | auto)"
            )
        return mode != "off" and not full_masks

    def flush_every_for(self, kernel_window: int) -> int:
        """Count-path flush interval for this kernel window: the explicit
        knob when set, else the int32-overflow-safe auto value; either way
        capped so ≤ 2^30 positions accumulate between flushes."""
        auto = max(1, (1 << 30) // max(kernel_window, 1))
        if self.flush_every is None:
            return auto
        return max(1, min(self.flush_every, auto))

    def split_size_or(self, default: int) -> int:
        return self.split_size if self.split_size is not None else default

    def replace(self, **kw) -> "Config":
        if "split_size" in kw and kw["split_size"] is not None:
            kw["split_size"] = parse_bytes(kw["split_size"])
        return dataclasses.replace(self, **kw)

    _PREFIX = "spark.bam."

    @classmethod
    def from_dict(cls, d: dict, base: "Config | None" = None) -> "Config":
        """Build from a flat ``spark.bam.*`` (or bare-key) mapping."""
        base = base or cls()
        fields = {f.name: f for f in dataclasses.fields(cls)}
        kw = {}
        for key, value in d.items():
            name = key[len(cls._PREFIX):] if key.startswith(cls._PREFIX) else key
            name = name.replace(".", "_").replace("-", "_")
            if name not in fields:
                raise KeyError(f"Unknown config key: {key}")
            f = fields[name]
            if f.type in ("int", int):
                value = parse_bytes(value) if isinstance(value, str) else int(value)
            elif f.type == "int | None":
                if value is None or str(value).lower() in ("auto", "none", ""):
                    value = None
                else:
                    value = parse_bytes(value)
            elif f.type in ("float", float):
                value = float(value)
            elif f.type in ("bool", bool, "bool | None"):
                if not isinstance(value, bool):
                    s = str(value).lower()
                    if "None" in str(f.type) and s in ("auto", "none", ""):
                        value = None
                    else:
                        value = s in ("1", "true", "yes")
            kw[name] = value
        return base.replace(**kw)

    # SPARK_BAM_* sub-namespaces that are NOT Config knobs (cloud backend
    # endpoints/tokens in core/cloud.py; cache-store location/budget in
    # sbi/store.py; telemetry artifact paths in obs/) — from_env must not
    # trip on them. Note the bare SPARK_BAM_CACHE still maps to the
    # ``cache`` knob.
    _ENV_NON_CONFIG = ("gs_", "s3_", "profile", "cache_",
                       "metrics_out", "flight_dir")

    @classmethod
    def from_env(cls, env=os.environ, base: "Config | None" = None) -> "Config":
        """Read ``SPARK_BAM_<KNOB>`` environment overrides."""
        d = {}
        for key, value in env.items():
            if key.startswith("SPARK_BAM_"):
                name = key[len("SPARK_BAM_"):].lower()
                if name.startswith(cls._ENV_NON_CONFIG):
                    continue
                d[name] = value
        return cls.from_dict(d, base=base) if d else (base or cls())


def default_config() -> Config:
    return Config.from_env()
