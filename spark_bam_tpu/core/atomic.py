"""Atomic file writes: same-directory temp + fsync + ``os.replace``.

Promoted from the columnar sink so every writer in the package — export
sinks, ``write_bam``, the rewrite CLI — shares one crash-safety idiom: a
crashed write never leaves a half-written file at the target path (for
BAM, that would be a truncated file with no EOF sentinel that readers
would trust). The temp name is pid-suffixed so concurrent writers to
the same target cannot interleave; the loser of the final ``os.replace``
race simply overwrites the winner with an equally complete file.
"""

from __future__ import annotations

import os


class AtomicFile:
    """Same-directory temp file, ``os.replace``d into place on commit."""

    def __init__(self, out_path: str):
        self.out_path = str(out_path)
        self.tmp_path = f"{self.out_path}.tmp.{os.getpid()}"
        self.f = open(self.tmp_path, "wb")

    def commit(self) -> None:
        self.f.flush()
        os.fsync(self.f.fileno())
        self.f.close()
        os.replace(self.tmp_path, self.out_path)

    def abort(self) -> None:
        try:
            self.f.close()
        finally:
            try:
                os.unlink(self.tmp_path)
            except OSError:
                pass
