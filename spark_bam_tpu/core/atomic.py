"""Atomic file writes: same-directory temp + fsync + ``os.replace``.

Promoted from the columnar sink so every writer in the package — export
sinks, ``write_bam``, the rewrite CLI — shares one crash-safety idiom: a
crashed write never leaves a half-written file at the target path (for
BAM, that would be a truncated file with no EOF sentinel that readers
would trust). The temp name is pid-suffixed so concurrent writers to
the same target cannot interleave; the loser of the final ``os.replace``
race simply overwrites the winner with an equally complete file.

Commit durability is two fsyncs: the file's bytes *and* the containing
directory after the rename — ``os.replace`` alone only updates the
directory in the page cache, so a power loss after "commit" could roll
the rename back (the file would still be at its temp name, or gone).
Writes, the rename and the fsyncs route through the disk-chaos seam
(core/faults.py ``wrap_disk``/``disk_replace``) so the durable-job
tests can inject ENOSPC/torn-write/rename failures deterministically.
"""

from __future__ import annotations

import os

from spark_bam_tpu.core import faults as _faults


def fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` — the half of a durable
    rename ``os.replace`` doesn't do. Best-effort: platforms that refuse
    ``open()`` on directories (or fsync on them) skip silently; the
    rename is still atomic there, just not power-loss durable."""
    parent = os.path.dirname(os.path.abspath(str(path))) or "."
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(parent, flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class AtomicFile:
    """Same-directory temp file, ``os.replace``d into place on commit."""

    def __init__(self, out_path: str):
        self.out_path = str(out_path)
        self.tmp_path = f"{self.out_path}.tmp.{os.getpid()}"
        self.f = _faults.wrap_disk(open(self.tmp_path, "wb"))

    def commit(self) -> None:
        self.f.flush()
        os.fsync(self.f.fileno())
        self.f.close()
        _faults.disk_replace(self.tmp_path, self.out_path)
        fsync_dir(self.out_path)

    def abort(self) -> None:
        try:
            self.f.close()
        finally:
            try:
                os.unlink(self.tmp_path)
            except OSError:
                pass
