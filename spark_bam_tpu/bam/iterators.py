"""Record and record-position iterators over a BAM.

Reference: check/.../bam/iterator/{RecordIterator,PosStream,RecordStream,
SeekableRecordIterator}.scala. ``PosStream`` walks record length-prefixes
without decoding; ``RecordStream`` fully decodes via our own codec
(bam/record.py) instead of HTSJDK's BAMRecordCodec. Seekable variants clamp
seeks to the first-record position (header.end_pos).
"""

from __future__ import annotations

from typing import Iterator, Optional

from spark_bam_tpu.bam.header import BamHeader, parse_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.stream import (
    BlockStream,
    SeekableBlockStream,
    SeekableUncompressedBytes,
    UncompressedBytes,
)
from spark_bam_tpu.core.channel import ByteChannel
from spark_bam_tpu.core.pos import Pos


class _RecordIteratorBase:
    """Shared: owns the uncompressed stream, parses the header on open."""

    def __init__(self, u: UncompressedBytes, header: Optional[BamHeader] = None):
        self.u = u
        if header is None:
            header = parse_header(u)
        self.header = header

    def cur_pos(self) -> Optional[Pos]:
        return self.u.cur_pos()

    def close(self) -> None:
        self.u.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PosStream(_RecordIteratorBase):
    """Yield the virtual position of every record start (no decoding).

    A truncation that cuts a record's length prefix raises EOFError (the
    reference's getInt does the same, PosStream.scala:18; IndexRecords
    catches it in tolerant mode); a cut elsewhere ends the stream cleanly,
    also like the reference.
    """

    def __iter__(self) -> Iterator[Pos]:
        while True:
            pos = self.cur_pos()
            if pos is None:
                return
            remaining = self.u.read_i32()  # EOFError propagates
            self.u.skip(remaining)
            yield pos

    @staticmethod
    def open(ch: ByteChannel) -> "PosStream":
        return PosStream(UncompressedBytes(BlockStream(ch)))


class RecordStream(_RecordIteratorBase):
    """Yield (Pos, BamRecord) pairs."""

    def __iter__(self) -> Iterator[tuple[Pos, BamRecord]]:
        while True:
            pos = self.cur_pos()
            if pos is None:
                return
            try:
                remaining = self.u.read_i32()
                body = self.u.read_fully(remaining)
            except EOFError:
                return
            rec, _ = BamRecord.decode(
                remaining.to_bytes(4, "little", signed=True) + body
            )
            yield pos, rec

    @staticmethod
    def open(ch: ByteChannel) -> "RecordStream":
        return RecordStream(UncompressedBytes(BlockStream(ch)))


class _SeekableMixin:
    u: SeekableUncompressedBytes
    header: BamHeader

    def seek(self, pos: Pos) -> None:
        """Seek, clamped so positions inside the header are rounded up to the
        first record (reference SeekableRecordIterator.scala:183-198)."""
        end = self.header.end_pos
        if (pos.block_pos, pos.offset) < (end.block_pos, end.offset):
            pos = end
        self.u.seek(pos)


class SeekablePosStream(PosStream, _SeekableMixin):
    @staticmethod
    def open(ch: ByteChannel) -> "SeekablePosStream":
        return SeekablePosStream(SeekableUncompressedBytes(SeekableBlockStream(ch)))


class SeekableRecordStream(RecordStream, _SeekableMixin):
    @staticmethod
    def open(ch: ByteChannel) -> "SeekableRecordStream":
        return SeekableRecordStream(SeekableUncompressedBytes(SeekableBlockStream(ch)))
