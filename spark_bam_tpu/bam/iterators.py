"""Record and record-position iterators over a BAM.

Reference: check/.../bam/iterator/{RecordIterator,PosStream,RecordStream,
SeekableRecordIterator}.scala. ``PosStream`` walks record length-prefixes
without decoding; ``RecordStream`` fully decodes via our own codec
(bam/record.py) instead of HTSJDK's BAMRecordCodec. Seekable variants clamp
seeks to the first-record position (header.end_pos).
"""

from __future__ import annotations

from typing import Iterator, Optional

from spark_bam_tpu.bam.header import BamHeader, parse_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.stream import (
    BlockStream,
    SeekableBlockStream,
    SeekableUncompressedBytes,
    UncompressedBytes,
)
from spark_bam_tpu.core import guard
from spark_bam_tpu.core.channel import ByteChannel
from spark_bam_tpu.core.guard import (
    LimitExceeded,
    MalformedInputError,
    RecordGapError,
    StructurallyInvalid,
    current_limits,
)
from spark_bam_tpu.core.pos import Pos

#: Smallest well-formed record body: 32 fixed field bytes + the name's NUL.
MIN_RECORD_BODY = 33


def _check_length_prefix(remaining: int, lim, pos: Pos) -> int:
    """Validate a record's length prefix before it sizes a read."""
    if remaining < MIN_RECORD_BODY:
        raise StructurallyInvalid(
            f"BAM record block_size {remaining} smaller than its fixed "
            f"fields", pos=pos,
        )
    if remaining > lim.max_record_bytes:
        raise LimitExceeded(
            f"BAM record block_size {remaining} exceeds limit "
            f"{lim.max_record_bytes}", pos=pos,
        )
    return remaining


class _RecordIteratorBase:
    """Shared: owns the uncompressed stream, parses the header on open."""

    def __init__(self, u: UncompressedBytes, header: Optional[BamHeader] = None):
        self.u = u
        if header is None:
            header = parse_header(u)
        self.header = header

    def cur_pos(self) -> Optional[Pos]:
        return self.u.cur_pos()

    def close(self) -> None:
        self.u.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PosStream(_RecordIteratorBase):
    """Yield the virtual position of every record start (no decoding).

    A truncation that cuts a record's length prefix raises EOFError (the
    reference's getInt does the same, PosStream.scala:18; IndexRecords
    catches it in tolerant mode); a cut elsewhere ends the stream cleanly,
    also like the reference.
    """

    def __iter__(self) -> Iterator[Pos]:
        lim = current_limits()
        while True:
            pos = self.cur_pos()
            if pos is None:
                return
            remaining = _check_length_prefix(
                self.u.read_i32(), lim, pos  # EOFError propagates
            )
            self.u.skip(remaining)
            yield pos

    @staticmethod
    def open(ch: ByteChannel) -> "PosStream":
        return PosStream(UncompressedBytes(BlockStream(ch)))


class RecordStream(_RecordIteratorBase):
    """Yield (Pos, BamRecord) pairs.

    On a tolerant underlying stream (``FaultPolicy.mode=tolerant``) a
    record that fails to decode is quarantined instead of raised: its
    length prefix already positioned the stream at the next record, so
    iteration skips exactly the damaged record, appends ``(pos, error)``
    to ``self.quarantined`` and counts ``guard.quarantined_records``. An
    untrustworthy length *prefix* can't be locally skipped — that raises
    ``RecordGapError`` once so the load layer re-finds a provable record
    boundary with the checker (load/api.py), the ``BlockGapError`` analog.
    """

    def __init__(self, u: UncompressedBytes, header: BamHeader | None = None):
        super().__init__(u, header)
        self.quarantined: list[tuple[Pos, MalformedInputError]] = []

    def __iter__(self) -> Iterator[tuple[Pos, BamRecord]]:
        lim = current_limits()
        tolerant = getattr(self.u.stream, "tolerant", False)
        while True:
            pos = self.cur_pos()
            if pos is None:
                return
            try:
                remaining = self.u.read_i32()
            except EOFError:
                return
            try:
                _check_length_prefix(remaining, lim, pos)
            except MalformedInputError as e:
                if not tolerant:
                    raise
                self.quarantined.append((pos, e))
                guard.note_quarantined_records()
                raise RecordGapError(pos, str(e)) from e
            try:
                body = self.u.read_fully(remaining)
            except EOFError:
                return
            try:
                rec, _ = BamRecord.decode(
                    remaining.to_bytes(4, "little", signed=True) + body,
                    limits=lim,
                )
            except MalformedInputError as e:
                if not tolerant:
                    if e.pos is None:
                        e.pos = pos
                        e.args = (f"{e} [at {pos}]",)
                    raise
                # The prefix was sane, so the stream already stands at the
                # next record: lose exactly this one and continue.
                self.quarantined.append((pos, e))
                guard.note_quarantined_records()
                continue
            yield pos, rec

    @staticmethod
    def open(ch: ByteChannel) -> "RecordStream":
        return RecordStream(UncompressedBytes(BlockStream(ch)))


class _SeekableMixin:
    u: SeekableUncompressedBytes
    header: BamHeader

    def seek(self, pos: Pos) -> None:
        """Seek, clamped so positions inside the header are rounded up to the
        first record (reference SeekableRecordIterator.scala:183-198)."""
        end = self.header.end_pos
        if (pos.block_pos, pos.offset) < (end.block_pos, end.offset):
            pos = end
        self.u.seek(pos)


class SeekablePosStream(PosStream, _SeekableMixin):
    @staticmethod
    def open(ch: ByteChannel) -> "SeekablePosStream":
        return SeekablePosStream(SeekableUncompressedBytes(SeekableBlockStream(ch)))


class SeekableRecordStream(RecordStream, _SeekableMixin):
    @staticmethod
    def open(ch: ByteChannel) -> "SeekableRecordStream":
        return SeekableRecordStream(SeekableUncompressedBytes(SeekableBlockStream(ch)))
