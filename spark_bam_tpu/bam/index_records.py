"""Single-pass record indexer → ``.records`` sidecar (ground truth).

Emits ``blockPos,offset`` per record (reference
check/.../bam/index/IndexRecords.scala:107-180; line format :149). Tolerant
of truncated files by default: EOF mid-record ends the traversal with what
was seen (reference :160-174), unless ``strict``.
"""

from __future__ import annotations

import logging
import os
import time

from spark_bam_tpu.bam.iterators import PosStream
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.pos import Pos

log = logging.getLogger(__name__)


def format_record_line(pos: Pos) -> str:
    return f"{pos.block_pos},{pos.offset}"


def parse_record_line(line: str) -> Pos:
    block, off = line.strip().split(",")
    return Pos(int(block), int(off))


def read_records_index(path) -> list[Pos]:
    from spark_bam_tpu.core.channel import read_text

    return [
        parse_record_line(line)
        for line in read_text(path).splitlines()
        if line.strip()
    ]


def index_records(
    bam_path, out_path=None, strict: bool = False, heartbeat_seconds: float = 10.0
) -> tuple[str, int]:
    """Write the ``.records`` sidecar for ``bam_path``; returns (path, #records)."""
    out_path = str(out_path) if out_path is not None else str(bam_path) + ".records"
    count = 0
    last_beat = time.monotonic()
    # Write-then-rename (pid-suffixed: concurrent indexers must not
    # interleave): a crash mid-index must never leave a truncated sidecar
    # that downstream consumers would trust as ground truth.
    tmp_path = f"{out_path}.tmp{os.getpid()}"
    try:
        with open_channel(bam_path) as ch, open(tmp_path, "w") as out:
            stream = PosStream.open(ch)
            try:
                for pos in stream:
                    out.write(format_record_line(pos) + "\n")
                    count += 1
                    now = time.monotonic()
                    if now - last_beat >= heartbeat_seconds:
                        log.info("indexed %d records (at %s)", count, pos)
                        last_beat = now
            except (EOFError, IOError):
                if strict:
                    raise
                log.warning("truncated BAM: stopping after %d records", count)
        os.replace(tmp_path, out_path)
    finally:
        if os.path.exists(tmp_path):  # failure path only; replace moved it
            os.unlink(tmp_path)
    return out_path, count
