"""BAM header parsing: magic, SAM text, contig dictionary.

Reference: check/.../bam/header/Header.scala:13-79 (magic check :29, contig
dict :37-53) and ContigLengths.scala. ``end_pos`` — the virtual position of
the first alignment record — is the left fence for every seek/scan; record
iterators clamp to it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

from spark_bam_tpu.bgzf.stream import BlockStream, UncompressedBytes
from spark_bam_tpu.core.channel import ByteChannel, open_channel
from spark_bam_tpu.core.guard import (
    StructurallyInvalid,
    TruncatedInput,
    check_count,
    current_limits,
)
from spark_bam_tpu.core.pos import Pos


class ContigLengths(Mapping[int, tuple[str, int]]):
    """Ordered map: reference index → (contig name, length)."""

    def __init__(self, entries):
        self._entries: dict[int, tuple[str, int]] = dict(entries)

    def __getitem__(self, idx: int) -> tuple[str, int]:
        return self._entries[idx]

    def __iter__(self):
        return iter(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def name(self, idx: int) -> str:
        return "*" if idx < 0 else self._entries[idx][0]

    def lengths_list(self) -> list[int]:
        """Lengths in index order (the array shipped to the TPU checker)."""
        return [self._entries[i][1] for i in range(len(self._entries))]

    def __repr__(self) -> str:
        items = ", ".join(f"{i}:{n}({l})" for i, (n, l) in self._entries.items())
        return f"ContigLengths({items})"

    def __eq__(self, other):
        return isinstance(other, ContigLengths) and self._entries == other._entries


@dataclass(frozen=True)
class BamHeader:
    contig_lengths: ContigLengths
    end_pos: Pos            # virtual position of the first alignment record
    uncompressed_size: int  # uncompressed bytes occupied by the header
    text: str = ""          # raw SAM-text header

    @property
    def num_contigs(self) -> int:
        return len(self.contig_lengths)


def parse_header(u: UncompressedBytes, keep_text: bool = True) -> BamHeader:
    """Parse from an uncompressed-byte stream positioned at 0.

    Every length/count field is bounds-checked against ``DecodeLimits``
    before it sizes an allocation or a loop (a corrupt ``text_len`` used
    to ``read_fully`` gigabytes; a corrupt ``num_refs`` used to iterate
    2³¹ times); truncation mid-header raises ``TruncatedInput``.
    """
    lim = current_limits()
    magic = u.read_fully(4)
    if magic != b"BAM\x01":
        raise StructurallyInvalid(f"Not a BAM: bad magic {magic!r}")
    try:
        text_len = check_count(
            u.read_i32(), "BAM header text_len", lim.max_header_text
        )
        if keep_text:
            text = u.read_fully(text_len).decode("latin-1").rstrip("\x00")
        else:
            if u.skip(text_len) != text_len:
                raise EOFError(f"header text: wanted {text_len} bytes")
            text = ""
        num_refs = check_count(
            u.read_i32(), "BAM header num_refs", lim.max_refs
        )
        entries = {}
        for idx in range(num_refs):
            name_len = check_count(
                u.read_i32(), f"BAM ref {idx} name_len", lim.max_name_len
            )
            name = u.read_fully(name_len).rstrip(b"\x00").decode("latin-1")
            length = check_count(u.read_i32(), f"BAM ref {idx} length")
            entries[idx] = (name, length)
    except EOFError as e:
        # A header is never optional: bytes that end inside it are a
        # malformed file, not a clean truncation.
        raise TruncatedInput(f"BAM header truncated: {e}") from e
    end_pos = u.cur_pos()
    if end_pos is None:
        # Header-only BAM: first-record position is one past the last byte.
        end_pos = Pos(0, 0)
    return BamHeader(ContigLengths(entries), end_pos, u.tell(), text)


def read_header(path_or_channel, keep_text: bool = True) -> BamHeader:
    """Read the header of a BAM file (path or open channel)."""
    if isinstance(path_or_channel, ByteChannel):
        return parse_header(UncompressedBytes(BlockStream(path_or_channel)), keep_text)
    with open_channel(path_or_channel) as ch:
        return parse_header(UncompressedBytes(BlockStream(ch)), keep_text)


def contig_lengths(path) -> ContigLengths:
    return read_header(path, keep_text=False).contig_lengths
