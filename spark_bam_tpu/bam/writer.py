"""BGZF/BAM writing: block compressor, header + record encoder.

Enables the reference's ``htsjdk-rewrite`` capability (round-trip a BAM so
records stop being block-aligned — cli/.../rewrite/HTSJDKRewrite.scala:347-418)
and synthetic-fixture generation for tests, without HTSJDK.
"""

from __future__ import annotations

import struct
import zlib

from spark_bam_tpu.bam.header import BamHeader
from spark_bam_tpu.bam.record import BamRecord

# Standard 28-byte BGZF EOF sentinel block.
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)

# Keep uncompressed payloads under 64 KiB so compressed size fits the u16 field.
DEFAULT_BLOCK_PAYLOAD = 0xFF00


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """One complete BGZF block (header + raw-deflate payload + footer)."""
    compressor = zlib.compressobj(level, zlib.DEFLATED, -15)
    comp = compressor.compress(payload) + compressor.flush()
    bsize = 18 + len(comp) + 8  # header + payload + footer
    if bsize > 0x10000:
        raise ValueError("Block too large after compression; lower payload size")
    header = (
        b"\x1f\x8b\x08\x04"        # gzip magic, deflate, FEXTRA
        + b"\x00\x00\x00\x00"      # mtime
        + b"\x00\xff"              # XFL, OS
        + b"\x06\x00"              # XLEN = 6
        + b"BC\x02\x00"            # BC subfield
        + struct.pack("<H", bsize - 1)
    )
    footer = struct.pack("<II", zlib.crc32(payload), len(payload))
    return header + comp + footer


class BgzfWriter:
    """Buffer bytes; flush complete BGZF blocks to a file object."""

    def __init__(self, fobj, block_payload: int = DEFAULT_BLOCK_PAYLOAD, level: int = 6):
        self.f = fobj
        self.block_payload = block_payload
        self.level = level
        self.buf = bytearray()

    def write(self, data: bytes) -> None:
        self.buf += data
        while len(self.buf) >= self.block_payload:
            self._flush_block(self.block_payload)

    def _flush_block(self, n: int) -> None:
        payload, self.buf = bytes(self.buf[:n]), self.buf[n:]
        self.f.write(compress_block(payload, self.level))

    def close(self) -> None:
        if self.buf:
            self._flush_block(len(self.buf))
        self.f.write(BGZF_EOF)
        self.f.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def encode_bam_header(header: BamHeader) -> bytes:
    text = header.text.encode("latin-1")
    if text and not text.endswith(b"\n"):
        text += b"\n"
    out = bytearray(b"BAM\x01")
    out += struct.pack("<i", len(text))
    out += text
    out += struct.pack("<i", header.num_contigs)
    for idx in range(header.num_contigs):
        name, length = header.contig_lengths[idx]
        name_b = name.encode("latin-1") + b"\x00"
        out += struct.pack("<i", len(name_b)) + name_b + struct.pack("<i", length)
    return bytes(out)


def write_bam(
    path,
    header: BamHeader,
    records,
    block_payload: int = DEFAULT_BLOCK_PAYLOAD,
    level: int = 6,
) -> int:
    """Write a BAM file; returns the number of records written.

    Records are packed back-to-back into fixed-size uncompressed payloads, so
    record starts are deliberately *not* block-aligned — the property the
    reference's htsjdk-rewrite manufactures for adversarial split tests.
    """
    count = 0
    with open(path, "wb") as f, BgzfWriter(f, block_payload, level) as w:
        w.write(encode_bam_header(header))
        for rec in records:
            rec = rec[1] if isinstance(rec, tuple) else rec  # accept (Pos, rec)
            assert isinstance(rec, BamRecord)
            w.write(rec.encode())
            count += 1
    return count
