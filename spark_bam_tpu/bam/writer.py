"""BGZF/BAM writing: block codecs, header + record encoder.

Enables the reference's ``htsjdk-rewrite`` capability (round-trip a BAM so
records stop being block-aligned — cli/.../rewrite/HTSJDKRewrite.scala:347-418)
and synthetic-fixture generation for tests, without HTSJDK.

The compressor is pluggable (``compress/codec.py``): the default is the
host zlib path, while ``--deflate`` / ``SPARK_BAM_DEFLATE`` routes whole
batches of payload lanes through the device CRC32/fixed-Huffman kernels.
``BgzfWriter`` drives any codec through its dispatch/materialize split
with up to two batches in flight — the write-side mirror of the inflate
pipeline's double-buffering — and records per-member ``Metadata`` as it
goes, so rewrite can emit ``.blocks``/``.sbi`` sidecars without ever
re-reading its own output.
"""

from __future__ import annotations

import struct
from collections import deque
from dataclasses import dataclass, field

from spark_bam_tpu.bam.header import BamHeader
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.compress.huffman import zlib_member

# Standard 28-byte BGZF EOF sentinel block.
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)

# Keep uncompressed payloads under 64 KiB so compressed size fits the u16 field.
DEFAULT_BLOCK_PAYLOAD = 0xFF00


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """One complete BGZF block (header + raw-deflate payload + footer).

    The host escape hatch every device demotion lands on. An
    incompressible payload whose zlib output would overflow the u16
    BSIZE field falls back to a stored-block member (bounded 5-byte
    expansion — always fits at the default payload size); only a payload
    too large even for stored raises ``core/guard.py LimitExceeded``.
    """
    return zlib_member(payload, level)


@dataclass
class WriteResult:
    """Everything ``write_bam_result`` learned while packing: enough to
    build every sidecar (``.blocks``/``.records``/``.sbi``) in memory."""

    count: int = 0
    header_len: int = 0
    blocks: "list[Metadata]" = field(default_factory=list)
    #: Flat (uncompressed-stream) offset of each record start, in order.
    record_flats: "list[int]" = field(default_factory=list)
    bytes_out: int = 0


class BgzfWriter:
    """Buffer bytes; flush complete BGZF blocks to a file object.

    ``codec`` is any ``compress/codec.py`` block codec; payloads batch up
    to ``codec.lanes`` per dispatch and at most two batches stay in
    flight (dispatch batch N while materializing batch N-1). ``blocks``
    accumulates one ``Metadata`` per member in file order — the same
    rows ``bgzf/index_blocks.py`` would scan back, minus the EOF
    sentinel — and ``flat_tell`` exposes the uncompressed-stream offset
    so callers can note record starts as they pack.
    """

    def __init__(self, fobj, block_payload: int = DEFAULT_BLOCK_PAYLOAD,
                 level: int = 6, codec=None):
        if codec is None:
            from spark_bam_tpu.compress.codec import HostZlibCodec

            codec = HostZlibCodec(level)
        self.f = fobj
        self.block_payload = block_payload
        self.level = level
        self.codec = codec
        self.buf = bytearray()
        self.blocks: "list[Metadata]" = []
        self._batch: "list[bytes]" = []
        self._pending: "deque[tuple[list[int], object]]" = deque()
        self._offset = 0
        self._flat = 0

    @property
    def flat_tell(self) -> int:
        """Uncompressed-stream offset of the next byte written."""
        return self._flat

    def write(self, data: bytes) -> None:
        self._flat += len(data)
        self.buf += data
        while len(self.buf) >= self.block_payload:
            payload = bytes(self.buf[: self.block_payload])
            del self.buf[: self.block_payload]
            self._enqueue(payload)

    def _enqueue(self, payload: bytes) -> None:
        self._batch.append(payload)
        if len(self._batch) >= max(int(getattr(self.codec, "lanes", 1)), 1):
            self._dispatch_batch()

    def _dispatch_batch(self) -> None:
        if not self._batch:
            return
        plens = [len(p) for p in self._batch]
        handle = self.codec.dispatch(self._batch)
        self._batch = []
        self._pending.append((plens, handle))
        while len(self._pending) > 1:
            self._write_oldest()

    def _write_oldest(self) -> None:
        plens, handle = self._pending.popleft()
        for n, member in zip(plens, self.codec.materialize(handle)):
            self.f.write(member)
            self.blocks.append(Metadata(self._offset, len(member), n))
            self._offset += len(member)

    def close(self) -> None:
        if self.buf:
            payload = bytes(self.buf)
            self.buf = bytearray()
            self._enqueue(payload)
        self._dispatch_batch()
        while self._pending:
            self._write_oldest()
        self.f.write(BGZF_EOF)
        self._offset += len(BGZF_EOF)
        self.f.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def encode_bam_header(header: BamHeader) -> bytes:
    text = header.text.encode("latin-1")
    if text and not text.endswith(b"\n"):
        text += b"\n"
    out = bytearray(b"BAM\x01")
    out += struct.pack("<i", len(text))
    out += text
    out += struct.pack("<i", header.num_contigs)
    for idx in range(header.num_contigs):
        name, length = header.contig_lengths[idx]
        name_b = name.encode("latin-1") + b"\x00"
        out += struct.pack("<i", len(name_b)) + name_b + struct.pack("<i", length)
    return bytes(out)


def write_bam_result(
    path,
    header: BamHeader,
    records,
    block_payload: int = DEFAULT_BLOCK_PAYLOAD,
    level: int = 6,
    deflate=None,
    codec=None,
) -> WriteResult:
    """``write_bam`` returning the full :class:`WriteResult` (counts,
    per-member metadata, record-start flat offsets).

    The output lands via ``core/atomic.AtomicFile`` — a crash mid-write
    never leaves a truncated BAM (no EOF sentinel) at ``path``.
    ``deflate`` is a ``DeflateConfig``/spec string selecting the codec
    ("" /None/mode=off ⇒ host zlib at ``level``); ``codec`` overrides
    it with a pre-built codec instance.
    """
    from spark_bam_tpu.core.atomic import AtomicFile

    if codec is None:
        from spark_bam_tpu.compress.codec import make_codec

        codec = make_codec(deflate, level=level)
    from spark_bam_tpu.core.guard import map_write_error

    result = WriteResult()
    out = AtomicFile(path)
    try:
        with BgzfWriter(out.f, block_payload, level, codec=codec) as w:
            w.write(encode_bam_header(header))
            result.header_len = w.flat_tell
            for rec in records:
                rec = rec[1] if isinstance(rec, tuple) else rec  # accept (Pos, rec)
                assert isinstance(rec, BamRecord)
                result.record_flats.append(w.flat_tell)
                w.write(rec.encode())
                result.count += 1
        result.blocks = w.blocks
        result.bytes_out = w._offset
    except OSError as exc:
        # ENOSPC/EIO/EDQUOT mid-write become the guard taxonomy's
        # retryable ResourceExhausted instead of a raw OSError escaping
        # the fault model's classification entirely.
        out.abort()
        raise map_write_error(exc, "BAM write", path=path) from exc
    except BaseException:
        out.abort()
        raise
    try:
        out.commit()
    except OSError as exc:
        out.abort()
        raise map_write_error(exc, "BAM commit", path=path) from exc
    return result


def write_bam(
    path,
    header: BamHeader,
    records,
    block_payload: int = DEFAULT_BLOCK_PAYLOAD,
    level: int = 6,
    deflate=None,
    codec=None,
) -> int:
    """Write a BAM file; returns the number of records written.

    Records are packed back-to-back into fixed-size uncompressed payloads, so
    record starts are deliberately *not* block-aligned — the property the
    reference's htsjdk-rewrite manufactures for adversarial split tests.
    """
    return write_bam_result(
        path, header, records,
        block_payload=block_payload, level=level, deflate=deflate, codec=codec,
    ).count
