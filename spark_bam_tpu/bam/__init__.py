from spark_bam_tpu.bam.bai import BaiIndex, build_bai, index_bam
from spark_bam_tpu.bam.header import BamHeader, ContigLengths, read_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.iterators import (
    PosStream,
    RecordStream,
    SeekablePosStream,
    SeekableRecordStream,
)

__all__ = [
    "BaiIndex",
    "build_bai",
    "index_bam",
    "BamHeader",
    "ContigLengths",
    "read_header",
    "BamRecord",
    "PosStream",
    "RecordStream",
    "SeekablePosStream",
    "SeekableRecordStream",
]
