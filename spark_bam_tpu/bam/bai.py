"""``.bai`` BAM-index reader and interval → chunk queries.

Reference: check/.../bam/index/Index.scala:11-93 (METADATA_BIN_ID :92) plus
the HTSJDK-delegating chunk query used by ``loadBamIntervals``
(load/.../CanLoadBam.scala:387-421). Here both live in one module: parse the
BAI binning + linear index, and answer "which (start,end) virtual-position
chunks can contain alignments overlapping [start,end) on contig c".
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from spark_bam_tpu.core.guard import StructurallyInvalid, TruncatedInput
from spark_bam_tpu.core.pos import Pos

METADATA_BIN_ID = 37450  # magic bin holding per-reference metadata pseudo-chunks
LINEAR_INDEX_SHIFT = 14  # 16 KiB linear-index windows


def _bai_count(n: int, what: str, data: bytes, off: int, item_size: int,
               path) -> int:
    """Validate an index count before it sizes a loop or an allocation: a
    corrupt ``n_intv`` used to size a multi-GB ``struct.unpack_from``."""
    if n < 0:
        raise StructurallyInvalid(
            f".bai {what} is negative: {n}", path=str(path), pos=off
        )
    if off + n * item_size > len(data):
        raise TruncatedInput(
            f".bai {what} {n} needs {n * item_size} bytes, "
            f"have {len(data) - off}", path=str(path), pos=off,
        )
    return n


@dataclass(frozen=True)
class Chunk:
    start: Pos
    end: Pos

    def size(self, estimated_compression_ratio: float = 3.0) -> int:
        """Approximate compressed size (used for bin-packing into partitions)."""
        return self.end.distance(self.start, estimated_compression_ratio)


@dataclass
class Reference:
    bins: dict[int, list[Chunk]]
    linear_index: list[int]  # virtual offsets, one per 16 KiB window
    metadata_chunks: list[Chunk]


@dataclass
class BaiIndex:
    references: list[Reference]
    n_no_coor: int | None

    @staticmethod
    def read(path) -> "BaiIndex":
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != b"BAI\x01":
            raise StructurallyInvalid(
                f"Not a BAI index: bad magic {data[:4]!r}", path=str(path)
            )
        try:
            return BaiIndex._parse(data, path)
        except struct.error as e:
            raise TruncatedInput(f"truncated .bai: {e}", path=str(path)) from e

    @staticmethod
    def _parse(data: bytes, path) -> "BaiIndex":
        off = 4
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        # 8 = the per-reference minimum (n_bin i32 + n_intv i32).
        _bai_count(n_ref, "n_ref", data, off, 8, path)
        refs = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            _bai_count(n_bin, "n_bin", data, off, 8, path)
            bins: dict[int, list[Chunk]] = {}
            meta: list[Chunk] = []
            for _ in range(n_bin):
                bin_id, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                _bai_count(n_chunk, "n_chunk", data, off, 16, path)
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append(Chunk(Pos.from_htsjdk(beg), Pos.from_htsjdk(end)))
                if bin_id == METADATA_BIN_ID:
                    meta = chunks
                else:
                    bins[bin_id] = chunks
            (n_intv,) = struct.unpack_from("<i", data, off)
            off += 4
            _bai_count(n_intv, "n_intv", data, off, 8, path)
            linear = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            refs.append(Reference(bins, linear, meta))
        n_no_coor = None
        if off + 8 <= len(data):
            (n_no_coor,) = struct.unpack_from("<Q", data, off)
        return BaiIndex(refs, n_no_coor)

    # ------------------------------------------------------------------ queries
    def chunk_starts(self) -> list[Pos]:
        return sorted(
            {c.start for ref in self.references for cs in ref.bins.values() for c in cs}
        )

    def all_addresses(self) -> list[Pos]:
        out = set()
        for ref in self.references:
            for chunks in ref.bins.values():
                for c in chunks:
                    out.add(c.start)
                    out.add(c.end)
        return sorted(out)

    def query(self, ref_idx: int, start: int, end: int) -> list[Chunk]:
        """Chunks possibly containing alignments overlapping [start, end)."""
        if ref_idx >= len(self.references):
            return []
        ref = self.references[ref_idx]
        min_offset = Pos(0, 0)
        win = start >> LINEAR_INDEX_SHIFT
        if ref.linear_index and win < len(ref.linear_index):
            min_offset = Pos.from_htsjdk(ref.linear_index[win])
        chunks = [
            c
            for bin_id in reg2bins(start, end)
            for c in ref.bins.get(bin_id, ())
            if (c.end.block_pos, c.end.offset) > (min_offset.block_pos, min_offset.offset)
        ]
        return merge_chunks(sorted(chunks, key=lambda c: (c.start, c.end)))


    # ------------------------------------------------------------------ write
    def write(self, out_path) -> str:
        """Serialize in the standard BAI layout (readable by this module's
        reader and by htsjdk/samtools). Write-then-rename, like every
        sidecar writer here: a crash must not leave a truncated index."""
        parts = [b"BAI\x01", struct.pack("<i", len(self.references))]
        for ref in self.references:
            n_bin = len(ref.bins) + (1 if ref.metadata_chunks else 0)
            parts.append(struct.pack("<i", n_bin))
            for bin_id in sorted(ref.bins):
                chunks = ref.bins[bin_id]
                parts.append(struct.pack("<Ii", bin_id, len(chunks)))
                for c in chunks:
                    parts.append(
                        struct.pack("<QQ", c.start.to_htsjdk(), c.end.to_htsjdk())
                    )
            if ref.metadata_chunks:
                parts.append(
                    struct.pack("<Ii", METADATA_BIN_ID, len(ref.metadata_chunks))
                )
                for c in ref.metadata_chunks:
                    parts.append(
                        struct.pack("<QQ", c.start.to_htsjdk(), c.end.to_htsjdk())
                    )
            parts.append(struct.pack("<i", len(ref.linear_index)))
            parts.append(struct.pack(f"<{len(ref.linear_index)}Q", *ref.linear_index))
        if self.n_no_coor is not None:
            parts.append(struct.pack("<Q", self.n_no_coor))
        import os

        tmp_path = f"{out_path}.tmp{os.getpid()}"
        try:
            with open(tmp_path, "wb") as f:
                f.write(b"".join(parts))
            os.replace(tmp_path, out_path)
        finally:
            if os.path.exists(tmp_path):  # failure path only
                os.unlink(tmp_path)
        return str(out_path)


def build_bai(bam_path) -> BaiIndex:
    """Build the BAI binning + linear index for a coordinate-sorted BAM —
    the samtools-index role (beyond the reference, which consumes ``.bai``
    via HTSJDK but never writes one; load/.../CanLoadBam.scala:387-421).

    One sequential pass: each record contributes its virtual-position span
    ``[start, next record's start)`` to its ``reg2bin`` bin and its minimum
    start offset to every 16 KiB linear window it overlaps. Placed-unmapped
    reads index at ``[pos, pos+1)``; unplaced reads count into
    ``n_no_coor``. Per-reference metadata pseudo-bins (37450) carry the
    begin/end offsets and mapped/unmapped counts, as samtools writes them.
    """
    from spark_bam_tpu.bam.iterators import RecordStream
    from spark_bam_tpu.core.channel import open_channel, path_size

    ch = open_channel(bam_path)
    stream = RecordStream.open(ch)
    header = stream.header
    n_ref = header.num_contigs
    eof_pos = Pos(path_size(bam_path), 0)
    after_pos = None  # virtual offset just past the most recent record

    bins: list[dict[int, list[Chunk]]] = [{} for _ in range(n_ref)]
    linear: list[dict[int, int]] = [{} for _ in range(n_ref)]
    span: list[list] = [[None, None, 0, 0] for _ in range(n_ref)]  # beg,end,mapped,unmapped
    n_no_coor = 0

    def add(ref_id: int, beg: int, end_coord: int, vstart: Pos, vend: Pos):
        b = reg2bin(beg, end_coord)
        chunks = bins[ref_id].setdefault(b, [])
        if chunks and (
            (vstart.block_pos, vstart.offset)
            <= (chunks[-1].end.block_pos, chunks[-1].end.offset)
            or vstart.block_pos == chunks[-1].end.block_pos
        ):
            # Adjacent/same-block chunks coalesce (samtools/htsjdk do too).
            if (vend.block_pos, vend.offset) > (
                chunks[-1].end.block_pos, chunks[-1].end.offset
            ):
                chunks[-1] = Chunk(chunks[-1].start, vend)
        else:
            chunks.append(Chunk(vstart, vend))
        vs = vstart.to_htsjdk()
        lin = linear[ref_id]
        for w in range(beg >> LINEAR_INDEX_SHIFT,
                       max(beg, end_coord - 1) >> LINEAR_INDEX_SHIFT):
            lin[w] = min(lin.get(w, vs), vs)
        w = max(beg, end_coord - 1) >> LINEAR_INDEX_SHIFT
        lin[w] = min(lin.get(w, vs), vs)
        sp = span[ref_id]
        sp[0] = vstart if sp[0] is None else sp[0]
        sp[1] = vend

    try:
        prev = None
        prev_key = None
        for pos, rec in stream:
            after_pos = _tell_after(stream)
            if rec.ref_id >= 0 and rec.pos >= 0:
                key = (rec.ref_id, rec.pos)
                if prev_key is not None and key < prev_key:
                    # An index built from unsorted input would silently
                    # drop records at query time (the linear-index pruning
                    # assumes coordinate order) — refuse, like samtools.
                    raise ValueError(
                        f"{bam_path}: not coordinate-sorted at {pos} "
                        f"(ref {rec.ref_id} pos {rec.pos} after "
                        f"ref {prev_key[0]} pos {prev_key[1]})"
                    )
                prev_key = key
            if prev is not None:
                _index_one(prev[1], prev[0], pos, add, span)
            prev = (pos, rec)
            if rec.ref_id < 0 or rec.pos < 0:
                n_no_coor += 1
        if prev is not None:
            # The final record's chunk ends at the virtual offset just past
            # it (what samtools writes), not at the physical file size —
            # Pos(file_size, 0) would drag the BGZF EOF sentinel into the
            # last chunk and byte-differ from samtools output.
            _index_one(
                prev[1], prev[0],
                eof_pos if after_pos is None else after_pos, add, span,
            )
    finally:
        ch.close()

    refs = []
    for r in range(n_ref):
        lin = linear[r]
        n_win = (max(lin) + 1) if lin else 0
        # Gap windows carry the previous window's value (samtools layout);
        # leading gaps are 0 (= unconstrained for query pruning).
        arr = []
        last = 0
        for w in range(n_win):
            last = lin.get(w, last)
            arr.append(last)
        meta = []
        beg_v, end_v, n_mapped, n_unmapped = span[r]
        if beg_v is not None:
            meta = [
                Chunk(beg_v, end_v),
                Chunk(Pos.from_htsjdk(n_mapped), Pos.from_htsjdk(n_unmapped)),
            ]
        refs.append(Reference(bins[r], arr, meta))
    return BaiIndex(refs, n_no_coor)


def _tell_after(stream) -> Pos | None:
    """The stream cursor as samtools' ``bgzf_tell`` would report it: when
    the just-read record exhausted its block, the *next* block's compressed
    start with offset 0 (htslib normalizes block-end to next-block-start;
    for the final record that is the BGZF EOF sentinel's offset, which is
    the exclusive bound samtools writes into the index). Side-effect free —
    unlike ``cur_pos`` it never advances the block cursor."""
    blk = stream.u.stream.head()
    if blk is None:
        return None
    if blk.idx >= len(blk.data):
        return Pos(blk.next_start, 0)
    return blk.pos


def _index_one(rec, vstart: Pos, vend: Pos, add, span) -> None:
    if rec.ref_id < 0 or rec.pos < 0:
        return
    if rec.is_unmapped:
        add(rec.ref_id, rec.pos, rec.pos + 1, vstart, vend)
        span[rec.ref_id][3] += 1
    else:
        add(rec.ref_id, rec.pos, rec.end_pos(), vstart, vend)
        span[rec.ref_id][2] += 1


def index_bam(bam_path, out_path=None) -> tuple[str, "BaiIndex"]:
    """Build and write ``bam_path``'s ``.bai``; returns (path, index)."""
    out_path = str(out_path) if out_path is not None else str(bam_path) + ".bai"
    index = build_bai(bam_path)
    index.write(out_path)
    return out_path, index


def reg2bins(beg: int, end: int) -> list[int]:
    """All bin ids overlapping [beg, end) in the UCSC binning scheme."""
    end -= 1
    bins = [0]
    for shift, offset in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(offset + (beg >> shift), offset + (end >> shift) + 1))
    return bins


def reg2bin(beg: int, end: int) -> int:
    """Smallest bin containing [beg, end) (for the BAM writer)."""
    end -= 1
    for shift, offset in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        if beg >> shift == end >> shift:
            return offset + (beg >> shift)
    return 0


def merge_chunks(chunks: list[Chunk]) -> list[Chunk]:
    """Coalesce adjacent/overlapping chunks (matches HTSJDK's optimization)."""
    out: list[Chunk] = []
    for c in chunks:
        if out and (c.start.block_pos, c.start.offset) <= (
            out[-1].end.block_pos,
            out[-1].end.offset,
        ):
            if (c.end.block_pos, c.end.offset) > (out[-1].end.block_pos, out[-1].end.offset):
                out[-1] = Chunk(out[-1].start, c.end)
        else:
            out.append(c)
    return out
