"""``.bai`` BAM-index reader and interval → chunk queries.

Reference: check/.../bam/index/Index.scala:11-93 (METADATA_BIN_ID :92) plus
the HTSJDK-delegating chunk query used by ``loadBamIntervals``
(load/.../CanLoadBam.scala:387-421). Here both live in one module: parse the
BAI binning + linear index, and answer "which (start,end) virtual-position
chunks can contain alignments overlapping [start,end) on contig c".
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from spark_bam_tpu.core.pos import Pos

METADATA_BIN_ID = 37450  # magic bin holding per-reference metadata pseudo-chunks
LINEAR_INDEX_SHIFT = 14  # 16 KiB linear-index windows


@dataclass(frozen=True)
class Chunk:
    start: Pos
    end: Pos

    def size(self, estimated_compression_ratio: float = 3.0) -> int:
        """Approximate compressed size (used for bin-packing into partitions)."""
        return self.end.distance(self.start, estimated_compression_ratio)


@dataclass
class Reference:
    bins: dict[int, list[Chunk]]
    linear_index: list[int]  # virtual offsets, one per 16 KiB window
    metadata_chunks: list[Chunk]


@dataclass
class BaiIndex:
    references: list[Reference]
    n_no_coor: int | None

    @staticmethod
    def read(path) -> "BaiIndex":
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != b"BAI\x01":
            raise ValueError(f"Not a BAI index: bad magic {data[:4]!r}")
        off = 4
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        refs = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            bins: dict[int, list[Chunk]] = {}
            meta: list[Chunk] = []
            for _ in range(n_bin):
                bin_id, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append(Chunk(Pos.from_htsjdk(beg), Pos.from_htsjdk(end)))
                if bin_id == METADATA_BIN_ID:
                    meta = chunks
                else:
                    bins[bin_id] = chunks
            (n_intv,) = struct.unpack_from("<i", data, off)
            off += 4
            linear = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            refs.append(Reference(bins, linear, meta))
        n_no_coor = None
        if off + 8 <= len(data):
            (n_no_coor,) = struct.unpack_from("<Q", data, off)
        return BaiIndex(refs, n_no_coor)

    # ------------------------------------------------------------------ queries
    def chunk_starts(self) -> list[Pos]:
        return sorted(
            {c.start for ref in self.references for cs in ref.bins.values() for c in cs}
        )

    def all_addresses(self) -> list[Pos]:
        out = set()
        for ref in self.references:
            for chunks in ref.bins.values():
                for c in chunks:
                    out.add(c.start)
                    out.add(c.end)
        return sorted(out)

    def query(self, ref_idx: int, start: int, end: int) -> list[Chunk]:
        """Chunks possibly containing alignments overlapping [start, end)."""
        if ref_idx >= len(self.references):
            return []
        ref = self.references[ref_idx]
        min_offset = Pos(0, 0)
        win = start >> LINEAR_INDEX_SHIFT
        if ref.linear_index and win < len(ref.linear_index):
            min_offset = Pos.from_htsjdk(ref.linear_index[win])
        chunks = [
            c
            for bin_id in reg2bins(start, end)
            for c in ref.bins.get(bin_id, ())
            if (c.end.block_pos, c.end.offset) > (min_offset.block_pos, min_offset.offset)
        ]
        return merge_chunks(sorted(chunks, key=lambda c: (c.start, c.end)))


def reg2bins(beg: int, end: int) -> list[int]:
    """All bin ids overlapping [beg, end) in the UCSC binning scheme."""
    end -= 1
    bins = [0]
    for shift, offset in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(offset + (beg >> shift), offset + (end >> shift) + 1))
    return bins


def reg2bin(beg: int, end: int) -> int:
    """Smallest bin containing [beg, end) (for the BAM writer)."""
    end -= 1
    for shift, offset in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        if beg >> shift == end >> shift:
            return offset + (beg >> shift)
    return 0


def merge_chunks(chunks: list[Chunk]) -> list[Chunk]:
    """Coalesce adjacent/overlapping chunks (matches HTSJDK's optimization)."""
    out: list[Chunk] = []
    for c in chunks:
        if out and (c.start.block_pos, c.start.offset) <= (
            out[-1].end.block_pos,
            out[-1].end.offset,
        ):
            if (c.end.block_pos, c.end.offset) > (out[-1].end.block_pos, out[-1].end.offset):
                out[-1] = Chunk(out[-1].start, c.end)
        else:
            out.append(c)
    return out
