"""BAM record codec: decode, SAM rendering, encode.

A from-scratch replacement for the reference's dependence on HTSJDK's
``BAMRecordCodec`` (check/.../iterator/RecordStream.scala:48-57). One record:

    block_size i32            # bytes that follow (the reference's "remainingBytes")
    refID i32, pos i32
    l_read_name u8, mapq u8, bin u16
    n_cigar_op u16, flag u16
    l_seq i32
    next_refID i32, next_pos i32, tlen i32
    read_name  l_read_name bytes (NUL-terminated)
    cigar      n_cigar_op × u32 (len<<4 | op)
    seq        (l_seq+1)//2 bytes of 4-bit codes
    qual       l_seq bytes
    tags       rest

The encoder enables the htsjdk-rewrite analog (bam/rewrite.py) and synthetic
test-BAM generation.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from spark_bam_tpu.core.guard import (
    DecodeLimits,
    LimitExceeded,
    StructurallyInvalid,
    TruncatedInput,
    current_limits,
)

CIGAR_OPS = "MIDNSHP=X"
SEQ_CODES = "=ACMGRSVTWYHKDBN"

FLAG_UNMAPPED = 0x4

_FIXED = struct.Struct("<iiiBBHHHiiii")  # block_size..tlen (36 bytes)


@dataclass
class BamRecord:
    ref_id: int
    pos: int          # 0-based
    mapq: int
    bin: int
    flag: int
    next_ref_id: int
    next_pos: int
    tlen: int
    read_name: str
    cigar: list[tuple[int, int]] = field(default_factory=list)  # (length, op-code)
    seq: str = ""
    qual: bytes = b""
    tags: bytes = b""

    # ------------------------------------------------------------------ decode
    @staticmethod
    def decode(
        buf: bytes | memoryview, offset: int = 0,
        limits: DecodeLimits | None = None,
    ) -> tuple["BamRecord", int]:
        """Decode one record; returns (record, bytes consumed incl. length prefix).

        Every length field is validated before it sizes a slice or a loop:
        truncation raises ``TruncatedInput``, contradictory fields raise
        ``StructurallyInvalid``, fields beyond ``limits`` raise
        ``LimitExceeded`` — never a silent short slice (core/guard.py).
        """
        lim = limits or current_limits()
        avail = len(buf) - offset
        if avail < 36:  # length prefix + the 32 fixed field bytes
            raise TruncatedInput(
                f"BAM record fixed section: need 36 bytes, have {avail}"
            )
        (
            block_size,
            ref_id,
            pos,
            l_read_name,
            mapq,
            bin_,
            n_cigar,
            flag,
            l_seq,
            next_ref_id,
            next_pos,
            tlen,
        ) = _FIXED.unpack_from(buf, offset)
        if block_size < 32 + 1:  # fixed fields + the name's NUL
            raise StructurallyInvalid(
                f"BAM record block_size {block_size} smaller than its "
                f"fixed fields"
            )
        if block_size > lim.max_record_bytes:
            raise LimitExceeded(
                f"BAM record block_size {block_size} exceeds limit "
                f"{lim.max_record_bytes}"
            )
        if 4 + block_size > avail:
            raise TruncatedInput(
                f"BAM record: declared {4 + block_size} bytes, have {avail}"
            )
        if l_read_name == 0:
            raise StructurallyInvalid(
                "BAM record l_read_name is 0 (name must be NUL-terminated)"
            )
        if l_seq < 0:
            raise StructurallyInvalid(f"BAM record l_seq is negative ({l_seq})")
        if l_seq > lim.max_seq_len:
            raise LimitExceeded(
                f"BAM record l_seq {l_seq} exceeds limit {lim.max_seq_len}"
            )
        if n_cigar > lim.max_cigar_ops:
            raise LimitExceeded(
                f"BAM record n_cigar {n_cigar} exceeds limit "
                f"{lim.max_cigar_ops}"
            )
        # The declared sub-regions must fit the declared extent — a short
        # slice here used to yield a silently-wrong record.
        need = 32 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
        if need > block_size:
            raise StructurallyInvalid(
                f"BAM record fields need {need} bytes but block_size is "
                f"{block_size}"
            )
        p = offset + 36
        read_name = bytes(buf[p: p + l_read_name - 1]).decode("latin-1")
        p += l_read_name
        cigar = []
        for _ in range(n_cigar):
            cig = struct.unpack_from("<I", buf, p)[0]
            cigar.append((cig >> 4, cig & 0xF))
            p += 4
        n_seq_bytes = (l_seq + 1) // 2
        seq_bytes = bytes(buf[p: p + n_seq_bytes])
        p += n_seq_bytes
        seq = "".join(
            SEQ_CODES[(seq_bytes[i >> 1] >> (4 if i % 2 == 0 else 0)) & 0xF]
            for i in range(l_seq)
        )
        qual = bytes(buf[p: p + l_seq])
        p += l_seq
        end = offset + 4 + block_size
        tags = bytes(buf[p:end])
        rec = BamRecord(
            ref_id, pos, mapq, bin_, flag, next_ref_id, next_pos, tlen,
            read_name, cigar, seq, qual, tags,
        )
        return rec, 4 + block_size

    # ------------------------------------------------------------------ encode
    def encode(self) -> bytes:
        name_bytes = self.read_name.encode("latin-1") + b"\x00"
        cigar_bytes = b"".join(
            struct.pack("<I", (length << 4) | op) for length, op in self.cigar
        )
        l_seq = len(self.seq)
        seq_bytes = bytearray((l_seq + 1) // 2)
        for i, base in enumerate(self.seq):
            code = SEQ_CODES.index(base) if base in SEQ_CODES else 15
            seq_bytes[i >> 1] |= code << (4 if i % 2 == 0 else 0)
        qual = self.qual if len(self.qual) == l_seq else b"\xff" * l_seq
        body = (
            struct.pack(
                "<iiBBHHHiiii",
                self.ref_id,
                self.pos,
                len(name_bytes),
                self.mapq,
                self.bin,
                len(self.cigar),
                self.flag,
                l_seq,
                self.next_ref_id,
                self.next_pos,
                self.tlen,
            )
            + name_bytes
            + cigar_bytes
            + bytes(seq_bytes)
            + qual
            + self.tags
        )
        return struct.pack("<i", len(body)) + body

    # ------------------------------------------------------------------ derived
    @property
    def is_unmapped(self) -> bool:
        return bool(self.flag & FLAG_UNMAPPED)

    @property
    def read_length(self) -> int:
        return len(self.seq)

    def cigar_string(self) -> str:
        if not self.cigar:
            return "*"
        return "".join(f"{length}{CIGAR_OPS[op]}" for length, op in self.cigar)

    def reference_span(self) -> int:
        """Bases of reference consumed (cigar ops M/D/N/=/X)."""
        return sum(length for length, op in self.cigar if op in (0, 2, 3, 7, 8))

    def end_pos(self) -> int:
        """0-based exclusive reference end (pos+1 for unmapped/empty-cigar)."""
        span = self.reference_span()
        return self.pos + (span if span else 1)

    # ------------------------------------------------------------------ SAM
    def to_sam(self, contigs) -> str:
        rname = contigs.name(self.ref_id)
        if self.next_ref_id < 0:
            rnext = "*"
        elif self.next_ref_id == self.ref_id:
            rnext = "="
        else:
            rnext = contigs.name(self.next_ref_id)
        qual = (
            "*"
            if not self.qual or all(q == 0xFF for q in self.qual)
            else "".join(chr(q + 33) for q in self.qual)
        )
        fields = [
            self.read_name or "*",
            str(self.flag),
            rname,
            str(self.pos + 1),
            str(self.mapq),
            self.cigar_string(),
            rnext,
            str(self.next_pos + 1),
            str(self.tlen),
            self.seq or "*",
            qual,
        ]
        tag_strs = render_tags(self.tags)
        return "\t".join(fields + tag_strs)


def render_tags(raw: bytes) -> list[str]:
    """Render the raw tag block as SAM ``TAG:TYPE:VALUE`` strings.

    Total on arbitrary bytes: any inconsistency (short value, missing NUL,
    negative/overflowing B-array count, unknown subtype) stops rendering
    at that tag — never an unbounded loop or an untyped crash (the raw
    bytes stay preserved on the record either way).
    """
    out = []
    p = 0
    n = len(raw)
    while p + 3 <= n:
        tag = raw[p: p + 2].decode("latin-1")
        typ = chr(raw[p + 2])
        p += 3
        if typ == "A":
            if p >= n:
                break
            out.append(f"{tag}:A:{chr(raw[p])}")
            p += 1
        elif typ in "cCsSiI":
            fmt, size = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2),
                         "S": ("<H", 2), "i": ("<i", 4), "I": ("<I", 4)}[typ]
            if p + size > n:
                break
            val = struct.unpack_from(fmt, raw, p)[0]
            out.append(f"{tag}:i:{val}")
            p += size
        elif typ == "f":
            if p + 4 > n:
                break
            val = struct.unpack_from("<f", raw, p)[0]
            out.append(f"{tag}:f:{val:g}")
            p += 4
        elif typ in "ZH":
            end = raw.find(b"\x00", p)
            if end < 0:
                break
            out.append(f"{tag}:{typ}:{raw[p:end].decode('latin-1')}")
            p = end + 1
        elif typ == "B":
            if p + 5 > n:
                break
            sub = chr(raw[p])
            count = struct.unpack_from("<i", raw, p + 1)[0]
            p += 5
            entry = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2),
                     "S": ("<H", 2), "i": ("<i", 4), "I": ("<I", 4),
                     "f": ("<f", 4)}.get(sub)
            if entry is None or count < 0 or p + count * entry[1] > n:
                break
            fmt, size = entry
            vals = [str(struct.unpack_from(fmt, raw, p + i * size)[0]) for i in range(count)]
            out.append(f"{tag}:B:{sub},{','.join(vals)}")
            p += count * size
        else:
            break  # unknown type: stop rendering (raw bytes still preserved)
    return out


def parse_sam_line(line: str, contigs_by_name: dict[str, int]) -> BamRecord:
    """Parse one SAM alignment line into a BamRecord (tags re-encoded)."""
    parts = line.rstrip("\n").split("\t")
    qname, flag, rname, pos, mapq, cigar_s, rnext, pnext, tlen, seq, qual = parts[:11]
    ref_id = -1 if rname == "*" else contigs_by_name[rname]
    if rnext == "*":
        next_ref = -1
    elif rnext == "=":
        next_ref = ref_id
    else:
        next_ref = contigs_by_name[rnext]
    cigar = []
    if cigar_s != "*":
        num = ""
        for c in cigar_s:
            if c.isdigit():
                num += c
            else:
                cigar.append((int(num), CIGAR_OPS.index(c)))
                num = ""
    tags = b"".join(encode_tag(t) for t in parts[11:])
    return BamRecord(
        ref_id=ref_id,
        pos=int(pos) - 1,
        mapq=int(mapq),
        bin=0,
        flag=int(flag),
        next_ref_id=next_ref,
        next_pos=int(pnext) - 1,
        tlen=int(tlen),
        read_name=qname if qname != "*" else "",
        cigar=cigar,
        seq=seq if seq != "*" else "",
        qual=b"" if qual == "*" else bytes(ord(c) - 33 for c in qual),
        tags=tags,
    )


def encode_tag(s: str) -> bytes:
    tag, typ, value = s.split(":", 2)
    head = tag.encode("latin-1")
    if typ == "A":
        return head + b"A" + value.encode("latin-1")
    if typ == "i":
        v = int(value)
        return head + b"i" + struct.pack("<i", v)
    if typ == "f":
        return head + b"f" + struct.pack("<f", float(value))
    if typ in ("Z", "H"):
        return head + typ.encode() + value.encode("latin-1") + b"\x00"
    if typ == "B":
        sub = value[0]
        vals = value[2:].split(",") if len(value) > 2 else []
        fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i", "I": "<I", "f": "<f"}[sub]
        body = b"".join(
            struct.pack(fmt, float(v) if sub == "f" else int(v)) for v in vals
        )
        return head + b"B" + sub.encode() + struct.pack("<i", len(vals)) + body
    raise ValueError(f"Unknown tag type: {s}")
