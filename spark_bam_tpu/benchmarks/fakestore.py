"""In-process fake object store: ranged GETs + injected pathologies.

One implementation for every consumer that needs a stand-in GCS/S3/HTTP
origin — the bench's remote legs and the cloud/remote test suites — so
Range-handling fixes land once. Serves one object (``data``/``key``) or
many (``objects``) at any path ending in a registered key; everything else
404s (sidecar probes must read as absent).

Beyond base ``latency_s``, the store models the failure modes the remote
data plane (core/remote_plan.py) is built to absorb, all **seeded and
offline** so hedging/adaptive-depth tests are deterministic without a
network:

- ``jitter_s``: uniform per-request latency jitter on top of the base.
- ``straggler_rate``/``straggler_factor``: a seeded fraction of requests
  take ``factor``× the base latency — the tail hedged GETs must cut.
- ``throttle_rate``/``retry_after_s``: a seeded fraction answer
  429 + ``Retry-After`` (object-store throttling storms).
- ``bandwidth_Bps``: a shared-pipe bandwidth model — concurrent responses
  serialize through one token bucket, so throughput stops scaling with
  request depth once the pipe saturates (the depth ladder's knee).
- ``ignore_range``: answer 200 + full body despite a ``Range`` header
  (the misbehaving-origin case ``HttpRangeChannel`` must reject).

Per-request randomness comes from ``random.Random(seed ^ request_index)``
— the same seed replays the same storm, mirroring the chaos harness
(core/faults.py)."""

from __future__ import annotations

import http.server
import random
import threading
import time


class FakeObjectStore:
    """``with FakeObjectStore(data, key="obj.bam", latency_s=0.1) as s:``
    exposes ``s.url_base`` (http://127.0.0.1:port) and live ``s.stats``
    (``requests``, ``auth_failures``, ``stragglers``, ``throttles``)."""

    def __init__(
        self,
        data: bytes = b"",
        key: str = "remote.bam",
        latency_s: float = 0.0,
        require_bearer: str | None = None,
        objects: "dict[str, bytes] | None" = None,
        jitter_s: float = 0.0,
        straggler_rate: float = 0.0,
        straggler_factor: float = 10.0,
        throttle_rate: float = 0.0,
        retry_after_s: float = 0.05,
        bandwidth_Bps: float | None = None,
        seed: int = 0,
        ignore_range: bool = False,
    ):
        #: key → bytes; the single-object (data, key) form maps into it.
        self.objects = dict(objects) if objects is not None else {key: data}
        self.latency_s = latency_s
        self.require_bearer = require_bearer
        self.jitter_s = jitter_s
        self.straggler_rate = straggler_rate
        self.straggler_factor = straggler_factor
        self.throttle_rate = throttle_rate
        self.retry_after_s = retry_after_s
        self.bandwidth_Bps = bandwidth_Bps
        self.seed = seed
        self.ignore_range = ignore_range
        self.stats = {
            "requests": 0, "auth_failures": 0,
            "stragglers": 0, "throttles": 0,
        }
        self._lock = threading.Lock()
        self._bw_free_at = 0.0  # shared-pipe model: when the pipe frees up
        store = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive, like a real object-store front end.
            # The default (HTTP/1.0, close-per-response) forces every GET
            # through a fresh TCP connect; under deep prefetch bursts the
            # listener backlog overflows and dropped SYNs retransmit after
            # ~1 s, which reads as fake 10×-RTT stragglers.
            protocol_version = "HTTP/1.1"

            def _empty(self, status: int, headers: dict | None = None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _object(self) -> bytes | None:
                for key, data in store.objects.items():
                    if self.path.endswith("/" + key):
                        return data
                return None

            def _gate(self) -> bytes | None:
                """Admission: accounting, latency model, 404/403/429.
                Returns the object bytes, or None when a response was
                already sent."""
                with store._lock:
                    store.stats["requests"] += 1
                    idx = store.stats["requests"]
                # Deterministic per-request pathology: same seed, same
                # request ordinal → same jitter/straggler/throttle draw.
                rng = random.Random((store.seed << 20) ^ idx)
                wait = store.latency_s
                if store.jitter_s:
                    wait += rng.uniform(0.0, store.jitter_s)
                if (
                    store.straggler_rate
                    and rng.random() < store.straggler_rate
                ):
                    with store._lock:
                        store.stats["stragglers"] += 1
                    wait *= store.straggler_factor
                if wait:
                    time.sleep(wait)
                if (
                    store.throttle_rate
                    and rng.random() < store.throttle_rate
                ):
                    with store._lock:
                        store.stats["throttles"] += 1
                    self._empty(
                        429, {"Retry-After": f"{store.retry_after_s:g}"}
                    )
                    return None
                data = self._object()
                if data is None:
                    self._empty(404)
                    return None
                if store.require_bearer is not None:
                    ok = (
                        self.headers.get("Authorization")
                        == f"Bearer {store.require_bearer}"
                    )
                    if not ok:
                        with store._lock:
                            store.stats["auth_failures"] += 1
                        self._empty(403)
                        return None
                return data

            def _pipe(self, nbytes: int) -> None:
                """Shared-bandwidth model: every response reserves pipe
                time; concurrent transfers queue behind each other, so
                aggregate throughput caps at ``bandwidth_Bps`` no matter
                the request depth."""
                if not store.bandwidth_Bps:
                    return
                cost = nbytes / store.bandwidth_Bps
                with store._lock:
                    now = time.monotonic()
                    start = max(now, store._bw_free_at)
                    store._bw_free_at = start + cost
                    done_at = store._bw_free_at
                delay = done_at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)

            def do_HEAD(self):
                data = self._gate()
                if data is None:
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Accept-Ranges", "bytes")
                self.end_headers()

            def do_GET(self):
                data = self._gate()
                if data is None:
                    return
                rng = self.headers.get("Range")
                if rng and not store.ignore_range:
                    lo_s, _, hi_s = rng.split("=")[1].partition("-")
                    lo = int(lo_s)
                    # RFC 9110: an open-ended "bytes=lo-" runs to the end.
                    hi = int(hi_s) if hi_s else len(data) - 1
                    hi = min(hi, len(data) - 1)
                    if lo >= len(data):
                        self.send_response(416)
                        self.send_header(
                            "Content-Range", f"bytes */{len(data)}"
                        )
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    body = data[lo:hi + 1]
                    self._pipe(len(body))
                    self.send_response(206)
                    self.send_header(
                        "Content-Range",
                        f"bytes {lo}-{lo + len(body) - 1}/{len(data)}",
                    )
                else:
                    body = data
                    self._pipe(len(body))
                    self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128  # absorb depth-64 connect bursts

        self._srv = _Server(("127.0.0.1", 0), Handler)
        self.url_base = f"http://127.0.0.1:{self._srv.server_port}"
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def data(self) -> bytes:
        """Single-object back-compat accessor (first registered object)."""
        return next(iter(self.objects.values()))

    @property
    def key(self) -> str:
        return next(iter(self.objects))

    def close(self):
        self._srv.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
