"""In-process fake object store: ranged GETs + injected latency.

One implementation for every consumer that needs a stand-in GCS/S3/HTTP
origin — the bench's remote-latency leg and the cloud/remote test suites —
so Range-handling fixes land once. Serves a single object at any path
ending in the registered key; everything else 404s (sidecar probes must
read as absent)."""

from __future__ import annotations

import http.server
import threading
import time


class FakeObjectStore:
    """``with FakeObjectStore(data, key="obj.bam", latency_s=0.1) as s:``
    exposes ``s.url_base`` (http://127.0.0.1:port) and live ``s.stats``
    (``requests``, ``auth_failures``)."""

    def __init__(
        self,
        data: bytes,
        key: str = "remote.bam",
        latency_s: float = 0.0,
        require_bearer: str | None = None,
    ):
        self.data = data
        self.key = key
        self.latency_s = latency_s
        self.require_bearer = require_bearer
        self.stats = {"requests": 0, "auth_failures": 0}
        store = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _empty(self, status: int):
                self.send_response(status)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _gate(self) -> bool:
                store.stats["requests"] += 1
                if store.latency_s:
                    time.sleep(store.latency_s)
                if not self.path.endswith("/" + store.key):
                    self._empty(404)
                    return False
                if store.require_bearer is not None:
                    ok = (
                        self.headers.get("Authorization")
                        == f"Bearer {store.require_bearer}"
                    )
                    if not ok:
                        store.stats["auth_failures"] += 1
                        self._empty(403)
                        return False
                return True

            def do_HEAD(self):
                if not self._gate():
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(store.data)))
                self.send_header("Accept-Ranges", "bytes")
                self.end_headers()

            def do_GET(self):
                if not self._gate():
                    return
                data = store.data
                rng = self.headers.get("Range")
                if rng:
                    lo_s, _, hi_s = rng.split("=")[1].partition("-")
                    lo = int(lo_s)
                    # RFC 9110: an open-ended "bytes=lo-" runs to the end.
                    hi = int(hi_s) if hi_s else len(data) - 1
                    hi = min(hi, len(data) - 1)
                    if lo >= len(data):
                        self.send_response(416)
                        self.send_header(
                            "Content-Range", f"bytes */{len(data)}"
                        )
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    body = data[lo:hi + 1]
                    self.send_response(206)
                    self.send_header(
                        "Content-Range",
                        f"bytes {lo}-{lo + len(body) - 1}/{len(data)}",
                    )
                else:
                    body = data
                    self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self._srv = _Server(("127.0.0.1", 0), Handler)
        self.url_base = f"http://127.0.0.1:{self._srv.server_port}"
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self):
        self._srv.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
