"""Benchmark-output harvesting → TSV rows.

Reference: the ``benchmarks`` sbt module (benchmarks/.../BAM.scala:5-192,
TSV.scala:201-238) regex-parses ``check-bam`` / ``check-blocks`` output
files into per-BAM spreadsheet rows. Ours parses the same report shapes this
repo's CLI emits (byte-compatible with the reference's for check-bam).

Usage:
    python -m spark_bam_tpu.benchmarks.harvest OUT1 [OUT2 ...] > results.tsv
"""

from __future__ import annotations

import re
import sys
from dataclasses import dataclass


@dataclass
class BamInfo:
    path: str = ""
    uncompressed_positions: int | None = None
    compressed_size: str | None = None
    compression_ratio: float | None = None
    num_reads: int | None = None
    false_positives: int = 0
    false_negatives: int = 0
    all_matched: bool = False
    # check-blocks specifics
    num_blocks: int | None = None
    bad_blocks: int = 0
    bad_compressed_positions: int = 0
    total_compressed_positions: int | None = None

    FIELDS = (
        "path", "uncompressed_positions", "compressed_size",
        "compression_ratio", "num_reads", "false_positives",
        "false_negatives", "all_matched", "num_blocks", "bad_blocks",
        "bad_compressed_positions", "total_compressed_positions",
    )

    def tsv_row(self) -> str:
        return "\t".join(
            "" if getattr(self, f) is None else str(getattr(self, f))
            for f in self.FIELDS
        )


_PATTERNS = [
    (re.compile(r"^(\d+) uncompressed positions"),
     lambda m, b: setattr(b, "uncompressed_positions", int(m.group(1)))),
    (re.compile(r"^(\S+) compressed$"),
     lambda m, b: setattr(b, "compressed_size", m.group(1))),
    (re.compile(r"^Compression ratio: ([\d.]+)"),
     lambda m, b: setattr(b, "compression_ratio", float(m.group(1)))),
    (re.compile(r"^(\d+) reads$"),
     lambda m, b: setattr(b, "num_reads", int(m.group(1)))),
    (re.compile(r"^(\d+) false positives, (\d+) false negatives"),
     lambda m, b: (setattr(b, "false_positives", int(m.group(1))),
                   setattr(b, "false_negatives", int(m.group(2))))),
    (re.compile(r"^All calls matched!"),
     lambda m, b: setattr(b, "all_matched", True)),
    (re.compile(r"^First read-position matched in (\d+) BGZF blocks"),
     lambda m, b: (setattr(b, "num_blocks", int(m.group(1))),
                   setattr(b, "all_matched", True))),
    (re.compile(r"^First read-position mismatched in (\d+) of (\d+) BGZF blocks"),
     lambda m, b: (setattr(b, "bad_blocks", int(m.group(1))),
                   setattr(b, "num_blocks", int(m.group(2))))),
    (re.compile(r"^(\d+) of (\d+) \([\d.eE-]+\) compressed positions would lead"),
     lambda m, b: (setattr(b, "bad_compressed_positions", int(m.group(1))),
                   setattr(b, "total_compressed_positions", int(m.group(2))))),
]


def parse_output(path: str) -> BamInfo:
    info = BamInfo(path=path)
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            for pattern, action in _PATTERNS:
                m = pattern.match(line)
                if m:
                    action(m, info)
                    break
    return info


def main(argv=None) -> int:
    paths = (argv if argv is not None else sys.argv[1:])
    print("\t".join(BamInfo.FIELDS))
    for path in paths:
        print(parse_output(path).tsv_row())
    return 0


if __name__ == "__main__":
    sys.exit(main())
