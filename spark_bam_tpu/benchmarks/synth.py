"""Synthesize large benchmark BAMs by compressed-block repetition.

The reference's headline numbers are whole-workload wall-clock on multi-GB
BAMs (reference docs/benchmarks.md:53-62 — count-reads / time-load on
559 GB-14 TB corpora); the small checked-in fixtures can't exercise that
regime. This builds an arbitrarily large, fully valid BAM out of ``2.bam``
in seconds: the fixture's record region (everything after the BAM header)
is re-compressed into a self-contained run of BGZF blocks *once*, then that
compressed run is byte-repeated N times. Every repeat starts at a block
boundary and at a record boundary, so the result is a spec-valid BAM whose
read count is exactly ``reps * 2500``.

Generation cost is one ~1.5 MB compression plus file IO — no per-record
work — so a ≥1 GB file materializes in a few seconds and can be cached
across runs (``ensure_big_bam``).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.writer import (
    BGZF_EOF,
    DEFAULT_BLOCK_PAYLOAD as _PAYLOAD,
    compress_block,
)
from spark_bam_tpu.bgzf.flat import flatten_file

FIXTURE = Path("/root/reference/test_bams/src/main/resources/2.bam")
FIXTURE_READS = 2500


def _count_records(rec_bytes: memoryview) -> int:
    """Record count of a flat record region (length-prefix walk)."""
    import struct

    n, off, total = 0, 0, len(rec_bytes)
    while off + 4 <= total:
        (size,) = struct.unpack_from("<i", rec_bytes, off)
        off += 4 + size
        n += 1
    if off != total:
        raise ValueError("record region does not end on a record boundary")
    return n


def _chunks_to_blocks(data: bytes, level: int = 6) -> bytes:
    out = bytearray()
    for i in range(0, len(data), _PAYLOAD):
        out += compress_block(data[i: i + _PAYLOAD], level)
    return bytes(out)


def synth_bam(
    out_path: Path,
    target_bytes: int,
    fixture: Path = FIXTURE,
    level: int = 1,
) -> dict:
    """Write a ≥``target_bytes`` (compressed) BAM to ``out_path``.

    Returns a manifest dict: reps, reads, compressed/uncompressed sizes.
    """
    flat = flatten_file(fixture)
    hdr = read_header(fixture)
    split = hdr.uncompressed_size
    rec_bytes = flat.data[split:].tobytes()
    reads_per_rep = _count_records(memoryview(rec_bytes))
    hdr_blob = _chunks_to_blocks(flat.data[:split].tobytes(), level)
    rec_blob = _chunks_to_blocks(rec_bytes, level)
    body = max(target_bytes - len(hdr_blob) - len(BGZF_EOF), len(rec_blob))
    reps = -(-body // len(rec_blob))  # ceil

    tmp = out_path.with_suffix(".tmp")
    with open(tmp, "wb") as f:
        f.write(hdr_blob)
        for _ in range(reps):
            f.write(rec_blob)
        f.write(BGZF_EOF)
    os.replace(tmp, out_path)

    rec_usize = flat.size - split
    manifest = {
        "fixture": str(fixture),
        "reps": reps,
        "reads": reps * reads_per_rep,
        "compressed_bytes": out_path.stat().st_size,
        "uncompressed_bytes": split + reps * rec_usize,
        "level": level,
    }
    out_path.with_suffix(".manifest.json").write_text(json.dumps(manifest))
    return manifest


def ensure_big_bam(
    target_bytes: int = 1 << 30,
    cache_dir: Path = Path("/tmp/spark_bam_bench"),
    fixture: Path = FIXTURE,
) -> tuple[Path, dict]:
    """Build (or reuse a cached) ≥``target_bytes`` benchmark BAM."""
    cache_dir.mkdir(parents=True, exist_ok=True)
    out = cache_dir / f"big_{target_bytes >> 20}mb.bam"
    mf = out.with_suffix(".manifest.json")
    if out.exists() and mf.exists():
        manifest = json.loads(mf.read_text())
        if manifest.get("compressed_bytes") == out.stat().st_size:
            return out, manifest
    return out, synth_bam(out, target_bytes, fixture)
