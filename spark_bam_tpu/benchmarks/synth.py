"""Synthesize large benchmark BAMs by compressed-block repetition.

The reference's headline numbers are whole-workload wall-clock on multi-GB
BAMs (reference docs/benchmarks.md:53-62 — count-reads / time-load on
559 GB-14 TB corpora); the small checked-in fixtures can't exercise that
regime. This builds an arbitrarily large, fully valid BAM out of ``2.bam``
in seconds: the fixture's record region (everything after the BAM header)
is re-compressed into a self-contained run of BGZF blocks *once*, then that
compressed run is byte-repeated N times. Every repeat starts at a block
boundary and at a record boundary, so the result is a spec-valid BAM whose
read count is exactly ``reps * 2500``.

Generation cost is one ~1.5 MB compression plus file IO — no per-record
work — so a ≥1 GB file materializes in a few seconds and can be cached
across runs (``ensure_big_bam``).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.writer import (
    BGZF_EOF,
    DEFAULT_BLOCK_PAYLOAD as _PAYLOAD,
    compress_block,
)
from spark_bam_tpu.bgzf.flat import flatten_file

FIXTURE = Path("/root/reference/test_bams/src/main/resources/2.bam")
FIXTURE_READS = 2500


def synthetic_fixture(
    cache_dir: Path = Path("/tmp/spark_bam_bench"), reads: int = 2500
) -> Path:
    """Deterministic in-package seed BAM for hosts without the reference
    fixture assets: coordinate-sorted mapped reads over two contigs,
    written with the package's own encoder. Cached across runs."""
    cache_dir.mkdir(parents=True, exist_ok=True)
    out = cache_dir / f"synthetic_fixture_{reads}.bam"
    if out.exists():
        return out
    import random

    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.core.pos import Pos

    rng = random.Random(0x5BA17)
    contigs = [("chr1", 248_956_422), ("chr2", 242_193_529)]
    text = "@HD\tVN:1.6\tSO:coordinate\n" + "".join(
        f"@SQ\tSN:{name}\tLN:{length}\n" for name, length in contigs
    )
    header = BamHeader(
        ContigLengths(dict(enumerate(contigs))), Pos(0, 0), 0, text
    )

    def records():
        per_contig = -(-reads // len(contigs))
        i = 0
        for ref_id in range(len(contigs)):
            pos = 0
            for _ in range(per_contig):
                if i >= reads:
                    return
                pos += rng.randrange(1, 400)
                read_len = rng.randrange(80, 151)
                yield BamRecord(
                    ref_id=ref_id, pos=pos, mapq=rng.randrange(1, 60),
                    bin=0, flag=0, next_ref_id=-1, next_pos=-1, tlen=0,
                    read_name=f"syn{i:06d}",
                    cigar=[(read_len, 0)],
                    seq="".join(rng.choices("ACGT", k=read_len)),
                    qual=bytes(
                        rng.randrange(2, 41) for _ in range(read_len)
                    ),
                )
                i += 1

    tmp = out.with_suffix(".tmp")
    write_bam(tmp, header, records())
    os.replace(tmp, out)
    return out


def _count_records(rec_bytes: memoryview) -> int:
    """Record count of a flat record region (length-prefix walk)."""
    import struct

    n, off, total = 0, 0, len(rec_bytes)
    while off + 4 <= total:
        (size,) = struct.unpack_from("<i", rec_bytes, off)
        off += 4 + size
        n += 1
    if off != total:
        raise ValueError("record region does not end on a record boundary")
    return n


def _chunks_to_blocks(data: bytes, level: int = 6) -> bytes:
    out = bytearray()
    for i in range(0, len(data), _PAYLOAD):
        out += compress_block(data[i: i + _PAYLOAD], level)
    return bytes(out)


def synth_bam(
    out_path: Path,
    target_bytes: int,
    fixture: Path = FIXTURE,
    level: int = 1,
) -> dict:
    """Write a ≥``target_bytes`` (compressed) BAM to ``out_path``.

    Returns a manifest dict: reps, reads, compressed/uncompressed sizes.
    """
    if not Path(fixture).exists():
        fixture = synthetic_fixture()
    flat = flatten_file(fixture)
    hdr = read_header(fixture)
    split = hdr.uncompressed_size
    rec_bytes = flat.data[split:].tobytes()
    reads_per_rep = _count_records(memoryview(rec_bytes))
    hdr_blob = _chunks_to_blocks(flat.data[:split].tobytes(), level)
    rec_blob = _chunks_to_blocks(rec_bytes, level)
    body = max(target_bytes - len(hdr_blob) - len(BGZF_EOF), len(rec_blob))
    reps = -(-body // len(rec_blob))  # ceil

    tmp = out_path.with_suffix(".tmp")
    with open(tmp, "wb") as f:
        f.write(hdr_blob)
        for _ in range(reps):
            f.write(rec_blob)
        f.write(BGZF_EOF)
    os.replace(tmp, out_path)

    rec_usize = flat.size - split
    manifest = {
        "fixture": str(fixture),
        "reps": reps,
        "reads": reps * reads_per_rep,
        "compressed_bytes": out_path.stat().st_size,
        "uncompressed_bytes": split + reps * rec_usize,
        "level": level,
    }
    out_path.with_suffix(".manifest.json").write_text(json.dumps(manifest))
    return manifest


def ensure_big_bam(
    target_bytes: int = 1 << 30,
    cache_dir: Path = Path("/tmp/spark_bam_bench"),
    fixture: Path = FIXTURE,
) -> tuple[Path, dict]:
    """Build (or reuse a cached) ≥``target_bytes`` benchmark BAM."""
    cache_dir.mkdir(parents=True, exist_ok=True)
    out = cache_dir / f"big_{target_bytes >> 20}mb.bam"
    mf = out.with_suffix(".manifest.json")
    if out.exists() and mf.exists():
        manifest = json.loads(mf.read_text())
        if manifest.get("compressed_bytes") == out.stat().st_size:
            return out, manifest
    return out, synth_bam(out, target_bytes, fixture)


# --------------------------------------------------------------- long reads

#: CHM13/GRCh38 chr1 length — realistic coordinate range for long reads.
LONGREAD_CONTIG = ("chr1", 248_956_422)


def _encode_longread(name: bytes, pos: int, seq_len: int, rng) -> bytes:
    """One spec-valid mapped BAM record with a ``seq_len``-base read,
    fields built with numpy (the pure-Python per-base encoder is far too
    slow at PacBio sizes)."""
    import struct

    from spark_bam_tpu.bam.bai import reg2bin

    n_name = len(name) + 1
    seq_bytes = (seq_len + 1) // 2
    remaining = 32 + n_name + 4 + seq_bytes + seq_len
    head = struct.pack(
        "<iiiBBHHHiiii",
        remaining,
        0,                      # ref_id
        pos,
        n_name,
        40,                     # mapq
        reg2bin(pos, pos + seq_len),
        1,                      # n_cigar
        0,                      # flag
        seq_len,
        -1, -1,                 # next_ref_id, next_pos
        0,                      # tlen
    )
    cigar = struct.pack("<I", (seq_len << 4) | 0)  # one M op
    # Random 4-bit base codes and quals: incompressible like real PacBio.
    nibbles = rng.integers(0x11, 0x88, seq_bytes, dtype=np.uint8).tobytes()
    quals = rng.integers(5, 40, seq_len, dtype=np.uint8).tobytes()
    return head + name + b"\x00" + cigar + nibbles + quals


def synth_longread_bam(
    out_path: Path,
    target_bytes: int,
    seed: int = 0,
    read_lens: tuple[int, int] = (80_000, 400_000),
    reads_per_rep: int = 12,
    ultra_seq_len: int = 3_000_000,
    level: int = 1,
) -> dict:
    """A ≥``target_bytes`` PacBio-class BAM: every record spans dozens of
    BGZF blocks, and each repeat carries one *ultra* read whose encoded
    record (~1.5 × ``ultra_seq_len`` bytes) exceeds the default 4 MB
    streaming halo — the regime where hadoop-bam's checker broke on GiaB
    PacBio data (reference docs/benchmarks.md:24-38;
    seqdoop/.../Checker.scala:40-43) and where this repo's escape/deferral
    path must engage and still resolve exactly.

    Same build strategy as ``synth_bam``: one record unit is generated and
    block-compressed once, then byte-repeated (every repeat starts on a
    block and record boundary), so multi-GB corpora materialize in seconds
    with exact manifests."""
    rng = np.random.default_rng(seed)
    name, ln = LONGREAD_CONTIG
    sam = f"@HD\tVN:1.6\n@SQ\tSN:{name}\tLN:{ln}\n"
    import struct

    header_blob = (
        b"BAM\x01"
        + struct.pack("<i", len(sam))
        + sam.encode()
        + struct.pack("<i", 1)
        + struct.pack("<i", len(name) + 1)
        + name.encode() + b"\x00"
        + struct.pack("<i", ln)
    )
    recs = []
    pos = 1000
    for i in range(reads_per_rep):
        seq_len = int(rng.integers(*read_lens))
        recs.append(_encode_longread(b"lr_%d" % i, pos, seq_len, rng))
        pos += int(rng.integers(1_000, 50_000))
    recs.append(_encode_longread(b"lr_ultra", pos, ultra_seq_len, rng))
    unit = b"".join(recs)

    hdr_blob = _chunks_to_blocks(header_blob, level)
    unit_blob = _chunks_to_blocks(unit, level)
    body = max(target_bytes - len(hdr_blob) - len(BGZF_EOF), len(unit_blob))
    reps = -(-body // len(unit_blob))

    tmp = out_path.with_suffix(".tmp")
    with open(tmp, "wb") as f:
        f.write(hdr_blob)
        for _ in range(reps):
            f.write(unit_blob)
        f.write(BGZF_EOF)
    os.replace(tmp, out_path)

    manifest = {
        "kind": "longread",
        "reps": reps,
        "reads": reps * (reads_per_rep + 1),
        "ultra_reads": reps,
        "ultra_record_bytes": len(recs[-1]),
        "compressed_bytes": out_path.stat().st_size,
        "uncompressed_bytes": len(header_blob) + reps * len(unit),
        "level": level,
        "seed": seed,
    }
    out_path.with_suffix(".manifest.json").write_text(json.dumps(manifest))
    return manifest


def ensure_longread_bam(
    target_bytes: int = 256 << 20,
    cache_dir: Path = Path("/tmp/spark_bam_bench"),
    **kw,
) -> tuple[Path, dict]:
    """Build (or reuse a cached) ≥``target_bytes`` long-read BAM."""
    cache_dir.mkdir(parents=True, exist_ok=True)
    out = cache_dir / f"longread_{target_bytes >> 20}mb.bam"
    mf = out.with_suffix(".manifest.json")
    if out.exists() and mf.exists():
        manifest = json.loads(mf.read_text())
        if (
            manifest.get("kind") == "longread"
            and manifest.get("compressed_bytes") == out.stat().st_size
        ):
            return out, manifest
    return out, synth_longread_bam(out, target_bytes, **kw)
