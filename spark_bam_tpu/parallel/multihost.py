"""Multi-host sharded checking: jax.distributed bring-up + runnable worker.

The reference scales with a Spark cluster (driver + executors over the
network, SURVEY.md §2.9); the TPU-native analog is one JAX process per host
joined through ``jax.distributed``, with the sharded check step's stat
reductions riding XLA collectives (``psum``) over ICI/DCN. Each host feeds
its *own* windows (per-host file shards) — the workload needs no cross-host
data motion beyond the ≤64 KiB halos stitched host-side at batch assembly.

Launch recipe — run ONE of these per host (same command, distinct
``--process-id``; process 0's host is the coordinator):

    python -m spark_bam_tpu.parallel.multihost \
        --coordinator HOST0:12321 --num-processes N --process-id K

On TPU pods that's the whole recipe (each process grabs its local chips).
For a CPU rehearsal on one machine add ``--local-devices 4`` to every
process — 2 processes × 4 virtual devices = the same 8-way mesh the tests
use; ``tests/test_multihost.py`` drives exactly this.

The worker checks a deterministic synthetic batch (one window per global
device, content varying per window) and process 0 prints the globally
reduced confusion matrix as one JSON line — the smoke artifact proving the
cross-process mesh + collectives actually executed.
"""

from __future__ import annotations

import argparse
import json
import struct

import numpy as np

RECORD_NOISE = 1024


def example_window(w: int, n_records: int = 50, seed: int = 7):
    """A tiny synthetic BAM-record stream in a flat window buffer.

    Returns (padded, n, record_starts): ``n`` counts the records plus a
    trailing burst of noise bytes (which breaks the final records' chains —
    they become checker false *negatives* relative to raw record starts),
    and ``record_starts`` is the ground truth for confusion-matrix tests.
    """
    from spark_bam_tpu.tpu.checker import PAD

    rng = np.random.default_rng(seed)
    buf = bytearray()
    starts = []
    for i in range(n_records):
        starts.append(len(buf))
        name = f"read{i}".encode() + b"\x00"
        n_cigar = 1
        seq_len = 8
        body = (
            struct.pack(
                "<iiBBHHHiiii",
                0,                      # refID
                1000 + i,               # pos
                len(name), 30, 0,       # l_read_name, mapq, bin
                n_cigar, 0,             # n_cigar, flag
                seq_len, 0, 1000 + i, 0,  # l_seq, next_refID, next_pos, tlen
            )
            + name
            + struct.pack("<I", (seq_len << 4) | 0)
            + bytes((seq_len + 1) // 2)
            + bytes([30] * seq_len)
        )
        buf += struct.pack("<i", len(body)) + body
    n = len(buf)
    padded = np.zeros(w + PAD, dtype=np.uint8)
    padded[:n] = np.frombuffer(bytes(buf), dtype=np.uint8)
    # Noise after the records exercises the reject path.
    padded[n: n + RECORD_NOISE] = rng.integers(0, 256, RECORD_NOISE, dtype=np.uint8)
    return padded, np.int32(n + RECORD_NOISE), np.array(starts, dtype=np.int64)


def run_worker(
    coordinator: str | None,
    num_processes: int,
    process_id: int,
    local_devices: int = 0,
    window: int = 1 << 16,
) -> dict:
    """Join the cluster, run one sharded check step over a global batch
    (one window per global device), return the reduced stats (process 0)."""
    if local_devices:
        from spark_bam_tpu.core.platform import force_cpu_devices

        force_cpu_devices(local_devices, defer_init=num_processes > 1)
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_bam_tpu.parallel.mesh import make_mesh, make_shard_map_check_step
    from spark_bam_tpu.tpu.checker import PAD

    devices = jax.devices()
    n_global = len(devices)
    n_local = jax.local_device_count()
    mesh = make_mesh(devices)

    # This host's rows of the global batch: window contents vary per global
    # row (record count 40+row), so the reduction provably mixes every
    # host's distinct contribution.
    row0 = process_id * n_local
    windows = np.zeros((n_local, window + PAD), dtype=np.uint8)
    ns = np.zeros(n_local, dtype=np.int32)
    truth = np.zeros((n_local, window), dtype=bool)
    for j in range(n_local):
        n_records = 40 + row0 + j
        padded, n, starts = example_window(window, n_records)
        windows[j] = padded
        ns[j] = n
        truth[j, starts] = True
    at_eofs = np.ones(n_local, dtype=bool)
    lengths = np.zeros(1024, dtype=np.int32)
    lengths[0] = 249_250_621

    shard = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    windows_d = jax.make_array_from_process_local_data(shard, windows)
    ns_d = jax.make_array_from_process_local_data(shard, ns)
    eofs_d = jax.make_array_from_process_local_data(shard, at_eofs)
    truth_d = jax.make_array_from_process_local_data(shard, truth)
    lengths_d = jax.device_put(lengths, repl)

    step = make_shard_map_check_step(mesh)
    verdicts, totals = step(
        windows_d, ns_d, eofs_d, truth_d, lengths_d, jnp.int32(1)
    )
    verdicts.block_until_ready()
    totals = np.asarray(totals)  # replicated: addressable on every process

    # Expected: every row contributes its record count minus the 9 chains
    # the trailing noise breaks (a boundary needs 10 consecutive records).
    exp_tp = sum(40 + r - 9 for r in range(n_global))
    exp_fn = 9 * n_global
    stats = {
        "processes": num_processes,
        "process_id": process_id,
        "global_devices": n_global,
        "local_devices": n_local,
        "true_positives": int(totals[0]),
        "false_positives": int(totals[1]),
        "false_negatives": int(totals[2]),
        "true_negatives": int(totals[3]),
        "positions": int(totals[4]),
        "expected_tp": exp_tp,
        "expected_fn": exp_fn,
        "ok": int(totals[0]) == exp_tp
        and int(totals[2]) == exp_fn
        and int(totals[1]) == 0,
    }
    return stats


def run_worker_bam(
    path: str,
    coordinator: str | None,
    num_processes: int,
    process_id: int,
    local_devices: int = 0,
    row_bytes: int = 8 << 20,
    halo: int = 4 << 20,
    chunk_bytes: int = 192 << 20,
) -> dict:
    """Real-data multi-host count-reads: each process inflates only its own
    block-range shard of ``path`` (seam halos read from the following
    blocks — SURVEY.md §2.9's halo-exchange plan), checks its rows on its
    local devices, and the global count reduces with ``psum``.

    The sharding engine is ``parallel.stream_mesh.count_reads_sharded`` —
    the SAME codepath the single-host ``--sharded`` CLI modes run (VERDICT
    r4 item 6: one row discipline for both tiers); this worker only brings
    up the cluster and passes its process coordinates. The division of
    labor mirrors the reference's executor-per-split layout
    (load/.../SplitRDD.scala:43-79): block ranges are the shards, no
    cross-host byte motion beyond the halo overlap each host reads itself.
    """
    if local_devices:
        from spark_bam_tpu.core.platform import force_cpu_devices

        force_cpu_devices(local_devices, defer_init=num_processes > 1)
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )

    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.parallel.stream_mesh import count_reads_sharded

    stats: dict = {}
    count = count_reads_sharded(
        path,
        Config(),
        window_uncompressed=row_bytes,
        halo=halo,
        num_processes=num_processes,
        process_id=process_id,
        chunk_bytes=chunk_bytes,
        stats_out=stats,
    )
    return {
        "mode": "bam",
        "path": str(path),
        "processes": num_processes,
        "process_id": process_id,
        "global_devices": len(jax.devices()),
        "local_devices": jax.local_device_count(),
        "rows": stats.get("rows", 0),
        "chunks": stats.get("steps", 0),
        "count": int(count),
        "escaped": int(stats.get("escapes", 0)),
        "fallback": bool(stats.get("fallback", False)),
        "ok": True,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument(
        "--local-devices", type=int, default=0,
        help="force N virtual CPU devices (rehearsal mode); 0 = real devices",
    )
    ap.add_argument(
        "--bam", default=None,
        help="real-data mode: shard this BAM by block ranges and count reads",
    )
    ap.add_argument(
        "--serve", default=None, metavar="LISTEN",
        help="fabric-worker mode: after the jax.distributed bring-up, run "
             "one serving loop over THIS host's local devices listening on "
             "LISTEN (tcp:host:port / unix:path) until SIGTERM-drained — "
             "the per-host half of the serve fabric (docs/fabric.md); "
             "point the fabric router at every host's announced address",
    )
    ap.add_argument("--serve-spec", default="",
                    help="ServeConfig spec override (fabric-worker mode)")
    ap.add_argument("--row-bytes", type=int, default=8 << 20,
                    help="uncompressed bytes owned per row (--bam mode)")
    ap.add_argument("--halo", type=int, default=4 << 20,
                    help="lookahead bytes per row; must exceed one "
                         "reads-to-check chain's span (--bam mode)")
    ap.add_argument("--chunk-bytes", type=int, default=192 << 20,
                    help="host window-buffer budget per step call "
                         "(--bam mode; bounds host memory per chunk)")
    a = ap.parse_args(argv)
    if a.serve:
        from spark_bam_tpu.fabric.worker import serve_worker

        return serve_worker(
            listen=a.serve, devices=a.local_devices, serve=a.serve_spec,
            coordinator=a.coordinator, num_processes=a.num_processes,
            process_id=a.process_id,
        )
    if a.bam:
        stats = run_worker_bam(
            a.bam, a.coordinator, a.num_processes, a.process_id,
            a.local_devices, row_bytes=a.row_bytes, halo=a.halo,
            chunk_bytes=a.chunk_bytes,
        )
    else:
        stats = run_worker(
            a.coordinator, a.num_processes, a.process_id, a.local_devices
        )
    if stats["process_id"] == 0:
        print(json.dumps(stats))
    return 0 if stats["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
