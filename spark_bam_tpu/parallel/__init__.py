from spark_bam_tpu.parallel.executor import ParallelConfig, map_partitions

__all__ = ["ParallelConfig", "map_partitions"]
