from spark_bam_tpu.parallel.executor import (
    Attempt,
    JobReport,
    ParallelConfig,
    PartitionReport,
    last_report,
    map_partitions,
    reset_last_report,
    run_partitions,
)

__all__ = [
    "Attempt",
    "JobReport",
    "ParallelConfig",
    "PartitionReport",
    "last_report",
    "map_partitions",
    "reset_last_report",
    "run_partitions",
]
