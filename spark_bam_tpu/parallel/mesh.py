"""Device-mesh execution: sharded checking across chips.

The workload is data-parallel over windows of uncompressed bytes
(SURVEY.md §2.8-2.9): a batch of B windows shards across the mesh's ``data``
axis, every device runs the same check kernel on its shard, and the tiny
confusion-matrix / flag-histogram reductions ride ``psum`` over ICI —
replacing the reference's Spark accumulators (CheckerApp.scala:59-70).

Cross-shard record chains are handled the same way as cross-window chains on
one chip: each window carries a trailing halo of the next shard's bytes
(≤ a few MB — the "halo exchange" in SURVEY §2.9 is done host-side at batch
assembly; on multi-host deployments this is the only inter-host data motion).

``sharded_check_step`` is the framework's "training step" equivalent: the
jitted, mesh-partitioned unit of work the driver dry-runs for multi-chip
validation (``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_bam_tpu import obs
from spark_bam_tpu.tpu.checker import PAD, check_window


def make_mesh(devices=None, axis: str = "data") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def local_mesh(axis: str = "data") -> Mesh:
    """Mesh over THIS process's addressable devices — the per-host
    serving loop's mesh (fabric/worker.py). A step compiled over the
    global multi-host mesh is collective: every process must enter every
    dispatch, which deadlocks a worker answering only its own requests.
    Single-host, this is exactly ``make_mesh()``."""
    return make_mesh(jax.local_devices(), axis)


def _instrument_step(kind: str, step):
    """Wrap a jit'd mesh step so each call emits a ``mesh.dispatch`` span
    (joining whatever trace is bound — the batcher row's request trace).
    Measures host dispatch/enqueue time, not device compute: the arrays
    come back asynchronous, and the caller's own span (``serve.tick``)
    covers the sync. When obs is disabled this is one enabled() check per
    dispatch."""

    def dispatch(*args):
        if not obs.enabled():
            return step(*args)
        with obs.span("mesh.dispatch", step=kind):
            return step(*args)

    dispatch.__wrapped__ = step
    return dispatch


class MeshSteps:
    """Resident per-mesh step registry: shardings and jit'd ``shard_map``
    steps built ONCE and reused for the mesh's lifetime.

    Every ``make_shard_map_*_step`` call closes over fresh Python
    functions, so calling a maker per request yields a distinct jit object
    and a full re-trace each time — fine for one-shot batch jobs, fatal
    for a serving daemon dispatching per tick. ``MeshSteps`` keys each
    step by its static parameters, so same-shape requests share one
    compiled executable (the serve/ tier's "build at startup, serve
    forever" contract — ROADMAP item 3).

    Thread-safe: the serving loop builds steps from worker threads.
    """

    def __init__(self, mesh: Mesh, axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.data_sharding = NamedSharding(mesh, P(axis))
        self.replicated = NamedSharding(mesh, P())
        self._steps: dict = {}
        self._lock = threading.Lock()

    def put(self, arr):
        """Place a batch-dim array with ``P(axis)`` sharding."""
        return jax.device_put(arr, self.data_sharding)

    def put_replicated(self, arr):
        return jax.device_put(arr, self.replicated)

    def _get(self, key, maker):
        with self._lock:
            step = self._steps.get(key)
            if step is None:
                step = self._steps[key] = _instrument_step(key[0], maker())
            return step

    def count_step(self, reads_to_check: int = 10, flags_impl: str = "xla",
                   funnel: bool = False):
        return self._get(
            ("count", reads_to_check, flags_impl, funnel),
            lambda: make_shard_map_count_step(
                self.mesh, reads_to_check=reads_to_check, axis=self.axis,
                flags_impl=flags_impl, funnel=funnel,
            ),
        )

    def confusion_step(self, reads_to_check: int = 10,
                       flags_impl: str = "xla", funnel: bool = False):
        return self._get(
            ("confusion", reads_to_check, flags_impl, funnel),
            lambda: make_shard_map_confusion_step(
                self.mesh, reads_to_check=reads_to_check, axis=self.axis,
                flags_impl=flags_impl, funnel=funnel,
            ),
        )

    def full_step(self, reads_to_check: int = 10, flags_impl: str = "xla",
                  k_positions: int = 4096):
        return self._get(
            ("full", reads_to_check, flags_impl, k_positions),
            lambda: make_shard_map_full_step(
                self.mesh, reads_to_check=reads_to_check, axis=self.axis,
                flags_impl=flags_impl, k_positions=k_positions,
            ),
        )

    def serve_step(self, reads_to_check: int = 10, flags_impl: str = "xla",
                   funnel: bool = False):
        return self._get(
            ("serve", reads_to_check, flags_impl, funnel),
            lambda: make_shard_map_serve_step(
                self.mesh, reads_to_check=reads_to_check, axis=self.axis,
                flags_impl=flags_impl, funnel=funnel,
            ),
        )

    def agg_step(self, plan, nc: int):
        """Sharded aggregate-reduction carry step (agg/kernels.py) for
        one (plan, contig-count) shape — the serve ``aggregate`` op's
        compiled-once tick. The plan is a frozen ``AggConfig`` and so
        hashes into the registry key like any other static param."""
        from spark_bam_tpu.agg.kernels import make_shard_map_agg_step

        return self._get(
            ("agg", plan, nc),
            lambda: make_shard_map_agg_step(
                self.mesh, plan, nc, axis=self.axis
            ),
        )


_mesh_steps: dict = {}
_mesh_steps_lock = threading.Lock()


def mesh_steps(mesh: Mesh, axis: str = "data") -> MeshSteps:
    """The process-wide ``MeshSteps`` registry for ``mesh`` — every tier
    (stream_mesh workloads, the serve/ daemon) shares the same compiled
    steps instead of rebuilding them per call."""
    key = (mesh, axis)
    with _mesh_steps_lock:
        st = _mesh_steps.get(key)
        if st is None:
            st = _mesh_steps[key] = MeshSteps(mesh, axis)
        return st


def init_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Multi-host bring-up: initialize jax.distributed (NCCL/MPI analog is
    XLA's ICI/DCN collectives; the reference's Spark cluster role).

    With no arguments, reads the standard JAX coordination env vars
    (JAX_COORDINATOR_ADDRESS etc.) or no-ops on single-host. Returns the
    global device count. Each host then feeds its own windows (the workload
    needs no cross-host data motion beyond ≤64 KiB halos at shard seams —
    SURVEY.md §2.9).
    """
    import os

    if coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return len(jax.devices())


@functools.partial(jax.jit, static_argnames=("reads_to_check",))
def sharded_check_step(
    windows: jnp.ndarray,      # (B, W+PAD) uint8, batch-dim sharded over the mesh
    ns: jnp.ndarray,           # (B,) int32 valid byte counts
    at_eofs: jnp.ndarray,      # (B,) bool
    truth: jnp.ndarray,        # (B, W) bool: indexed ground truth (or zeros)
    lengths: jnp.ndarray,      # (Cmax,) int32, replicated
    num_contigs: jnp.ndarray,  # () int32
    reads_to_check: int = 10,
):
    """One sharded unit of work: per-window check + global stat reduction.

    Inputs carry their sharding (GSPMD): place the batch with
    ``shard_windows`` and XLA partitions the vmap across devices and lowers
    the stat sums to all-reduces over ICI.

    Returns (per-window verdicts (B, W) bool, escapes, global stats dict).
    """

    def one(window, n, at_eof, tr):
        res = check_window(
            window, lengths, num_contigs, n, at_eof, reads_to_check=reads_to_check
        )
        w = window.shape[0] - PAD
        in_range = jnp.arange(w, dtype=jnp.int32) < n
        v = res["verdict"] & in_range
        t = tr & in_range
        stats = jnp.stack(
            [
                jnp.sum((v & t).astype(jnp.int32)),    # true positives
                jnp.sum((v & ~t).astype(jnp.int32)),   # false positives
                jnp.sum((~v & t).astype(jnp.int32)),   # false negatives
                jnp.sum((~v & ~t).astype(jnp.int32)),  # true negatives
                jnp.sum(in_range.astype(jnp.int32)),   # positions checked
            ]
        )
        return v, res["escaped"] & in_range, stats

    verdicts, escapes, stats = jax.vmap(one)(windows, ns, at_eofs, truth)
    totals = jnp.sum(stats, axis=0)
    return verdicts, escapes, {
        "true_positives": totals[0],
        "false_positives": totals[1],
        "false_negatives": totals[2],
        "true_negatives": totals[3],
        "positions": totals[4],
    }


def shard_windows(
    mesh: Mesh,
    windows: np.ndarray,
    axis: str = "data",
):
    """Place a (B, W+PAD) batch with batch-dim sharding over the mesh.

    Delegates to the mesh's cached ``MeshSteps`` shardings so repeated
    placements (a serving loop's per-tick batches) reuse one
    ``NamedSharding`` instead of constructing it per call."""
    return mesh_steps(mesh, axis).put(windows)


def _shard_map_compat():
    """jax.shard_map across the 0.6/0.7 API rename (check_rep → check_vma)."""
    try:
        from jax import shard_map as _shard_map

        def shard_map(f, *, mesh, in_specs, out_specs, check_rep):
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_rep,
            )

        return shard_map
    except ImportError:  # jax < 0.7
        from jax.experimental.shard_map import shard_map

        return shard_map


def make_shard_map_check_step(mesh: Mesh, reads_to_check: int = 10, axis: str = "data"):
    """Explicit-collective variant of the sharded step.

    Where ``sharded_check_step`` lets GSPMD infer the partitioning, this one
    is written per-shard with ``shard_map``: each device runs the kernel on
    its local windows and the stats reduce with an explicit ``lax.psum``
    over the mesh axis — the XLA collective riding ICI. Semantically
    identical; kept as the explicit form the multi-host deployment uses.
    """
    shard_map = _shard_map_compat()

    def local_step(windows, ns, at_eofs, truth, lengths, num_contigs):
        def one(window, n, at_eof, tr):
            res = check_window(
                window, lengths, num_contigs, n, at_eof,
                reads_to_check=reads_to_check,
            )
            w = window.shape[0] - PAD
            in_range = jnp.arange(w, dtype=jnp.int32) < n
            v = res["verdict"] & in_range
            t = tr & in_range
            return v, jnp.stack([
                jnp.sum((v & t).astype(jnp.int32)),
                jnp.sum((v & ~t).astype(jnp.int32)),
                jnp.sum((~v & t).astype(jnp.int32)),
                jnp.sum((~v & ~t).astype(jnp.int32)),
                jnp.sum(in_range.astype(jnp.int32)),
            ])

        verdicts, stats = jax.vmap(one)(windows, ns, at_eofs, truth)
        totals = jax.lax.psum(jnp.sum(stats, axis=0), axis)  # ← ICI all-reduce
        return verdicts, totals

    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(), P()),
            out_specs=(P(axis), P()),
            # The kernel's scan carries start from unvarying constants; skip
            # the replication check rather than thread pvary through shared
            # kernel code.
            check_rep=False,
        )
    )


def _make_sharded_stats_step(
    mesh: Mesh, reads_to_check: int, axis: str, row_stats, with_truth: bool,
    flags_impl: str = "xla", funnel: bool = False,
):
    """Shared scaffolding for the streaming-step makers below: per-row
    ``check_window`` + owned-span mask [lo, own), per-device ``vmap``, and
    the stat vector all-reduced with ``lax.psum`` over the mesh axis.
    ``row_stats(res, m, tr)`` stacks the workload's counters.
    ``funnel=True`` runs the two-stage candidate funnel per row — verdict
    projections only (the full-check step stays single-pass: its product
    is the per-position flag mask, which the funnel does not preserve).

    Every counter psum'd here must be record-scale (≤ positions/40 per
    step), never position-scale: the reduction is int32 and a
    position-scale counter overflows past ~64 devices × 32 MB windows.
    Position totals are host-derivable (callers know their owned spans).
    """
    shard_map = _shard_map_compat()

    # Interpret mode is decided by where THIS mesh's kernels actually run
    # (not the process-default backend): Mosaic compiles only on real TPUs.
    pallas_interpret = (
        flags_impl == "pallas"
        and mesh.devices.flat[0].platform != "tpu"
    )

    def one(window, n, at_eof, lo, own, tr, lengths, num_contigs):
        res = check_window(
            window, lengths, num_contigs, n, at_eof,
            reads_to_check=reads_to_check, flags_impl=flags_impl,
            pallas_interpret=pallas_interpret, funnel=funnel,
        )
        w = window.shape[0] - PAD
        i = jnp.arange(w, dtype=jnp.int32)
        m = (i >= lo) & (i < own)
        return row_stats(res, m, tr)

    if with_truth:
        def local_step(windows, ns, at_eofs, truth, los, owns, lengths, nc):
            stats = jax.vmap(
                lambda wd, n, e, t, lo, ow: one(wd, n, e, lo, ow, t, lengths, nc)
            )(windows, ns, at_eofs, truth, los, owns)
            return jax.lax.psum(jnp.sum(stats, axis=0), axis)  # ← ICI

        in_specs = (
            P(axis), P(axis), P(axis), P(axis), P(axis), P(axis), P(), P(),
        )
    else:
        def local_step(windows, ns, at_eofs, los, owns, lengths, nc):
            stats = jax.vmap(
                lambda wd, n, e, lo, ow: one(wd, n, e, lo, ow, None, lengths, nc)
            )(windows, ns, at_eofs, los, owns)
            return jax.lax.psum(jnp.sum(stats, axis=0), axis)  # ← ICI

        in_specs = (P(axis), P(axis), P(axis), P(axis), P(axis), P(), P())
    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(),
            check_rep=False,
        )
    )


def make_shard_map_count_step(
    mesh: Mesh, reads_to_check: int = 10, axis: str = "data",
    flags_impl: str = "xla", funnel: bool = False,
):
    """Sharded count-reads step: each device checks its window rows and the
    (boundary count, owned escapes) pair all-reduces with ``lax.psum`` —
    the count-reads workload (reference docs/benchmarks.md:53-59) as one
    mesh-partitioned unit. Rows carry per-row owned spans [lo, own) so
    halo bytes and the BAM header are counted exactly once globally.
    ``flags_impl="pallas"`` swaps the flag pass for the Pallas kernel
    (``spark.bam.backend=pallas`` reaches the mesh tier too)."""

    def row_stats(res, m, _tr):
        return jnp.stack([
            jnp.sum((res["verdict"] & m).astype(jnp.int32)),
            jnp.sum((res["escaped"] & m).astype(jnp.int32)),
        ])

    return _make_sharded_stats_step(
        mesh, reads_to_check, axis, row_stats, with_truth=False,
        flags_impl=flags_impl, funnel=funnel,
    )


def make_shard_map_confusion_step(
    mesh: Mesh, reads_to_check: int = 10, axis: str = "data",
    flags_impl: str = "xla", funnel: bool = False,
):
    """Sharded check-bam step: verdicts vs indexed truth at every owned
    position, the (tp, fp, fn, escapes) counters ``psum``'d over the mesh
    axis — the check-bam validation workload (reference
    CheckerApp.scala:59-70's accumulators) as one mesh-partitioned unit.
    Position totals and true negatives are deliberately NOT reduced on
    device: they are position-scale (int32-overflow risk at mesh scale)
    and the caller derives them exactly from its owned spans
    (tn = positions - tp - fp - fn)."""

    def row_stats(res, m, tr):
        v = res["verdict"] & m
        t = tr & m
        return jnp.stack([
            jnp.sum((v & t).astype(jnp.int32)),    # true positives
            jnp.sum((v & ~t).astype(jnp.int32)),   # false positives
            jnp.sum((~v & t).astype(jnp.int32)),   # false negatives
            jnp.sum((res["escaped"] & m).astype(jnp.int32)),
        ])

    return _make_sharded_stats_step(
        mesh, reads_to_check, axis, row_stats, with_truth=True,
        flags_impl=flags_impl, funnel=funnel,
    )


def make_shard_map_full_step(
    mesh: Mesh, reads_to_check: int = 10, axis: str = "data",
    flags_impl: str = "xla", k_positions: int = 4096,
):
    """Sharded full-check step (the third mesh workload, after count-reads
    and check-bam): every owned position's 19-flag mask, reduced to the
    FullCheck report's aggregations (reference FullCheck.scala:112-417)
    in one mesh-partitioned unit.

    Returns ``(totals, crit_idx, crit_mask, two_idx, two_mask)``:

    - ``totals`` (replicated, ``psum`` over ICI): ``[passes, bare_eof,
      crit_ct, two_ct, defer_ct, per_flag[0..18]]``. ``passes`` (mask==0
      record starts) and ``bare_eof`` (the lone at-EOF marker rule) let
      the caller derive the position-scale ``considered`` total from its
      owned spans without a position-scale device counter. The per-flag
      counts ARE position-scale per step — int32 stays safe because one
      step's positions are bounded by the host chunk budget (≪ 2^31);
      callers accumulate across steps in int64.
    - ``crit_idx``/``crit_mask`` (row-sharded, (B, K)): per-row compacted
      window-relative positions (fill −1) and masks where exactly one
      check failed — the report's "critical" sites; ``two_*`` likewise
      for two-check sites. A row with more than K sites under-reports the
      compaction vs its count — callers detect the mismatch and fall back
      to the exact single-device path (same policy as escapes).
    - ``defer_ct``: owned lanes whose masks are not yet exact (escaped or
      edge-inexact — the lanes the streaming engine defers); any nonzero
      means the device pass must be abandoned for the deferral-exact path.
    """
    from spark_bam_tpu.check.flags import BIT, FLAG_NAMES

    shard_map = _shard_map_compat()
    bit0 = int(BIT["tooFewFixedBlockBytes"])
    n_flags = len(FLAG_NAMES)
    pallas_interpret = (
        flags_impl == "pallas"
        and mesh.devices.flat[0].platform != "tpu"
    )

    def one(window, n, at_eof, lo, own, lengths, num_contigs):
        res = check_window(
            window, lengths, num_contigs, n, at_eof,
            reads_to_check=reads_to_check, flags_impl=flags_impl,
            pallas_interpret=pallas_interpret,
        )
        w = window.shape[0] - PAD
        i = jnp.arange(w, dtype=jnp.int32)
        m = (i >= lo) & (i < own)
        fm = jnp.where(m, res["fail_mask"], 0)
        rb = jnp.where(m, res["reads_before"], 0)
        passes = jnp.sum((m & (fm == 0)).astype(jnp.int32))
        bare_eof = jnp.sum((m & (fm == bit0) & (rb == 0)).astype(jnp.int32))
        considered = m & (fm != 0) & ~((fm == bit0) & (rb == 0))
        pop = jnp.zeros_like(fm)
        for b in range(n_flags):
            pop = pop + ((fm >> b) & 1)
        nf = pop + (rb > 0).astype(jnp.int32)
        crit = considered & (nf == 1)
        two = considered & (nf == 2)
        defer = m & (res["escaped"] | ~res["exact"])
        per_flag = jnp.stack([
            jnp.sum((considered & (((fm >> b) & 1) == 1)).astype(jnp.int32))
            for b in range(n_flags)
        ])
        head = jnp.stack([
            passes,
            bare_eof,
            jnp.sum(crit.astype(jnp.int32)),
            jnp.sum(two.astype(jnp.int32)),
            jnp.sum(defer.astype(jnp.int32)),
        ])
        (crit_idx,) = jnp.nonzero(crit, size=k_positions, fill_value=-1)
        (two_idx,) = jnp.nonzero(two, size=k_positions, fill_value=-1)
        crit_mask = jnp.where(crit_idx >= 0, fm[jnp.clip(crit_idx, 0)], 0)
        two_mask = jnp.where(two_idx >= 0, fm[jnp.clip(two_idx, 0)], 0)
        return (
            jnp.concatenate([head, per_flag]),
            crit_idx.astype(jnp.int32), crit_mask,
            two_idx.astype(jnp.int32), two_mask,
        )

    def local_step(windows, ns, at_eofs, los, owns, lengths, nc):
        stats, ci, cm, ti, tm = jax.vmap(
            lambda wd, n, e, lo, ow: one(wd, n, e, lo, ow, lengths, nc)
        )(windows, ns, at_eofs, los, owns)
        totals = jax.lax.psum(jnp.sum(stats, axis=0), axis)  # ← ICI
        return totals, ci, cm, ti, tm

    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(), P()),
            out_specs=(P(), P(axis), P(axis), P(axis), P(axis)),
            check_rep=False,
        )
    )


def make_shard_map_serve_step(
    mesh: Mesh, reads_to_check: int = 10, axis: str = "data",
    flags_impl: str = "xla", funnel: bool = False,
):
    """Sharded serving step: PER-ROW (boundary count, owned escapes) with
    NO cross-device reduction — ``out_specs=P(axis)`` keeps each row's
    pair on its shard so the host can scatter results back to the
    individual requests a batch coalesced (parallel/serve batching).

    Unlike the count step, ``lengths``/``num_contigs`` are per-row
    ``(B, Cmax)`` / ``(B,)`` inputs sharded with the batch: rows from
    DIFFERENT files (different contig dictionaries) share one dispatch,
    which is what lets a serving tick batch a fleet of BAMs together.
    The batch shape is fixed by the caller (pad to ``batch_rows``), so
    the jit traces exactly once per step config.
    """
    shard_map = _shard_map_compat()
    pallas_interpret = (
        flags_impl == "pallas"
        and mesh.devices.flat[0].platform != "tpu"
    )

    def one(window, n, at_eof, lo, own, lengths, num_contigs):
        res = check_window(
            window, lengths, num_contigs, n, at_eof,
            reads_to_check=reads_to_check, flags_impl=flags_impl,
            pallas_interpret=pallas_interpret, funnel=funnel,
        )
        w = window.shape[0] - PAD
        i = jnp.arange(w, dtype=jnp.int32)
        m = (i >= lo) & (i < own)
        return jnp.stack([
            jnp.sum((res["verdict"] & m).astype(jnp.int32)),
            jnp.sum((res["escaped"] & m).astype(jnp.int32)),
        ])

    def local_step(windows, ns, at_eofs, los, owns, lengths, ncs):
        return jax.vmap(one)(windows, ns, at_eofs, los, owns, lengths, ncs)

    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(axis),) * 7,
            out_specs=P(axis),
            check_rep=False,
        )
    )


def batch_windows(
    buf: np.ndarray,
    window: int,
    halo: int,
    batch: int,
    at_eof: bool = True,
    truth: np.ndarray | None = None,
):
    """Cut a flat buffer into a (B, W+PAD) batch of overlapping windows.

    Each window's trailing ``halo`` lets chains started in its owned span
    complete; ownership spans tile the buffer exactly. Returns (windows, ns,
    at_eofs, owned ranges, truth windows).
    """
    n_total = len(buf)
    step = max(window - halo, 1)
    starts = list(range(0, max(n_total, 1), step))
    # Trim starts that fall entirely beyond the buffer.
    starts = [s for s in starts if s == 0 or s < n_total]
    b = max(batch, len(starts))
    ws = np.zeros((b, window + PAD), dtype=np.uint8)
    ns = np.zeros(b, dtype=np.int32)
    eofs = np.zeros(b, dtype=bool)
    owned = []
    tr = np.zeros((b, window), dtype=bool)
    for i, s in enumerate(starts):
        e = min(s + window, n_total)
        ws[i, : e - s] = buf[s:e]
        ns[i] = e - s
        eofs[i] = at_eof and e == n_total
        own_end = e if e == n_total else min(s + step, n_total)
        owned.append((s, own_end))
        if truth is not None:
            tr[i, : e - s] = truth[s:e]
        if e == n_total:
            break
    return ws, ns, eofs, owned, tr
