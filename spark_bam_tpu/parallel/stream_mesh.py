"""Mesh-sharded streaming workloads: one BAM across all chips.

Bridges the two scale paths that already exist separately:

- ``tpu/stream_check.StreamChecker`` — whole-file streaming in O(window)
  host memory, single device;
- ``parallel/mesh``'s sharded step makers — the mesh-partitioned units
  (``lax.psum`` over ICI) that ``multihost.py`` feeds with preassembled
  window rows.

Here the host assembles consecutive halo-carried windows into a
``(n_devices, W+PAD)`` batch per step — the same carry/ownership
discipline as ``StreamChecker`` (each row's trailing ``halo`` is owned by
the next row, so every owned position has full chain lookahead; seam
semantics come from the shared ``halo_windows`` generator) — and every
step runs one sharded kernel with the tiny reduction riding the mesh.
This is the single-host multi-chip production path of:

- ``count_reads_sharded`` — the count-reads workload (reference
  docs/benchmarks.md:53-59);
- ``check_bam_sharded`` — the check-bam validation workload: verdicts vs
  the ``.records`` indexed ground truth at every uncompressed position,
  confusion matrix accumulated via ``psum`` (reference
  CheckerApp.scala:59-93's accumulator pipeline).

SURVEY.md §2.8 maps file/block data-parallelism onto per-core batch
pipelines; §2.9 replaces Spark accumulators with ``psum``.

Exactness: rows whose chains outrun the halo report escapes; any escape
aborts the device pass and the file re-runs through ``StreamChecker``'s
deferral-exact spans path (single device). On real data with the default
halo this never triggers — same policy as ``StreamChecker.count_reads``.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.parallel.mesh import (
    make_mesh,
    make_shard_map_confusion_step,
    make_shard_map_count_step,
)
from spark_bam_tpu.tpu.checker import PAD
from spark_bam_tpu.tpu.inflate import InflatePipeline
from spark_bam_tpu.tpu.stream_check import (
    StreamChecker,
    _next_pow2,
    halo_windows,
    pad_contig_lengths,
)


class _ShardedStream:
    """Shared plumbing: plan the stream, build the row batch arrays, and
    iterate ``halo_windows`` rows into ``n_devices``-row batches."""

    def __init__(
        self,
        path,
        config: Config,
        mesh,
        window_uncompressed: int | None,
        halo: int | None,
        metas: list | None,
        with_truth: bool = False,
    ):
        self.path = path
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_dev = int(self.mesh.devices.size)
        self.axis = self.mesh.axis_names[0]

        header = read_header(path)
        lens_list = header.contig_lengths.lengths_list()
        self.num_contigs = len(lens_list)
        self.lengths = pad_contig_lengths(np.asarray(lens_list, dtype=np.int32))

        self.fresh = window_uncompressed or config.window_size
        halo = config.halo_size if halo is None else halo
        self.halo = min(halo, self.fresh // 2)
        self.metas = metas
        self.pipeline = InflatePipeline(
            path, window_uncompressed=self.fresh,
            device_copy=config.device_inflate, metas=metas,
        )
        self.total = self.pipeline.total
        self.kernel_window = _next_pow2(
            min(self.fresh + self.halo, max(self.total, 1 << 16))
        )
        self.header_end = header.uncompressed_size

        self.row_sharding = NamedSharding(self.mesh, P(self.axis))
        repl = NamedSharding(self.mesh, P())
        self.lengths_d = jax.device_put(jnp.asarray(self.lengths), repl)
        self.nc = jnp.int32(self.num_contigs)

        kw = self.kernel_window
        self.ws = np.zeros((self.n_dev, kw + PAD), dtype=np.uint8)
        self.ns = np.zeros(self.n_dev, dtype=np.int32)
        self.eofs = np.zeros(self.n_dev, dtype=bool)
        self.los = np.zeros(self.n_dev, dtype=np.int32)
        self.owns = np.zeros(self.n_dev, dtype=np.int32)
        self.truth = (
            np.zeros((self.n_dev, kw), dtype=bool) if with_truth else None
        )

    def zero_tail_rows(self, k_rows: int):
        """Blank rows ≥ k_rows so a stale previous batch can't leak in."""
        self.ws[k_rows:] = 0
        self.ns[k_rows:] = 0
        self.eofs[k_rows:] = False
        self.los[k_rows:] = 0
        self.owns[k_rows:] = 0
        if self.truth is not None:
            self.truth[k_rows:] = False

    def batches(self, header_clamp: bool, fill_row=None):
        """Yield ``(k_rows, positions_done)`` after filling each batch of up
        to ``n_dev`` rows. ``fill_row(k, buf, base, n)`` fills aligned
        per-row extras (e.g. truth masks). ``header_clamp=False`` counts
        header bytes in owned spans (check-bam considers every position)."""
        he = self.header_end if header_clamp else 0
        k = 0
        done = 0
        for buf, base, own_end, lo, at_eof in halo_windows(
            self.pipeline, self.halo, he
        ):
            n = len(buf)
            self.ws[k, :n] = buf
            self.ws[k, n:] = 0
            self.ns[k] = n
            self.eofs[k] = at_eof
            self.los[k] = lo
            self.owns[k] = own_end
            if fill_row is not None:
                fill_row(k, buf, base, n)
            done = base + own_end
            k += 1
            if k == self.n_dev:
                yield k, done
                k = 0
        if k:
            yield k, done

    def sharded_args(self):
        put = jax.device_put
        rs = self.row_sharding
        args = [
            put(jnp.asarray(self.ws), rs),
            put(jnp.asarray(self.ns), rs),
            put(jnp.asarray(self.eofs), rs),
        ]
        if self.truth is not None:
            args.append(put(jnp.asarray(self.truth), rs))
        args += [put(jnp.asarray(self.los), rs), put(jnp.asarray(self.owns), rs)]
        return args + [self.lengths_d, self.nc]


def count_reads_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
    stats_out: dict | None = None,
) -> int:
    """Record count of ``path`` computed across ``mesh`` (default: all
    devices). ``progress(steps_done, positions_done, total_positions)``
    fires after each sharded step. ``stats_out``, when given, receives
    ``{"steps", "escapes", "fallback"}`` — callers that must know whether
    the mesh pass itself produced the count (vs the escape fallback)
    read ``fallback`` (e.g. hardware smoke tests)."""
    st = _ShardedStream(
        path, config, mesh, window_uncompressed, halo, metas
    )
    step = make_shard_map_count_step(
        st.mesh, reads_to_check=config.reads_to_check, axis=st.axis,
        flags_impl=config.flags_impl,
    )
    count = escapes = steps = 0
    # Closing the batch generator on early exit (escape break, error)
    # propagates into the pipeline iterator's finally, shutting down its
    # inflate pool and channel before any fallback reopens the file.
    batches = st.batches(header_clamp=True)
    try:
        for k_rows, done in batches:
            st.zero_tail_rows(k_rows)
            totals = np.asarray(step(*st.sharded_args()))
            count += int(totals[0])
            escapes += int(totals[1])
            steps += 1
            if progress is not None:
                progress(steps, done, st.total)
            if escapes:
                break
    finally:
        batches.close()

    if stats_out is not None:
        stats_out.update(
            steps=steps, escapes=escapes, fallback=bool(escapes)
        )
    if escapes:
        # Ultra-long chains outran the halo: resolve bit-exactly through
        # the single-device deferral path (reusing the sharded pass's
        # block-metadata scan, not a second whole-file walk).
        return StreamChecker(
            path, config, window_uncompressed=st.fresh, halo=st.halo,
            metas=st.pipeline.metas,
        ).count_reads()
    return count


def _truth_flats(path, records_path, metas) -> np.ndarray:
    """The ``.records`` ground truth as sorted absolute flat offsets."""
    from spark_bam_tpu.bam.index_records import read_records_index
    from spark_bam_tpu.bgzf.flat import metas_block_table
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    records_path = (
        str(path) + ".records" if records_path is None else records_path
    )
    positions = read_records_index(records_path)
    metas = list(blocks_metadata(path)) if metas is None else metas
    block_starts, block_flat = metas_block_table(metas)
    blocks = np.array([p.block_pos for p in positions], dtype=np.int64)
    offs = np.array([p.offset for p in positions], dtype=np.int64)
    idx = np.searchsorted(block_starts, blocks)
    if len(idx) and (
        idx.max() >= len(block_starts)
        or not np.array_equal(block_starts[idx], blocks)
    ):
        raise ValueError(
            f"{records_path}: block positions not in {path}'s block table "
            "(stale sidecar?)"
        )
    return np.sort(block_flat[idx] + offs)


def check_bam_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    records_path=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
) -> dict:
    """check-bam across the mesh: the vectorized checker's verdict vs the
    ``.records`` indexed ground truth at **every uncompressed position** of
    the file (header bytes included — reference check-bam semantics), the
    confusion matrix ``psum``'d per sharded step.

    Returns ``{"true_positives", "false_positives", "false_negatives",
    "true_negatives", "positions", "devices"}`` (``devices`` = the mesh
    size the verdicts actually ran on). Escaped chains fall back to the
    single-device deferral-exact spans path, so the returned matrix is
    always exact.
    """
    st = _ShardedStream(
        path, config, mesh, window_uncompressed, halo, metas, with_truth=True
    )
    # The pipeline already walked every block header; reuse its scan for
    # the truth table instead of a second whole-file metadata walk.
    truth_flats = _truth_flats(path, records_path, st.pipeline.metas)
    step = make_shard_map_confusion_step(
        st.mesh, reads_to_check=config.reads_to_check, axis=st.axis,
        flags_impl=config.flags_impl,
    )

    def fill_row(k, buf, base, n):
        row = st.truth[k]
        row[:] = False
        i0, i1 = np.searchsorted(truth_flats, (base, base + n))
        row[truth_flats[i0:i1] - base] = True

    # Device stats are [tp, fp, fn, escapes] — record-scale counters only.
    # Position totals and tn are host-derived (owned spans tile [0, total)
    # exactly), which keeps the device reduction int32-safe at mesh scale.
    agg = np.zeros(4, dtype=np.int64)
    steps = 0
    batches = st.batches(header_clamp=False, fill_row=fill_row)
    try:
        for k_rows, done in batches:
            st.zero_tail_rows(k_rows)
            agg += np.asarray(step(*st.sharded_args()), dtype=np.int64)
            steps += 1
            if progress is not None:
                progress(steps, done, st.total)
            if agg[3]:
                break
    finally:
        batches.close()

    if agg[3]:
        stats = _check_bam_exact(
            path, config, st.fresh, st.halo, st.pipeline.metas, truth_flats,
            st.total,
        )
        stats["devices"] = 1  # the exact fallback is single-device
        return stats
    tp, fp, fn = int(agg[0]), int(agg[1]), int(agg[2])
    return {
        "true_positives": tp,
        "false_positives": fp,
        "false_negatives": fn,
        "true_negatives": st.total - tp - fp - fn,
        "positions": st.total,
        "devices": st.n_dev,
    }


def _check_bam_exact(
    path, config, fresh, halo, metas, truth_flats, total
) -> dict:
    """Escape fallback: predicted-boundary set from the deferral-exact
    single-device spans, confusion by set arithmetic."""
    checker = StreamChecker(
        path, config, window_uncompressed=fresh, halo=halo, metas=metas
    )
    parts = [base + np.flatnonzero(v) for base, v in checker.spans()]
    pred = (
        np.sort(np.concatenate(parts)) if parts
        else np.empty(0, dtype=np.int64)
    )
    tp = int(np.isin(pred, truth_flats).sum())
    fp = len(pred) - tp
    fn = len(truth_flats) - tp
    return {
        "true_positives": tp,
        "false_positives": fp,
        "false_negatives": fn,
        "true_negatives": total - tp - fp - fn,
        "positions": total,
    }
