"""Mesh-sharded streaming workloads: one BAM across all chips — and hosts.

THE sharding engine for both scale tiers (VERDICT r4 item 6: one
codepath):

- single-host multi-chip: ``count_reads_sharded`` / ``check_bam_sharded``
  assemble rows over the local mesh (the CLI ``--sharded`` modes);
- multi-host: ``parallel/multihost.py --bam`` calls the same functions
  with ``num_processes``/``process_id`` — each process assembles only its
  own row slice, and the tiny reductions ride the global mesh's
  collectives (``lax.psum`` over ICI/DCN).

Row discipline (the property multi-host needs — any row computable from
``(path, metas)`` alone, no sequential carry):

- ``window_plan`` groups consecutive BGZF blocks into ≈window-sized
  uncompressed runs; row *g* OWNS group *g*'s uncompressed span, which
  tiles ``[0, total)`` exactly;
- each row's buffer extends past its owned span with following blocks
  until ≥ ``halo`` lookahead bytes are present (re-inflated overlap —
  ≤ halo + one block per row — traded for seam independence; the
  reference's analog is hadoop-bam re-reading across split edges,
  load/.../SplitRDD.scala:43-79);
- a chain that outruns even the halo reports an *escape*; any escape
  aborts the device pass and the file re-runs through ``StreamChecker``'s
  deferral-exact spans path (single device) — same policy as
  ``StreamChecker.count_reads``. On real data with the default halo this
  never triggers.

Workloads (SURVEY.md §2.8 maps file/block data-parallelism onto per-core
batch pipelines; §2.9 replaces Spark accumulators with ``psum``):

- ``count_reads_sharded`` — the count-reads workload (reference
  docs/benchmarks.md:53-59);
- ``check_bam_sharded`` — check-bam validation: verdicts vs the
  ``.records`` indexed ground truth at every uncompressed position,
  confusion matrix accumulated via ``psum`` (reference
  CheckerApp.scala:59-93's accumulator pipeline).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_bam_tpu import obs
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE
from spark_bam_tpu.bgzf.flat import inflate_blocks
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.parallel.mesh import make_mesh, mesh_steps
from spark_bam_tpu.tpu.checker import PAD
from spark_bam_tpu.tpu.inflate import (
    inflate_group_device,
    resolve_device_inflate,
    window_plan,
)
from spark_bam_tpu.tpu.stream_check import (
    StreamChecker,
    _next_pow2,
    pad_contig_lengths,
)


def _plan_rows(metas: list, fresh: int, n_global: int, num_processes: int):
    """The row-planning arithmetic shared by the sharded engine and
    ``host_shard_plan`` (one implementation — a scheduler plan must match
    what the engine actually reads BY CONSTRUCTION): block groups, each
    group's first block index and uncompressed size/flat start, and the
    per-process row count (global rows padded to a multiple of the device
    count so every process loops identical step counts)."""
    groups = window_plan(metas, fresh)
    sizes = np.array(
        [sum(m.uncompressed_size for m in g) for g in groups], dtype=np.int64
    )
    flat_starts = np.zeros(len(groups), dtype=np.int64)
    first_block = np.zeros(len(groups), dtype=np.int64)
    if len(groups):
        np.cumsum(sizes[:-1], out=flat_starts[1:])
        np.cumsum([len(g) for g in groups[:-1]], out=first_block[1:])
    n_rows = -(-max(len(groups), 1) // n_global) * n_global
    per_proc = n_rows // num_processes
    return groups, sizes, flat_starts, first_block, per_proc


def _halo_block_range(
    metas: list, groups: list, first_block, g0: int, g1: int, halo: int
) -> tuple[int, int]:
    """Block index range [b0, b1) covering groups [g0, g1) plus trailing
    blocks until ≥ ``halo`` lookahead bytes — the engine's row extension
    and the plan's per-host read range, one implementation."""
    b0 = int(first_block[g0])
    b1 = b0 + sum(len(groups[g]) for g in range(g0, g1))
    extra = 0
    while b1 < len(metas) and extra < halo:
        extra += metas[b1].uncompressed_size
        b1 += 1
    return b0, b1


class _ShardedStream:
    """Shared plumbing: plan the block groups, assemble this process's row
    slice into mesh-wide batches (double-buffered), build sharded args."""

    def __init__(
        self,
        path,
        config: Config,
        mesh,
        window_uncompressed: int | None,
        halo: int | None,
        metas: list | None,
        with_truth: bool = False,
        num_processes: int = 1,
        process_id: int = 0,
        chunk_bytes: int = 192 << 20,
    ):
        from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

        self.path = path
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_global = int(self.mesh.devices.size)
        self.axis = self.mesh.axis_names[0]
        self.num_processes = num_processes
        self.process_id = process_id

        header = read_header(path)
        lens_list = header.contig_lengths.lengths_list()
        self.num_contigs = len(lens_list)
        self.lengths = pad_contig_lengths(np.asarray(lens_list, dtype=np.int32))
        self.header_end = header.uncompressed_size

        self.fresh = window_uncompressed or config.window_size
        halo = config.halo_size if halo is None else halo
        self.halo = min(halo, self.fresh // 2)
        self.metas = list(blocks_metadata(path)) if metas is None else metas
        (
            self.groups, self.sizes, self.flat_starts, self.first_block,
            self.per_proc,
        ) = _plan_rows(self.metas, self.fresh, self.n_global, num_processes)
        self.total = int(self.sizes.sum())
        # Row buffer bound: owned span (≤ fresh, or one oversized block) +
        # halo + ≤ one block of halo-extension overshoot.
        row_bound = max(self.fresh, MAX_BLOCK_SIZE) + self.halo + MAX_BLOCK_SIZE
        self.kernel_window = _next_pow2(
            min(row_bound, max(self.total, 1 << 16))
        )
        self.device_inflate = resolve_device_inflate(config)

        n_local = self.n_global // num_processes
        kw = self.kernel_window
        self.step_rows_local = n_local * max(
            1, chunk_bytes // ((kw + PAD) * max(n_local, 1))
        )
        if self.per_proc:
            self.step_rows_local = min(self.step_rows_local, self.per_proc)
        self.with_truth = with_truth

        self.row_sharding = NamedSharding(self.mesh, P(self.axis))
        repl = NamedSharding(self.mesh, P())
        self.lengths_d = jax.device_put(jnp.asarray(self.lengths), repl)
        self.nc = jnp.int32(self.num_contigs)

    # ------------------------------------------------------------- assembly
    def _row(self, ch, g: int):
        """Inflate global row ``g``: returns (buf, n, at_eof, own, base)."""
        b0, b1 = _halo_block_range(
            self.metas, self.groups, self.first_block, g, g + 1, self.halo
        )
        run = self.metas[b0:b1]
        view = None
        if self.device_inflate:
            try:
                view = inflate_group_device(ch, run)
            except Exception:
                view = None  # host zlib is the permanent fallback
        if view is None:
            view = inflate_blocks(ch, run, threads=8)
        at_eof = b1 == len(self.metas)
        own = (
            view.size
            if at_eof and g == len(self.groups) - 1
            else int(self.sizes[g])
        )
        return view.data, view.size, at_eof, own, int(self.flat_starts[g])

    def _assemble(self, ch, c0: int, header_clamp: bool, fill_row):
        """One step's process-local arrays (fixed shapes; padding rows are
        all-zero and own nothing)."""
        kw = self.kernel_window
        k = self.step_rows_local
        ws = np.zeros((k, kw + PAD), dtype=np.uint8)
        ns = np.zeros(k, dtype=np.int32)
        eofs = np.zeros(k, dtype=bool)
        los = np.zeros(k, dtype=np.int32)
        owns = np.zeros(k, dtype=np.int32)
        truth = np.zeros((k, kw), dtype=bool) if self.with_truth else None
        he = self.header_end if header_clamp else 0
        for j in range(k):
            g = self.process_id * self.per_proc + c0 + j
            if c0 + j >= self.per_proc or g >= len(self.groups):
                continue
            buf, n, at_eof, own, base = self._row(ch, g)
            ws[j, :n] = buf
            ns[j] = n
            eofs[j] = at_eof
            owns[j] = own
            los[j] = min(max(he - base, 0), own)
            if fill_row is not None:
                fill_row(truth[j], buf, base, n)
        return ws, ns, eofs, los, owns, truth

    def batches(self, header_clamp: bool, fill_row=None):
        """Yield ``(sharded_args, positions_done, c0)`` per step (``c0`` =
        the step's first process-local row index — row ``j`` of the step is
        global group ``process_id * per_proc + c0 + j``), assembling the
        next step's rows while the caller's device work runs (one step of
        lookahead — the double-buffering the single-host pipeline had)."""
        if not self.per_proc:
            return
        steps = list(range(0, self.per_proc, self.step_rows_local))
        with open_channel(self.path) as ch, ThreadPoolExecutor(1) as pool:
            pending = pool.submit(
                self._assemble, ch, steps[0], header_clamp, fill_row
            )
            for i, c0 in enumerate(steps):
                arrays = pending.result()
                if i + 1 < len(steps):
                    pending = pool.submit(
                        self._assemble, ch, steps[i + 1], header_clamp, fill_row
                    )
                # Highest global row completed this step (process-major row
                # order: the last process owns the file's final groups).
                g_hi = min(
                    (self.num_processes - 1) * self.per_proc
                    + c0 + self.step_rows_local,
                    len(self.groups),
                ) - 1
                done = int(self.flat_starts[g_hi] + self.sizes[g_hi])
                yield self._sharded_args(arrays), done, c0

    def _sharded_args(self, arrays):
        ws, ns, eofs, los, owns, truth = arrays
        rs = self.row_sharding

        def put(a):
            return jax.make_array_from_process_local_data(rs, a)

        args = [put(ws), put(ns), put(eofs)]
        if truth is not None:
            args.append(put(truth))
        args += [put(los), put(owns)]
        return args + [self.lengths_d, self.nc]


def _mostly_dirty(dirty: list, steps: int) -> bool:
    """The escape-everywhere guard: stop burning device work when the
    input is dirty nearly everywhere (undersized halo) — all-dirty early,
    or ≥90% dirty once enough steps have run (a lone clean step must not
    disable the guard)."""
    return (steps >= 4 and len(dirty) == steps) or (
        steps >= 8 and len(dirty) * 10 >= steps * 9
    )


class _RowGrowth:
    """The shared grown-buffer protocol of the escape-localized patch
    primitives: global row ``g``'s block range extended with halo
    lookahead, re-inflated at geometrically-doubled spans until the
    resolver is satisfied, with one adversarial-growth cap at
    ``(reads_to_check + 2) x max_read_size`` of lookahead."""

    def __init__(self, st: "_ShardedStream", g: int):
        self.st = st
        self.lo_abs = int(st.flat_starts[g])
        self.hi_abs = self.lo_abs + int(st.sizes[g])
        self.b0 = int(st.first_block[g])
        b_end = (
            int(st.first_block[g + 1]) if g + 1 < len(st.groups)
            else len(st.metas)
        )
        self.nblocks = len(st.metas)
        self.cap_bytes = (
            (st.config.reads_to_check + 2) * st.config.max_read_size
        )
        self.b1 = min(
            b_end + max(1, st.halo // MAX_BLOCK_SIZE + 1), self.nblocks
        )

    def view(self, ch):
        return inflate_blocks(ch, self.st.metas[self.b0: self.b1], threads=8)

    @property
    def at_eof(self) -> bool:
        return self.b1 == self.nblocks

    def grow(self, view_size: int) -> bool:
        """Double the block span; False once lookahead exceeds the cap
        (adversarial size fields — callers bail to the whole-file path)."""
        if view_size - (self.hi_abs - self.lo_abs) > self.cap_bytes:
            return False
        self.b1 = min(self.b0 + 2 * (self.b1 - self.b0), self.nblocks)
        return True


def _exact_row_true_positions(
    st: "_ShardedStream", g: int, lo_clamp: int, ch
):
    """Exact absolute record-start positions inside global row ``g``'s
    owned span, via the native tri-state walk over a geometrically-grown
    buffer (only still-uncertain candidates re-check per growth round);
    ``ch`` is an open channel on ``st.path`` (callers patch many rows —
    one open serves them all).

    The escape-localized patch primitive: a row whose device verdicts
    escaped (ultra chains beyond the halo) is re-derived from
    ``(path, metas)`` alone — the row discipline — without touching any
    other row. Returns None when the native library is unavailable or
    the lookahead outgrows the adversarial cap; callers fall back to the
    whole-file deferral-exact path, which bounds memory by
    construction."""
    from spark_bam_tpu.native.build import eager_check_window_native

    rg = _RowGrowth(st, g)
    lo_eval = max(rg.lo_abs, lo_clamp)
    if lo_eval >= rg.hi_abs:
        return np.empty(0, dtype=np.int64)
    lens = st.lengths[: st.num_contigs]
    # Candidates walk the owned span in bounded chunks: per-position state
    # (int64 cand_abs + uint8 res ≈ 9 bytes/position) over a whole
    # multi-MB row would cost ~9x the row's uncompressed size in host
    # memory; a 1 Mi-position chunk caps it at ~9 MB. ``rg`` persists
    # across chunks, so lookahead growth won by an early chunk serves the
    # rest of the row, and the inflated view is reused until it grows.
    chunk_positions = 1 << 20
    obs.count("mesh.patch_rows")
    view = rg.view(ch)
    hits: list[np.ndarray] = []
    for c_lo in range(lo_eval, rg.hi_abs, chunk_positions):
        c_hi = min(c_lo + chunk_positions, rg.hi_abs)
        cand_abs = np.arange(c_lo, c_hi, dtype=np.int64)
        res = np.full(len(cand_abs), 2, dtype=np.uint8)
        obs.count("mesh.patch_chunks")
        obs.observe("mesh.patch_chunk_positions", len(cand_abs))
        while True:
            unc = np.flatnonzero(res == 2)
            tri = eager_check_window_native(
                view.data, cand_abs[unc] - rg.lo_abs, lens,
                reads_to_check=st.config.reads_to_check, exact_eof=rg.at_eof,
            )
            if tri is None:
                return None
            res[unc] = tri
            if rg.at_eof or not (res == 2).any():
                hits.append(cand_abs[res == 1])
                break
            if not rg.grow(view.size):
                return None
            view = rg.view(ch)
    return (
        np.concatenate(hits) if hits else np.empty(0, dtype=np.int64)
    )


def _exact_row_flags(st: "_ShardedStream", g: int, ch):
    """Exact (fail_mask, reads_before) for global row ``g``'s owned span
    via the NumPy engine over a geometrically-grown buffer — the
    flags-projection counterpart of ``_exact_row_true_positions`` (the
    native tri-state walk yields verdicts only; full-check patches need
    the complete 19-flag masks, which only the full flag pass produces).
    Grows until every owned candidate is exact and unescaped (or EOF);
    returns None past the adversarial-growth cap."""
    from spark_bam_tpu.check.vectorized import check_flat

    rg = _RowGrowth(st, g)
    if rg.lo_abs >= rg.hi_abs:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    span = rg.hi_abs - rg.lo_abs
    lens = st.lengths[: st.num_contigs]
    while True:
        view = rg.view(ch)
        # candidates=None takes the survivor-compaction fast path (~99%
        # of positions resolve elementwise from the flag pass); the
        # owned span is a slice of the all-position result.
        res = check_flat(
            view.data, lens, at_eof=rg.at_eof,
            reads_to_check=st.config.reads_to_check,
        )
        need = (res.escaped | ~res.exact)[:span]
        if rg.at_eof or not need.any():
            return (
                np.asarray(res.fail_mask[:span], dtype=np.int32),
                np.asarray(res.reads_before[:span], dtype=np.int32),
            )
        if not rg.grow(view.size):
            return None


def _step_global_rows(st: "_ShardedStream", c0: int) -> list[int]:
    """Global group indices a sharded step at local row offset ``c0``
    covered, across ALL processes (fill rows excluded) — the rows a
    dirty-step patch must recompute so every process lands the same
    global result."""
    rows = []
    for p in range(st.num_processes):
        for j in range(c0, min(c0 + st.step_rows_local, st.per_proc)):
            g = p * st.per_proc + j
            if g < len(st.groups):
                rows.append(g)
    return rows


def count_reads_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
    stats_out: dict | None = None,
    num_processes: int = 1,
    process_id: int = 0,
    chunk_bytes: int = 192 << 20,
) -> int:
    """Record count of ``path`` computed across ``mesh`` (default: all
    devices; multi-host callers pass their process coordinates and get the
    globally reduced count on every process). ``progress(steps_done,
    positions_done, total_positions)`` fires after each sharded step.
    ``stats_out``, when given, receives ``{"steps", "escapes", "fallback",
    "patched_steps"}`` — escaped steps are normally re-derived exactly on
    host (``patched_steps`` counts them; the other steps' device totals
    stand); ``fallback`` is True only when the whole-file exact path ran
    instead (no native library, adversarial lookahead growth, or an
    escape-everywhere input)."""
    st = _ShardedStream(
        path, config, mesh, window_uncompressed, halo, metas,
        num_processes=num_processes, process_id=process_id,
        chunk_bytes=chunk_bytes,
    )
    # Cached per (mesh, params): repeat invocations — and the serve/
    # daemon's ticks — reuse one traced executable instead of re-jitting.
    step = mesh_steps(st.mesh, st.axis).count_step(
        reads_to_check=config.reads_to_check,
        flags_impl=config.flags_impl, funnel=config.funnel_enabled(),
    )
    count = escapes = steps = 0
    dirty: list[int] = []  # local row offsets (c0) of escaped steps
    whole_file = False
    # Closing the batch generator on early exit (escape break, error)
    # shuts down the assembly pool and channel before any fallback
    # reopens the file.
    batches = st.batches(header_clamp=True)
    try:
        for args, done, c0 in batches:
            with obs.span("mesh.step", workload="count", c0=c0):
                totals = np.asarray(step(*args))
            esc = int(totals[1])
            steps += 1
            obs.count("mesh.steps")
            if esc:
                obs.count("mesh.dirty_steps")
                obs.count("mesh.escapes", esc)
                # Escape-localized handling: the dirty STEP's device
                # totals are untrusted (an escaped chain's verdict can be
                # wrong in either direction), but every other step stands.
                # Record the step for a host-side exact patch instead of
                # discarding the whole device pass.
                escapes += esc
                dirty.append(c0)
            else:
                count += int(totals[0])
            if progress is not None:
                progress(steps, done, st.total)
            # Pathological guard (mirrors count_reads' window-4 escape
            # checkpoint): if nearly every step escapes, the halo is
            # undersized for this input — stop burning device work and
            # take the whole-file exact path.
            if _mostly_dirty(dirty, steps):
                whole_file = True
                break
    finally:
        batches.close()

    patched = None
    if dirty and not whole_file:
        patched = 0
        rows = {g for c0 in dirty for g in _step_global_rows(st, c0)}
        with open_channel(path) as ch:
            for g in rows:
                pos = _exact_row_true_positions(st, g, st.header_end, ch)
                if pos is None:
                    patched = None  # no native lib / adversarial growth
                    break
                patched += len(pos)

    if stats_out is not None:
        stats_out.update(
            steps=steps, escapes=escapes,
            fallback=bool(escapes) and patched is None,
            patched_steps=0 if patched is None else len(dirty),
            rows=len(st.groups),
        )
    if escapes and patched is None:
        # Whole-file exact fallback (no native library, adversarial
        # lookahead growth, or an escape-everywhere input): resolve
        # through the single-device deferral path (reusing this pass's
        # block-metadata scan). Multi-host: every process computes the
        # same exact count — redundant but correct.
        return StreamChecker(
            path, config, window_uncompressed=st.fresh, halo=st.halo,
            metas=st.metas,
        ).count_reads()
    return count + (patched or 0)


def full_check_summary_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
    k_positions: int = 4096,
    fallback_use_device: bool = True,
    stats_out: dict | None = None,
) -> dict:
    """The full-check workload's aggregations across the mesh — the third
    sharded workload (reference FullCheck.scala:112-417 as a Spark job;
    here one ``shard_map`` step per row batch): per-flag totals,
    considered-position count, and the critical / two-check sites with
    their masks. Same return shape as
    ``tpu.stream_check.full_check_summary_streaming`` plus ``devices``.

    Exactness policy mirrors the other sharded workloads: a step with
    deferred lanes (escaped or edge-inexact masks) keeps its device
    results OUT of the aggregation and its rows re-derive exactly on
    host (the escape-localized patch, via the NumPy engine's full flag
    pass over grown buffers). The whole-file single-device streaming
    summary remains the fallback for nearly-all-dirty inputs,
    adversarial lookahead growth, and per-row compaction overflow
    (> ``k_positions`` sites in one row) — ``devices`` = 1 then;
    ``fallback_use_device`` selects its engine (the CLI passes its
    hang-proof backend probe's verdict).
    Single-process only (the compacted site arrays are row-sharded device
    outputs; multi-host full-check would need an all-gather of variable
    site lists)."""
    from spark_bam_tpu.check.flags import FLAG_NAMES

    if jax.process_count() > 1:
        raise NotImplementedError(
            "full_check_summary_sharded is single-process only (row-sharded "
            "site outputs are not multi-host addressable); run it on one "
            "host or use the single-device streaming summary"
        )
    st = _ShardedStream(
        path, config, mesh, window_uncompressed, halo, metas
    )
    step = mesh_steps(st.mesh, st.axis).full_step(
        reads_to_check=config.reads_to_check,
        flags_impl=config.flags_impl, k_positions=k_positions,
    )
    n_flags = len(FLAG_NAMES)
    agg = np.zeros(5 + n_flags, dtype=np.int64)
    crit_pos: list[np.ndarray] = []
    crit_mask: list[np.ndarray] = []
    two_pos: list[np.ndarray] = []
    two_mask: list[np.ndarray] = []
    fallback = False
    defers = 0
    dirty: list[int] = []  # local row offsets (c0) of deferred steps
    steps = 0
    batches = st.batches(header_clamp=False)
    try:
        for args, done, c0 in batches:
            with obs.span("mesh.step", workload="full_check", c0=c0):
                totals, ci, cm, ti, tm = step(*args)
                totals = np.asarray(totals).astype(np.int64)
            steps += 1
            obs.count("mesh.steps")
            if totals[4]:
                obs.count("mesh.dirty_steps")
                # Deferred lanes: the device masks for this STEP are not
                # exact — skip its totals/sites and patch its rows on
                # host below (escape-localized, like count/check-bam).
                defers += int(totals[4])
                dirty.append(c0)
                if _mostly_dirty(dirty, steps):
                    fallback = True
                    break
                if progress is not None:
                    progress(steps, done, st.total)
                continue
            agg += totals
            ci, cm, ti, tm = (np.asarray(a) for a in (ci, cm, ti, tm))
            for j in range(ci.shape[0]):
                g = c0 + j
                if g >= len(st.groups):
                    continue  # padding row: no sites by construction
                base = int(st.flat_starts[g])
                for idx, masks, acc_p, acc_m in (
                    (ci[j], cm[j], crit_pos, crit_mask),
                    (ti[j], tm[j], two_pos, two_mask),
                ):
                    sel = idx >= 0
                    if sel.any():
                        acc_p.append(base + idx[sel].astype(np.int64))
                        acc_m.append(masks[sel].astype(np.int32))
            if progress is not None:
                progress(steps, done, st.total)
    finally:
        batches.close()

    if dirty and not fallback:
        from spark_bam_tpu.check.flags import (
            BIT,
            considered_mask,
            num_failing_fields,
        )

        bit0 = int(BIT["tooFewFixedBlockBytes"])
        rows = {g for c0 in dirty for g in _step_global_rows(st, c0)}
        with open_channel(path) as ch:
            for g in rows:
                out = _exact_row_flags(st, g, ch)
                if out is None:
                    fallback = True  # adversarial lookahead growth
                    break
                fm, rb = out
                base = int(st.flat_starts[g])
                agg[0] += int((fm == 0).sum())
                agg[1] += int(((fm == bit0) & (rb == 0)).sum())
                considered = considered_mask(fm, rb)
                masked = fm[considered]
                for i in range(n_flags):
                    agg[5 + i] += int(((masked >> i) & 1).sum())
                nf = num_failing_fields(fm, rb)
                ones = np.flatnonzero(considered & (nf == 1))
                twos = np.flatnonzero(considered & (nf == 2))
                agg[2] += len(ones)
                agg[3] += len(twos)
                if len(ones):
                    crit_pos.append(base + ones)
                    crit_mask.append(fm[ones].astype(np.int32))
                if len(twos):
                    two_pos.append(base + twos)
                    two_mask.append(fm[twos].astype(np.int32))

    n_crit = sum(map(len, crit_pos))
    n_two = sum(map(len, two_pos))
    if not fallback and (n_crit != int(agg[2]) or n_two != int(agg[3])):
        fallback = True  # a row overflowed the compaction buffer
    if stats_out is not None:
        # ``fallback`` tells hardware smokes whether the MESH pass itself
        # produced the summary (same contract as count_reads_sharded).
        stats_out.update(
            steps=steps, fallback=fallback, defers=defers,
            patched_steps=0 if fallback else len(dirty),
        )
    if fallback:
        from spark_bam_tpu.tpu.stream_check import (
            full_check_summary_streaming,
        )

        out = full_check_summary_streaming(
            path, config, window_uncompressed=st.fresh, halo=st.halo,
            use_device=fallback_use_device, metas=st.metas,
        )
        out["devices"] = 1
        return out

    def cat(parts, dtype):
        return np.concatenate(parts) if parts else np.empty(0, dtype=dtype)

    cp, cm = cat(crit_pos, np.int64), cat(crit_mask, np.int32)
    tp_, tm_ = cat(two_pos, np.int64), cat(two_mask, np.int32)
    if dirty:
        # Patched rows appended their sites after the clean steps'; the
        # report lists sites in ascending file order — restore it. (The
        # streaming summary sorts its deferred re-emissions the same way,
        # so the two paths agree on site ORDER whenever they agree on the
        # site set — same-order output is a consequence of both sorting,
        # not a standalone guarantee.)
        o = np.argsort(cp, kind="stable")
        cp, cm = cp[o], cm[o]
        o = np.argsort(tp_, kind="stable")
        tp_, tm_ = tp_[o], tm_[o]
    return {
        "per_flag": {
            name: int(agg[5 + i]) for i, name in enumerate(FLAG_NAMES)
        },
        # passes (mask==0) and the bare at-EOF markers are the only owned
        # positions NOT considered; the total is host-derived so no
        # position-scale counter rides the collective.
        "considered": st.total - int(agg[0]) - int(agg[1]),
        "critical_positions": cp,
        "critical_masks": cm,
        "two_check_positions": tp_,
        "two_check_masks": tm_,
        "positions": st.total,
        "devices": st.n_global,
    }


def host_shard_plan(
    path,
    num_hosts: int,
    devices_per_host: int,
    config: Config = Config(),
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
) -> list[dict]:
    """The per-host IO footprint of a ``num_hosts × devices_per_host``
    sharded run BEFORE any backend comes up — the scheduler-facing
    locality surface (reference ``SplitRDD.preferredLocations``,
    load/.../SplitRDD.scala:43-79: tell the scheduler where the bytes are;
    here: tell it which bytes each process will read, so it can place
    processes near data or pre-warm caches).

    Returns one dict per host: ``host`` (process id), ``groups`` (owned
    block-group index range, end-exclusive), ``compressed_range`` (the
    [lo, hi) file byte range the host reads, INCLUDING its trailing halo
    overlap), ``uncompressed`` (owned flat bytes). Owned group ranges
    partition the file exactly; compressed ranges overlap by ≤ halo + one
    block at each seam. Uses the same row arithmetic as the sharded
    engine, so the plan is exact, not an estimate."""
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    fresh = window_uncompressed or config.window_size
    h = config.halo_size if halo is None else halo
    h = min(h, fresh // 2)
    metas = list(blocks_metadata(path)) if metas is None else metas
    n_global = num_hosts * devices_per_host
    # The engine's own planning arithmetic (_plan_rows/_halo_block_range):
    # the plan matches what the engine reads by construction.
    groups, sizes, _flat_starts, first_block, per_proc = _plan_rows(
        metas, fresh, n_global, num_hosts
    )

    plan = []
    for p in range(num_hosts):
        g0 = min(p * per_proc, len(groups))
        g1 = min((p + 1) * per_proc, len(groups))
        if g0 == g1:
            plan.append({
                "host": p, "groups": (g0, g0),
                "compressed_range": (0, 0), "uncompressed": 0,
            })
            continue
        b0, b1 = _halo_block_range(metas, groups, first_block, g0, g1, h)
        lo = metas[b0].start
        hi = metas[b1 - 1].start + metas[b1 - 1].compressed_size
        plan.append({
            "host": p,
            "groups": (g0, g1),
            "compressed_range": (int(lo), int(hi)),
            "uncompressed": int(sizes[g0:g1].sum()),
        })
    return plan


def _truth_flats(path, records_path, metas) -> np.ndarray:
    """The ``.records`` ground truth as sorted absolute flat offsets."""
    from spark_bam_tpu.bam.index_records import read_records_index
    from spark_bam_tpu.bgzf.flat import metas_block_table
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    records_path = (
        str(path) + ".records" if records_path is None else records_path
    )
    positions = read_records_index(records_path)
    metas = list(blocks_metadata(path)) if metas is None else metas
    block_starts, block_flat = metas_block_table(metas)
    blocks = np.array([p.block_pos for p in positions], dtype=np.int64)
    offs = np.array([p.offset for p in positions], dtype=np.int64)
    idx = np.searchsorted(block_starts, blocks)
    if len(idx) and (
        idx.max() >= len(block_starts)
        or not np.array_equal(block_starts[idx], blocks)
    ):
        raise ValueError(
            f"{records_path}: block positions not in {path}'s block table "
            "(stale sidecar?)"
        )
    return np.sort(block_flat[idx] + offs)


def check_bam_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    records_path=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
    num_processes: int = 1,
    process_id: int = 0,
) -> dict:
    """check-bam across the mesh: the vectorized checker's verdict vs the
    ``.records`` indexed ground truth at **every uncompressed position** of
    the file (header bytes included — reference check-bam semantics), the
    confusion matrix ``psum``'d per sharded step.

    Returns ``{"true_positives", "false_positives", "false_negatives",
    "true_negatives", "positions", "devices"}`` (``devices`` = the mesh
    size the verdicts actually ran on). Escaped chains fall back to the
    single-device deferral-exact spans path, so the returned matrix is
    always exact.
    """
    st = _ShardedStream(
        path, config, mesh, window_uncompressed, halo, metas,
        with_truth=True, num_processes=num_processes, process_id=process_id,
    )
    truth_flats = _truth_flats(path, records_path, st.metas)
    step = mesh_steps(st.mesh, st.axis).confusion_step(
        reads_to_check=config.reads_to_check,
        flags_impl=config.flags_impl, funnel=config.funnel_enabled(),
    )

    def fill_row(row, buf, base, n):
        i0, i1 = np.searchsorted(truth_flats, (base, base + n))
        row[truth_flats[i0:i1] - base] = True

    # Device stats are [tp, fp, fn, escapes] — record-scale counters only.
    # Position totals and tn are host-derived (owned spans tile [0, total)
    # exactly), which keeps the device reduction int32-safe at mesh scale.
    agg = np.zeros(4, dtype=np.int64)
    steps = 0
    dirty: list[int] = []  # local row offsets (c0) of escaped steps
    whole_file = False
    batches = st.batches(header_clamp=False, fill_row=fill_row)
    try:
        for args, done, c0 in batches:
            with obs.span("mesh.step", workload="check_bam", c0=c0):
                totals = np.asarray(step(*args), dtype=np.int64)
            steps += 1
            obs.count("mesh.steps")
            if totals[3]:
                obs.count("mesh.dirty_steps")
                # Escape-localized handling (see count_reads_sharded):
                # the dirty step's confusion counters are untrusted and
                # its rows re-derive exactly on host below.
                dirty.append(c0)
            else:
                agg += totals
            if progress is not None:
                progress(steps, done, st.total)
            if _mostly_dirty(dirty, steps):
                whole_file = True
                break
    finally:
        batches.close()

    if dirty and not whole_file:
        rows = {g for c0 in dirty for g in _step_global_rows(st, c0)}
        with open_channel(path) as ch:
            for g in rows:
                pos = _exact_row_true_positions(st, g, 0, ch)
                if pos is None:
                    whole_file = True  # no native lib / adversarial growth
                    break
                lo = int(st.flat_starts[g])
                hi = lo + int(st.sizes[g])
                i0, i1 = np.searchsorted(truth_flats, (lo, hi))
                t = truth_flats[i0:i1]
                tp_g = int(np.isin(pos, t).sum())
                agg[0] += tp_g
                agg[1] += len(pos) - tp_g
                agg[2] += len(t) - tp_g
    if whole_file:
        stats = _check_bam_exact(
            path, config, st.fresh, st.halo, st.metas, truth_flats,
            st.total,
        )
        stats["devices"] = 1  # the exact fallback is single-device
        return stats
    tp, fp, fn = int(agg[0]), int(agg[1]), int(agg[2])
    return {
        "true_positives": tp,
        "false_positives": fp,
        "false_negatives": fn,
        "true_negatives": st.total - tp - fp - fn,
        "positions": st.total,
        "devices": st.n_global,
    }


def _check_bam_exact(
    path, config, fresh, halo, metas, truth_flats, total
) -> dict:
    """Escape fallback: predicted-boundary set from the deferral-exact
    single-device spans, confusion by set arithmetic."""
    checker = StreamChecker(
        path, config, window_uncompressed=fresh, halo=halo, metas=metas
    )
    parts = [base + np.flatnonzero(v) for base, v in checker.spans()]
    pred = (
        np.sort(np.concatenate(parts)) if parts
        else np.empty(0, dtype=np.int64)
    )
    tp = int(np.isin(pred, truth_flats).sum())
    fp = len(pred) - tp
    fn = len(truth_flats) - tp
    return {
        "true_positives": tp,
        "false_positives": fp,
        "false_negatives": fn,
        "true_negatives": total - tp - fp - fn,
        "positions": total,
    }
