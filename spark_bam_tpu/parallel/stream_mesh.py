"""Mesh-sharded streaming count-reads: one BAM across all chips.

Bridges the two scale paths that already exist separately:

- ``tpu/stream_check.StreamChecker`` — whole-file streaming in O(window)
  host memory, single device;
- ``parallel/mesh.make_shard_map_count_step`` — the mesh-partitioned
  count unit (``lax.psum`` over ICI) that ``multihost.py`` feeds with
  preassembled window rows.

Here the host assembles consecutive halo-carried windows into a
``(n_devices, W+PAD)`` batch per step — the same carry/ownership
discipline as ``StreamChecker`` (each row's trailing ``halo`` is owned by
the next row, so every owned position has full chain lookahead) — and
every step runs one sharded kernel with the global count reduced on the
mesh. This is the single-host multi-chip production path of the
count-reads workload (reference docs/benchmarks.md:53-59; SURVEY.md §2.8
maps file/block data-parallelism onto per-core batch pipelines, §2.9
replaces Spark accumulators with ``psum``).

Exactness: rows whose chains outrun the halo report escapes; any escape
aborts the device pass and the file re-runs through ``StreamChecker``'s
deferral-exact spans path (single device). On real data with the default
halo this never triggers — same policy as ``StreamChecker.count_reads``.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.parallel.mesh import make_mesh, make_shard_map_count_step
from spark_bam_tpu.tpu.checker import PAD
from spark_bam_tpu.tpu.inflate import InflatePipeline
from spark_bam_tpu.tpu.stream_check import (
    _next_pow2,
    halo_windows,
    pad_contig_lengths,
)


def count_reads_sharded(
    path,
    config: Config = Config(),
    mesh=None,
    window_uncompressed: int | None = None,
    halo: int | None = None,
    metas: list | None = None,
    progress: Callable[[int, int, int], None] | None = None,
) -> int:
    """Record count of ``path`` computed across ``mesh`` (default: all
    devices). ``progress(steps_done, positions_done, total_positions)``
    fires after each sharded step."""
    mesh = mesh if mesh is not None else make_mesh()
    n_dev = int(mesh.devices.size)
    axis = mesh.axis_names[0]

    header = read_header(path)
    lens_list = header.contig_lengths.lengths_list()
    lengths = pad_contig_lengths(np.asarray(lens_list, dtype=np.int32))

    fresh = window_uncompressed or config.window_size
    halo = config.halo_size if halo is None else halo
    halo = min(halo, fresh // 2)
    pipeline = InflatePipeline(
        path, window_uncompressed=fresh, device_copy=config.device_inflate,
        metas=metas,
    )
    total = pipeline.total
    kernel_window = _next_pow2(min(fresh + halo, max(total, 1 << 16)))
    header_end = header.uncompressed_size

    step = make_shard_map_count_step(
        mesh, reads_to_check=config.reads_to_check, axis=axis
    )
    row_sharding = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    lengths_d = jax.device_put(jnp.asarray(lengths), repl)
    nc = jnp.int32(len(lens_list))

    count = 0
    escapes = 0
    steps = 0
    done_positions = 0

    ws = np.zeros((n_dev, kernel_window + PAD), dtype=np.uint8)
    ns = np.zeros(n_dev, dtype=np.int32)
    eofs = np.zeros(n_dev, dtype=bool)
    los = np.zeros(n_dev, dtype=np.int32)
    owns = np.zeros(n_dev, dtype=np.int32)

    def flush(k_rows: int):
        nonlocal count, escapes, steps
        if k_rows == 0:
            return
        # Zero unused rows so a stale previous batch can't leak in.
        ws[k_rows:] = 0
        ns[k_rows:] = 0
        eofs[k_rows:] = False
        los[k_rows:] = 0
        owns[k_rows:] = 0
        totals = np.asarray(step(
            jax.device_put(jnp.asarray(ws), row_sharding),
            jax.device_put(jnp.asarray(ns), row_sharding),
            jax.device_put(jnp.asarray(eofs), row_sharding),
            jax.device_put(jnp.asarray(los), row_sharding),
            jax.device_put(jnp.asarray(owns), row_sharding),
            lengths_d, nc,
        ))
        count += int(totals[0])
        escapes += int(totals[1])
        steps += 1
        if progress is not None:
            progress(steps, done_positions, total)

    # Seam semantics (carry, ownership, header clamp) come from the same
    # generator StreamChecker uses — one source of truth, so the mesh path
    # and its exact fallback can never diverge.
    k = 0
    for buf, base, own_end, lo, at_eof in halo_windows(
        pipeline, halo, header_end
    ):
        n = len(buf)
        ws[k, :n] = buf
        ws[k, n: kernel_window + PAD] = 0
        ns[k] = n
        eofs[k] = at_eof
        los[k] = lo
        owns[k] = own_end
        done_positions = base + own_end
        k += 1
        if k == n_dev:
            flush(k)
            if escapes:
                break
            k = 0
    if not escapes:
        flush(k)

    if escapes:
        # Ultra-long chains outran the halo: resolve bit-exactly through
        # the single-device deferral path.
        from spark_bam_tpu.tpu.stream_check import StreamChecker

        return StreamChecker(
            path, config, window_uncompressed=fresh, halo=halo, metas=metas,
        ).count_reads()
    return count
