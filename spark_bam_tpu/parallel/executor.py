"""Host-side partition orchestration with Spark-grade fault tolerance.

Replaces the Spark driver/executor substrate (SURVEY.md §2.9): partitions are
planned on the host and executed by a pluggable pool — sequential, threads
(zlib/NumPy release the GIL, so threads saturate cores for this workload), or
processes. The reference's analogous knob is ``ParallelConfig``
(check/.../bam/spark/ParallelConfig.scala:127-148, Threads-vs-Spark).

What Spark supplied for free — failed-task retry, straggler speculation,
job-level failure semantics — lives here now (``run_partitions``), governed
by a ``FaultPolicy`` (core/faults.py):

- transient failures (the OSError family) retry with jittered exponential
  backoff, up to ``max_retries`` per partition;
- an attempt exceeding ``deadline`` seconds is written off as timed out and
  a fresh attempt launched (the stale one's late success is still accepted);
- with ``hedge_after`` set, a partition running longer than N× the median
  completed-attempt latency gets a speculative twin — first finisher wins
  (Spark's speculative execution);
- exhausted retries either raise (``strict``) or quarantine the partition
  and continue (``tolerant``), with every attempt recorded in a
  ``JobReport`` returned alongside the results.

Accumulator-style reductions become plain fold-left over per-partition
results; device-side reductions (psum over a mesh) live in parallel/mesh.py.
"""

from __future__ import annotations

import os
import statistics
import time
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ProcessPoolExecutor,
    ThreadPoolExecutor,
    wait,
)
from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence, TypeVar

from spark_bam_tpu import obs
from spark_bam_tpu.core import guard
from spark_bam_tpu.core.faults import FaultPolicy, retryable
from spark_bam_tpu.obs import trace as obs_trace

T = TypeVar("T")
R = TypeVar("R")

_MODES = ("sequential", "threads", "processes")

#: Coordinator wake interval when deadlines/hedging need a clock (s).
_WATCH_TICK = 0.02
#: Hedging needs this many completed attempts before the median means much.
_HEDGE_MIN_SAMPLES = 3


@dataclass(frozen=True)
class ParallelConfig:
    mode: str = "threads"   # sequential | threads | processes
    workers: int = 0        # 0 → os.cpu_count()

    @property
    def num_workers(self) -> int:
        return self.workers or os.cpu_count() or 1

    @staticmethod
    def parse(s: str) -> "ParallelConfig":
        """``"sequential"`` | ``"threads[=N]"`` | ``"processes[=N]"``."""
        mode, _, n = s.partition("=")
        workers = 0
        if n:
            try:
                workers = int(n)
            except ValueError:
                raise ValueError(
                    f"Bad parallel worker count {n!r} in {s!r}: want an integer"
                )
        if mode not in _MODES:
            raise ValueError(
                f"Unknown parallel mode {mode!r} in {s!r}: expected one of "
                f"{', '.join(_MODES)}"
            )
        if workers < 0:
            raise ValueError(
                f"Parallel worker count must be >= 0 (0 = all cores): {s!r}"
            )
        return ParallelConfig(mode, workers)


# ------------------------------------------------------------- job reporting
@dataclass
class Attempt:
    """One execution attempt of one partition."""

    partition: int
    number: int          # 0-based attempt index (hedges share the primary's)
    speculative: bool
    outcome: str         # ok | error | timeout | lost
    ms: float
    error: str | None = None


@dataclass
class PartitionReport:
    index: int
    status: str = "pending"   # pending | ok | quarantined
    attempts: list[Attempt] = field(default_factory=list)
    error: str | None = None


@dataclass
class JobReport:
    """Per-partition attempt/outcome ledger for one ``run_partitions`` call
    — the observable replacement for Spark's task-level UI."""

    partitions: list[PartitionReport]
    #: Decode-level losses (tolerant mode): records/blocks quarantined by
    #: the guard layer (core/guard.py) while this job ran — finer-grained
    #: than partition quarantine, which loses a whole partition at once.
    lost_records: int = 0
    lost_blocks: int = 0

    @property
    def quarantined(self) -> list[int]:
        return [p.index for p in self.partitions if p.status == "quarantined"]

    @property
    def retries(self) -> int:
        return sum(
            1
            for p in self.partitions
            for a in p.attempts
            if a.number > 0 and not a.speculative
        )

    @property
    def hedges(self) -> int:
        hedged = {
            (a.partition, a.number)
            for p in self.partitions
            for a in p.attempts
            if a.speculative
        }
        return len(hedged)

    def summary(self) -> str:
        lines = [
            f"fault tolerance: {len(self.partitions)} partitions, "
            f"{self.retries} retries, {self.hedges} hedges, "
            f"{len(self.quarantined)} quarantined"
        ]
        if self.lost_records or self.lost_blocks:
            lines.append(
                f"\tmalformed input: {self.lost_records} records and "
                f"{self.lost_blocks} blocks quarantined by decode guards"
            )
        for p in self.partitions:
            if p.status == "quarantined":
                lines.append(f"\tquarantined partition {p.index}: {p.error}")
        return "\n".join(lines)


# The most recent JobReport, whatever Dataset/CLI layer triggered it — the
# CLI reads this after a subcommand to print the quarantine summary without
# threading the report through every action's return type.
_last_report: JobReport | None = None


def last_report() -> JobReport | None:
    return _last_report


def reset_last_report() -> None:
    global _last_report
    _last_report = None


def _errstr(e: BaseException) -> str:
    return f"{type(e).__name__}: {e}"


def _record(report: PartitionReport, attempt: Attempt) -> None:
    report.attempts.append(attempt)
    obs.observe("faults.attempt_ms", attempt.ms)


def _fail_partition(
    report: PartitionReport, err: BaseException, policy: FaultPolicy
) -> None:
    """Exhausted retries: quarantine (tolerant) or re-raise (strict)."""
    if policy.tolerant:
        report.status = "quarantined"
        report.error = _errstr(err)
        obs.count("faults.quarantined")
    else:
        raise err


# ------------------------------------------------------------ the executor
def run_partitions(
    fn: Callable[[T], R],
    items: Sequence[T],
    config: ParallelConfig = ParallelConfig(),
    policy: FaultPolicy | None = None,
    pool=None,
) -> tuple[list[R | None], JobReport]:
    """Apply ``fn`` to every partition under ``policy``, preserving order.

    Returns ``(results, report)``; quarantined partitions (tolerant mode
    only) hold ``None`` in ``results`` and are listed in
    ``report.quarantined``. Strict mode raises the partition's final error
    after its retries are exhausted.

    ``pool`` lends an existing executor (a ``ThreadPoolExecutor``-shaped
    object) for the pooled modes instead of spawning one per call — the
    serving daemon (serve/) runs many small jobs against one persistent
    pool, where per-call pool spin-up/teardown would dominate. A lent
    pool is never shut down here; on failure only this job's in-flight
    futures are cancelled. Callers own sizing/lifetime.
    """
    global _last_report
    policy = policy or FaultPolicy()
    if config.mode not in _MODES:
        raise ValueError(
            f"Unknown parallel mode: {config.mode} (expected one of "
            f"{', '.join(_MODES)})"
        )
    reports = [PartitionReport(i) for i in range(len(items))]
    report = JobReport(reports)
    _last_report = report
    # Snapshot the process-wide decode-loss tally around the run: the delta
    # is what this job's partitions quarantined (the tally is global, so
    # thread-pool workers land in it too; process pools under-report).
    rec0, blk0 = guard.loss_totals()
    if config.mode == "sequential" or len(items) <= 1:
        results = _run_sequential(fn, items, policy, reports)
    else:
        results = _run_pooled(fn, items, config, policy, reports, pool=pool)
    rec1, blk1 = guard.loss_totals()
    report.lost_records = rec1 - rec0
    report.lost_blocks = blk1 - blk0
    return results, report


def map_partitions(
    fn: Callable[[T], R],
    items: Sequence[T],
    config: ParallelConfig = ParallelConfig(),
    policy: FaultPolicy | None = None,
) -> list[R]:
    """Apply ``fn`` to every partition, preserving order (results only)."""
    results, _ = run_partitions(fn, items, config, policy)
    return results


def _run_sequential(fn, items, policy, reports) -> list:
    results: list = [None] * len(items)
    for i, item in enumerate(items):
        last: BaseException | None = None
        for attempt in range(policy.max_retries + 1):
            t0 = time.perf_counter()
            try:
                value = fn(item)
            except Exception as e:
                ms = (time.perf_counter() - t0) * 1e3
                _record(reports[i], Attempt(i, attempt, False, "error", ms,
                                            _errstr(e)))
                last = e
                if not retryable(e) or attempt == policy.max_retries:
                    break
                obs.count("faults.retries")
                time.sleep(policy.backoff_delay(attempt))
            else:
                ms = (time.perf_counter() - t0) * 1e3
                _record(reports[i], Attempt(i, attempt, False, "ok", ms))
                reports[i].status = "ok"
                results[i] = value
                last = None
                break
        if last is not None:
            _fail_partition(reports[i], last, policy)
    return results


def _run_pooled(fn, items, config, policy, reports, pool=None) -> list:
    n = len(items)
    pool_cls = (
        ThreadPoolExecutor if config.mode == "threads" else ProcessPoolExecutor
    )
    # Pool threads don't inherit the submitter's contextvars: capture the
    # trace context HERE (the serve handler's request span) and rebind it
    # around every attempt, so partition spans land in the request's
    # trace. Process pools skip this — a closure over the context would
    # break pickling, and spans in a child process feed a different
    # registry anyway.
    ctx = obs_trace.current()
    if ctx is not None and config.mode == "threads":
        inner_fn = fn

        def fn(item, _ctx=ctx, _fn=inner_fn):
            token = obs_trace.set_current(_ctx)
            try:
                return _fn(item)
            finally:
                obs_trace.reset(token)
    owns_pool = pool is None
    results: list = [None] * n
    resolved = [False] * n
    attempts_started = [0] * n          # non-speculative attempts submitted
    hedged = [False] * n
    completed_ms: list[float] = []      # successful latencies (hedge median)
    inflight: dict[Future, tuple[int, int, bool, float]] = {}
    abandoned: set[Future] = set()      # deadline-expired but still running
    retry_due: list[tuple[float, int, int]] = []  # (due, partition, attempt)
    unresolved = n
    if owns_pool:
        pool = pool_cls(max_workers=config.num_workers)

    def submit(i: int, attempt_no: int, speculative: bool) -> None:
        if not speculative:
            attempts_started[i] += 1
        fut = pool.submit(fn, items[i])
        inflight[fut] = (i, attempt_no, speculative, time.monotonic())

    def inflight_attempts(i: int) -> int:
        return sum(
            1
            for fut, (j, _, _, _) in inflight.items()
            if j == i and fut not in abandoned
        )

    def after_failure(i: int, attempt_no: int, err: BaseException) -> None:
        """A live attempt of unresolved partition ``i`` just failed: retry
        if the budget and error class allow, else — once nothing else is
        running for it — quarantine or raise."""
        reports[i].error = _errstr(err)
        if retryable(err) and attempts_started[i] <= policy.max_retries:
            retry_due.append(
                (time.monotonic() + policy.backoff_delay(attempt_no), i,
                 attempts_started[i])
            )
            return
        if inflight_attempts(i) or any(j == i for _, j, _ in retry_due):
            return  # a twin/retry is still in play; let it decide
        nonlocal unresolved
        resolved[i] = True
        unresolved -= 1
        _fail_partition(reports[i], err, policy)

    # Feed the pool a bounded backlog instead of submitting every
    # partition upfront: a fleet load (load/api.load_fleet) can carry
    # hundreds of partitions, and a full-depth queue defeats both the
    # deadline watchdog (queued futures age without running) and the
    # remote data plane's in-flight quota (every queued partition would
    # open its channels the moment a worker frees up, all at once).
    backlog_cap = max(2 * config.num_workers, 4)
    next_to_submit = 0

    def feed() -> None:
        nonlocal next_to_submit
        while (
            next_to_submit < n
            and len(inflight) - len(abandoned) < backlog_cap
        ):
            submit(next_to_submit, 0, speculative=False)
            next_to_submit += 1

    try:
        feed()
        watch = policy.deadline is not None or policy.hedge_after is not None
        while unresolved:
            feed()
            now = time.monotonic()
            for entry in [e for e in retry_due if e[0] <= now]:
                retry_due.remove(entry)
                _, i, attempt_no = entry
                if not resolved[i]:
                    obs.count("faults.retries")
                    submit(i, attempt_no, speculative=False)
            timeout = None
            if retry_due:
                timeout = max(0.0, min(d for d, _, _ in retry_due) - now)
            if watch:
                timeout = _WATCH_TICK if timeout is None else min(
                    timeout, _WATCH_TICK
                )
            if not inflight:
                if not retry_due:
                    break  # every partition resolved or failed
                time.sleep(timeout or _WATCH_TICK)
                continue
            done, _ = wait(
                list(inflight), timeout=timeout, return_when=FIRST_COMPLETED
            )
            now = time.monotonic()
            for fut in done:
                i, attempt_no, speculative, t0 = inflight.pop(fut)
                stale = fut in abandoned
                abandoned.discard(fut)
                ms = (now - t0) * 1e3
                err = fut.exception()
                if err is None:
                    if resolved[i]:
                        _record(reports[i],
                                Attempt(i, attempt_no, speculative, "lost", ms))
                        continue
                    _record(reports[i],
                            Attempt(i, attempt_no, speculative, "ok", ms))
                    reports[i].status = "ok"
                    results[i] = fut.result()
                    resolved[i] = True
                    unresolved -= 1
                    completed_ms.append(ms)
                else:
                    _record(reports[i],
                            Attempt(i, attempt_no, speculative, "error", ms,
                                    _errstr(err)))
                    if resolved[i] or stale:
                        # Stale: its deadline expiry already scheduled the
                        # recovery; don't double-consume the budget.
                        continue
                    after_failure(i, attempt_no, err)
            if policy.deadline is not None:
                for fut, (i, attempt_no, speculative, t0) in list(
                    inflight.items()
                ):
                    if fut in abandoned or resolved[i]:
                        continue
                    if now - t0 > policy.deadline:
                        abandoned.add(fut)
                        _record(reports[i],
                                Attempt(i, attempt_no, speculative, "timeout",
                                        (now - t0) * 1e3,
                                        "partition deadline exceeded"))
                        if not speculative:
                            after_failure(
                                i, attempt_no,
                                TimeoutError(
                                    f"partition {i} attempt {attempt_no} "
                                    f"exceeded deadline {policy.deadline}s"
                                ),
                            )
            if (
                policy.hedge_after is not None
                and len(completed_ms) >= _HEDGE_MIN_SAMPLES
            ):
                median = statistics.median(completed_ms)
                for fut, (i, attempt_no, speculative, t0) in list(
                    inflight.items()
                ):
                    if speculative or resolved[i] or hedged[i]:
                        continue
                    if fut in abandoned:
                        continue
                    if (now - t0) * 1e3 > policy.hedge_after * median:
                        hedged[i] = True
                        obs.count("faults.hedges")
                        submit(i, attempt_no, speculative=True)
    except BaseException:
        # Strict-mode failure (or interrupt): stop feeding the pool and
        # don't join running attempts — they're discarded, not awaited.
        # A lent pool outlives this job: cancel only our own futures.
        if owns_pool:
            pool.shutdown(wait=False, cancel_futures=True)
        else:
            for fut in inflight:
                fut.cancel()
        raise
    if owns_pool:
        pool.shutdown(wait=False)
    return results


def fold_results(results: Iterable[R], zero, merge) -> object:
    """Accumulator analog: host-side fold of per-partition results."""
    acc = zero
    for r in results:
        acc = merge(acc, r)
    return acc
