"""Host-side partition orchestration.

Replaces the Spark driver/executor substrate (SURVEY.md §2.9): partitions are
planned on the host and executed by a pluggable pool — sequential, threads
(zlib/NumPy release the GIL, so threads saturate cores for this workload), or
processes. The reference's analogous knob is ``ParallelConfig``
(check/.../bam/spark/ParallelConfig.scala:127-148, Threads-vs-Spark).

Accumulator-style reductions become plain fold-left over per-partition
results; device-side reductions (psum over a mesh) live in parallel/mesh.py.
"""

from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Iterable, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


@dataclass(frozen=True)
class ParallelConfig:
    mode: str = "threads"   # sequential | threads | processes
    workers: int = 0        # 0 → os.cpu_count()

    @property
    def num_workers(self) -> int:
        return self.workers or os.cpu_count() or 1

    @staticmethod
    def parse(s: str) -> "ParallelConfig":
        """``"sequential"`` | ``"threads[=N]"`` | ``"processes[=N]"``."""
        if "=" in s:
            mode, n = s.split("=", 1)
            return ParallelConfig(mode, int(n))
        return ParallelConfig(s)


def map_partitions(
    fn: Callable[[T], R],
    items: Sequence[T],
    config: ParallelConfig = ParallelConfig(),
) -> list[R]:
    """Apply ``fn`` to every partition, preserving order."""
    if config.mode == "sequential" or len(items) <= 1:
        return [fn(item) for item in items]
    if config.mode == "threads":
        with ThreadPoolExecutor(max_workers=config.num_workers) as pool:
            return list(pool.map(fn, items))
    if config.mode == "processes":
        with ProcessPoolExecutor(max_workers=config.num_workers) as pool:
            return list(pool.map(fn, items))
    raise ValueError(f"Unknown parallel mode: {config.mode}")


def fold_results(results: Iterable[R], zero, merge) -> object:
    """Accumulator analog: host-side fold of per-partition results."""
    acc = zero
    for r in results:
        acc = merge(acc, r)
    return acc
