"""Write-path knobs: the ``Config.deflate`` string spec.

Same compact-spec pattern as ``faults``/``columnar``/``serve`` so the
frozen Config stays hashable and the ``SPARK_BAM_DEFLATE`` env var and
``--deflate`` CLI flag work through the existing plumbing:

    mode=fixed,level=6,lanes=32,device=auto

``mode`` picks the block codec every BGZF member goes through:

* ``off``    — host ``zlib.compressobj`` (dynamic Huffman), the seed
  behavior; ``level`` is its compression level.
* ``stored`` — stored-block members (BTYPE=00): no entropy coding, just
  framing + CRC32, the fully parallel stage-1 codec.
* ``fixed``  — fixed-Huffman literal-only DEFLATE (BTYPE=01), picking
  the smaller of {fixed, stored} per block the way zlib does.
* ``auto``   — ``fixed`` while the device path is healthy; any device
  error demotes that window to host ``zlib`` (``compress_block``), the
  inflate side's demote-to-host policy mirrored.

``stored``/``fixed`` are *deterministic* codecs: the host reference in
compress/huffman.py produces byte-identical members, so ``device=off``
(or a runtime demotion under those modes) changes nothing but speed.
``lanes`` is the payload batch per device dispatch (the (B, 64 KiB)
kernel geometry); ``device`` force-enables/disables the jax path.
Dynamic Huffman stays a documented non-goal (docs/design.md).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

MODES = ("off", "stored", "fixed", "auto")
DEVICE = ("on", "off", "auto")


@dataclass(frozen=True)
class DeflateConfig:
    mode: str = "off"
    level: int = 6
    lanes: int = 16
    device: str = "auto"

    @property
    def enabled(self) -> bool:
        """True when writes go through the compress/ codec family at all."""
        return self.mode != "off"

    @property
    def deterministic(self) -> bool:
        """True when output bytes are independent of where they were
        computed (stored/fixed have a byte-identical host reference)."""
        return self.mode in ("stored", "fixed")

    @staticmethod
    @functools.lru_cache(maxsize=64)
    def parse(spec: str) -> "DeflateConfig":
        """Parse a ``mode=...,level=...,lanes=...,device=...`` spec ("" ⇒
        defaults, i.e. the host zlib path). Raises ``ValueError`` on
        unknown keys/values — the CLI validates before any work starts,
        like every other knob."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                # Bare token shorthand: "--deflate fixed" reads naturally.
                if part in MODES:
                    kw["mode"] = part
                    continue
                raise ValueError(
                    f"Bad deflate spec {spec!r}: {part!r} is not key=value"
                )
            key, value = part.split("=", 1)
            key, value = key.strip(), value.strip()
            if key == "mode":
                if value not in MODES:
                    raise ValueError(
                        f"Bad deflate mode {value!r}: expected "
                        f"{' | '.join(MODES)}"
                    )
                kw["mode"] = value
            elif key == "level":
                level = int(value)
                if not 0 <= level <= 9:
                    raise ValueError(f"deflate level must be 0..9: {value}")
                kw["level"] = level
            elif key == "lanes":
                lanes = int(value)
                if lanes <= 0:
                    raise ValueError(f"deflate lanes must be positive: {value}")
                kw["lanes"] = lanes
            elif key == "device":
                if value not in DEVICE:
                    raise ValueError(
                        f"Bad deflate device {value!r}: expected "
                        f"{' | '.join(DEVICE)}"
                    )
                kw["device"] = value
            else:
                raise ValueError(f"Unknown deflate key {key!r} in {spec!r}")
        return DeflateConfig(**kw)
