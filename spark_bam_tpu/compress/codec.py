"""Block codecs: the pluggable compressor behind ``BgzfWriter``.

A codec turns uncompressed payloads into complete BGZF members. The
writer drives it through a two-phase ``dispatch``/``materialize`` split
so a device codec overlaps like the inflate pipeline does: dispatch N
(async kernel launch) while materializing batch N-1 (D2H + member
assembly) — real double-buffering, the device never idles on host
framing and the host never idles on the kernel.

Hardening mirrors tpu/inflate.py:

* per-window demote-to-host on ANY device error — under ``stored`` /
  ``fixed`` the host reference (compress/huffman.py) is byte-identical,
  so demotion is invisible in the output; under ``auto`` the escape
  hatch is host zlib (``zlib_member``, the seed ``compress_block``
  body) — different bytes, same validity;
* the demotion warning logs once per codec, every occurrence counts in
  ``deflate.demotions``;
* phase attribution (``deflate.pack_ms`` / ``device_ms`` / ``d2h_ms`` /
  ``host_ms``) lands as gauge + histogram pairs, explicit device syncs
  only under a live registry (``obs.enabled()``) — the production path
  keeps the async dispatch.
"""

from __future__ import annotations

import logging
import time

from spark_bam_tpu import obs
from spark_bam_tpu.compress.config import DeflateConfig
from spark_bam_tpu.compress.huffman import (
    MAX_STORED_PAYLOAD,
    bgzf_member,
    fixed_member,
    stored_body,
    stored_member,
    zlib_member,
)
from spark_bam_tpu.core.guard import LimitExceeded

log = logging.getLogger(__name__)


def attribute_ms(host_ms=None, pack_ms=None, d2h_ms=None, device_ms=None):
    """Write-path phase attribution — the inflate side's gauge+histogram
    convention under the ``deflate.*`` layer. No-op without a registry."""
    r = obs.registry()
    if r is None:
        return
    for name, v in (("deflate.host_ms", host_ms),
                    ("deflate.pack_ms", pack_ms),
                    ("deflate.d2h_ms", d2h_ms),
                    ("deflate.device_ms", device_ms)):
        if v is not None:
            r.gauge(name).set(round(v, 3))
            r.histogram(name, unit="ms").observe(v)


def _check_payloads(payloads) -> None:
    for p in payloads:
        if len(p) > MAX_STORED_PAYLOAD:
            # Truly impossible to emit while guaranteeing a valid member
            # (even the stored fallback overflows BSIZE) — typed, never a
            # demotion candidate.
            raise LimitExceeded(
                f"{len(p)}-byte payload cannot fit any BGZF member "
                f"(max {MAX_STORED_PAYLOAD})"
            )


class HostZlibCodec:
    """mode=off: host ``zlib.compressobj`` per block, the seed path (with
    the stored-fallback hardening from ``zlib_member``)."""

    lanes = 1

    def __init__(self, level: int = 6):
        self.level = level

    def dispatch(self, payloads: "list[bytes]") -> "list[bytes]":
        _check_payloads(payloads)
        return [zlib_member(p, self.level) for p in payloads]

    def materialize(self, pending: "list[bytes]") -> "list[bytes]":
        return pending

    def encode_blocks(self, payloads: "list[bytes]") -> "list[bytes]":
        return self.dispatch(payloads)


class _Pending:
    """One in-flight batch: the payloads (for demotion / stored bodies)
    plus the un-synced device arrays (None ⇒ host-only batch)."""

    __slots__ = ("payloads", "dev", "t_dispatch")

    def __init__(self, payloads, dev, t_dispatch=0.0):
        self.payloads = payloads
        self.dev = dev
        self.t_dispatch = t_dispatch


class DeviceDeflateCodec:
    """mode=stored|fixed|auto: batched device CRC32 (+ fixed-Huffman
    pack), lanes payloads per dispatch, host member assembly."""

    def __init__(self, cfg: DeflateConfig):
        if cfg.mode not in ("stored", "fixed", "auto"):
            raise ValueError(f"DeviceDeflateCodec cannot serve mode={cfg.mode!r}")
        self.cfg = cfg
        self.mode = cfg.mode
        self.lanes = cfg.lanes
        self._kernels = None
        self._device = cfg.device != "off"
        self._warned = False
        if cfg.device == "on":
            self._load_kernels()  # fail loudly now, not mid-write

    # ------------------------------------------------------------ device
    def _load_kernels(self):
        if self._kernels is None:
            from spark_bam_tpu.compress import kernels

            self._kernels = kernels
        return self._kernels

    def _demote(self, exc: Exception) -> None:
        obs.count("deflate.demotions")
        if not self._warned:
            self._warned = True
            log.warning(
                "device deflate unavailable (%s: %s); window demoted to "
                "host %s — output stays valid%s",
                type(exc).__name__, exc,
                "zlib" if self.mode == "auto" else self.mode,
                "" if self.mode == "auto" else " and byte-identical",
            )
        if self.cfg.device == "auto" and self._kernels is None:
            self._device = False  # import failed: stop retrying per window

    def _host_member(self, payload: bytes) -> bytes:
        if self.mode == "stored":
            return stored_member(payload)
        if self.mode == "fixed":
            return fixed_member(payload)
        return zlib_member(payload, self.cfg.level)  # auto's escape hatch

    # ------------------------------------------------------------ phases
    def dispatch(self, payloads: "list[bytes]") -> _Pending:
        _check_payloads(payloads)
        if not payloads or not self._device:
            return _Pending(payloads, None)
        t0 = time.perf_counter()
        try:
            k = self._load_kernels()
            import jax.numpy as jnp

            data, lengths, _ = k.pack_lanes(payloads)
            t1 = time.perf_counter()
            data_dev = jnp.asarray(data)
            lengths_dev = jnp.asarray(lengths)
            if self.mode == "stored":
                dev = (k.crc32_lanes(data_dev, lengths_dev),)
            else:
                dev = k.deflate_fixed_lanes(data_dev, lengths_dev)
            if obs.enabled():
                with obs.span("deflate.dispatch", lanes=len(payloads)):
                    for arr in dev:
                        arr.block_until_ready()
                attribute_ms(pack_ms=(t1 - t0) * 1e3,
                             device_ms=(time.perf_counter() - t1) * 1e3)
            obs.count("deflate.device_windows")
        except LimitExceeded:
            raise
        except Exception as exc:
            self._demote(exc)
            return _Pending(payloads, None)
        return _Pending(payloads, dev, t0)

    def materialize(self, pending: _Pending) -> "list[bytes]":
        import numpy as np

        payloads = pending.payloads
        if not payloads:
            return []
        if pending.dev is None:
            members = [self._host_member(p) for p in payloads]
        else:
            t0 = time.perf_counter()
            try:
                host = [np.asarray(a) for a in pending.dev]
            except Exception as exc:
                self._demote(exc)
                members = [self._host_member(p) for p in payloads]
            else:
                t1 = time.perf_counter()
                members = self._assemble(payloads, host)
                if obs.enabled():
                    attribute_ms(d2h_ms=(t1 - t0) * 1e3,
                                 host_ms=(time.perf_counter() - t1) * 1e3)
        obs.count("compress.members", len(payloads))
        obs.count("compress.batches")
        obs.count("compress.bytes_in", sum(len(p) for p in payloads))
        obs.count("compress.bytes_out", sum(len(m) for m in members))
        return members

    def _assemble(self, payloads, host) -> "list[bytes]":
        """Device results → members; per-lane pick-smaller under fixed.
        Same policy as ``huffman.fixed_member`` so a demoted window is
        byte-identical (stored/fixed modes)."""
        members = []
        stored_n = fixed_n = 0
        if self.mode == "stored":
            (crc,) = host
            for i, p in enumerate(payloads):
                members.append(stored_member(p, crc=int(crc[i])))
            stored_n = len(payloads)
        else:
            packed, total_bits, crc = host
            for i, p in enumerate(payloads):
                nbytes = (int(total_bits[i]) + 7) // 8
                if nbytes >= len(p) + 5:
                    members.append(
                        bgzf_member(stored_body(p), int(crc[i]), len(p))
                    )
                    stored_n += 1
                else:
                    members.append(
                        bgzf_member(
                            packed[i, :nbytes].tobytes(), int(crc[i]), len(p)
                        )
                    )
                    fixed_n += 1
        if stored_n:
            obs.count("compress.stored", stored_n)
        if fixed_n:
            obs.count("compress.fixed", fixed_n)
        return members

    def encode_blocks(self, payloads: "list[bytes]") -> "list[bytes]":
        return self.materialize(self.dispatch(payloads))


def encode_zlib_stream(raw: bytes, spec: "str | None" = None) -> bytes:
    """Zlib-stream encoder for the columnar container's ``codec=deflate``
    buffers (columnar/native.py ``_encode_buffer``): multi-block
    fixed-Huffman DEFLATE wrapped per RFC 1950, device-packed when the
    deflate spec (``spec`` or ``SPARK_BAM_DEFLATE``) enables the device,
    host :func:`huffman.zlib_stream` otherwise. The two paths are
    byte-identical — kernel parity plus the shared bit stitcher — so the
    container stays deterministic across environments. A lane whose
    fixed stream overflows the kernel's output stride (mostly ≥144
    bytes) is re-packed on host; demotion of the whole call follows the
    codec's demote-to-host rule."""
    import os

    from spark_bam_tpu.compress.huffman import (
        fixed_stream_bits,
        zlib_stream,
    )

    if spec is None:
        spec = os.environ.get("SPARK_BAM_DEFLATE", "")
    cfg = DeflateConfig.parse(spec)
    if not cfg.enabled or cfg.device == "off":
        return zlib_stream(raw)
    import struct
    import zlib as _zlib

    import numpy as np

    try:
        from spark_bam_tpu.compress import kernels as k
        import jax.numpy as jnp

        window = MAX_STORED_PAYLOAD
        mv = memoryview(raw)
        nwin = max(1, (len(mv) + window - 1) // window)
        chunks = [bytes(mv[i * window:(i + 1) * window]) for i in range(nwin)]
        data, lengths, _ = k.pack_lanes(chunks)
        packed, total_bits, _crc = k.deflate_fixed_lanes(
            jnp.asarray(data), jnp.asarray(lengths)
        )
        packed = np.asarray(packed)
        total_bits = np.asarray(total_bits)
        obs.count("deflate.device_windows", nwin)
    except LimitExceeded:
        raise
    except Exception:
        return zlib_stream(raw)
    bit_arrays = []
    for i, chunk in enumerate(chunks):
        tb = int(total_bits[i])
        if tb > k.OUT_BYTES * 8:
            # Kernel output stride overflow (incompressible window):
            # host re-pack, same bytes by construction.
            bit_arrays.append(fixed_stream_bits(chunk, final=i == nwin - 1))
        else:
            bit_arrays.append(fixed_stream_bits(
                chunk, final=i == nwin - 1,
                packed=packed[i, :(tb + 7) // 8].tobytes(), total_bits=tb,
            ))
    body = np.packbits(np.concatenate(bit_arrays), bitorder="little").tobytes()
    return (
        b"\x78\x01" + body
        + struct.pack(">I", _zlib.adler32(raw) & 0xFFFFFFFF)
    )


def make_codec(cfg: "DeflateConfig | str | None", level: "int | None" = None):
    """The codec for a deflate spec/config; ``None``/"" /mode=off ⇒ host
    zlib at ``level`` (the seed write path)."""
    if cfg is None:
        cfg = DeflateConfig()
    elif isinstance(cfg, str):
        cfg = DeflateConfig.parse(cfg)
    if level is not None and level != cfg.level:
        cfg = DeflateConfig(cfg.mode, level, cfg.lanes, cfg.device)
    if not cfg.enabled:
        return HostZlibCodec(cfg.level)
    return DeviceDeflateCodec(cfg)
