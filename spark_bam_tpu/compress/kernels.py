"""Device kernels for the write path: batched CRC32 + fixed-Huffman pack.

Mirror image of tpu/inflate.py's geometry: many ≤64 KiB payload lanes
per dispatch, ``(B, STRIDE)`` u8 with the batch dim padded to a power of
two so jit shape churn stays bounded. Both kernels are XLA programs
(jnp + lax) — the same tier the LZ77 resolve kernel runs at; a Pallas
variant would slot in behind the same entry points the way
``lz77_resolve_pallas`` does for inflate.

**CRC32** is the sequential half: slice-by-4 table lookups
(four 256-entry u32 tables as baked constants), one ``fori_loop``
iteration per 4-byte group across all lanes at once. Variable lane
lengths are handled by *masking, not padding*: zero padding would
corrupt the digest, so groups fully inside a lane's length take the
slice-by-4 update while groups straddling the boundary re-compute
byte-wise with per-byte ``where`` masks (identical result where both
apply). The loop bound is the batch's max length, traced.

**Fixed-Huffman pack** is the parallel half: per-byte (nbits, reversed
code) table lookups, an exclusive cumulative sum for every code's
absolute bit offset (3 header bits lead; a 7-bit all-zero end-of-block
trails), then one scatter-add of every *set* bit into a zeroed output
byte plane — bit ``i`` lands in ``out[i >> 3]`` as ``1 << (i & 7)``.
Bit positions are unique so the adds never collide; zero bits and the
zero padding need no writes at all. Lanes whose fixed stream would
exceed the stored alternative scatter with ``mode='drop'`` past the
buffer edge — the codec picks stored for them anyway.

compress/huffman.py holds the byte-identical host reference; parity is
pinned by tests/test_deflate.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE
from spark_bam_tpu.compress.huffman import NBITS, RCODE

#: Fixed lane width — one BGZF payload never exceeds this (bgzf/block.py).
STRIDE = MAX_BLOCK_SIZE
#: Output byte plane per lane: a useful fixed stream is < payload + 5
#: bytes (else stored wins), so STRIDE + 8 covers every kept result.
OUT_BYTES = STRIDE + 8


def _crc_tables() -> np.ndarray:
    """Slice-by-4 CRC32 tables, ``(4, 256) u32``; row 0 is the standard
    reflected CRC-32 (poly 0xEDB88320) byte table."""
    t = np.zeros((4, 256), dtype=np.uint64)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0xEDB88320 if c & 1 else c >> 1
        t[0, i] = c
    for k in range(1, 4):
        prev = t[k - 1]
        t[k] = (prev >> 8) ^ t[0][prev & 0xFF]
    return t.astype(np.uint32)


_T = _crc_tables()


def _crc_body(data: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Traced CRC32 over ``(B, STRIDE)`` u8 lanes of ``lengths`` bytes."""
    t0, t1, t2, t3 = (jnp.asarray(_T[k]) for k in range(4))
    lens = lengths.astype(jnp.int32)

    def lookup(table, idx):
        return jnp.take(table, (idx & 0xFF).astype(jnp.int32))

    def body(g, crc):
        grp = lax.dynamic_slice_in_dim(data, 4 * g, 4, axis=1)
        b = [grp[:, j].astype(jnp.uint32) for j in range(4)]
        word = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
        c = crc ^ word
        full = (
            lookup(t3, c) ^ lookup(t2, c >> 8)
            ^ lookup(t1, c >> 16) ^ lookup(t0, c >> 24)
        )
        # Boundary groups: byte-at-a-time with per-byte validity masks
        # (zero padding would change the digest; masking cannot).
        bw = crc
        for j in range(4):
            step = (bw >> 8) ^ lookup(t0, bw ^ b[j])
            bw = jnp.where(4 * g + j < lens, step, bw)
        return jnp.where(4 * g + 4 <= lens, full, bw)

    n_groups = (jnp.max(lens) + 3) // 4
    crc0 = jnp.full(data.shape[0], 0xFFFFFFFF, dtype=jnp.uint32)
    return lax.fori_loop(0, n_groups, body, crc0) ^ jnp.uint32(0xFFFFFFFF)


@jax.jit
def crc32_lanes(data: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """``(B,) u32`` CRC32 of each lane — the whole device side of stored
    mode (stored bodies are framing around the raw bytes)."""
    return _crc_body(data, lengths)


@jax.jit
def deflate_fixed_lanes(data: jnp.ndarray, lengths: jnp.ndarray):
    """Fixed-Huffman pack + CRC32 for every lane in one program.

    Returns ``(packed (B, OUT_BYTES) u8, total_bits (B,) i32,
    crc (B,) u32)``. ``packed``'s first ``ceil(total_bits / 8)`` bytes
    are the complete DEFLATE body (header bits, codes, end-of-block,
    zero pad) — byte-identical to ``huffman.fixed_pack``. A lane whose
    stream outgrows ``OUT_BYTES`` has its tail bits dropped; its
    ``total_bits`` still reports the true size so the codec's
    pick-smaller step selects stored and never reads the clipped bytes.
    """
    b_dim, stride = data.shape
    byte_idx = data.astype(jnp.int32)
    nb = jnp.take(jnp.asarray(NBITS.astype(np.int32)), byte_idx)
    rc = jnp.take(jnp.asarray(RCODE.astype(np.int32)), byte_idx)
    valid = jnp.arange(stride, dtype=jnp.int32)[None, :] < (
        lengths.astype(jnp.int32)[:, None]
    )
    nbv = jnp.where(valid, nb, 0)
    pos = 3 + jnp.cumsum(nbv, axis=1) - nbv          # exclusive, header-led
    total_bits = 3 + jnp.sum(nbv, axis=1) + 7        # + all-zero EOB

    span = jnp.arange(9, dtype=jnp.int32)[None, None, :]
    bit_idx = pos[:, :, None] + span                 # (B, S, 9)
    live = (
        valid[:, :, None]
        & (span < nb[:, :, None])
        & (((rc[:, :, None] >> span) & 1) == 1)
        & (bit_idx < OUT_BYTES * 8)                  # clip: stored wins there
    )
    lane = jnp.arange(b_dim, dtype=jnp.int32)[:, None, None]
    flat = jnp.where(
        live, lane * OUT_BYTES + (bit_idx >> 3), b_dim * OUT_BYTES
    )
    val = (jnp.int32(1) << (bit_idx & 7)).astype(jnp.uint8)
    out = jnp.zeros(b_dim * OUT_BYTES, dtype=jnp.uint8)
    out = out.at[flat.reshape(-1)].add(val.reshape(-1), mode="drop")
    out = out.reshape(b_dim, OUT_BYTES)
    out = out.at[:, 0].add(3)                        # BFINAL=1, BTYPE=01
    return out, total_bits, _crc_body(data, lengths)


def pack_lanes(payloads: "list[bytes]"):
    """Host staging: payload list → ``(data (B', STRIDE) u8,
    lengths (B',) i32, b)`` with ``B'`` the power-of-two pad of ``b``
    (bounded jit shape churn, the tokenize_pack idiom)."""
    b = len(payloads)
    b_pad = max(1 << max(b - 1, 0).bit_length(), 1)
    data = np.zeros((b_pad, STRIDE), dtype=np.uint8)
    lengths = np.zeros(b_pad, dtype=np.int32)
    for i, p in enumerate(payloads):
        data[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lengths[i] = len(p)
    return data, lengths, b
