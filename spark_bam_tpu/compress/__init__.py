"""Device-side BGZF compression — the write path's mirror of tpu/inflate.

Layers (jax imports stay out of this package's import path until a
device codec actually dispatches):

* config.py  — the ``Config.deflate`` / ``SPARK_BAM_DEFLATE`` spec
* huffman.py — host-reference member builders (the byte authority)
* kernels.py — batched XLA CRC32 + fixed-Huffman pack (lazy import)
* codec.py   — the pluggable ``BgzfWriter`` codec family with
  dispatch/materialize double-buffering and demote-to-host

See docs/design.md, "The write path".
"""

from spark_bam_tpu.compress.codec import (
    DeviceDeflateCodec,
    HostZlibCodec,
    encode_zlib_stream,
    make_codec,
)
from spark_bam_tpu.compress.config import DeflateConfig
from spark_bam_tpu.compress.huffman import (
    MAX_STORED_PAYLOAD,
    bgzf_member,
    fixed_member,
    stored_member,
    zlib_member,
    zlib_stream,
)

__all__ = [
    "DeflateConfig",
    "DeviceDeflateCodec",
    "HostZlibCodec",
    "MAX_STORED_PAYLOAD",
    "bgzf_member",
    "encode_zlib_stream",
    "fixed_member",
    "make_codec",
    "stored_member",
    "zlib_member",
    "zlib_stream",
]
