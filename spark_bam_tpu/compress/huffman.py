"""Host-reference BGZF member builders: stored, fixed-Huffman, zlib.

This module is the *byte authority* for the write path. The device
kernels in compress/kernels.py reproduce ``fixed_pack`` / ``crc32``
bit-for-bit (same bit layout, same zero padding), so a runtime demotion
from device to host under ``mode=stored|fixed`` changes nothing but
speed — the demote-to-host parity property tests/test_deflate.py pins.

Framing recap (every builder returns one complete BGZF member):

    18-byte gzip header (FEXTRA "BC" subfield carrying BSIZE)
    raw-DEFLATE body
    8-byte footer: CRC32(payload), ISIZE = len(payload)

with ``BSIZE = total member size - 1`` a u16 — the format's hard 64 KiB
member bound. A *stored* body is ``\\x01 LEN NLEN payload`` (5 bytes of
framing), so any payload up to :data:`MAX_STORED_PAYLOAD` always fits
regardless of entropy; that makes stored the universal fallback when
zlib output or fixed-Huffman output would overflow BSIZE.

Fixed-Huffman here is literal-only (no LZ77 match search): each byte
costs 8 bits (0–143) or 9 bits (144–255) plus a 3-bit block header and
a 7-bit end-of-block code. Huffman codes are written MSB-first into
DEFLATE's LSB-first bitstream, so the tables below store *bit-reversed*
codes and every writer emits them LSB-first. Dynamic Huffman is a
documented non-goal for this subsystem (docs/design.md, write path).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from spark_bam_tpu.core.guard import LimitExceeded

#: Largest payload a stored-block member can carry:
#: 18 (header) + 5 (stored framing) + payload + 8 (footer) ≤ 65536.
MAX_STORED_PAYLOAD = 0x10000 - 18 - 5 - 8

_HEADER_PREFIX = (
    b"\x1f\x8b\x08\x04"        # gzip magic, deflate, FEXTRA
    b"\x00\x00\x00\x00"        # mtime
    b"\x00\xff"                # XFL, OS
    b"\x06\x00"                # XLEN = 6
    b"BC\x02\x00"              # BC subfield
)


def _fixed_tables() -> "tuple[np.ndarray, np.ndarray]":
    """(nbits[256] u8, bit-reversed code[256] u16) for the RFC 1951 fixed
    literal alphabet restricted to byte values (we never emit matches)."""
    nbits = np.where(np.arange(256) < 144, 8, 9).astype(np.uint8)
    rcode = np.empty(256, dtype=np.uint16)
    for b in range(256):
        code = 0x30 + b if b < 144 else 0x190 + (b - 144)
        n = int(nbits[b])
        rev = 0
        for _ in range(n):
            rev = (rev << 1) | (code & 1)
            code >>= 1
        rcode[b] = rev
    return nbits, rcode


NBITS, RCODE = _fixed_tables()


def bgzf_member(body: bytes, crc: int, isize: int) -> bytes:
    """Frame a raw-DEFLATE body into one BGZF member."""
    bsize = 18 + len(body) + 8
    if bsize > 0x10000:
        raise LimitExceeded(
            f"BGZF member would be {bsize} bytes; the BSIZE field caps "
            f"members at 65536 (body {len(body)}B)"
        )
    return (
        _HEADER_PREFIX
        + struct.pack("<H", bsize - 1)
        + body
        + struct.pack("<II", crc & 0xFFFFFFFF, isize)
    )


def stored_body(payload: bytes) -> bytes:
    """Final stored DEFLATE block: BFINAL=1/BTYPE=00 header byte, then
    LEN/NLEN and the raw bytes."""
    n = len(payload)
    return b"\x01" + struct.pack("<HH", n, n ^ 0xFFFF) + payload


def stored_member(payload: bytes, crc: "int | None" = None) -> bytes:
    """One stored-block BGZF member — the entropy-free universal format.
    ``crc`` lets a device batch supply the already-computed CRC32."""
    if len(payload) > MAX_STORED_PAYLOAD:
        raise LimitExceeded(
            f"{len(payload)}-byte payload cannot fit a stored BGZF member "
            f"(max {MAX_STORED_PAYLOAD})"
        )
    if crc is None:
        crc = zlib.crc32(payload)
    return bgzf_member(stored_body(payload), crc, len(payload))


def fixed_pack(payload: bytes) -> "tuple[bytes, int]":
    """Literal-only fixed-Huffman DEFLATE body for ``payload``; returns
    ``(packed_bytes, total_bits)``. Bit layout (LSB-first within bytes):
    3 header bits (BFINAL=1, BTYPE=01 → 1,1,0), then each byte's
    bit-reversed code, then the 7-bit all-zero end-of-block code; the
    final partial byte is zero-padded. The device kernel reproduces this
    layout exactly (scatter-add of set bits into a zero buffer)."""
    arr = np.frombuffer(payload, dtype=np.uint8)
    nb = NBITS[arr].astype(np.int64)
    total = 3 + int(nb.sum()) + 7
    bits = np.zeros(total, dtype=np.uint8)
    bits[0] = 1
    bits[1] = 1  # BTYPE=01, LSB first: 1 then 0 (bits[2] stays 0)
    if len(arr):
        pos = 3 + np.cumsum(nb) - nb
        span = np.arange(9)
        idx = pos[:, None] + span[None, :]
        sel = span[None, :] < nb[:, None]
        vals = (RCODE[arr][:, None].astype(np.int64) >> span[None, :]) & 1
        bits[idx[sel]] = vals[sel]
    # EOB = 7 zero bits: already zero, only accounted in ``total``.
    return np.packbits(bits, bitorder="little").tobytes(), total


def fixed_member(
    payload: bytes,
    crc: "int | None" = None,
    packed: "bytes | None" = None,
) -> bytes:
    """One fixed-Huffman BGZF member, demoting to stored when stored is
    no larger (zlib's own pick-smaller policy; high-entropy payloads
    cost 9 bits/byte under the fixed alphabet). ``packed`` lets a device
    batch supply the already-packed body."""
    if len(payload) > MAX_STORED_PAYLOAD:
        raise LimitExceeded(
            f"{len(payload)}-byte payload cannot fit a stored BGZF member "
            f"(max {MAX_STORED_PAYLOAD})"
        )
    if packed is None:
        packed, _ = fixed_pack(payload)
    if crc is None:
        crc = zlib.crc32(payload)
    if len(packed) >= len(payload) + 5:
        return bgzf_member(stored_body(payload), crc, len(payload))
    return bgzf_member(packed, crc, len(payload))


def fixed_stream_bits(
    payload: bytes,
    final: bool,
    packed: "bytes | None" = None,
    total_bits: "int | None" = None,
) -> np.ndarray:
    """One fixed-Huffman DEFLATE block as a u8 bit array (LSB-first
    order), BFINAL set per ``final`` — the stitching unit for multi-block
    streams. ``packed``/``total_bits`` let a device batch supply the
    already-packed body (:func:`fixed_pack` layout, BFINAL=1); the bit
    is rewritten here, so device and host chunks stitch identically."""
    if packed is None:
        packed, total_bits = fixed_pack(payload)
    bits = np.unpackbits(
        np.frombuffer(packed, dtype=np.uint8), bitorder="little"
    )[:total_bits].copy()
    bits[0] = 1 if final else 0
    return bits


def zlib_stream(payload: bytes, window: int = MAX_STORED_PAYLOAD) -> bytes:
    """A spec-valid RFC 1950 zlib stream over ``payload``: ``0x78 0x01``
    header, one literal-only fixed-Huffman DEFLATE block per ``window``
    bytes (BFINAL only on the last), Adler-32 trailer. This is the
    columnar container's ``codec=deflate`` buffer encoding —
    ``zlib.decompress`` reads it unchanged, so the read side needs no new
    code. Fixed blocks have no BSIZE cap, so any payload length works;
    windowing exists only to match the device kernel's lane stride."""
    mv = memoryview(payload)
    nwin = max(1, (len(mv) + window - 1) // window)
    bits = np.concatenate([
        fixed_stream_bits(bytes(mv[i * window:(i + 1) * window]),
                          final=(i == nwin - 1))
        for i in range(nwin)
    ])
    body = np.packbits(bits, bitorder="little").tobytes()
    return (
        b"\x78\x01" + body
        + struct.pack(">I", zlib.adler32(payload) & 0xFFFFFFFF)
    )


def zlib_member(payload: bytes, level: int = 6) -> bytes:
    """One BGZF member via host zlib (dynamic Huffman) — the seed
    ``compress_block`` behavior plus the stored fallback: an
    incompressible payload whose zlib output overflows BSIZE demotes to
    a stored member (bounded 5-byte expansion, always fits up to
    :data:`MAX_STORED_PAYLOAD`); only a payload too big even for stored
    is a true :class:`LimitExceeded`."""
    compressor = zlib.compressobj(level, zlib.DEFLATED, -15)
    comp = compressor.compress(payload) + compressor.flush()
    crc = zlib.crc32(payload)
    if 18 + len(comp) + 8 > 0x10000:
        return stored_member(payload, crc)
    return bgzf_member(comp, crc, len(payload))
