from spark_bam_tpu.bgzf.header import (
    Header,
    HeaderParseException,
    HeaderSearchFailedException,
)
from spark_bam_tpu.bgzf.block import Block, Metadata, MAX_BLOCK_SIZE, FOOTER_SIZE
from spark_bam_tpu.bgzf.stream import (
    BlockStream,
    SeekableBlockStream,
    MetadataStream,
    UncompressedBytes,
    SeekableUncompressedBytes,
    pos_iterator,
)
from spark_bam_tpu.bgzf.find_block_start import find_block_start

__all__ = [
    "Header",
    "HeaderParseException",
    "HeaderSearchFailedException",
    "Block",
    "Metadata",
    "MAX_BLOCK_SIZE",
    "FOOTER_SIZE",
    "BlockStream",
    "SeekableBlockStream",
    "MetadataStream",
    "UncompressedBytes",
    "SeekableUncompressedBytes",
    "pos_iterator",
    "find_block_start",
]
