"""Single-pass BGZF block indexer → ``.blocks`` sidecar.

Emits ``start,compressedSize,uncompressedSize`` per block (reference
bgzf/.../index/IndexBlocks.scala:11-52; line format :42). The sidecar is the
durable accelerator consumed by the split planner (check/blocks.py) — reading
it skips the parallel block search.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterable

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.core.channel import (
    is_url,
    open_channel,
    path_exists,
    path_size,
)
from spark_bam_tpu.core.faults import Unrecoverable

log = logging.getLogger(__name__)


class StaleBlocksIndexError(IOError, Unrecoverable):
    """Strict mode: the ``.blocks`` sidecar contradicts the BAM it names.
    Deterministic — retrying the read cannot reconcile them."""


def format_block_line(meta: Metadata) -> str:
    return f"{meta.start},{meta.compressed_size},{meta.uncompressed_size}"


def parse_block_line(line: str) -> Metadata:
    parts = line.strip().split(",")
    if len(parts) != 3:
        raise ValueError(f"Bad blocks-index line: {line!r}")
    return Metadata(int(parts[0]), int(parts[1]), int(parts[2]))


def read_blocks_index(path) -> list[Metadata]:
    from spark_bam_tpu.core.channel import read_text

    return [
        parse_block_line(line)
        for line in read_text(path).splitlines()
        if line.strip()
    ]


def index_blocks(
    bam_path, out_path=None, heartbeat_seconds: float = 10.0
) -> tuple[str, int]:
    """Write the ``.blocks`` sidecar for ``bam_path``; returns (path, #blocks)."""
    out_path = str(out_path) if out_path is not None else str(bam_path) + ".blocks"
    count = 0
    last_beat = time.monotonic()
    # Write-then-rename (pid-suffixed: concurrent indexers must not
    # interleave): a crash mid-index must never leave a truncated sidecar.
    tmp_path = f"{out_path}.tmp{os.getpid()}"
    try:
        with open_channel(bam_path) as ch, open(tmp_path, "w") as out:
            for meta in MetadataStream(ch):
                out.write(format_block_line(meta) + "\n")
                count += 1
                now = time.monotonic()
                if now - last_beat >= heartbeat_seconds:
                    log.info(
                        "indexed %d blocks (at offset %d)", count, meta.start
                    )
                    last_beat = now
        os.replace(tmp_path, out_path)
    finally:
        if os.path.exists(tmp_path):  # failure path only; replace moved it
            os.unlink(tmp_path)
    return out_path, count


def validate_blocks_index(blocks: list[Metadata], file_size: int) -> str | None:
    """Why ``blocks`` cannot describe a BAM of ``file_size`` bytes, or None
    when it checks out: non-empty, starting at 0, a contiguous chain, and
    covering the file up to an optional 28-byte BGZF EOF sentinel (which
    ``MetadataStream`` excludes from the index)."""
    if not blocks:
        return "empty index for a non-empty file" if file_size else None
    if blocks[0].start != 0:
        return f"first block starts at {blocks[0].start}, not 0"
    for prev, cur in zip(blocks, blocks[1:]):
        if prev.start + prev.compressed_size != cur.start:
            return (
                f"gap/overlap at offset {cur.start}: previous block ends at "
                f"{prev.start + prev.compressed_size}"
            )
    last_end = blocks[-1].start + blocks[-1].compressed_size
    if file_size - last_end not in (0, 28):
        return (
            f"index covers {last_end} of {file_size} bytes "
            "(not an EOF-sentinel remainder)"
        )
    return None


def blocks_metadata(
    bam_path, strict: bool = False, config=None
) -> Iterable[Metadata]:
    """All block Metadata of a BAM: from the ``.blocks`` sidecar when
    present *and* consistent with the file (start-chain contiguity + size
    coverage — a stale sidecar from an overwritten BAM must not poison the
    split plan), else from the ``.sbi`` cache tier (fingerprint-validated;
    sbi/store.py), else by scan — with the scan result written through to
    the ``.sbi`` tier so the next load (and every fleet member after the
    first) derives its fetch plan without touching the BAM body.
    ``strict`` raises on a stale sidecar instead of silently rescanning,
    mirroring FaultPolicy's strict mode."""
    from spark_bam_tpu.sbi.store import cached_blocks, store_blocks

    remote = is_url(str(bam_path))
    if remote:
        # Remote paths consult the ``.sbi`` tier FIRST: a warm hit costs
        # two round-trips (the fingerprint's size + head-CRC probe), while
        # the ``.blocks`` existence probe alone is a round-trip against a
        # sidecar that usually does not exist. Local paths keep
        # sidecar-first — the existence check is free and a user-authored
        # sidecar should win. The fingerprint binds the hit to the current
        # file bytes, so precedence cannot serve a stale table.
        blocks = cached_blocks(bam_path, config)
        if blocks is not None:
            return blocks
    sidecar = str(bam_path) + ".blocks"
    if path_exists(sidecar):
        blocks = read_blocks_index(sidecar)
        reason = validate_blocks_index(blocks, path_size(bam_path))
        if reason is None:
            return blocks
        if strict:
            raise StaleBlocksIndexError(f"{sidecar}: {reason}")
        from spark_bam_tpu import obs

        obs.count("cache.invalidations")
        log.warning(
            "ignoring stale .blocks sidecar %s (%s); rescanning", sidecar,
            reason,
        )
    if not remote:
        blocks = cached_blocks(bam_path, config)
        if blocks is not None:
            return blocks
    with open_channel(bam_path) as ch:
        blocks = list(MetadataStream(ch))
    try:
        store_blocks(bam_path, blocks, config)
    except Exception:  # write-through is an accelerator, never a failure
        log.debug("block-table write-through failed", exc_info=True)
    return blocks
