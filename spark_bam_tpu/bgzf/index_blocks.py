"""Single-pass BGZF block indexer → ``.blocks`` sidecar.

Emits ``start,compressedSize,uncompressedSize`` per block (reference
bgzf/.../index/IndexBlocks.scala:11-52; line format :42). The sidecar is the
durable accelerator consumed by the split planner (check/blocks.py) — reading
it skips the parallel block search.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterable

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.core.channel import open_channel, path_exists

log = logging.getLogger(__name__)


def format_block_line(meta: Metadata) -> str:
    return f"{meta.start},{meta.compressed_size},{meta.uncompressed_size}"


def parse_block_line(line: str) -> Metadata:
    parts = line.strip().split(",")
    if len(parts) != 3:
        raise ValueError(f"Bad blocks-index line: {line!r}")
    return Metadata(int(parts[0]), int(parts[1]), int(parts[2]))


def read_blocks_index(path) -> list[Metadata]:
    from spark_bam_tpu.core.channel import read_text

    return [
        parse_block_line(line)
        for line in read_text(path).splitlines()
        if line.strip()
    ]


def index_blocks(
    bam_path, out_path=None, heartbeat_seconds: float = 10.0
) -> tuple[str, int]:
    """Write the ``.blocks`` sidecar for ``bam_path``; returns (path, #blocks)."""
    out_path = str(out_path) if out_path is not None else str(bam_path) + ".blocks"
    count = 0
    last_beat = time.monotonic()
    # Write-then-rename (pid-suffixed: concurrent indexers must not
    # interleave): a crash mid-index must never leave a truncated sidecar
    # (blocks_metadata trusts it blindly, as the reference does).
    tmp_path = f"{out_path}.tmp{os.getpid()}"
    try:
        with open_channel(bam_path) as ch, open(tmp_path, "w") as out:
            for meta in MetadataStream(ch):
                out.write(format_block_line(meta) + "\n")
                count += 1
                now = time.monotonic()
                if now - last_beat >= heartbeat_seconds:
                    log.info(
                        "indexed %d blocks (at offset %d)", count, meta.start
                    )
                    last_beat = now
        os.replace(tmp_path, out_path)
    finally:
        if os.path.exists(tmp_path):  # failure path only; replace moved it
            os.unlink(tmp_path)
    return out_path, count


def blocks_metadata(bam_path) -> Iterable[Metadata]:
    """All block Metadata of a BAM: from the sidecar if present, else by scan."""
    sidecar = str(bam_path) + ".blocks"
    if path_exists(sidecar):
        return read_blocks_index(sidecar)
    with open_channel(bam_path) as ch:
        return list(MetadataStream(ch))
