"""BGZF block values (reference bgzf/.../block/Block.scala, Metadata.scala)."""

from __future__ import annotations

from dataclasses import dataclass, field

from spark_bam_tpu.core.guard import StructurallyInvalid
from spark_bam_tpu.core.pos import Pos

MAX_BLOCK_SIZE = 64 * 1024  # uncompressed payload never exceeds 64 KiB
FOOTER_SIZE = 8             # CRC32 + uncompressed-size, both u32
# A member's raw-DEFLATE payload can't exceed BSIZE's u16 ceiling minus
# the minimal wrapper (18-byte header + 8-byte footer): the bound the
# device tokenizer's staged-row width is sized against (bgzf/flat.py
# stage_run_payloads).
MAX_COMPRESSED_PAYLOAD = (1 << 16) - 18 - FOOTER_SIZE


def check_isize(uncompressed_size: int, start: int) -> int:
    """Validate a block footer's ISIZE before anything allocates on it —
    a corrupt 4 GB ISIZE sizes the inflate buffer otherwise."""
    if uncompressed_size > MAX_BLOCK_SIZE:
        raise StructurallyInvalid(
            f"BGZF ISIZE {uncompressed_size} exceeds the "
            f"{MAX_BLOCK_SIZE}-byte block limit", pos=start
        )
    return uncompressed_size


@dataclass(frozen=True)
class Metadata:
    """Block coordinates without the payload."""
    start: int             # compressed-file offset of the block start
    compressed_size: int
    uncompressed_size: int


@dataclass
class Block:
    """Decompressed block payload + coordinates; carries a read cursor ``idx``."""
    data: bytes
    start: int
    compressed_size: int
    idx: int = field(default=0, compare=False)

    @property
    def uncompressed_size(self) -> int:
        return len(self.data)

    @property
    def pos(self) -> Pos:
        return Pos(self.start, self.idx)

    @property
    def next_start(self) -> int:
        return self.start + self.compressed_size

    def metadata(self) -> Metadata:
        return Metadata(self.start, self.compressed_size, self.uncompressed_size)
