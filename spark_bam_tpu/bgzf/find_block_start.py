"""BGZF block-boundary search from an arbitrary compressed offset.

Scan forward ≤ MAX_BLOCK_SIZE bytes; the first offset where
``bgzf_blocks_to_check`` consecutive block headers parse wins
(reference bgzf/.../block/FindBlockStart.scala:8-36; false-positive
probability ≈ 2^(-32N)).

Three implementations:
- ``find_block_start``      — the production scan: one vectorized
  single-header mask over the window pre-filters candidates (the mask is
  exactly ``Header.parse``'s fixed-byte contract, so it admits no false
  negatives), then the sequential chain check verifies each — identical
  results to the faithful scan at ~1/10,000 the Python-bytecode cost
  (split resolution runs this once per split; at WGS scale that was the
  load path's dominant term)
- ``find_block_start_sequential`` — the faithful per-offset scan
  (reference FindBlockStart.scala:8-36 shape); the differential oracle
- ``find_block_starts_np``  — vectorized NumPy scan over an in-memory window,
  used by the TPU-era split planner to resolve many shard starts at once
"""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE
from spark_bam_tpu.bgzf.header import Header, HeaderSearchFailedException
from spark_bam_tpu.core.channel import ByteChannel
from spark_bam_tpu.core.guard import StructurallyInvalid


def find_block_start(
    ch: ByteChannel,
    start: int,
    bgzf_blocks_to_check: int = 5,
    path: str = "<channel>",
) -> int:
    """First valid block-start offset ≥ ``start``.

    Offsets that could possibly parse (the 7 fixed header bytes — magic,
    FLG.FEXTRA, leading BC subfield — match) come from one NumPy mask over
    the ≤64 KiB scan window; only those run the sequential chain check, so
    the result (and every exception surface) is identical to
    ``find_block_start_sequential`` by construction.
    """
    size = ch.size
    span = min(MAX_BLOCK_SIZE, max(size - start, 0))
    window = np.frombuffer(
        ch.read_at(start, min(span + 17, max(size - start, 0))), dtype=np.uint8
    )
    m = len(window) - 17
    if m > 0:
        w = window
        ok = (
            (w[0:m] == 31)
            & (w[1:m + 1] == 139)
            & (w[2:m + 2] == 8)
            & (w[3:m + 3] == 4)
            & (w[12:m + 12] == 66)
            & (w[13:m + 13] == 67)
            & (w[14:m + 14] == 2)
        )
        for off in np.flatnonzero(ok).tolist():
            try:
                _check_chain(ch, start + off, bgzf_blocks_to_check)
                return start + off
            except (StructurallyInvalid, EOFError):
                # HeaderParseException or a bad XLEN/BSIZE: not a block start.
                continue
    raise HeaderSearchFailedException(path, start, min(MAX_BLOCK_SIZE, size - start))


def find_block_start_sequential(
    ch: ByteChannel,
    start: int,
    bgzf_blocks_to_check: int = 5,
    path: str = "<channel>",
) -> int:
    """The faithful per-offset scan (reference FindBlockStart.scala:8-36) —
    the differential oracle for ``find_block_start``."""
    size = ch.size
    for delta in range(MAX_BLOCK_SIZE):
        pos = start + delta
        if pos >= size:
            break
        try:
            _check_chain(ch, pos, bgzf_blocks_to_check)
            return pos
        except (StructurallyInvalid, EOFError):
            continue
    raise HeaderSearchFailedException(path, start, min(MAX_BLOCK_SIZE, size - start))


def _check_chain(ch: ByteChannel, pos: int, n: int) -> None:
    """Parse up to n consecutive headers starting at pos (EOF earlier is OK
    only if at least the first header parsed — mirrors MetadataStream.take(n)
    which succeeds with fewer elements at EOF)."""
    ch.seek(pos)
    for i in range(n):
        try:
            header = Header.read(ch)
        except EOFError:
            if i == 0:
                raise
            return
        ch.skip(header.compressed_size - header.size)


def find_block_starts_np(
    buf: np.ndarray, n_chain: int = 5, base: int = 0
) -> np.ndarray:
    """All offsets in ``buf`` where ``n_chain`` consecutive BGZF headers parse.

    ``buf`` is a uint8 window of the compressed file starting at file offset
    ``base``. An offset qualifies if headers chain ``n_chain`` deep *within
    the window* (chains running off the window end count, matching the
    sequential scan's EOF tolerance only when the window is the file tail —
    callers pass windows padded by ``n_chain`` max-size blocks to avoid that
    edge). Returns absolute file offsets.
    """
    n = len(buf)
    if n < 18:
        return np.empty(0, dtype=np.int64)
    # Single-header validity mask over every offset with 18 bytes available.
    m = n - 17
    ok = (
        (buf[0:m] == 31)
        & (buf[1:m + 1] == 139)
        & (buf[2:m + 2] == 8)
        & (buf[3:m + 3] == 4)
        & (buf[12:m + 12] == 66)
        & (buf[13:m + 13] == 67)
        & (buf[14:m + 14] == 2)
    )
    # Match Header.parse's structural checks: XLEN must hold the BC
    # subfield and BSIZE must cover header + footer (xlen + 20 bytes).
    xlen = (
        buf[10:m + 10].astype(np.int64) | (buf[11:m + 11].astype(np.int64) << 8)
    )
    csize = (
        buf[16:m + 16].astype(np.int64) | (buf[17:m + 17].astype(np.int64) << 8)
    ) + 1
    ok &= (xlen >= 6) & (csize >= xlen + 20)
    nxt = np.arange(m, dtype=np.int64) + csize
    # Chain n_chain-1 jumps: header at i valid & header at i+csize valid & ...
    chain_ok = ok.copy()
    cur = nxt.copy()
    for _ in range(n_chain - 1):
        in_window = cur < m
        # Off-window chains: treat as OK (padded windows make this the EOF case).
        step_ok = np.where(in_window, ok[np.minimum(cur, m - 1)], True)
        chain_ok &= step_ok
        cur = np.where(in_window, nxt[np.minimum(cur, m - 1)], cur)
    return np.flatnonzero(chain_ok).astype(np.int64) + base
