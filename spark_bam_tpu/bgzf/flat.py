"""Flat uncompressed views of BGZF files.

The vectorized checkers operate on *flat buffers*: the concatenated
uncompressed payloads of a run of blocks, plus the block table needed to map
``Pos(block, offset) ↔ flat index``. This replaces the reference's per-byte
``UncompressedBytes`` iterators for all bulk work (SURVEY.md §7 step 4a:
"inflate on host, ship uncompressed blocks to HBM").

Inflation fans out across threads: zlib releases the GIL, so a thread pool
saturates host cores (the Pallas in-device inflate is the planned upgrade,
tpu/inflate.py).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from spark_bam_tpu import obs
from spark_bam_tpu.bgzf.block import Metadata, FOOTER_SIZE
from spark_bam_tpu.bgzf.header import Header
from spark_bam_tpu.bgzf.stream import MetadataStream, inflate_block_payload
from spark_bam_tpu.core.channel import ByteChannel, MMapChannel, open_channel


@dataclass
class FlatView:
    """Uncompressed bytes of blocks[first:last] of a file, flat-addressable."""

    data: np.ndarray          # uint8, concatenated uncompressed payloads
    block_starts: np.ndarray  # int64, compressed-file offset per block
    block_flat: np.ndarray    # int64, flat offset of each block's first byte
    file_total: int | None    # total flat size of the *whole* file, if known
    at_eof: bool = False      # view ends exactly at the file's uncompressed end

    @property
    def size(self) -> int:
        return int(self.data.shape[0])

    def flat_of_pos(self, block_pos: int, offset: int) -> int:
        i = int(np.searchsorted(self.block_starts, block_pos))
        if i >= len(self.block_starts) or self.block_starts[i] != block_pos:
            raise KeyError(f"block {block_pos} not in view")
        return int(self.block_flat[i]) + offset

    def pos_of_flat(self, flat: int) -> tuple[int, int]:
        return pos_of_flat_tables(self.block_starts, self.block_flat, flat)

    def pos_of_flat_many(self, flat: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        idx = np.searchsorted(self.block_flat, flat, side="right") - 1
        return self.block_starts[idx], flat - self.block_flat[idx]


def metas_block_table(metas) -> tuple[np.ndarray, np.ndarray]:
    """(block_starts, block_flat) arrays for a Metadata list — the same
    tables a FlatView carries, without inflating any payloads."""
    block_starts = np.array([m.start for m in metas], dtype=np.int64)
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    block_flat = np.zeros(len(metas), dtype=np.int64)
    if len(metas):
        np.cumsum(usizes[:-1], out=block_flat[1:])
    return block_starts, block_flat


def pos_of_flat_tables(
    block_starts: np.ndarray, block_flat: np.ndarray, flat: int
) -> tuple[int, int]:
    """Flat offset → (block_pos, intra-block offset); the single source of
    truth for the boundary convention (shared with FlatView.pos_of_flat)."""
    i = int(np.searchsorted(block_flat, flat, side="right")) - 1
    return int(block_starts[i]), int(flat - block_flat[i])


def read_block_payload(ch: ByteChannel, meta: Metadata):
    """The raw-DEFLATE payload bytes of one block (header/footer stripped);
    zero-copy on mmap-backed channels."""
    if isinstance(ch, MMapChannel):
        comp = ch.memoryview(meta.start, meta.compressed_size)
    else:
        # Positioned read: no shared-cursor mutation, safe for the
        # concurrent block readers above this.
        comp = ch.read_at(meta.start, meta.compressed_size)
        if len(comp) != meta.compressed_size:
            raise EOFError(
                f"wanted {meta.compressed_size} bytes at {meta.start}, "
                f"got {len(comp)}"
            )
    header = Header.parse(comp[:18])
    return comp[header.size: meta.compressed_size - FOOTER_SIZE]


def read_run_payloads(
    ch: ByteChannel, metas: list[Metadata], threads: int = 8
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """``(comp, offsets, lengths)`` for a run of blocks: a u8 buffer plus
    each block's raw-DEFLATE payload ``(offset, length)`` into it.

    A contiguous run — the BGZF norm, and what the window planner hands
    out — is fetched with ONE positioned read, so a plan-driven remote
    channel (core/remote_plan.py) sees a single large request instead of
    one call per block; per-call locking/assembly overhead is what
    dominates thousand-block windows on a busy host. Non-contiguous runs
    fan per-block reads across ``threads`` so high-latency channels still
    overlap round-trips."""
    offsets = np.empty(len(metas), dtype=np.int64)
    lengths = np.empty(len(metas), dtype=np.int64)
    if not metas:
        return np.empty(0, dtype=np.uint8), offsets, lengths
    lo = metas[0].start
    hi = metas[-1].start + metas[-1].compressed_size
    if hi - lo == sum(m.compressed_size for m in metas):
        blob = ch.read_at(lo, hi - lo)
        if len(blob) != hi - lo:
            raise EOFError(f"wanted {hi - lo} bytes at {lo}, got {len(blob)}")
        for i, m in enumerate(metas):
            at = m.start - lo
            header = Header.parse(blob[at: at + 18])
            offsets[i] = at + header.size
            lengths[i] = m.compressed_size - header.size - FOOTER_SIZE
        return np.frombuffer(blob, dtype=np.uint8), offsets, lengths
    with ThreadPoolExecutor(max_workers=min(8, max(threads, 1))) as pool:
        parts = list(
            pool.map(
                lambda m: np.frombuffer(
                    read_block_payload(ch, m), dtype=np.uint8
                ),
                metas,
            )
        )
    off = 0
    for i, part in enumerate(parts):
        offsets[i] = off
        lengths[i] = len(part)
        off += len(part)
    comp = np.concatenate(parts) if parts else np.empty(0, dtype=np.uint8)
    return comp, offsets, lengths


def stage_run_payloads(
    ch: ByteChannel, metas: list[Metadata], threads: int = 8
) -> tuple[np.ndarray, np.ndarray]:
    """Stage a run of blocks' raw-DEFLATE payloads for the device
    tokenizer: ``(staged (B_pad, C_pad) u8, clens (B_pad,) i32)``.

    The bit-reader kernel (tpu/tokenize_device.py) wants one row per
    block, zero-padded so its 4-byte bit reads never leave the row
    (≥ 8 bytes of tail slack). Both dims are padded to powers of two —
    rows because jit shape churn must stay log-bounded (the
    ``tokenize_pack`` batch policy), columns because a window's blocks
    share one compiled kernel; ``MAX_COMPRESSED_PAYLOAD`` bounds C_pad
    at 64 KiB. Pad rows have ``clen == 0`` (callers treat them as
    vacuously valid). This is the ONLY buffer that crosses H2D in
    device-tokenize mode — compressed bytes, not token planes."""
    from spark_bam_tpu.bgzf.block import MAX_COMPRESSED_PAYLOAD

    comp, offsets, lengths = read_run_payloads(ch, metas, threads=threads)
    b = len(metas)
    b_pad = max(1 << max(b - 1, 0).bit_length(), 1)
    longest = int(lengths.max()) if b else 0
    if longest > MAX_COMPRESSED_PAYLOAD:
        raise EOFError(
            f"raw payload of {longest} bytes exceeds the BGZF "
            f"{MAX_COMPRESSED_PAYLOAD}-byte ceiling"
        )
    c_pad = max(1 << max(longest + 8 - 1, 0).bit_length(), 1024)
    staged = np.zeros((b_pad, c_pad), dtype=np.uint8)
    for i in range(b):
        o, n = int(offsets[i]), int(lengths[i])
        staged[i, :n] = comp[o: o + n]
    clens = np.zeros(b_pad, dtype=np.int32)
    clens[:b] = lengths
    return staged, clens


def _inflate_one(ch: ByteChannel, meta: Metadata, out: np.ndarray, flat_off: int):
    payload = read_block_payload(ch, meta)
    data = inflate_block_payload(payload, meta.uncompressed_size)
    out[flat_off: flat_off + len(data)] = np.frombuffer(data, dtype=np.uint8)


def _inflate_fast_native(
    ch: ByteChannel, metas: list[Metadata], out: np.ndarray, block_flat: np.ndarray,
    usizes: np.ndarray, threads: int = 1,
) -> bool:
    """Batched native fast inflate. On mmap channels the compressed bytes
    are consumed zero-copy straight from the page cache. With ``threads``,
    contiguous block slices inflate in parallel (the C call releases the
    GIL); each slice writes a disjoint, exact-size output region, so
    word-copy slack never races a neighbour. Returns False when the native
    library is unavailable."""
    from spark_bam_tpu.native.build import inflate_blocks_fast_into, load_native

    if load_native() is None or not metas:
        return False
    offsets = np.empty(len(metas), dtype=np.int64)
    lengths = np.empty(len(metas), dtype=np.int64)
    if isinstance(ch, MMapChannel):
        comp = np.frombuffer(ch.memoryview(0, ch.size), dtype=np.uint8)
        for i, m in enumerate(metas):
            header = Header.parse(ch.memoryview(m.start, 18))
            offsets[i] = m.start + header.size
            lengths[i] = m.compressed_size - header.size - FOOTER_SIZE
    else:
        comp, offsets, lengths = read_run_payloads(ch, metas, threads=threads)

    n_chunks = max(1, min(threads, len(metas) // 32))
    if n_chunks == 1:
        return inflate_blocks_fast_into(
            comp, offsets, lengths, out, block_flat, usizes
        )
    bounds = np.linspace(0, len(metas), n_chunks + 1, dtype=np.int64)

    def run_chunk(k: int) -> bool:
        lo, hi = int(bounds[k]), int(bounds[k + 1])
        flat_lo = int(block_flat[lo])
        flat_hi = (
            len(out) if hi == len(metas) else int(block_flat[hi])
        )
        return inflate_blocks_fast_into(
            comp, offsets[lo:hi], lengths[lo:hi],
            out[flat_lo:flat_hi], block_flat[lo:hi] - flat_lo, usizes[lo:hi],
        )

    with ThreadPoolExecutor(max_workers=n_chunks) as pool:
        return all(pool.map(run_chunk, range(n_chunks)))


def inflate_blocks(
    ch: ByteChannel,
    metas: list[Metadata],
    file_total: int | None = None,
    at_eof: bool = False,
    threads: int = 8,
) -> FlatView:
    """Inflate a run of blocks into one flat buffer.

    Prefers the native table-driven decoder (~1.3-2x zlib, single call for the
    whole run); falls back to parallel host zlib when the native library is
    unavailable.
    """
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    block_flat = np.zeros(len(metas), dtype=np.int64)
    if len(metas):
        np.cumsum(usizes[:-1], out=block_flat[1:])
    total = int(usizes.sum())
    # 8 bytes of slack: the native decoder's word copies may overrun a
    # block's end (never the allocation); the view handed out is exact.
    out_alloc = np.empty(total + 8, dtype=np.uint8)
    out = out_alloc[:total]
    with obs.span("inflate.window", blocks=len(metas), bytes=total) as sp:
        native = _inflate_fast_native(
            ch, metas, out_alloc, block_flat, usizes, threads=threads
        )
        if not native:
            if len(metas) > 1 and threads > 1:
                with ThreadPoolExecutor(max_workers=threads) as pool:
                    list(
                        pool.map(
                            lambda im: _inflate_one(
                                ch, im[1], out, int(block_flat[im[0]])
                            ),
                            enumerate(metas),
                        )
                    )
            else:
                for i, m in enumerate(metas):
                    _inflate_one(ch, m, out, int(block_flat[i]))
        sp.set(engine="native" if native else "zlib")
    obs.count("inflate.windows")
    obs.count("inflate.blocks", len(metas))
    obs.count("inflate.bytes", total)
    return FlatView(
        out,
        np.array([m.start for m in metas], dtype=np.int64),
        block_flat,
        file_total,
        at_eof or (file_total is not None and total == file_total),
    )


def flatten_file(path, threads: int = 8) -> FlatView:
    """Inflate an entire BAM into one flat buffer (fixtures / small files)."""
    with open_channel(path) as ch, obs.span(
        "bgzf.read", kind="metadata_scan", path=str(path)
    ):
        metas = list(MetadataStream(ch))
    with open_channel(path) as ch:
        total = sum(m.uncompressed_size for m in metas)
        return inflate_blocks(ch, metas, file_total=total, at_eof=True, threads=threads)
