"""BGZF block streams and uncompressed-byte views.

Host-side equivalents of the reference's block layer
(bgzf/.../block/{Stream,MetadataStream,UncompressedBytes,PosIterator}.scala):

- ``BlockStream``            — iterate decompressed ``Block``s (zlib raw-deflate)
- ``SeekableBlockStream``    — adds ``seek`` + an LRU cache of 100 blocks
- ``MetadataStream``         — iterate ``Metadata`` without decompressing
- ``UncompressedBytes``      — linear byte-channel view over the blocks
- ``SeekableUncompressedBytes`` — virtual-position addressable variant
- ``pos_iterator``           — all candidate ``Pos`` of a block

The TPU hot path does not use these per-byte views; it inflates whole windows
of blocks into flat buffers (``spark_bam_tpu.tpu.inflate``). These streams
serve header parsing, indexing, oracles and golden tests.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Iterator, Optional

from spark_bam_tpu import obs
from spark_bam_tpu.bgzf.block import Block, Metadata, FOOTER_SIZE, check_isize
from spark_bam_tpu.bgzf.header import Header
from spark_bam_tpu.core import guard
from spark_bam_tpu.core.channel import ByteChannel
from spark_bam_tpu.core.faults import (
    BlockCorruptionError,
    BlockGapError,
    ShortReadError,
)
from spark_bam_tpu.core.guard import MalformedInputError
from spark_bam_tpu.core.pos import Pos


def inflate_block_payload(comp: bytes | memoryview, uncompressed_size: int) -> bytes:
    """Raw-DEFLATE inflate of one block payload (reference Stream.scala:49-54)."""
    try:
        data = zlib.decompress(
            bytes(comp), wbits=-15, bufsize=max(uncompressed_size, 1)
        )
    except zlib.error as e:
        raise BlockCorruptionError(f"BGZF payload inflate failed: {e}") from e
    if len(data) != uncompressed_size:
        raise BlockCorruptionError(
            f"Expected {uncompressed_size} decompressed bytes, found {len(data)}"
        )
    return data


def read_block(ch: ByteChannel) -> Optional[Block]:
    """Read + inflate the block at the channel position; None at EOF sentinel/EOF.

    The ISIZE length check and CRC32 verification classify damaged payloads
    as ``BlockCorruptionError`` (unrecoverable — retrying re-reads the same
    bytes), distinct from the retryable transport-level errors.
    """
    start = ch.position()
    try:
        header = Header.read(ch)
    except EOFError:
        return None
    remaining = header.compressed_size - header.size
    payload = ch.read_fully(remaining)
    data_length = remaining - FOOTER_SIZE
    uncompressed_size = check_isize(
        int.from_bytes(payload[-4:], "little"), start
    )
    if data_length == 2:
        # 28-byte empty terminator block (reference Stream.scala:56-58)
        return None
    # Per-block span only when a registry is live (the stream path's
    # inflate unit of work is one ~64 KiB block); disabled runs pay one
    # None-check. Counters track read vs inflate volume either way.
    with obs.span("inflate.block", start=start):
        data = inflate_block_payload(payload[:data_length], uncompressed_size)
    crc = int.from_bytes(payload[data_length:data_length + 4], "little")
    if zlib.crc32(data) & 0xFFFFFFFF != crc:
        raise BlockCorruptionError(
            f"BGZF block at {start}: CRC32 mismatch "
            f"(stored {crc:#010x}, computed {zlib.crc32(data) & 0xFFFFFFFF:#010x})"
        )
    obs.count("bgzf.blocks_read")
    obs.count("bgzf.bytes_read", header.compressed_size)
    obs.count("bgzf.bytes_inflated", uncompressed_size)
    return Block(data, start, header.compressed_size)


class BlockStream:
    """Iterator of decompressed Blocks from a channel (reference ``Stream``).

    ``tolerant=False`` (default, the historical semantics + anomaly
    classification): a genuinely truncated file still ends cleanly, but
    mid-file byte loss raises retryable ``ShortReadError`` and a damaged
    block raises ``BlockCorruptionError`` — no more silent truncation.

    ``tolerant=True`` (``FaultPolicy.mode=tolerant``): a damaged block is
    quarantined instead — the stream re-syncs to the next sound block
    header (``find_block_start``), records the gap in ``self.quarantined``,
    and raises ``BlockGapError`` once so the caller can account for the gap
    (the record layer re-finds a record boundary; a plain block consumer
    may simply continue iterating — the channel is already positioned at
    the resync point).
    """

    def __init__(self, ch: ByteChannel, tolerant: bool = False):
        self.ch = ch
        self.tolerant = tolerant
        self.quarantined: list[BlockGapError] = []
        self._head: Optional[Block] = None
        self._done = False

    def _advance(self) -> Optional[Block]:
        start = self.ch.position()
        try:
            return read_block(self.ch)
        except EOFError as e:
            if self.ch.position() >= self.ch.size:
                # The missing bytes never existed (truncated file): clean
                # stream end, the reference's tolerant-truncation shape.
                return None
            err = ShortReadError(
                f"mid-file EOF in block at {start} "
                f"(channel at {self.ch.position()} of {self.ch.size}): {e}"
            )
            if not self.tolerant:
                raise err from e
            self._resync(start, err)
        except (BlockCorruptionError, MalformedInputError) as e:
            # MalformedInputError covers HeaderParseException plus the
            # structural guards (bad XLEN/BSIZE/ISIZE, core/guard.py).
            if not self.tolerant:
                raise
            self._resync(start, e)

    def _resync(self, damaged_start: int, err: Exception) -> None:
        """Quarantine the damaged block: position the channel at the next
        sound block header and raise ``BlockGapError`` describing the gap."""
        from spark_bam_tpu.bgzf.find_block_start import find_block_start
        from spark_bam_tpu.bgzf.header import HeaderSearchFailedException

        try:
            resync = find_block_start(self.ch, damaged_start + 1)
        except (HeaderSearchFailedException, EOFError):
            resync = None
        self.ch.seek(resync if resync is not None else self.ch.size)
        gap = BlockGapError(
            damaged_start, resync, f"{type(err).__name__}: {err}"
        )
        self.quarantined.append(gap)
        obs.count("faults.quarantined_blocks")
        guard.note_quarantined_block()
        raise gap from err

    def head(self) -> Optional[Block]:
        if self._head is None and not self._done:
            self._head = self._advance()
            if self._head is None:
                self._done = True
        return self._head

    def __iter__(self) -> Iterator[Block]:
        return self

    def __next__(self) -> Block:
        blk = self.head()
        if blk is None:
            raise StopIteration
        self._head = None
        return blk

    def close(self) -> None:
        self.ch.close()


class SeekableBlockStream(BlockStream):
    """BlockStream + ``seek(block_pos)`` + LRU cache of decompressed blocks.

    Cache size 100 matches the reference (Stream.scala:83-92).
    """

    MAX_CACHE_SIZE = 100

    def __init__(self, ch: ByteChannel, tolerant: bool = False):
        super().__init__(ch, tolerant=tolerant)
        self._cache: OrderedDict[int, Block] = OrderedDict()

    def _advance(self) -> Optional[Block]:
        start = self.ch.position()
        blk = self._cache.get(start)
        if blk is not None:
            self._cache.move_to_end(start)
            self.ch.seek(start + blk.compressed_size)
            blk.idx = 0
            return blk
        blk = super()._advance()
        if blk is not None:
            self._cache[start] = blk
            if len(self._cache) > self.MAX_CACHE_SIZE:
                self._cache.popitem(last=False)
        return blk

    def seek(self, block_pos: int) -> None:
        head = self._head
        if head is not None and head.start == block_pos:
            head.idx = 0
            return
        self._head = None
        self._done = False
        self.ch.seek(block_pos)


class MetadataStream:
    """Iterate block Metadata without inflating (reference MetadataStream.scala)."""

    def __init__(self, ch: ByteChannel):
        self.ch = ch

    def __iter__(self) -> Iterator[Metadata]:
        while True:
            start = self.ch.position()
            try:
                header = Header.read(self.ch)
            except EOFError:
                return
            remaining = header.compressed_size - header.size
            self.ch.skip(remaining - 4)
            uncompressed_size = self.ch.read_i32()
            if remaining - FOOTER_SIZE == 2:
                return  # EOF sentinel block
            obs.count("bgzf.blocks_scanned")
            yield Metadata(start, header.compressed_size, uncompressed_size)

    def close(self) -> None:
        self.ch.close()


def pos_iterator(meta: Metadata) -> Iterator[Pos]:
    """All candidate virtual positions of a block (reference PosIterator.scala)."""
    for offset in range(meta.uncompressed_size):
        yield Pos(meta.start, offset)


class UncompressedBytes:
    """Linear reader over the concatenated uncompressed bytes of a block stream.

    ``tell()`` is a linear coordinate counted from construction/last seek —
    the checkers only use differences and equality against it (see
    eager.Checker.scala:36-47,116-119).
    """

    def __init__(self, stream: BlockStream):
        self.stream = stream
        self._linear = 0

    # -- position ------------------------------------------------------------
    def tell(self) -> int:
        return self._linear

    def cur_pos(self) -> Optional[Pos]:
        blk = self.stream.head()
        if blk is None:
            return None
        if blk.idx >= len(blk.data):
            next(self.stream, None)
            return self.cur_pos()
        return blk.pos

    def cur_block(self) -> Optional[Block]:
        if self.cur_pos() is None:
            return None
        return self.stream.head()

    # -- reads ---------------------------------------------------------------
    def read(self, n: int) -> bytes:
        out = bytearray()
        while n > 0:
            blk = self.cur_block()
            if blk is None:
                break
            take = min(n, len(blk.data) - blk.idx)
            out += blk.data[blk.idx: blk.idx + take]
            blk.idx += take
            self._linear += take
            n -= take
        return bytes(out)

    def read_fully(self, n: int) -> bytes:
        data = self.read(n)
        if len(data) != n:
            raise EOFError(f"wanted {n} bytes, got {len(data)}")
        return data

    def read_i32(self) -> int:
        return int.from_bytes(self.read_fully(4), "little", signed=True)

    def read_u8(self) -> int:
        return self.read_fully(1)[0]

    def skip(self, n: int) -> int:
        """Advance up to n bytes; returns bytes actually skipped."""
        skipped = 0
        while n > 0:
            blk = self.cur_block()
            if blk is None:
                break
            take = min(n, len(blk.data) - blk.idx)
            blk.idx += take
            self._linear += take
            skipped += take
            n -= take
        return skipped

    def has_next(self) -> bool:
        return self.cur_pos() is not None

    def next_byte(self) -> int:
        blk = self.cur_block()
        if blk is None:
            raise EOFError("at end of stream")
        b = blk.data[blk.idx]
        blk.idx += 1
        self._linear += 1
        return b

    def close(self) -> None:
        self.stream.close()


class SeekableUncompressedBytes(UncompressedBytes):
    """UncompressedBytes addressable by virtual position."""

    def __init__(self, stream: SeekableBlockStream):
        super().__init__(stream)
        self.stream: SeekableBlockStream = stream

    @staticmethod
    def open(ch: ByteChannel, tolerant: bool = False) -> "SeekableUncompressedBytes":
        return SeekableUncompressedBytes(SeekableBlockStream(ch, tolerant=tolerant))

    def seek(self, pos: Pos) -> None:
        self.stream.seek(pos.block_pos)
        self._linear = 0
        blk = self.stream.head()
        if blk is not None:
            blk.idx = pos.offset
