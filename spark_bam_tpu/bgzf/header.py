"""BGZF block-header parsing.

A BGZF block is a gzip member with a BAM-specific "BC" extra subfield carrying
the compressed block size. The 18 fixed header bytes are enough to learn the
header size and compressed size (reference bgzf/.../block/Header.scala:14-88).
"""

from __future__ import annotations

from dataclasses import dataclass

from spark_bam_tpu.core.guard import StructurallyInvalid

EXPECTED_HEADER_SIZE = 18
#: Minimum XLEN: the mandatory 6-byte "BC" extra subfield (2 id + 2 len +
#: 2 payload). Anything smaller cannot carry the block size.
MIN_XLEN = 6

# (index, expected byte): gzip magic + deflate + FEXTRA, then the BAM "BC" subfield
_MAGIC_CHECKS = (
    (0, 31),
    (1, 139),
    (2, 8),
    (3, 4),
    (12, 66),   # 'B'
    (13, 67),   # 'C'
    (14, 2),    # subfield length = 2
)


class HeaderParseException(StructurallyInvalid):
    """A fixed header byte didn't match.

    Message format matches the reference ("Position %d: %d != %d",
    bgzf/.../block/HeaderParseException.scala:5-11) — it is a user-visible
    contract (load tests assert "Position 0: 64 != 31" when a SAM is loaded
    as BAM). Part of the ``MalformedInputError`` taxonomy (core/guard.py)
    so block scanners and the fault model classify it uniformly.
    """

    def __init__(self, idx: int, actual: int, expected: int):
        super().__init__(f"Position {idx}: {actual} != {expected}")
        self.idx = idx
        self.actual = actual
        self.expected = expected


class HeaderSearchFailedException(Exception):
    """No valid BGZF block start found within a full block-size of scanning."""

    def __init__(self, path, start: int, positions_attempted: int):
        super().__init__(
            f"Failed to find BGZF block boundary in {path} starting from {start}"
            f" ({positions_attempted} positions attempted)"
        )
        self.path = path
        self.start = start
        self.positions_attempted = positions_attempted


@dataclass(frozen=True)
class Header:
    size: int             # total header size: 18 + extra subfield bytes
    compressed_size: int  # whole-block compressed size (header + payload + footer)

    @staticmethod
    def parse(buf: bytes | memoryview) -> "Header":
        """Parse from ≥18 bytes. Raises HeaderParseException /
        StructurallyInvalid / EOFError."""
        if len(buf) < EXPECTED_HEADER_SIZE:
            raise EOFError(
                f"Expected {EXPECTED_HEADER_SIZE} header bytes, got {len(buf)}"
            )
        for idx, expected in _MAGIC_CHECKS[:4]:
            actual = buf[idx]
            if actual != expected:
                raise HeaderParseException(idx, actual, expected)
        xlen = buf[10] | (buf[11] << 8)
        if xlen < MIN_XLEN:
            # No room for the mandatory BC subfield; a negative ``extra``
            # here used to misparse the whole block geometry.
            raise StructurallyInvalid(
                f"BGZF XLEN {xlen} < {MIN_XLEN}: no BC subfield"
            )
        extra = xlen - MIN_XLEN
        for idx, expected in _MAGIC_CHECKS[4:]:
            actual = buf[idx]
            if actual != expected:
                raise HeaderParseException(idx, actual, expected)
        compressed_size = (buf[16] | (buf[17] << 8)) + 1
        header_size = EXPECTED_HEADER_SIZE + extra
        if compressed_size < header_size + 8:  # + CRC32/ISIZE footer
            raise StructurallyInvalid(
                f"BGZF BSIZE {compressed_size - 1} too small for its own "
                f"header ({header_size} bytes) + footer"
            )
        return Header(header_size, compressed_size)

    @staticmethod
    def read(ch) -> "Header":
        """Parse from a ByteChannel positioned at a block start; consumes the header."""
        header = Header.parse(ch.read_fully(EXPECTED_HEADER_SIZE))
        ch.skip(header.size - EXPECTED_HEADER_SIZE)
        return header
