"""On-device analytics plane: fused aggregation inside the mesh tick.

``plan`` holds the ``AggSpec`` grammar and the JSON+binary result
schema, ``kernels`` the jit/shard_map reduction steps over the parser's
flat planes, ``host`` the numpy oracle (differential truth + CPU
fallback). Serving surface: the ``aggregate`` op (serve/service.py),
``load.api.aggregate`` / ``Dataset.aggregate``, and the
``spark-bam-tpu aggregate`` CLI subcommand — docs/analytics.md
"Aggregation".
"""

from spark_bam_tpu.agg.plan import (
    DEFAULT_SPEC,
    AggConfig,
    AggSpec,
    decode_result,
    encode_result,
)
from spark_bam_tpu.agg.kernels import aggregate_planes, make_shard_map_agg_step
from spark_bam_tpu.agg.host import (
    columns_from_records,
    combine,
    host_aggregate,
)

__all__ = [
    "AggConfig",
    "AggSpec",
    "DEFAULT_SPEC",
    "aggregate_planes",
    "columns_from_records",
    "combine",
    "decode_result",
    "encode_result",
    "host_aggregate",
    "make_shard_map_agg_step",
]
