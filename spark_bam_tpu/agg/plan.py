"""Aggregation specs + the typed result schema (docs/analytics.md).

The ``AggSpec`` grammar is the same compact string-spec pattern every
other plane uses (``Config.agg`` / ``SPARK_BAM_AGG`` / ``--agg``):
``metric[:k=v,...]`` entries joined by ``;`` —

    coverage:bin=1000,bins=512,cap=16;flagstat;mapq;tlen:max=2000;count

Metrics (every result vector is int64; layouts below are the *wire*
contract — the device kernels (agg/kernels.py) and the numpy oracle
(agg/host.py) must both produce them byte-identically):

``count``     ``[records, mapped, bases]`` — valid records, records with
              the unmapped bit (0x4) clear, and Σ ``l_seq``.
``flagstat``  13 entries: total valid records, then one count per SAM
              flag bit 0x1..0x800 (flagstat-style tallies).
``mapq``      256-bucket histogram of MAPQ (one bucket per value —
              MAPQ is a u8 by construction).
``tlen``      ``max+2`` buckets of \\|tlen\\|: bucket ``i`` counts
              records with \\|tlen\\| == i for i ≤ max; the final bucket
              collapses everything beyond ``max``.
``coverage``  per-contig binned base depth, shape ``(ncontigs, bins)``
              flattened row-major. A record covering reference span
              ``[pos, pos+max(ref_span,1))`` adds its per-bucket overlap
              (in bases) to buckets of width ``bin``; buckets at or past
              ``bins-1`` collapse into the last bucket, and a single
              record contributes to at most ``cap`` consecutive buckets
              (spans beyond that are truncated — the clamp keeps the
              reduction a single fixed-shape XLA program; the oracle
              applies the identical clamp). Only mapped records with a
              contig in range contribute.

Results serialize as one small JSON + binary frame through the existing
serve protocol: the JSON carries the metric directory (name, params,
element offset/length/shape) and the contig dictionary; the single
binary frame is the concatenated little-endian int64 vectors. Kilobytes,
not gigabytes — the whole point of the plane (ROADMAP item 3).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

#: SAM flag bits, flagstat order (0x1 paired .. 0x800 supplementary).
FLAG_BITS = tuple(1 << b for b in range(12))

#: metric name → (param name → default). Unknown names/params are
#: ValueError at parse time, so a typo fails before any device work.
METRICS: "dict[str, dict[str, int]]" = {
    "count": {},
    "flagstat": {},
    "mapq": {},
    "tlen": {"max": 2000},
    "coverage": {"bin": 1000, "bins": 512, "cap": 16},
}

#: What an empty spec ("" / unset Config.agg) means: every metric at
#: defaults, in this canonical order.
DEFAULT_SPEC = "count;flagstat;mapq;tlen;coverage"


@dataclass(frozen=True)
class AggSpec:
    """One parsed ``metric[:params]`` entry. ``params`` is a sorted
    tuple of (key, value) pairs so the spec stays hashable — the
    MeshSteps registry keys compiled reduction steps by it."""

    name: str
    params: "tuple[tuple[str, int], ...]" = ()

    def get(self, key: str) -> int:
        for k, v in self.params:
            if k == key:
                return v
        return METRICS[self.name][key]

    def canonical(self) -> str:
        if not self.params:
            return self.name
        body = ",".join(f"{k}={v}" for k, v in self.params)
        return f"{self.name}:{body}"

    def length(self, nc: int) -> int:
        """Result vector length (int64 elements) for ``nc`` contigs."""
        if self.name == "count":
            return 3
        if self.name == "flagstat":
            return 1 + len(FLAG_BITS)
        if self.name == "mapq":
            return 256
        if self.name == "tlen":
            return self.get("max") + 2
        return nc * self.get("bins")          # coverage

    def shape(self, nc: int) -> "tuple[int, ...]":
        if self.name == "coverage":
            return (nc, self.get("bins"))
        return (self.length(nc),)


@dataclass(frozen=True)
class AggConfig:
    """The parsed plan: an ordered tuple of :class:`AggSpec`."""

    specs: "tuple[AggSpec, ...]"

    @staticmethod
    @lru_cache(maxsize=128)
    def parse(spec: str) -> "AggConfig":
        """Parse ``"metric[:k=v,...];..."``; ``""`` ⇒ :data:`DEFAULT_SPEC`."""
        spec = (spec or "").strip() or DEFAULT_SPEC
        specs: "list[AggSpec]" = []
        seen: set = set()
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, body = entry.partition(":")
            name = name.strip()
            if name not in METRICS:
                raise ValueError(
                    f"Unknown agg metric {name!r}: expected one of "
                    f"{', '.join(sorted(METRICS))}"
                )
            if name in seen:
                raise ValueError(f"Duplicate agg metric {name!r} in {spec!r}")
            seen.add(name)
            params: "dict[str, int]" = {}
            for part in body.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(
                        f"Bad agg param {part!r} in {entry!r} "
                        f"(expected k=v)"
                    )
                key, value = (t.strip() for t in part.split("=", 1))
                if key not in METRICS[name]:
                    raise ValueError(
                        f"Unknown agg param {key!r} for metric {name!r}: "
                        f"expected one of "
                        f"{', '.join(sorted(METRICS[name])) or '(none)'}"
                    )
                try:
                    params[key] = int(value)
                except ValueError as exc:
                    raise ValueError(
                        f"Bad agg param value {part!r} in {entry!r}"
                    ) from exc
                if params[key] < 1:
                    raise ValueError(f"agg param {part!r} must be >= 1")
            specs.append(AggSpec(name, tuple(sorted(params.items()))))
        if not specs:
            raise ValueError(f"Empty agg spec: {spec!r}")
        return AggConfig(tuple(specs))

    def canonical(self) -> str:
        return ";".join(s.canonical() for s in self.specs)

    def total_length(self, nc: int) -> int:
        return sum(s.length(nc) for s in self.specs)


# ------------------------------------------------------------ wire schema
def encode_result(
    plan: AggConfig, nc: int, contigs, vectors: "dict[str, np.ndarray]",
) -> "tuple[dict, bytes]":
    """(JSON-able metric directory, one binary payload). The payload is
    the plan's int64 vectors concatenated little-endian in spec order;
    each directory entry locates its vector by element offset/length.
    Deterministic by construction — same plan + same answers ⇒ same
    bytes, which is what lets the streaming-failover resume token and
    the chaos byte-equality gates apply to ``aggregate`` unchanged."""
    directory: "list[dict]" = []
    parts: "list[np.ndarray]" = []
    offset = 0
    for spec in plan.specs:
        vec = np.ascontiguousarray(vectors[spec.name], dtype=np.int64).ravel()
        want = spec.length(nc)
        if len(vec) != want:
            raise ValueError(
                f"metric {spec.name!r}: vector has {len(vec)} elements, "
                f"plan wants {want}"
            )
        directory.append({
            "name": spec.name,
            "spec": spec.canonical(),
            "offset": offset,
            "length": want,
            "shape": list(spec.shape(nc)),
        })
        parts.append(vec)
        offset += want
    payload = b"".join(p.astype("<i8", copy=False).tobytes() for p in parts)
    meta = {
        "agg": plan.canonical(),
        "dtype": "int64",
        "elements": offset,
        "metrics": directory,
        "contigs": [[str(n), int(l)] for n, l in (contigs or [])],
    }
    return meta, payload


def decode_result(meta: dict, payload: bytes) -> "dict[str, np.ndarray]":
    """Inverse of :func:`encode_result`: metric name → shaped int64
    array. Validates the directory against the payload length."""
    n = int(meta.get("elements", 0))
    flat = np.frombuffer(payload, dtype="<i8")
    if len(flat) != n:
        raise ValueError(
            f"agg payload has {len(flat)} int64 elements, "
            f"directory declares {n}"
        )
    out: "dict[str, np.ndarray]" = {}
    for ent in meta.get("metrics", []):
        off, length = int(ent["offset"]), int(ent["length"])
        if off < 0 or off + length > n:
            raise ValueError(f"agg metric {ent.get('name')!r}: bad extent")
        out[ent["name"]] = flat[off: off + length].reshape(
            tuple(int(d) for d in ent["shape"])
        ).copy()
    return out
