"""Fused on-device reduction kernels over the parser's flat planes.

Each metric in an :class:`~spark_bam_tpu.agg.plan.AggConfig` lowers to
masked sums / scatter-adds over the already-parsed record planes
(``flag``, ``mapq``, ``tlen``, ``l_seq``, ``pos``, ``ref_span``,
``ref_id``, masked by ``valid``) — one XLA program per window for the
WHOLE plan, with the partial-state carry threaded device-to-device so a
multi-window file reduces without host round-trips. Predicate pushdown
happens before any of this: interval/flag/tag filters narrow ``valid``
(load/tpu_load.py ``_apply_filter``) and the kernels only ever read the
mask — filtered records are never materialized.

Overflow discipline (the mesh tier's contract, parallel/mesh.py): the
device state is int32 — record-scale counters are safe per flush
interval, and :func:`aggregate_planes` drains the carry into host int64
totals every ``_FLUSH_RECORDS`` records (sized so ≤2³⁰ bases accumulate
between flushes at ≤512 b mean read length; shrink ``chunk`` for
ultralong data). The wire result is always int64 (agg/plan.py).

Two execution shapes share ``_reduce_chunk``:

- the plain jit path (:func:`update_fn`) — the one-shot API / CPU
  fallback, no mesh required;
- :func:`make_shard_map_agg_step` — records sharded over the mesh's
  ``data`` axis, per-device partial deltas ``psum``'d over ICI, state
  replicated. Registered once per (plan, nc) in ``MeshSteps`` so the
  serve daemon dispatches every aggregate tick through one compiled
  executable (the build-at-startup, serve-forever contract).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_bam_tpu.agg.plan import FLAG_BITS, AggConfig
from spark_bam_tpu.tpu.parser import _next_pow2

#: Planes a reduction reads, in the positional order every step takes.
PLANES = ("valid", "flag", "mapq", "tlen", "l_seq", "pos", "ref_span",
          "ref_id")

#: Default records per device window (pow2 — at most log2 distinct
#: compile shapes across files).
DEFAULT_CHUNK = 1 << 16

#: Host-flush interval, in records: ≤2³⁰ bases accumulate in the int32
#: carry between flushes at ≤512 b mean reads.
_FLUSH_RECORDS = 1 << 21


def state_zeros(plan: AggConfig, nc: int) -> "dict[str, np.ndarray]":
    """Fresh int32 carry for one reduction pass."""
    return {
        spec.name: np.zeros(spec.length(nc), dtype=np.int32)
        for spec in plan.specs
    }


def _reduce_chunk(plan: AggConfig, nc: int, planes: dict) -> dict:
    """One window's partial vectors (int32) — the traced core shared by
    the plain jit and the shard_map step."""
    valid = planes["valid"].astype(jnp.int32)
    flag = planes["flag"]
    out: dict = {}
    for spec in plan.specs:
        if spec.name == "count":
            mapped = valid * ((flag & 4) == 0).astype(jnp.int32)
            bases = jnp.sum(valid * planes["l_seq"])
            out["count"] = jnp.stack(
                [jnp.sum(valid), jnp.sum(mapped), bases]
            )
        elif spec.name == "flagstat":
            out["flagstat"] = jnp.concatenate([
                jnp.sum(valid)[None],
                jnp.stack([
                    jnp.sum(valid * ((flag & bit) != 0).astype(jnp.int32))
                    for bit in FLAG_BITS
                ]),
            ])
        elif spec.name == "mapq":
            idx = jnp.clip(planes["mapq"], 0, 255)
            out["mapq"] = jnp.zeros(256, dtype=jnp.int32).at[idx].add(valid)
        elif spec.name == "tlen":
            mx = spec.get("max")
            idx = jnp.minimum(jnp.abs(planes["tlen"]), mx + 1)
            out["tlen"] = (
                jnp.zeros(mx + 2, dtype=jnp.int32).at[idx].add(valid)
            )
        elif spec.name == "coverage":
            out["coverage"] = _coverage_chunk(spec, nc, planes, valid)
    return out


def _coverage_chunk(spec, nc: int, planes: dict, valid) -> jnp.ndarray:
    """Segment-sum of (pos, pos+ref_span) intervals into per-contig
    buckets — a static ``cap``-step unroll of the bucket walk, each step
    one masked scatter-add (the wire contract's clamps: last-bucket
    collapse, ``cap``-bucket truncation; agg/plan.py)."""
    B, bins, cap = spec.get("bin"), spec.get("bins"), spec.get("cap")
    ref = planes["ref_id"]
    pos = planes["pos"]
    flag = planes["flag"]
    span = jnp.maximum(planes["ref_span"], 1)
    use = (
        (valid > 0) & ((flag & 4) == 0)
        & (ref >= 0) & (ref < nc) & (pos >= 0)
    )
    s = pos
    e = s + span
    sb = jnp.minimum(s // B, bins - 1)
    eb = jnp.minimum(jnp.minimum((e - 1) // B, bins - 1), sb + cap - 1)
    base = jnp.clip(ref, 0, nc - 1) * bins
    cov = jnp.zeros(nc * bins, dtype=jnp.int32)
    for j in range(cap):
        k = sb + j
        active = use & (k <= eb)
        lo = jnp.maximum(s, k * B)
        hi = jnp.where(k == bins - 1, e, jnp.minimum(e, (k + 1) * B))
        ov = jnp.where(active, jnp.maximum(hi - lo, 0), 0)
        cov = cov.at[base + jnp.clip(k, 0, bins - 1)].add(ov)
    return cov


@functools.lru_cache(maxsize=64)
def update_fn(plan: AggConfig, nc: int):
    """The plain jit carry step: ``state' = state + reduce(planes)``.
    Cached per (plan, nc) — the plan is frozen/hashable by design."""

    @jax.jit
    def update(state: dict, planes: dict) -> dict:
        delta = _reduce_chunk(plan, nc, planes)
        return {k: state[k] + delta[k] for k in state}

    return update


def make_shard_map_agg_step(mesh, plan: AggConfig, nc: int,
                            axis: str = "data"):
    """Sharded carry step: record planes shard over the mesh's ``data``
    axis, each device reduces its slice, deltas all-reduce with
    ``lax.psum`` over ICI, and the replicated state advances — the same
    explicit-collective shape as the count/serve steps
    (parallel/mesh.py), with the aggregate state as the carried operand.
    Rows pad with ``valid=False`` so the pad never counts."""
    from spark_bam_tpu.parallel.mesh import _shard_map_compat

    shard_map = _shard_map_compat()

    def local_step(state: dict, planes: dict) -> dict:
        delta = _reduce_chunk(plan, nc, planes)
        delta = {k: jax.lax.psum(v, axis) for k, v in delta.items()}  # ← ICI
        return {k: state[k] + delta[k] for k in state}

    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=P(),
            check_rep=False,
        )
    )


def _pad_planes(columns: dict, lo: int, hi: int, multiple: int) -> dict:
    """One window's planes, padded to pow2 (≥ ``multiple``) with
    valid=False rows — at most log2 distinct shapes reach the jit."""
    m = hi - lo
    m_pad = max(_next_pow2(m), multiple)
    out = {}
    for name in PLANES:
        col = np.asarray(columns[name])
        if name == "valid":
            pad = np.zeros(m_pad, dtype=bool)
        else:
            pad = np.zeros(m_pad, dtype=np.int32)
        pad[:m] = col[lo:hi]
        out[name] = pad
    return out


def aggregate_planes(
    columns: "dict[str, np.ndarray]",
    plan: AggConfig,
    nc: int,
    *,
    steps=None,
    chunk: "int | None" = None,
) -> "dict[str, np.ndarray]":
    """Reduce flat planes to the plan's int64 vectors on device.

    ``steps`` is a ``MeshSteps`` registry: when given, windows dispatch
    through its compiled-once sharded agg step; otherwise the plain jit
    carry runs on the default device. ``chunk`` bounds records per
    window (tests shrink it to force the multi-window carry). Returns
    metric name → int64 vector, byte-compatible with the host oracle.
    """
    m = len(columns["valid"])
    chunk = int(chunk or DEFAULT_CHUNK)
    if chunk < 1:
        raise ValueError(f"agg chunk must be >= 1: {chunk}")
    multiple = 1
    if steps is not None:
        step = steps.agg_step(plan, nc)
        multiple = int(steps.mesh.devices.size)
    else:
        step = update_fn(plan, nc)
    totals = {
        spec.name: np.zeros(spec.length(nc), dtype=np.int64)
        for spec in plan.specs
    }
    state = {k: jnp.asarray(v) for k, v in state_zeros(plan, nc).items()}
    since_flush = 0
    for lo in range(0, max(m, 1), chunk):
        hi = min(lo + chunk, m)
        if hi <= lo:
            break
        planes = {
            k: jnp.asarray(v)
            for k, v in _pad_planes(columns, lo, hi, multiple).items()
        }
        state = step(state, planes)       # device-to-device carry
        since_flush += hi - lo
        if since_flush >= _FLUSH_RECORDS:
            for k, v in state.items():
                totals[k] += np.asarray(v, dtype=np.int64)
            state = {
                k: jnp.asarray(v)
                for k, v in state_zeros(plan, nc).items()
            }
            since_flush = 0
    for k, v in state.items():
        totals[k] += np.asarray(v, dtype=np.int64)
    return totals
