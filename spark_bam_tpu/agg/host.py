"""Host numpy oracle for the aggregation plane.

Independent reference implementation of every metric in agg/plan.py —
written against the *wire contract* (vector layouts, clamps) rather
than sharing code with the device kernels, so the differential tests
(tests/test_agg.py) compare two derivations of the same definition.
Doubles as the CPU fallback: ``SplitService._handle_aggregate`` demotes
here when the device reduction raises, and the record-based entry point
serves the CRAM/SAM loaders whose records never materialize as flat
planes.

All arithmetic is int64 end-to-end — the oracle has no overflow
discipline to manage, which is exactly why it is the truth the int32
device carry is tested against.
"""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.agg.plan import FLAG_BITS, AggConfig


def host_aggregate(
    columns: "dict[str, np.ndarray]", plan: AggConfig, nc: int,
) -> "dict[str, np.ndarray]":
    """Reduce parser flat planes (numpy arrays, ``valid`` already
    narrowed by any filter) to the plan's int64 vectors. ``columns``
    needs ``valid`` plus whichever planes the plan's metrics read
    (``flag``, ``mapq``, ``tlen``, ``l_seq``, ``pos``, ``ref_span``,
    ``ref_id``)."""
    valid = np.asarray(columns["valid"], dtype=bool)
    out: "dict[str, np.ndarray]" = {}
    for spec in plan.specs:
        if spec.name == "count":
            flag = np.asarray(columns["flag"], dtype=np.int64)[valid]
            lseq = np.asarray(columns["l_seq"], dtype=np.int64)[valid]
            out["count"] = np.array(
                [valid.sum(), int((flag & 4 == 0).sum()), int(lseq.sum())],
                dtype=np.int64,
            )
        elif spec.name == "flagstat":
            flag = np.asarray(columns["flag"], dtype=np.int64)[valid]
            vec = np.zeros(1 + len(FLAG_BITS), dtype=np.int64)
            vec[0] = len(flag)
            for i, bit in enumerate(FLAG_BITS):
                vec[1 + i] = int((flag & bit != 0).sum())
            out["flagstat"] = vec
        elif spec.name == "mapq":
            mapq = np.asarray(columns["mapq"], dtype=np.int64)[valid]
            out["mapq"] = np.bincount(
                np.clip(mapq, 0, 255), minlength=256
            ).astype(np.int64)
        elif spec.name == "tlen":
            mx = spec.get("max")
            tlen = np.abs(np.asarray(columns["tlen"], dtype=np.int64)[valid])
            out["tlen"] = np.bincount(
                np.minimum(tlen, mx + 1), minlength=mx + 2
            ).astype(np.int64)
        elif spec.name == "coverage":
            out["coverage"] = _host_coverage(columns, spec, nc, valid)
    return out


def _host_coverage(columns, spec, nc: int, valid) -> np.ndarray:
    """Per-contig binned base depth — the oracle's per-record bucket
    walk, applying the wire contract's clamps (last-bucket collapse,
    ``cap``-bucket truncation) literally."""
    B, bins, cap = spec.get("bin"), spec.get("bins"), spec.get("cap")
    ref = np.asarray(columns["ref_id"], dtype=np.int64)
    pos = np.asarray(columns["pos"], dtype=np.int64)
    span = np.maximum(np.asarray(columns["ref_span"], dtype=np.int64), 1)
    flag = np.asarray(columns["flag"], dtype=np.int64)
    use = valid & (flag & 4 == 0) & (ref >= 0) & (ref < nc) & (pos >= 0)
    cov = np.zeros((nc, bins), dtype=np.int64)
    for i in np.flatnonzero(use):
        s = int(pos[i])
        e = s + int(span[i])
        sb = min(s // B, bins - 1)
        eb = min(min((e - 1) // B, bins - 1), sb + cap - 1)
        for k in range(sb, eb + 1):
            lo = max(s, k * B)
            hi = e if k == bins - 1 else min(e, (k + 1) * B)
            if hi > lo:
                cov[int(ref[i]), k] += hi - lo
    return cov.reshape(-1)


#: CIGAR op codes that consume reference bases: M, D, N, =, X — the
#: same set the device parser folds into ``ref_span`` (tpu/parser.py).
_REF_CONSUMING = {0, 2, 3, 7, 8}


def record_ref_span(rec) -> int:
    """Reference span of one ``BamRecord`` — Σ CIGAR lengths over the
    ref-consuming ops, matching the parser's ``ref_span`` plane."""
    return sum(n for n, op in (rec.cigar or []) if op in _REF_CONSUMING)


def columns_from_records(records) -> "dict[str, np.ndarray]":
    """Flat-plane columns for an iterable of ``BamRecord`` — the bridge
    that lets the CRAM/SAM record loaders (and ``Dataset.aggregate``)
    feed the same reductions as the BAM flat-plane path. Items may be
    bare records or tuples whose last element is one (the ``(Pos, rec)``
    load shapes)."""
    flag, mapq, tlen, lseq, pos, span, ref = [], [], [], [], [], [], []
    for rec in records:
        if isinstance(rec, tuple):          # the (Pos, record) load shapes
            rec = rec[-1]
        flag.append(int(rec.flag))
        mapq.append(int(rec.mapq))
        tlen.append(int(rec.tlen))
        lseq.append(len(rec.seq) if rec.seq and rec.seq != "*" else 0)
        pos.append(int(rec.pos))
        span.append(record_ref_span(rec))
        ref.append(int(rec.ref_id))
    n = len(flag)
    return {
        "valid": np.ones(n, dtype=bool),
        "flag": np.asarray(flag, dtype=np.int32),
        "mapq": np.asarray(mapq, dtype=np.int32),
        "tlen": np.asarray(tlen, dtype=np.int32),
        "l_seq": np.asarray(lseq, dtype=np.int32),
        "pos": np.asarray(pos, dtype=np.int32),
        "ref_span": np.asarray(span, dtype=np.int32),
        "ref_id": np.asarray(ref, dtype=np.int32),
    }


def combine(
    parts: "list[dict[str, np.ndarray]]", plan: AggConfig, nc: int,
) -> "dict[str, np.ndarray]":
    """Sum per-partition partial vectors — every metric is a pure sum,
    so partition order doesn't matter (the RDD-accumulator property the
    reference's benchmark harvesting relied on)."""
    out = {
        spec.name: np.zeros(spec.length(nc), dtype=np.int64)
        for spec in plan.specs
    }
    for part in parts:
        if part is None:
            continue                          # quarantined partition
        for name, vec in part.items():
            out[name] += np.asarray(vec, dtype=np.int64).ravel()
    return out
