from spark_bam_tpu.check.flags import Flags, Success, FLAG_NAMES
from spark_bam_tpu.check.checker import (
    ALLOWED_NAME_CHAR_MIN,
    ALLOWED_NAME_CHAR_MAX,
    EXCLUDED_NAME_CHAR,
    FIXED_FIELDS_SIZE,
    MAX_CIGAR_OP,
    make_checker,
)
from spark_bam_tpu.check.eager import EagerChecker
from spark_bam_tpu.check.full import FullChecker
from spark_bam_tpu.check.indexed import IndexedChecker

__all__ = [
    "Flags",
    "Success",
    "FLAG_NAMES",
    "FIXED_FIELDS_SIZE",
    "MAX_CIGAR_OP",
    "ALLOWED_NAME_CHAR_MIN",
    "ALLOWED_NAME_CHAR_MAX",
    "EXCLUDED_NAME_CHAR",
    "EagerChecker",
    "FullChecker",
    "IndexedChecker",
    "make_checker",
]
