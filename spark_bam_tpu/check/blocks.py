"""Block partition planning: BGZF blocks → ≈split-size partitions.

Reference check/.../bam/check/Blocks.scala:22-214. Two paths:

- **Indexed** (``.blocks`` sidecar exists): parse block metadata, filter by
  byte ranges, prefix-sum compressed sizes, assign each block to partition
  ``cum_offset // split_size`` (ref :70-140).
- **Search**: split the file into ``split_size`` byte ranges; per range, find
  the first block boundary then stream metadata while inside the range
  (ref :141-207). Ranges are resolved in parallel on the host.

Default split size 2 MB (ref :64).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.bgzf.index_blocks import read_blocks_index
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.core.channel import open_channel, path_exists, path_size
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.ranges import RangeSet
from spark_bam_tpu.parallel.executor import ParallelConfig, map_partitions


@dataclass
class Blocks:
    """Partitioned block metadata + per-partition byte bounds."""

    partitions: list[list[Metadata]]
    bounds: list[tuple[int, int]]

    @property
    def num_blocks(self) -> int:
        return sum(len(p) for p in self.partitions)

    def all_blocks(self) -> list[Metadata]:
        return [m for p in self.partitions for m in p]


def plan_blocks(
    path,
    config: Config = Config(),
    ranges: RangeSet | None = None,
    blocks_path=None,
    parallel: ParallelConfig = ParallelConfig(),
) -> Blocks:
    split_size = config.split_size_or(Config.CHECK_SPLIT_SIZE_DEFAULT)
    blocks_path = str(blocks_path) if blocks_path else str(path) + ".blocks"

    if path_exists(blocks_path):
        metas = [
            m
            for m in read_blocks_index(blocks_path)
            if ranges is None or m.start in ranges
        ]
        # Exclusive prefix sum of compressed sizes over the *filtered* blocks
        # (the reference scans after filtering, Blocks.scala:89-107).
        partitions: dict[int, list[Metadata]] = {}
        offset = 0
        last_partition = -1
        for m in metas:
            last_partition = offset // split_size
            partitions.setdefault(last_partition, []).append(m)
            offset += m.compressed_size
        # Partition count runs through the *last block's* partition (pinned
        # by the reference's BlocksTest boundaries golden: trailing empties
        # beyond it are not materialized).
        num_partitions = last_partition + 1
        return Blocks(
            partitions=[partitions.get(i, []) for i in range(num_partitions)],
            bounds=[
                (i * split_size, (i + 1) * split_size) for i in range(num_partitions)
            ],
        )

    size = path_size(path)
    num_splits = math.ceil(size / split_size)
    split_idxs = [
        i
        for i in range(num_splits)
        if ranges is None or ranges.overlaps(i * split_size, (i + 1) * split_size)
    ]

    def resolve(idx: int) -> list[Metadata]:
        start, end = idx * split_size, (idx + 1) * split_size
        with open_channel(path) as ch:
            block_start = find_block_start(
                ch, start, config.bgzf_blocks_to_check, path=str(path)
            )
            ch.seek(block_start)
            out = []
            for m in MetadataStream(ch):
                if m.start >= end:
                    break
                if ranges is None or m.start in ranges:
                    out.append(m)
            return out

    partitions = map_partitions(resolve, split_idxs, parallel)
    return Blocks(
        partitions=partitions,
        bounds=[(i * split_size, (i + 1) * split_size) for i in split_idxs],
    )


def align_indexed_records(
    blocks: Blocks, records_path, strict: bool = True
) -> "list[np.ndarray]":
    """Partition-align the ``.records`` ground truth with a block plan.

    The reference pairs its blocks RDD with the sorted record-position RDD
    partition-by-partition so each task scores its own blocks against its
    own slice of the truth (IndexedRecordPositions.scala:57-117 ``toSets`` +
    BlocksAndIndexedRecords.scala:134-180). Here the sidecar positions
    bucket by their block's partition with one global sort; the returned
    list matches ``blocks.partitions`` index-for-index, each entry a sorted
    ``(n, 2)`` int64 array of (block_pos, offset) rows.

    ``strict`` (default): a truth position whose block is absent from the
    plan raises — a stale sidecar or planner hole must not silently shrink
    the ground truth. Pass ``strict=False`` when the plan was legitimately
    filtered with ``ranges``.
    """
    import numpy as np

    from spark_bam_tpu.bam.index_records import read_records_index

    pos = np.array(
        [(p.block_pos, p.offset) for p in read_records_index(records_path)],
        dtype=np.int64,
    ).reshape(-1, 2)

    starts = []
    part_of_block = []
    for i, part in enumerate(blocks.partitions):
        for m in part:
            starts.append(m.start)
            part_of_block.append(i)
    starts = np.array(starts, dtype=np.int64)
    part_of_block = np.array(part_of_block, dtype=np.int64)
    order = np.argsort(starts)
    starts, part_of_block = starts[order], part_of_block[order]

    n_parts = len(blocks.partitions)
    out: list[np.ndarray] = [
        np.empty((0, 2), dtype=np.int64) for _ in range(n_parts)
    ]
    if not len(pos) or not len(starts):
        if strict and len(pos):
            raise ValueError(
                f"{len(pos)} .records positions reference blocks missing "
                "from the plan (stale sidecar?)"
            )
        return out
    idx = np.searchsorted(starts, pos[:, 0])
    known = (idx < len(starts)) & (
        starts[np.clip(idx, 0, len(starts) - 1)] == pos[:, 0]
    )
    if strict and not known.all():
        bad = pos[~known][:5, 0].tolist()
        raise ValueError(
            f"{int((~known).sum())} .records positions reference blocks "
            f"missing from the plan (first: {bad}; stale sidecar?)"
        )
    pos, idx = pos[known], idx[known]
    parts = part_of_block[idx]
    # One global (partition, block, offset) sort, then split — O(N log N).
    order = np.lexsort((pos[:, 1], pos[:, 0], parts))
    pos, parts = pos[order], parts[order]
    cuts = np.searchsorted(parts, np.arange(1, n_parts))
    for i, rows in enumerate(np.split(pos, cuts)):
        out[i] = rows
    return out
