"""Block partition planning: BGZF blocks → ≈split-size partitions.

Reference check/.../bam/check/Blocks.scala:22-214. Two paths:

- **Indexed** (``.blocks`` sidecar exists): parse block metadata, filter by
  byte ranges, prefix-sum compressed sizes, assign each block to partition
  ``cum_offset // split_size`` (ref :70-140).
- **Search**: split the file into ``split_size`` byte ranges; per range, find
  the first block boundary then stream metadata while inside the range
  (ref :141-207). Ranges are resolved in parallel on the host.

Default split size 2 MB (ref :64).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.bgzf.index_blocks import read_blocks_index
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.ranges import RangeSet
from spark_bam_tpu.parallel.executor import ParallelConfig, map_partitions


@dataclass
class Blocks:
    """Partitioned block metadata + per-partition byte bounds."""

    partitions: list[list[Metadata]]
    bounds: list[tuple[int, int]]

    @property
    def num_blocks(self) -> int:
        return sum(len(p) for p in self.partitions)

    def all_blocks(self) -> list[Metadata]:
        return [m for p in self.partitions for m in p]


def plan_blocks(
    path,
    config: Config = Config(),
    ranges: RangeSet | None = None,
    blocks_path=None,
    parallel: ParallelConfig = ParallelConfig(),
) -> Blocks:
    split_size = config.split_size_or(Config.CHECK_SPLIT_SIZE_DEFAULT)
    blocks_path = str(blocks_path) if blocks_path else str(path) + ".blocks"

    if os.path.exists(blocks_path):
        metas = [
            m
            for m in read_blocks_index(blocks_path)
            if ranges is None or m.start in ranges
        ]
        # Exclusive prefix sum of compressed sizes over the *filtered* blocks
        # (the reference scans after filtering, Blocks.scala:89-107).
        partitions: dict[int, list[Metadata]] = {}
        offset = 0
        last_partition = -1
        for m in metas:
            last_partition = offset // split_size
            partitions.setdefault(last_partition, []).append(m)
            offset += m.compressed_size
        # Partition count runs through the *last block's* partition (pinned
        # by the reference's BlocksTest boundaries golden: trailing empties
        # beyond it are not materialized).
        num_partitions = last_partition + 1
        return Blocks(
            partitions=[partitions.get(i, []) for i in range(num_partitions)],
            bounds=[
                (i * split_size, (i + 1) * split_size) for i in range(num_partitions)
            ],
        )

    size = os.path.getsize(path)
    num_splits = math.ceil(size / split_size)
    split_idxs = [
        i
        for i in range(num_splits)
        if ranges is None or ranges.overlaps(i * split_size, (i + 1) * split_size)
    ]

    def resolve(idx: int) -> list[Metadata]:
        start, end = idx * split_size, (idx + 1) * split_size
        with open_channel(path) as ch:
            block_start = find_block_start(
                ch, start, config.bgzf_blocks_to_check, path=str(path)
            )
            ch.seek(block_start)
            out = []
            for m in MetadataStream(ch):
                if m.start >= end:
                    break
                if ranges is None or m.start in ranges:
                    out.append(m)
            return out

    partitions = map_partitions(resolve, split_idxs, parallel)
    return Blocks(
        partitions=partitions,
        bounds=[(i * split_size, (i + 1) * split_size) for i in split_idxs],
    )
