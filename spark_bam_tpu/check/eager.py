"""CPU ``eager`` checker: the sequential semantics oracle.

Boolean verdict per candidate Pos; short-circuits on the first failing check
and chains ``reads_to_check`` consecutive records
(reference check/.../bam/check/eager/Checker.scala:18-177). The TPU and NumPy
engines (tpu/checker.py, check/vectorized.py) are differentially tested
against this at every position of the fixtures.

Semantics pinned here (each is a golden-test subject):
- name length is ``i32 & 0xff`` (only the low byte)           — ref :52
- EOF with *zero* bytes at the record edge after ≥1 success ⇒ valid — ref :36-39
- contig-length bound is strict ``>`` (equal is allowed)      — ref PosChecker.scala:59
- logical/physical cursor divergence after a negative seq-len record is
  preserved: the recursion trusts ``nextOffset`` while reads continue from
  the physical cursor                                          — ref :116-125
"""

from __future__ import annotations

import struct

from spark_bam_tpu.bam.header import ContigLengths, contig_lengths as read_contig_lengths
from spark_bam_tpu.bgzf.stream import SeekableBlockStream, SeekableUncompressedBytes
from spark_bam_tpu.check.checker import name_char_allowed, register_checker
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.pos import Pos


class EagerChecker:
    def __init__(
        self,
        u: SeekableUncompressedBytes,
        contigs: ContigLengths,
        reads_to_check: int = 10,
    ):
        self.u = u
        self.contigs = contigs
        self.num_contigs = len(contigs)
        self.lengths = contigs.lengths_list()
        self.reads_to_check = reads_to_check

    @staticmethod
    def open(path, config=None) -> "EagerChecker":
        from spark_bam_tpu.core.config import default_config

        config = config or default_config()
        ch = open_channel(path)
        return EagerChecker(
            SeekableUncompressedBytes(SeekableBlockStream(ch)),
            read_contig_lengths(path),
            config.reads_to_check,
        )

    def __call__(self, pos: Pos) -> bool:
        self.u.seek(pos)
        return self._apply(self.u.tell(), 0)

    def _ref_pos_error(self, ref_idx: int, ref_pos: int) -> bool:
        if ref_idx < -1:
            return True
        if ref_idx >= self.num_contigs:
            return True
        if ref_pos < -1:
            return True
        if ref_idx >= 0 and ref_pos > self.lengths[ref_idx]:
            return True
        return False

    def _apply(self, start: int, successes: int) -> bool:
        u = self.u
        if successes == self.reads_to_check:
            return True

        fixed = u.read(36)
        if len(fixed) < 36:
            # Zero bytes at exactly the expected record edge, with ≥1 chained
            # success, is a valid EOF (ref :36-39); anything else fails.
            return len(fixed) == 0 and u.tell() - len(fixed) == start and successes > 0

        (
            remaining,
            ref_idx,
            ref_pos,
            name_len_i32,
            flags_n_cigar,
            seq_len,
            next_ref_idx,
            next_ref_pos,
            _tlen,
        ) = struct.unpack("<9i", fixed)

        next_offset = start + 4 + remaining

        if self._ref_pos_error(ref_idx, ref_pos):
            return False

        name_len = name_len_i32 & 0xFF
        if name_len in (0, 1):
            return False

        flags = (flags_n_cigar >> 16) & 0xFFFF
        n_cigar = flags_n_cigar & 0xFFFF
        n_cigar_bytes = 4 * n_cigar

        if (flags & 4) == 0 and (seq_len == 0 or n_cigar == 0):
            return False

        # int32-wrapping arithmetic with truncating division, as on the JVM.
        t = _wrap32(seq_len + 1)
        n_seq_qual = _wrap32(_trunc_div2(t) + seq_len)
        if remaining < _wrap32(32 + name_len + n_cigar_bytes + n_seq_qual):
            return False

        if self._ref_pos_error(next_ref_idx, next_ref_pos):
            return False

        name = u.read(name_len)
        if len(name) < name_len:
            return False
        if name[-1] != 0:
            return False
        if any(not name_char_allowed(b) for b in name[:-1]):
            return False

        cigar = u.read(n_cigar_bytes)
        if len(cigar) < n_cigar_bytes:
            return False
        for k in range(n_cigar):
            if cigar[4 * k] & 0xF > 8:
                return False

        bytes_to_skip = next_offset - u.tell()
        if bytes_to_skip > 0:
            u.skip(bytes_to_skip)

        return self._apply(next_offset, successes + 1)

    # ------------------------------------------------------------ read scan
    def next_read_start_with_delta(
        self, start: Pos, max_read_size: int = 10_000_000
    ) -> tuple[Pos, int] | None:
        """Advance byte-by-byte until a position passes (ref :128-162).

        Returns None when EOF is reached without a boundary; raises
        NoReadFoundException when the max_read_size budget runs out mid-file.
        """
        from spark_bam_tpu.check.checker import NoReadFoundException

        u = self.u
        u.seek(start)
        for idx in range(max_read_size):
            pos = u.cur_pos()
            if pos is None:
                return None
            if self(pos):
                return pos, idx
            u.seek(pos)
            if not u.has_next():
                return None
            u.next_byte()
        raise NoReadFoundException("<stream>", start, max_read_size)

    def next_read_start(self, start: Pos, max_read_size: int = 10_000_000) -> Pos | None:
        found = self.next_read_start_with_delta(start, max_read_size)
        return found[0] if found else None

    def close(self) -> None:
        self.u.close()


def _wrap32(x: int) -> int:
    x &= 0xFFFFFFFF
    return x - 0x100000000 if x >= 0x80000000 else x


def _trunc_div2(x: int) -> int:
    """JVM-style Int division by 2 (truncates toward zero)."""
    return -((-x) // 2) if x < 0 else x // 2


@register_checker("eager")
def _make_eager(path, config, **kw):
    return EagerChecker.open(path, config)
