"""The 19-check error model.

Reference: check/.../bam/check/full/error/{Error,Flags,RefPosError,
ReadNameError,CigarOpsError}.scala. Flag order (= bit index) follows the
reference's BitSet serialization (Flags.scala:201-223) so masks interchange.
The same bitmask encoding is what the vectorized engines (NumPy/JAX) emit —
``Flags.from_mask`` decodes a device result into the rich form.
"""

from __future__ import annotations

from dataclasses import dataclass, fields

FLAG_NAMES = (
    "tooFewFixedBlockBytes",        # bit 0
    "negativeReadIdx",              # bit 1
    "tooLargeReadIdx",              # bit 2
    "negativeReadPos",              # bit 3
    "tooLargeReadPos",              # bit 4
    "negativeNextReadIdx",          # bit 5
    "tooLargeNextReadIdx",          # bit 6
    "negativeNextReadPos",          # bit 7
    "tooLargeNextReadPos",          # bit 8
    "tooFewBytesForReadName",       # bit 9
    "nonNullTerminatedReadName",    # bit 10
    "nonASCIIReadName",             # bit 11
    "noReadName",                   # bit 12
    "emptyReadName",                # bit 13
    "tooFewBytesForCigarOps",       # bit 14
    "invalidCigarOp",               # bit 15
    "emptyMappedCigar",             # bit 16
    "emptyMappedSeq",               # bit 17
    "tooFewRemainingBytesImplied",  # bit 18
)

BIT = {name: 1 << i for i, name in enumerate(FLAG_NAMES)}


def considered_mask(fail_mask, reads_before):
    """FullCheck's "considered" rule: failing positions minus the bare
    at-EOF marker (reference FullCheck.scala:144-147). Vectorized over
    numpy arrays; the single shared definition for the CLI report and the
    streaming summary."""
    bit0 = BIT["tooFewFixedBlockBytes"]
    return (fail_mask != 0) & ~((fail_mask == bit0) & (reads_before == 0))


def num_failing_fields(fail_mask, reads_before):
    """Failing-field count per position: flag popcount plus the
    chained-reads field when reads succeeded before the failure."""
    import numpy as np

    popcount = np.zeros(len(fail_mask), dtype=np.int32)
    for i in range(len(FLAG_NAMES)):
        popcount += (fail_mask >> i) & 1
    return popcount + (reads_before > 0)


@dataclass(frozen=True)
class Success:
    """A position that chained ``reads_parsed`` valid records (or hit EOF)."""
    reads_parsed: int

    @property
    def call(self) -> bool:
        return True


@dataclass(frozen=True)
class Flags:
    tooFewFixedBlockBytes: bool = False
    negativeReadIdx: bool = False
    tooLargeReadIdx: bool = False
    negativeReadPos: bool = False
    tooLargeReadPos: bool = False
    negativeNextReadIdx: bool = False
    tooLargeNextReadIdx: bool = False
    negativeNextReadPos: bool = False
    tooLargeNextReadPos: bool = False
    tooFewBytesForReadName: bool = False
    nonNullTerminatedReadName: bool = False
    nonASCIIReadName: bool = False
    noReadName: bool = False
    emptyReadName: bool = False
    tooFewBytesForCigarOps: bool = False
    invalidCigarOp: bool = False
    emptyMappedCigar: bool = False
    emptyMappedSeq: bool = False
    tooFewRemainingBytesImplied: bool = False
    readsBeforeError: int = 0

    @property
    def call(self) -> bool:
        return False

    def to_mask(self) -> int:
        mask = 0
        for i, name in enumerate(FLAG_NAMES):
            if getattr(self, name):
                mask |= 1 << i
        return mask

    @staticmethod
    def from_mask(mask: int, reads_before_error: int = 0) -> "Flags":
        return Flags(
            **{name: bool(mask & (1 << i)) for i, name in enumerate(FLAG_NAMES)},
            readsBeforeError=reads_before_error,
        )

    def true_flags(self) -> list[str]:
        return [name for name in FLAG_NAMES if getattr(self, name)]

    def num_checks_failed(self) -> int:
        """Failing checks + (readsBeforeError>0), the reference's
        numNonZeroFields (Flags.scala:118-124)."""
        return len(self.true_flags()) + (1 if self.readsBeforeError > 0 else 0)

    def __str__(self) -> str:
        return ",".join(self.true_flags())


def flags_fields() -> list[str]:
    return [f.name for f in fields(Flags)]


class Counts(dict):
    """Per-flag Long counters, summable (reference error/Counts.scala)."""

    def __init__(self):
        super().__init__({name: 0 for name in FLAG_NAMES})

    def add(self, flags: Flags) -> None:
        for name in FLAG_NAMES:
            if getattr(flags, name):
                self[name] += 1

    def add_mask_counts(self, mask_counts: dict[int, int]) -> None:
        """Accumulate from a histogram of flag masks (vectorized results)."""
        for mask, count in mask_counts.items():
            for i, name in enumerate(FLAG_NAMES):
                if mask & (1 << i):
                    self[name] += count

    def merge(self, other: "Counts") -> "Counts":
        for name in FLAG_NAMES:
            self[name] += other[name]
        return self

    def show(self, indent: str = "\t") -> str:
        width = max(len(str(v)) for v in self.values())
        return "\n".join(
            f"{indent}{str(self[name]).rjust(width)}:\t{name}"
            for name in sorted(FLAG_NAMES, key=lambda n: -self[n])
        )
