"""``seqdoop`` checker: behavioral emulation of hadoop-bam's BAMSplitGuesser.

The reference wraps the actual upstream library to measure its accuracy
in-harness (seqdoop/.../seqdoop/Checker.scala:22-108 + the truncated stream
reproducing its fixed read window :119-164). We implement the *behavior* from
the reference's documented comparison (docs/motivation.md checks table and
:123-140) and pin it with the fixture goldens:

- anchor record: reference/mate idx bounds and negative-position checks, name
  NUL-termination, length-consistency — but NOT locus-too-large, NOT
  name-emptiness/charset, NOT cigar-op validity, NOT empty-mapped checks
- succeeding records: structural decode validity *including* cigar ops,
  chained until ``blocks_needed`` distinct BGZF block positions are visited
- the window is capped at ``max_bytes_read`` *compressed* bytes past the
  candidate's block; hitting the cap mid-decode "passes" if any record
  decoded (the upstream EOF/decodedAny quirk, motivation.md:123-140)

Golden contract (tests/test_seqdoop.py): exactly the 5 known false positives
on 1.bam, zero disagreements on 2.bam, and the 239479→311 next-read-start.
"""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.bgzf.flat import FlatView, flatten_file
from spark_bam_tpu.check.checker import register_checker
from spark_bam_tpu.core.pos import Pos

MAX_BYTES_READ = 3 * 0xFFFF * 2  # upstream BAMSplitGuesser.MAX_BYTES_READ
BLOCKS_NEEDED = 3                # upstream BLOCKS_NEEDED_FOR_GUESS


def _fields(buf: np.ndarray):
    n = len(buf)
    p = np.zeros(n + 40, dtype=np.uint8)
    p[:n] = buf
    u = (
        p[:-3].astype(np.uint32)
        | (p[1:-2].astype(np.uint32) << 8)
        | (p[2:-1].astype(np.uint32) << 16)
        | (p[3:].astype(np.uint32) << 24)
    )
    i32 = u.view(np.int32)
    return p, u, i32


def seqdoop_masks(
    buf: np.ndarray, num_contigs: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(anchor_ok, succ_ok, next_offset) per position.

    ``anchor_ok``: hadoop-bam's checkRecordStart checks.
    ``succ_ok``:   decode-time validity of a succeeding record (adds cigar-op
                   validity; keeps idx/neg-pos checks; still no locus bound).
    """
    n = len(buf)
    p, u, i32 = _fields(buf)
    remaining = i32[0:n]
    ref_idx = i32[4: n + 4]
    ref_pos = i32[8: n + 8]
    name_len = p[12: n + 12].astype(np.int32)
    fnc = u[16: n + 16]
    n_cigar = (fnc & 0xFFFF).astype(np.int32)
    seq_len = i32[20: n + 20]
    next_ref_idx = i32[24: n + 24]
    next_ref_pos = i32[28: n + 28]

    idx = np.arange(n, dtype=np.int64)
    fits = idx + 36 <= n

    ref_ok = (
        (ref_idx >= -1) & (ref_idx < num_contigs) & (ref_pos >= -1)
        & (next_ref_idx >= -1) & (next_ref_idx < num_contigs) & (next_ref_pos >= -1)
    )

    # Length-consistency, JVM int32 wrap + truncating division.
    with np.errstate(over="ignore"):
        t = (seq_len + np.int32(1)).astype(np.int32)
        half = t // 2 + ((t < 0) & (t % 2 != 0))
        rhs = (
            np.int32(32) + name_len + np.int32(4) * n_cigar
            + half.astype(np.int32) + seq_len
        ).astype(np.int32)
    size_ok = remaining >= rhs

    name_end = idx + 36 + name_len
    name_ok = (
        (name_len >= 1)
        & (name_end <= n)
        & (p[np.clip(name_end - 1, 0, n + 39)] == 0)
    )

    anchor_ok = fits & ref_ok & size_ok & name_ok

    # Cigar-op validity via stride-4 suffix sums (as in check/vectorized.py).
    pad = 4 * 65535 + 300 + 4
    bad_op = np.zeros(n + pad, dtype=np.int32)
    readable = max(n - 3, 0)
    bad_op[:readable] = (p[:readable] & 0xF) > 8
    B = np.zeros(n + pad, dtype=np.int32)
    for r in range(4):
        B[r::4] = bad_op[r::4][::-1].cumsum()[::-1]
    cig_start = np.where(name_len >= 1, name_end, idx + 36)
    cig_end = cig_start + 4 * n_cigar.astype(np.int64)
    bad_count = B[np.clip(cig_start, 0, n + pad - 1)] - B[np.clip(cig_end, 0, n + pad - 1)]
    cigar_ok = (bad_count == 0) & (cig_end <= n)

    succ_ok = fits & ref_ok & size_ok & name_ok & cigar_ok

    next_offset = idx + 4 + remaining.astype(np.int64)
    return anchor_ok, succ_ok, next_offset


def seqdoop_check_flat(
    view: FlatView,
    num_contigs: int,
    candidates: np.ndarray | None = None,
    max_bytes_read: int = MAX_BYTES_READ,
    blocks_needed: int = BLOCKS_NEEDED,
    max_steps: int = 50_000,
) -> np.ndarray:
    """Seqdoop verdict for every position (or given candidates) of a view."""
    buf = view.data
    n = view.size
    anchor_ok, succ_ok, nxt = seqdoop_masks(buf, num_contigs)

    # Block bookkeeping: block index of each flat position and the flat cap
    # implied by the compressed read window of each candidate's block.
    block_flat = view.block_flat
    block_starts = view.block_starts
    n_blocks = len(block_starts)

    verdict = np.zeros(n, dtype=bool)
    cand = candidates if candidates is not None else np.flatnonzero(anchor_ok)
    cand = cand[anchor_ok[cand]]
    if len(cand) == 0:
        return verdict

    blk_of = np.searchsorted(block_flat, cand, side="right") - 1
    limit_comp = block_starts[blk_of] + max_bytes_read
    # First block NOT fully within the compressed window:
    comp_ends = block_starts + _compressed_sizes(view, n)
    cut_block = np.searchsorted(comp_ends, limit_comp, side="right")
    flat_limit = np.where(
        cut_block >= n_blocks, n, block_flat[np.clip(cut_block, 0, n_blocks - 1)]
    )

    m = len(cand)
    # The succeeding-records scan decodes from the anchor itself
    # (motivation.md:127-131): the anchor is record #0 (cigar NOT checked),
    # every later record is cigar-checked.
    pos = cand.astype(np.int64)
    cap = np.minimum(flat_limit, n)
    last_blk = np.full(m, -1, dtype=np.int64)
    visited = np.zeros(m, dtype=np.int32)
    decoded_any = np.zeros(m, dtype=bool)
    res = np.zeros(m, dtype=np.int8)     # 0 running, 1 pass, -1 fail

    for _ in range(max_steps):
        run = res == 0
        if not run.any():
            break

        pi = np.clip(pos, 0, n - 1)

        # Header or body crossing the (256 KB-window or file) end ⇒ EOF,
        # "valid iff anything was decoded" (the upstream decodedAny quirk).
        over = run & ((pos + 36 > cap) | (nxt[pi] > cap))
        res[over & decoded_any] = 1
        res[over & ~decoded_any] = -1
        run &= res == 0

        # Record decoded: body fit inside the window, so the field checks run
        # (including the codec-relative cigar scan — note its cigar offset
        # differs from eager's when l_read_name ∈ {0,1}, which is exactly why
        # the known FP anchors pass here while eager flags invalidCigarOp).
        bad = run & ~succ_ok[pi]
        res[bad] = -1
        run &= res == 0
        decoded_any = decoded_any | run

        # Count distinct BGZF blocks visited; enough ⇒ pass.
        b = np.searchsorted(block_flat, pi, side="right") - 1
        newblk = run & (b != last_blk)
        visited[newblk] += 1
        last_blk = np.where(run, b, last_blk)
        done = run & (visited >= blocks_needed)
        res[done] = 1
        run &= res == 0

        pos = np.where(run, nxt[pi], pos)

    verdict[cand[res == 1]] = True
    return verdict


def _compressed_sizes(view: FlatView, n: int) -> np.ndarray:
    """Per-block compressed sizes from consecutive starts (the final block's
    true size isn't derivable from the view; approximate with its flat span,
    which errs small and only affects the cap by <64 KiB at EOF)."""
    starts = view.block_starts
    if len(starts) == 1:
        return np.array([n - view.block_flat[0]], dtype=np.int64)
    diffs = np.diff(starts)
    last = max(int(diffs[-1]), 1)
    return np.append(diffs, last)


class SeqdoopChecker:
    """Sequential plugin face over the vectorized seqdoop engine."""

    def __init__(self, view: FlatView, num_contigs: int):
        self.view = view
        self.num_contigs = num_contigs
        self._verdict: np.ndarray | None = None

    @staticmethod
    def open(path, config=None) -> "SeqdoopChecker":
        from spark_bam_tpu.bam.header import contig_lengths

        return SeqdoopChecker(flatten_file(path), len(contig_lengths(path)))

    @property
    def verdict(self) -> np.ndarray:
        if self._verdict is None:
            self._verdict = seqdoop_check_flat(self.view, self.num_contigs)
        return self._verdict

    def __call__(self, pos: Pos) -> bool:
        return bool(self.verdict[self.view.flat_of_pos(pos.block_pos, pos.offset)])

    def next_read_start(self, start: Pos, max_read_size: int = 10_000_000) -> Pos | None:
        flat = self.view.flat_of_pos(start.block_pos, start.offset)
        true_flat = np.flatnonzero(self.verdict)
        j = int(np.searchsorted(true_flat, flat))
        if j < len(true_flat) and true_flat[j] - flat < max_read_size:
            return Pos(*self.view.pos_of_flat(int(true_flat[j])))
        return None

    def close(self) -> None:
        pass


@register_checker("seqdoop")
def _make_seqdoop(path, config, **kw):
    return SeqdoopChecker.open(path, config)
