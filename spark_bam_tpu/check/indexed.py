"""``indexed`` checker: ground truth from a ``.records`` sidecar.

Reference check/.../bam/check/indexed/Checker.scala:12-34 — membership in the
sorted set of true record starts; ``next_read_start`` is the first indexed
position ≥ the query.
"""

from __future__ import annotations

import bisect

from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.check.checker import register_checker
from spark_bam_tpu.core.pos import Pos


class IndexedChecker:
    def __init__(self, positions: list[Pos]):
        self.positions = sorted(positions)

    @staticmethod
    def open(path, config=None) -> "IndexedChecker":
        return IndexedChecker(read_records_index(str(path) + ".records"))

    def __call__(self, pos: Pos) -> bool:
        i = bisect.bisect_left(self.positions, pos)
        return i < len(self.positions) and self.positions[i] == pos

    def next_read_start(self, start: Pos, max_read_size: int | None = None) -> Pos | None:
        i = bisect.bisect_left(self.positions, start)
        return self.positions[i] if i < len(self.positions) else None

    def close(self) -> None:
        pass


@register_checker("indexed")
def _make_indexed(path, config, **kw):
    return IndexedChecker.open(path, config)
