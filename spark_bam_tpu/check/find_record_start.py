"""Split-point resolution: first record boundary at/after a block start.

Reference check/.../bam/spark/FindRecordStart.scala:9-71 — scan byte-by-byte
with an eager checker until a position passes; ``NoReadFoundException`` after
``max_read_size`` attempts. Two engines:

- ``find_record_start``       — sequential oracle scan
- ``find_record_starts_flat`` — vectorized: one chain-walk over a flat view
  resolves *all* queried block starts at once (this is what the split
  planner batches onto TPU)
"""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.bgzf.flat import FlatView
from spark_bam_tpu.check.eager import EagerChecker
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.pos import Pos


from spark_bam_tpu.check.checker import NoReadFoundException  # re-export


def find_record_start(
    checker: EagerChecker,
    block_start: int,
    max_read_size: int = 10_000_000,
    path: str = "<channel>",
) -> Pos:
    found = checker.next_read_start(Pos(block_start, 0), max_read_size)
    if found is None:
        raise NoReadFoundException(path, block_start, max_read_size)
    return found


def find_record_starts_flat(
    view: FlatView,
    contig_lengths: np.ndarray,
    block_starts: list[int] | None = None,
    max_read_size: int = 10_000_000,
    reads_to_check: int = 10,
) -> dict[int, Pos | None]:
    """First record boundary at/after each block start, via one vectorized pass.

    Checks every position of the view in one flag pass + chain walk, then for
    each queried block start takes the first true verdict within
    ``max_read_size`` bytes. ``None`` marks block starts whose scan budget ran
    out inside the view; starts whose answer could lie beyond the view (not
    ``at_eof`` and budget crosses the end) are absent from the result.
    """
    if block_starts is None:
        block_starts = [int(s) for s in view.block_starts]
    result = check_flat(
        view.data, contig_lengths, at_eof=view.at_eof, reads_to_check=reads_to_check
    )
    verdict = result.verdict & result.exact
    true_flat = np.flatnonzero(verdict)
    out: dict[int, Pos | None] = {}
    for start in block_starts:
        flat = view.flat_of_pos(start, 0)
        j = int(np.searchsorted(true_flat, flat))
        if j < len(true_flat) and true_flat[j] - flat < max_read_size:
            block, off = view.pos_of_flat(int(true_flat[j]))
            out[start] = Pos(block, off)
        else:
            budget_end = flat + max_read_size
            if view.at_eof or budget_end <= view.size:
                out[start] = None  # budget definitively exhausted
            # else: unresolvable within this window — caller widens the view
    return out
