"""Checker plugin surface.

Reference: check/.../bam/check/Checker.scala:7-28 — a ``Checker[Call]`` is
``Pos → Call`` plus shared structural constants; ``MakeChecker`` builds one
per file handle. Here the plugin registry keys the ``spark.bam.checker``
config knob: ``eager`` / ``full`` / ``indexed`` / ``seqdoop`` (oracles), with
the vectorized engines (numpy/tpu) behind ``spark.bam.backend``.
"""

from __future__ import annotations

from typing import Callable, Protocol

from spark_bam_tpu.core.pos import Pos

FIXED_FIELDS_SIZE = 36  # 9 × i32 at the start of every BAM record
MAX_CIGAR_OP = 8

# Read-name alphabet: '!'..'?' ++ 'A'..'~'  — printable ASCII minus '@'
# (reference Checker.scala:12-17).
ALLOWED_NAME_CHAR_MIN = 0x21  # '!'
ALLOWED_NAME_CHAR_MAX = 0x7E  # '~'
EXCLUDED_NAME_CHAR = 0x40     # '@'


def name_char_allowed(b: int) -> bool:
    return ALLOWED_NAME_CHAR_MIN <= b <= ALLOWED_NAME_CHAR_MAX and b != EXCLUDED_NAME_CHAR


class Checker(Protocol):
    def __call__(self, pos: Pos): ...


class NoReadFoundException(Exception):
    """Scan budget (max_read_size) exhausted without finding a boundary.

    Reaching EOF cleanly is NOT this error: the reference throws there too
    (FindRecordStart.scala:22-28 via loadBam), which crashes on trailing
    splits of ultra-long-read files whose record starts all precede the
    split; we return "no boundary" instead and the partition loads empty.
    """

    def __init__(self, path, start, max_read_size: int):
        super().__init__(
            f"Failed to find a valid read-start in {max_read_size} attempts"
            f" in {path} from {start}"
        )
        self.path = path
        self.start = start
        self.max_read_size = max_read_size


_REGISTRY: dict[str, Callable] = {}


def register_checker(name: str):
    def deco(factory):
        _REGISTRY[name] = factory
        return factory
    return deco


def make_checker(name: str, path, config=None, **kw) -> Checker:
    """Build a checker by plugin name for a BAM path.

    Factories accept (path, config, **kw) and return a ``Pos → call`` object
    with a ``next_read_start(pos)`` method where applicable.
    """
    # Import for registration side effects.
    from spark_bam_tpu.check import eager, full, indexed, seqdoop  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"Unknown checker {name!r}; have {sorted(_REGISTRY)}")
    from spark_bam_tpu.core.config import default_config

    return _REGISTRY[name](path, config or default_config(), **kw)
