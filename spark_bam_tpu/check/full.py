"""CPU ``full`` checker: runs *all* checks, returns every failing flag.

Reference check/.../bam/check/full/Checker.scala:17-198. Differences from
``eager`` are diagnostic, not semantic: it never short-circuits inside a
record, so the returned ``Flags`` captures every failing condition of the
first bad record (with ``readsBeforeError`` = chained successes before it).

Order quirks preserved (each affects emitted flags, not the verdict):
- name-length 0/1 produce noReadName/emptyReadName and *no name bytes are
  consumed*, so the cigar scan reads from fixed-fields end  — ref :81-86,111
- a name read hitting EOF emits tooFewBytesForReadName and suppresses all
  cigar flags (exception path)                               — ref :140-144
- invalidCigarOp suppresses emptyMapped flags               — ref :113-132
"""

from __future__ import annotations

import struct
from typing import Union

from spark_bam_tpu.bam.header import ContigLengths, contig_lengths as read_contig_lengths
from spark_bam_tpu.bgzf.stream import SeekableBlockStream, SeekableUncompressedBytes
from spark_bam_tpu.check.checker import name_char_allowed, register_checker
from spark_bam_tpu.check.eager import _trunc_div2, _wrap32
from spark_bam_tpu.check.flags import Flags, Success
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.pos import Pos

Result = Union[Success, Flags]


class FullChecker:
    def __init__(
        self,
        u: SeekableUncompressedBytes,
        contigs: ContigLengths,
        reads_to_check: int = 10,
    ):
        self.u = u
        self.num_contigs = len(contigs)
        self.lengths = contigs.lengths_list()
        self.reads_to_check = reads_to_check

    @staticmethod
    def open(path, config=None) -> "FullChecker":
        from spark_bam_tpu.core.config import default_config

        config = config or default_config()
        ch = open_channel(path)
        return FullChecker(
            SeekableUncompressedBytes(SeekableBlockStream(ch)),
            read_contig_lengths(path),
            config.reads_to_check,
        )

    def __call__(self, pos: Pos) -> Result:
        self.u.seek(pos)
        return self._apply(self.u.tell(), 0)

    def _ref_pos_flags(self, ref_idx: int, ref_pos: int, next_: bool) -> dict:
        neg_idx = too_large_idx = neg_pos = too_large_pos = False
        if ref_idx < -1:
            neg_idx = True
            neg_pos = ref_pos < -1
        elif ref_idx >= self.num_contigs:
            too_large_idx = True
            neg_pos = ref_pos < -1
        elif ref_pos < -1:
            neg_pos = True
        elif ref_idx >= 0 and ref_pos > self.lengths[ref_idx]:
            too_large_pos = True
        prefix = "negativeNextRead" if next_ else "negativeRead"
        tprefix = "tooLargeNextRead" if next_ else "tooLargeRead"
        return {
            f"{prefix}Idx": neg_idx,
            f"{tprefix}Idx": too_large_idx,
            f"{prefix}Pos": neg_pos,
            f"{tprefix}Pos": too_large_pos,
        }

    def _apply(self, start: int, successes: int) -> Result:
        u = self.u
        if successes == self.reads_to_check:
            return Success(self.reads_to_check)

        fixed = u.read(36)
        if len(fixed) < 36:
            if len(fixed) == 0 and u.tell() == start and successes > 0:
                return Success(successes)
            return Flags(tooFewFixedBlockBytes=True, readsBeforeError=successes)

        (
            remaining,
            ref_idx,
            ref_pos,
            name_len_i32,
            flags_n_cigar,
            seq_len,
            next_ref_idx,
            next_ref_pos,
            _tlen,
        ) = struct.unpack("<9i", fixed)

        next_offset = start + 4 + remaining
        kw = self._ref_pos_flags(ref_idx, ref_pos, next_=False)
        kw.update(self._ref_pos_flags(next_ref_idx, next_ref_pos, next_=True))

        name_len = name_len_i32 & 0xFF
        flags = (flags_n_cigar >> 16) & 0xFFFF
        n_cigar = flags_n_cigar & 0xFFFF
        n_cigar_bytes = 4 * n_cigar

        t = _wrap32(seq_len + 1)
        n_seq_qual = _wrap32(_trunc_div2(t) + seq_len)
        kw["tooFewRemainingBytesImplied"] = remaining < _wrap32(
            32 + name_len + n_cigar_bytes + n_seq_qual
        )

        # --- read name (lengths 0/1 consume nothing; ref :81-86) ---
        name_failed_eof = False
        if name_len == 0:
            kw["noReadName"] = True
        elif name_len == 1:
            kw["emptyReadName"] = True
        else:
            name = u.read(name_len)
            if len(name) < name_len:
                kw["tooFewBytesForReadName"] = True
                name_failed_eof = True
            elif name[-1] != 0:
                kw["nonNullTerminatedReadName"] = True
            elif any(not name_char_allowed(b) for b in name[:-1]):
                kw["nonASCIIReadName"] = True

        # --- cigar (skipped entirely when the name read EOF'd; ref :140-144) ---
        if not name_failed_eof:
            cigar = u.read(n_cigar_bytes)
            # Sequential-read order: a bad op among the readable ints wins
            # over the EOF that a later int would have hit (ref :113-119).
            bad_op = any(
                cigar[4 * k] & 0xF > 8 for k in range(len(cigar) // 4)
            )
            if bad_op:
                kw["invalidCigarOp"] = True
            elif len(cigar) < n_cigar_bytes:
                kw["tooFewBytesForCigarOps"] = True
            elif (flags & 4) == 0 and (seq_len == 0 or n_cigar == 0):
                # Reference quirk preserved: full/Checker.scala:122-129 passes
                # (emptySeq, emptyCigar) into EmptyMapped's
                # (emptyMappedCigar, emptyMappedSeq) fields — swapped.
                kw["emptyMappedCigar"] = seq_len == 0
                kw["emptyMappedSeq"] = n_cigar == 0

        if any(kw.values()):
            return Flags(**kw, readsBeforeError=successes)

        bytes_to_skip = next_offset - u.tell()
        if bytes_to_skip > 0:
            u.skip(bytes_to_skip)
        return self._apply(next_offset, successes + 1)

    def close(self) -> None:
        self.u.close()


@register_checker("full")
def _make_full(path, config, **kw):
    return FullChecker.open(path, config)
