"""Vectorized record-boundary checking (host/NumPy engine).

This is the same algorithm the TPU engine (tpu/checker.py) runs via JAX —
NumPy here is the reference implementation and CPU fallback. Instead of the
reference's per-candidate seek/parse loop (eager/Checker.scala:24-126 — ~10
record parses per candidate byte), the work is restructured into two
fixed-shape passes over a flat uncompressed buffer:

1. **Flag pass** — for *every* byte offset ``i``, compute the 19-check flag
   bitmask ``F[i]`` of the would-be record at ``i`` (check/flags.py bit
   order). Variable-length scans become O(1) lookups against prefix sums:
   read-name character validity via a cumulative allowed-char count, cigar-op
   validity via stride-4 suffix sums of bad-op indicators. ``F[i] == 0`` ⇔
   the single record at ``i`` passes every check.

2. **Chain walk** — ``reads_to_check`` lock-step gather rounds follow each
   candidate's implied next-record pointers. Lanes carry a *logical* cursor
   (the reference's ``nextOffset`` bookkeeping) and a *physical* cursor (its
   stream position) so even the divergence after negative-seq-len records
   matches the oracle byte-for-byte.

Windowed mode (``at_eof=False``) marks candidates whose resolution needs
bytes beyond the buffer as *escaped* rather than guessing; callers re-check
those few against the next window or the sequential oracle. This is how
multi-GiB files shard across devices without any loss of exactness.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_bam_tpu import obs
from spark_bam_tpu.check.flags import BIT

# Bits that can only fire because the *buffer* ended (escape in windowed mode).
ESCAPE_MASK = (
    BIT["tooFewFixedBlockBytes"]
    | BIT["tooFewBytesForReadName"]
    | BIT["tooFewBytesForCigarOps"]
)
DEFINITIVE_MASK = (1 << 19) - 1 - ESCAPE_MASK

# Padding beyond any index the flag pass can touch:
# 36 fixed + 255 name + 4*65535 cigar + slack.
_PAD = 36 + 255 + 4 * 65535 + 16


@dataclass
class RecordMasks:
    """Per-position single-record results over a flat buffer."""

    F: np.ndarray          # int32 flag bitmask per position; 0 ⇒ record valid
    remaining: np.ndarray  # int32 length-prefix at each position
    body_end: np.ndarray   # int64: position after fixed+name+cigar reads
    n: int                 # buffer size (number of candidate positions)


def compute_flags(buf: np.ndarray, contig_lengths: np.ndarray) -> RecordMasks:
    """Flag pass: evaluate all 19 checks at every offset of ``buf``."""
    n = int(buf.shape[0])
    c = int(contig_lengths.shape[0])
    lengths = contig_lengths.astype(np.int32)

    p = np.zeros(n + _PAD, dtype=np.uint8)
    p[:n] = buf

    # Little-endian i32 at every byte offset (views below are zero-copy slices).
    u = (
        p[:-3].astype(np.uint32)
        | (p[1:-2].astype(np.uint32) << 8)
        | (p[2:-1].astype(np.uint32) << 16)
        | (p[3:].astype(np.uint32) << 24)
    )
    i32 = u.view(np.int32)

    remaining = i32[0:n]
    ref_idx = i32[4: n + 4]
    ref_pos = i32[8: n + 8]
    name_len = p[12: n + 12].astype(np.int32)  # i32 & 0xff ⇒ just the low byte
    fnc = u[16: n + 16]
    n_cigar = (fnc & 0xFFFF).astype(np.int32)
    mapped = (fnc >> 18) & 1 == 0  # (flags & 4) == 0
    seq_len = i32[20: n + 20]
    next_ref_idx = i32[24: n + 24]
    next_ref_pos = i32[28: n + 28]

    F = np.zeros(n, dtype=np.int32)

    # --- reference/mate position sanity (PosChecker.scala:43-63) ---
    def ref_pos_bits(idx, pos, b_neg_idx, b_large_idx, b_neg_pos, b_large_pos):
        neg_idx = idx < -1
        large_idx = ~neg_idx & (idx >= c)
        neg_pos = pos < -1
        idx_ok = ~neg_idx & ~large_idx
        if c > 0:
            len_at = lengths[np.clip(idx, 0, c - 1)]
            large_pos = idx_ok & ~neg_pos & (idx >= 0) & (pos > len_at)
        else:
            large_pos = np.zeros(n, dtype=bool)
        return (
            neg_idx * np.int32(b_neg_idx)
            | large_idx * np.int32(b_large_idx)
            | neg_pos * np.int32(b_neg_pos)
            | large_pos * np.int32(b_large_pos)
        )

    F |= ref_pos_bits(
        ref_idx, ref_pos,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F |= ref_pos_bits(
        next_ref_idx, next_ref_pos,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )

    # --- implied-size consistency, JVM int32 wrap + truncating division ---
    with np.errstate(over="ignore"):
        t = (seq_len + np.int32(1)).astype(np.int32)
        half = t // 2 + ((t < 0) & (t % 2 != 0))  # truncate toward zero
        rhs = (
            np.int32(32)
            + name_len
            + np.int32(4) * n_cigar
            + half.astype(np.int32)
            + seq_len
        ).astype(np.int32)
    F |= (remaining < rhs) * np.int32(BIT["tooFewRemainingBytesImplied"])

    # --- read name ---
    idx = np.arange(n, dtype=np.int64)
    name_start = idx + 36
    name_end = name_start + name_len  # exclusive
    has_name = name_len >= 2
    F |= (name_len == 0) * np.int32(BIT["noReadName"])
    F |= (name_len == 1) * np.int32(BIT["emptyReadName"])

    name_eof = has_name & (name_end > n)
    F |= name_eof * np.int32(BIT["tooFewBytesForReadName"])

    name_in = has_name & ~name_eof
    last_idx = np.clip(name_end - 1, 0, n + _PAD - 1)
    non_null = name_in & (p[last_idx] != 0)
    F |= non_null * np.int32(BIT["nonNullTerminatedReadName"])

    allowed = (p >= 0x21) & (p <= 0x7E) & (p != 0x40)
    acc = np.zeros(n + _PAD + 1, dtype=np.int64)
    np.cumsum(allowed, out=acc[1:])
    good_chars = acc[last_idx] - acc[np.clip(name_start, 0, n + _PAD)]
    bad_chars = name_in & ~non_null & (good_chars != name_len - 1)
    F |= bad_chars * np.int32(BIT["nonASCIIReadName"])

    # --- cigar ops (stride-4 suffix sums of bad-op indicators) ---
    # Op code is the low nibble of each int's first byte.
    bad_op = np.zeros(n + _PAD + 4, dtype=np.int32)
    readable = max(n - 3, 0)
    bad_op[:readable] = (p[:readable] & 0xF) > 8
    B = np.zeros(n + _PAD + 4, dtype=np.int32)
    for r in range(4):
        B[r::4] = bad_op[r::4][::-1].cumsum()[::-1]

    cig_start = name_start + np.where(has_name & ~name_eof, name_len, 0)
    # (name-len 0/1 consume no name bytes, so cigar reads begin at fixed end;
    #  full/Checker.scala:81-136)
    cig_end = cig_start + 4 * n_cigar.astype(np.int64)
    cig_considered = ~name_eof  # name EOF suppresses the cigar scan entirely
    bad_count = B[np.clip(cig_start, 0, n + _PAD)] - B[np.clip(cig_end, 0, n + _PAD)]
    has_bad = cig_considered & (bad_count > 0)
    F |= has_bad * np.int32(BIT["invalidCigarOp"])
    cig_eof = cig_considered & ~has_bad & (cig_end > n)
    F |= cig_eof * np.int32(BIT["tooFewBytesForCigarOps"])
    empty_ok = cig_considered & ~has_bad & ~cig_eof & mapped
    empty_seq = empty_ok & (seq_len == 0)
    empty_cig = empty_ok & (n_cigar == 0)
    # Reference quirk preserved: full/Checker.scala:122-129 constructs
    # EmptyMapped(emptySeq, emptyCigar) but the case class fields are
    # (emptyMappedCigar, emptyMappedSeq) — the two flags are swapped.
    F |= ((empty_seq | empty_cig) & empty_seq) * np.int32(BIT["emptyMappedCigar"])
    F |= ((empty_seq | empty_cig) & empty_cig) * np.int32(BIT["emptyMappedSeq"])

    # --- too few fixed bytes: the only flag when the 36-byte read fails ---
    few_fixed = idx > n - 36
    F = np.where(few_fixed, np.int32(BIT["tooFewFixedBlockBytes"]), F)

    body_end = np.where(
        few_fixed,
        idx + 36,
        cig_start + np.where(cig_considered, 4 * n_cigar.astype(np.int64), 0),
    )
    return RecordMasks(F=F, remaining=remaining, body_end=body_end, n=n)


@dataclass
class ChainResult:
    verdict: np.ndarray        # bool: is a record boundary
    reads_parsed: np.ndarray   # int32: chained successes for true verdicts
    fail_mask: np.ndarray      # int32: flags of the first failing record
    reads_before: np.ndarray   # int32: successes before the failing record
    exact: np.ndarray          # bool: resolution never touched buffer-end bits
    escaped: np.ndarray        # bool: unresolved (windowed mode only)


def chain_verdicts(
    masks: RecordMasks,
    candidates: np.ndarray,
    at_eof: bool = True,
    reads_to_check: int = 10,
) -> ChainResult:
    """Chain walk: resolve each candidate by following next-record pointers."""
    n = masks.n
    F, remaining, body_end = masks.F, masks.remaining, masks.body_end

    logical = candidates.astype(np.int64)
    physical = candidates.astype(np.int64)
    m = logical.shape[0]
    res = np.zeros(m, dtype=np.int8)  # 0 running, 1 true, -1 false, 2 escaped
    fail_mask = np.zeros(m, dtype=np.int32)
    reads_before = np.zeros(m, dtype=np.int32)
    reads_parsed = np.zeros(m, dtype=np.int32)
    exact = np.ones(m, dtype=bool)

    for step in range(reads_to_check):
        run = res == 0
        if not run.any():
            break
        at_end = physical >= n
        if at_eof:
            # Zero bytes exactly at the expected record edge after ≥1 success
            # ⇒ valid EOF (eager/Checker.scala:36-39).
            eof_ok = run & at_end & (physical == logical) & (step > 0)
            res[eof_ok] = 1
            reads_parsed[eof_ok] = step
            eof_bad = run & at_end & ~eof_ok
            res[eof_bad] = -1
            fail_mask[eof_bad] = BIT["tooFewFixedBlockBytes"]
            reads_before[eof_bad] = step
        else:
            esc = run & at_end
            res[esc] = 2
        run = res == 0

        f = F[np.clip(physical, 0, n - 1)]
        f = np.where(run, f, 0)
        definitive = f & DEFINITIVE_MASK
        boundary = f & ESCAPE_MASK

        fail = run & (definitive != 0)
        if at_eof:
            fail |= run & (boundary != 0)
        else:
            esc = run & (definitive == 0) & (boundary != 0)
            res[esc] = 2
            # A definitive failure whose flags also touch the buffer end is a
            # certain false verdict with possibly-incomplete flags.
            inexact = run & (definitive != 0) & (boundary != 0)
            exact &= ~inexact
        res[fail] = -1
        fail_mask[fail] = f[fail]
        reads_before[fail] = step
        run = res == 0

        ok = run & (f == 0)
        pi = np.clip(physical, 0, n - 1)
        next_logical = logical + 4 + remaining[pi].astype(np.int64)
        next_physical = np.maximum(body_end[pi], next_logical)
        if at_eof:
            next_physical = np.minimum(next_physical, n)
        else:
            esc = ok & (next_physical > n)
            res[esc] = 2
            ok &= res == 0
        logical = np.where(ok, next_logical, logical)
        physical = np.where(ok, next_physical, physical)

    full_chain = res == 0
    res[full_chain] = 1
    reads_parsed[full_chain] = reads_to_check
    escaped = res == 2
    exact &= ~escaped
    return ChainResult(
        verdict=res == 1,
        reads_parsed=reads_parsed,
        fail_mask=fail_mask,
        reads_before=reads_before,
        exact=exact,
        escaped=escaped,
    )


def check_flat(
    buf: np.ndarray,
    contig_lengths: np.ndarray,
    candidates: np.ndarray | None = None,
    at_eof: bool = True,
    reads_to_check: int = 10,
) -> ChainResult:
    """Flag pass + chain walk over one flat buffer.

    All-position mode mirrors the device kernel's survivor compaction:
    positions whose own record fails a check (F != 0, the overwhelming
    majority) resolve elementwise from the flag pass — their step-0 outcome
    in ``chain_verdicts`` depends only on F — and the 10-round walk runs
    only over survivors (~1% of positions).
    """
    masks = compute_flags(np.asarray(buf, dtype=np.uint8), contig_lengths)
    if candidates is not None:
        res = chain_verdicts(
            masks, candidates, at_eof=at_eof, reads_to_check=reads_to_check
        )
        _count_check_result(len(candidates), res)
        return res
    n = masks.n
    F = masks.F
    nonzero = F != 0
    if at_eof:
        fail0 = nonzero
        esc0 = np.zeros(n, dtype=bool)
        inexact0 = esc0
    else:
        definitive = F & DEFINITIVE_MASK
        boundary = F & ESCAPE_MASK
        fail0 = nonzero & (definitive != 0)
        esc0 = nonzero & (definitive == 0) & (boundary != 0)
        inexact0 = fail0 & (boundary != 0)
    verdict = np.zeros(n, dtype=bool)
    fail_mask = np.where(fail0, F, 0).astype(np.int32)
    reads_parsed = np.zeros(n, dtype=np.int32)
    reads_before = np.zeros(n, dtype=np.int32)
    escaped = esc0.copy()
    exact = ~(inexact0 | esc0)
    surv = np.flatnonzero(~nonzero).astype(np.int64)
    if len(surv):
        cr = chain_verdicts(
            masks, surv, at_eof=at_eof, reads_to_check=reads_to_check
        )
        verdict[surv] = cr.verdict
        fail_mask[surv] = cr.fail_mask
        reads_parsed[surv] = cr.reads_parsed
        reads_before[surv] = cr.reads_before
        exact[surv] = cr.exact
        escaped[surv] = cr.escaped
    res = ChainResult(verdict, reads_parsed, fail_mask, reads_before, exact, escaped)
    _count_check_result(n, res)
    return res


def _count_check_result(n_candidates: int, res: "ChainResult") -> None:
    """Registry accounting for one NumPy-engine check pass. Every reduction
    here is an extra O(candidates) array pass, so the whole body is gated on
    a live registry — disabled runs pay one None-check."""
    if not obs.enabled():
        return
    obs.count("check.candidates", n_candidates)
    obs.count("check.accepted", int(res.verdict.sum()))
    fm = res.fail_mask
    refuted = fm != 0
    if refuted.any():
        from spark_bam_tpu.check.flags import FLAG_NAMES

        masked = fm[refuted]
        for i, name in enumerate(FLAG_NAMES):
            hits = int(((masked >> i) & 1).sum())
            if hits:
                # lint: allow[obs-contract] suffix bounded by FLAG_NAMES
                obs.count(f"check.flag_refutations.{name}", hits)
