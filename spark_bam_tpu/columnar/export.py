"""The export driver: dataset → sink, through the fault-tolerant executor.

Partitions build their record batches in parallel under the dataset's
``FaultPolicy`` (retries/hedging/quarantine — chaos semantics apply to
export jobs exactly as to loads); the driver re-segments the resulting
batch stream to the configured row count and writes it to the sink.
Partition windows bound memory: at most ~2× the worker count of
partitions are in flight, so a WGS-scale export never materializes the
whole file of records on the host.

Frame segmentation is partition-independent (schema.Rebatcher), which is
what makes the output bytes a pure function of (query, config) — the
serve daemon's ``batch`` op produces the identical stream.
"""

from __future__ import annotations

import time

from spark_bam_tpu import obs
from spark_bam_tpu.columnar.config import ColumnarConfig
from spark_bam_tpu.columnar.native import container_meta
from spark_bam_tpu.columnar.schema import (
    Rebatcher,
    batches_from_records,
    normalize_columns,
)
from spark_bam_tpu.columnar.sink import open_sink
from spark_bam_tpu.parallel.executor import JobReport, run_partitions


def _merge_reports(reports: "list[JobReport]") -> JobReport:
    merged = JobReport(partitions=[])
    for rep in reports:
        merged.partitions.extend(rep.partitions)
        merged.lost_records += rep.lost_records
        merged.lost_blocks += rep.lost_blocks
    return merged


def _partition_batch_stream(ds, batch_rows: int, columns, reports: list):
    """Record batches from every partition, windowed through the executor.

    Each window runs ``run_partitions`` over a slice of the partition
    list; quarantined partitions yield nothing (their loss is visible in
    the merged JobReport), matching ``Dataset.collect`` semantics."""
    compute = ds.compute

    def build(p):
        return list(batches_from_records(compute(p), batch_rows, columns))

    window = max(2 * ds.parallel.num_workers, 4)
    for lo in range(0, len(ds.partitions), window):
        chunk = ds.partitions[lo: lo + window]
        t0 = time.monotonic()
        results, report = run_partitions(build, chunk, ds.parallel, ds.policy)
        obs.observe("columnar.build_ms", (time.monotonic() - t0) * 1000.0)
        reports.append(report)
        for part in results:
            if part is not None:
                yield from part


def export_dataset(
    ds,
    out,
    fmt: str = "native",
    columns=None,
    ccfg: ColumnarConfig = ColumnarConfig(),
    contigs=None,
) -> dict:
    """Export ``ds``'s records to ``out`` in ``fmt``; returns a summary
    dict (rows/batches/bytes/format/path + loss accounting)."""
    columns = normalize_columns(columns if columns is not None else ccfg.columns)
    meta = container_meta(
        columns, codec=ccfg.codec, level=ccfg.level, contigs=contigs
    )
    reports: "list[JobReport]" = []
    rebatcher = Rebatcher(ccfg.batch_rows)
    sink = open_sink(str(out), fmt, meta)
    t0 = time.monotonic()
    try:
        with obs.span("columnar.export", fmt=fmt,
                      partitions=len(ds.partitions)):
            for batch in _partition_batch_stream(
                ds, ccfg.batch_rows, columns, reports
            ):
                for frame in rebatcher.feed(batch):
                    te = time.monotonic()
                    sink.write(frame)
                    obs.observe(
                        "columnar.encode_ms", (time.monotonic() - te) * 1000.0
                    )
            for frame in rebatcher.flush():
                te = time.monotonic()
                sink.write(frame)
                obs.observe(
                    "columnar.encode_ms", (time.monotonic() - te) * 1000.0
                )
        sink.close()
    except BaseException:
        sink.abort()
        raise
    report = _merge_reports(reports)
    ds.last_report = report
    obs.count("columnar.rows", sink.rows)
    obs.count("columnar.bytes_out", sink.bytes_out)
    elapsed = time.monotonic() - t0
    return {
        "path": str(out),
        "format": fmt,
        "columns": list(columns),
        "rows": int(sink.rows),
        "batches": int(sink.batches),
        "bytes": int(sink.bytes_out),
        "seconds": elapsed,
        "lost_records": int(report.lost_records),
        "quarantined": len(report.quarantined),
        "retries": int(report.retries),
    }
