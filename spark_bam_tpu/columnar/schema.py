"""The columnar record schema and its host-side batch representation.

One schema, every outlet: the file sink, ``Dataset.to_batches()``, and
the serve daemon's ``batch`` op all speak these batches, so a consumer
can treat them interchangeably (docs/analytics.md).

Fixed fields are int32 planes (the dtypes the device parser already
emits); variable-length fields use the Arrow large-offset layout — an
``int64 (n+1)`` offsets array into one contiguous ``uint8`` values
buffer — so conversion to ``pyarrow.large_utf8``/``large_binary`` is
zero-copy. ``bin`` is intentionally absent (see package docstring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator

import numpy as np

SCHEMA_VERSION = 1

#: Fixed int32 columns, in canonical order.
FIXED_COLUMNS = (
    "flag", "ref_id", "pos", "mapq", "next_ref_id", "next_pos", "tlen",
)
#: Variable-length columns rendered as text (latin-1).
VAR_STR_COLUMNS = ("name", "cigar", "seq")
#: Variable-length columns kept as raw bytes.
VAR_BYTES_COLUMNS = ("qual", "tags")
VAR_COLUMNS = VAR_STR_COLUMNS + VAR_BYTES_COLUMNS
#: Canonical column order; projections preserve it.
COLUMNS = FIXED_COLUMNS + VAR_COLUMNS


def normalize_columns(columns) -> "tuple[str, ...]":
    """Validated projection in canonical order; None/empty ⇒ all columns."""
    if not columns:
        return COLUMNS
    if isinstance(columns, str):
        columns = [c for c in columns.replace("+", ",").split(",") if c]
    wanted = set()
    for c in columns:
        if c not in COLUMNS:
            raise ValueError(
                f"unknown column {c!r}: expected a subset of "
                f"{', '.join(COLUMNS)}"
            )
        wanted.add(c)
    return tuple(c for c in COLUMNS if c in wanted)


@dataclass
class VarColumn:
    """Arrow-style large-offset layout: values[offsets[i]:offsets[i+1]]."""

    offsets: np.ndarray  # (n+1,) int64
    values: np.ndarray   # (total,) uint8

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def value(self, i: int) -> bytes:
        return bytes(self.values[int(self.offsets[i]): int(self.offsets[i + 1])])


@dataclass
class RecordBatch:
    """One batch: column name → int32 array or :class:`VarColumn`."""

    columns: "dict[str, np.ndarray | VarColumn]"
    num_rows: int

    @property
    def column_names(self) -> "tuple[str, ...]":
        return tuple(self.columns)

    def nbytes(self) -> int:
        total = 0
        for col in self.columns.values():
            if isinstance(col, VarColumn):
                total += col.offsets.nbytes + col.values.nbytes
            else:
                total += col.nbytes
        return total


class BatchBuilder:
    """Row-at-a-time accumulator (the iterator-path producer).

    ``append`` takes a :class:`~spark_bam_tpu.bam.record.BamRecord`;
    ``build`` emits a batch with exactly the rows appended so far and
    resets. The field renderings match the parser-plane producer
    (columnar/from_parser.py) byte for byte — that equality is what makes
    serve responses byte-identical to file-sink output.
    """

    def __init__(self, columns=None):
        self.columns = normalize_columns(columns)
        self._fixed = {c: [] for c in self.columns if c in FIXED_COLUMNS}
        self._var = {c: bytearray() for c in self.columns if c in VAR_COLUMNS}
        self._offsets = {c: [0] for c in self._var}
        self._rows = 0

    def __len__(self) -> int:
        return self._rows

    def append(self, rec) -> None:
        for c, acc in self._fixed.items():
            acc.append(getattr(rec, c))
        for c, buf in self._var.items():
            if c == "name":
                piece = rec.read_name.encode("latin-1")
            elif c == "cigar":
                piece = rec.cigar_string().encode("latin-1")
            elif c == "seq":
                piece = rec.seq.encode("latin-1")
            elif c == "qual":
                piece = bytes(rec.qual)
            else:  # tags
                piece = bytes(rec.tags)
            buf.extend(piece)
            self._offsets[c].append(len(buf))
        self._rows += 1

    def build(self) -> RecordBatch:
        cols: "dict[str, np.ndarray | VarColumn]" = {}
        for c in self.columns:
            if c in self._fixed:
                cols[c] = np.asarray(self._fixed[c], dtype=np.int32)
            else:
                cols[c] = VarColumn(
                    np.asarray(self._offsets[c], dtype=np.int64),
                    np.frombuffer(bytes(self._var[c]), dtype=np.uint8),
                )
        batch = RecordBatch(cols, self._rows)
        self.__init__(self.columns)
        return batch


def batches_from_records(
    records: Iterable, batch_rows: int, columns=None
) -> Iterator[RecordBatch]:
    """Lazy batching of a record iterator. Items may be bare ``BamRecord``s
    or tuples whose last element is one (the ``(Pos, rec)`` /
    ``(path, Pos, rec)`` dataset shapes)."""
    builder = BatchBuilder(columns)
    for item in records:
        rec = item[-1] if isinstance(item, tuple) else item
        builder.append(rec)
        if len(builder) >= batch_rows:
            yield builder.build()
    if len(builder):
        yield builder.build()


def slice_batch(batch: RecordBatch, lo: int, hi: int) -> RecordBatch:
    """Rows [lo, hi) of ``batch`` (values buffers re-based to 0)."""
    cols: "dict[str, np.ndarray | VarColumn]" = {}
    for name, col in batch.columns.items():
        if isinstance(col, VarColumn):
            offs = col.offsets[lo: hi + 1]
            base = int(offs[0]) if len(offs) else 0
            cols[name] = VarColumn(
                (offs - base).astype(np.int64),
                col.values[base: int(offs[-1]) if len(offs) else 0],
            )
        else:
            cols[name] = col[lo:hi]
    return RecordBatch(cols, max(hi - lo, 0))


def concat_batches(batches: "list[RecordBatch]") -> RecordBatch:
    if len(batches) == 1:
        return batches[0]
    names = batches[0].column_names
    cols: "dict[str, np.ndarray | VarColumn]" = {}
    for name in names:
        parts = [b.columns[name] for b in batches]
        if isinstance(parts[0], VarColumn):
            offsets = [parts[0].offsets]
            base = int(parts[0].offsets[-1])
            for p in parts[1:]:
                offsets.append(p.offsets[1:] + base)
                base += int(p.offsets[-1])
            cols[name] = VarColumn(
                np.concatenate(offsets),
                np.concatenate([p.values for p in parts]),
            )
        else:
            cols[name] = np.concatenate(parts)
    return RecordBatch(cols, sum(b.num_rows for b in batches))


class Rebatcher:
    """Re-segment a batch stream into exactly ``batch_rows``-row frames
    (last one partial). Export needs this so frame boundaries depend only
    on the row stream, never on partition boundaries — the property that
    makes file-sink bytes reproducible and serve-identical."""

    def __init__(self, batch_rows: int):
        self.batch_rows = max(int(batch_rows), 1)
        self._pending: "list[RecordBatch]" = []
        self._rows = 0

    def feed(self, batch: RecordBatch) -> Iterator[RecordBatch]:
        if batch.num_rows == 0:
            return
        self._pending.append(batch)
        self._rows += batch.num_rows
        while self._rows >= self.batch_rows:
            merged = concat_batches(self._pending)
            yield slice_batch(merged, 0, self.batch_rows)
            rest = slice_batch(merged, self.batch_rows, merged.num_rows)
            self._pending = [rest] if rest.num_rows else []
            self._rows = rest.num_rows

    def flush(self) -> Iterator[RecordBatch]:
        if self._rows:
            yield concat_batches(self._pending)
        self._pending, self._rows = [], 0


def project(batch: RecordBatch, columns) -> RecordBatch:
    cols = normalize_columns(columns)
    return RecordBatch({c: batch.columns[c] for c in cols}, batch.num_rows)


def iter_rows(batch: RecordBatch) -> Iterator[dict]:
    """Row dicts (str columns decoded latin-1) — the reader-side product
    tests compare against the iterator path."""
    for i in range(batch.num_rows):
        row = {}
        for name, col in batch.columns.items():
            if isinstance(col, VarColumn):
                v = col.value(i)
                row[name] = v.decode("latin-1") if name in VAR_STR_COLUMNS else v
            else:
                row[name] = int(col[i])
        yield row
