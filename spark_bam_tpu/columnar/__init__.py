"""Columnar analytics plane: self-describing record batches from the parse.

The TPU parser already produces per-field planes (tpu/parser.py
``ReadBatch``); this package gives them a stable schema and three outlets
(docs/analytics.md):

- **file sink** — Arrow IPC / Parquet via the optional ``pyarrow`` extra,
  or the zero-dependency native container (``native.py``, mirroring the
  ``.sbi`` framing discipline), written streamingly with atomic
  tmp+replace;
- **API sink** — ``Dataset.to_batches()`` / ``load.api.export()``, routed
  through the fault-tolerant executor;
- **serve sink** — the daemon's ``batch`` op streams the same container
  frames length-prefixed over the wire (serve/service.py), byte-identical
  to the file sink for the same query.

Schema note: ``bin`` is deliberately NOT a column — it is derivable
(``reg2bin(pos, end)``) and BAM files may carry stale values, so exporting
it would break the BAM↔CRAM byte-equality contract (the CRAM reader
recomputes it).
"""

from spark_bam_tpu.columnar.config import ColumnarConfig
from spark_bam_tpu.columnar.native import (
    ColumnarFormatError,
    NativeReader,
    batch_frame,
    container_head,
    container_meta,
    end_frame,
    read_container,
)
from spark_bam_tpu.columnar.schema import (
    COLUMNS,
    SCHEMA_VERSION,
    BatchBuilder,
    RecordBatch,
    VarColumn,
    batches_from_records,
    concat_batches,
    iter_rows,
    normalize_columns,
    project,
    slice_batch,
)

__all__ = [
    "COLUMNS",
    "SCHEMA_VERSION",
    "BatchBuilder",
    "ColumnarConfig",
    "ColumnarFormatError",
    "NativeReader",
    "RecordBatch",
    "VarColumn",
    "batch_frame",
    "batches_from_records",
    "concat_batches",
    "container_head",
    "container_meta",
    "end_frame",
    "iter_rows",
    "normalize_columns",
    "project",
    "read_container",
    "slice_batch",
]
