"""ReadBatch → RecordBatch: the parser-plane producer.

The device parse (tpu/parser.py) already holds every fixed field as an
int32 plane and the flat buffer the variable-length payloads live in;
this module gathers them into schema batches without ever materializing
``BamRecord`` objects. The renderings (cigar string, seq letters, raw
qual/tags bytes) are defined to match ``BamRecord.decode`` exactly, so
a batch built here is byte-identical to one built by the iterator-path
:class:`~spark_bam_tpu.columnar.schema.BatchBuilder` over the same rows
— the serve daemon's byte-equality contract rests on this.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from spark_bam_tpu.bam.record import CIGAR_OPS, SEQ_CODES
from spark_bam_tpu.columnar.schema import (
    FIXED_COLUMNS,
    RecordBatch,
    VarColumn,
    normalize_columns,
)

_SEQ_LUT = np.frombuffer(SEQ_CODES.encode("ascii"), dtype=np.uint8)


def _var_piece(name: str, batch, i: int) -> bytes:
    """One row's rendering of a variable-length column, straight from the
    flat buffer (offsets per the BAM record layout, bam/record.py)."""
    cols = batch.columns
    buf = batch.buf
    start = int(batch.starts[i])
    name_off = int(cols["name_offset"][i])
    l_name = int(cols["l_read_name"][i])
    n_cigar = int(cols["n_cigar"][i])
    l_seq = int(cols["l_seq"][i])
    cig_off = name_off + l_name
    seq_off = cig_off + 4 * n_cigar
    qual_off = seq_off + (l_seq + 1) // 2
    if name == "name":
        return bytes(buf[name_off: name_off + l_name - 1])
    if name == "cigar":
        if n_cigar == 0:
            return b"*"
        ops = np.frombuffer(
            bytes(buf[cig_off: cig_off + 4 * n_cigar]), dtype="<u4"
        )
        return "".join(
            f"{int(v) >> 4}{CIGAR_OPS[int(v) & 0xF]}" for v in ops
        ).encode("latin-1")
    if name == "seq":
        if l_seq == 0:
            return b""
        packed = np.frombuffer(
            bytes(buf[seq_off: seq_off + (l_seq + 1) // 2]), dtype=np.uint8
        )
        nibbles = np.empty(2 * len(packed), dtype=np.uint8)
        nibbles[0::2] = packed >> 4
        nibbles[1::2] = packed & 0xF
        return _SEQ_LUT[nibbles[:l_seq]].tobytes()
    if name == "qual":
        return bytes(buf[qual_off: qual_off + l_seq])
    # tags: everything after qual through the record's declared extent
    end = start + 4 + int(cols["block_size"][i])
    return bytes(buf[qual_off + l_seq: end])


def read_batch_to_record_batches(
    batch, batch_rows: int, columns=None
) -> Iterator[RecordBatch]:
    """Schema batches of ``batch``'s valid rows, ``batch_rows`` per frame
    (last partial), in file order."""
    columns = normalize_columns(columns)
    idx = np.flatnonzero(np.asarray(batch.columns["valid"]))
    batch_rows = max(int(batch_rows), 1)
    for lo in range(0, len(idx), batch_rows):
        rows = idx[lo: lo + batch_rows]
        cols: "dict[str, np.ndarray | VarColumn]" = {}
        for name in columns:
            if name in FIXED_COLUMNS:
                cols[name] = np.ascontiguousarray(
                    np.asarray(batch.columns[name])[rows], dtype=np.int32
                )
            else:
                values = bytearray()
                offsets = np.empty(len(rows) + 1, dtype=np.int64)
                offsets[0] = 0
                for k, i in enumerate(rows):
                    values.extend(_var_piece(name, batch, int(i)))
                    offsets[k + 1] = len(values)
                cols[name] = VarColumn(
                    offsets, np.frombuffer(bytes(values), dtype=np.uint8)
                )
        yield RecordBatch(cols, len(rows))
