"""Columnar-plane knobs: the ``Config.columnar`` string spec.

Same compact-spec pattern as ``faults``/``serve``/``remote`` so the
frozen Config stays hashable and the ``SPARK_BAM_COLUMNAR`` env var and
``--columnar`` CLI flag work through the existing plumbing:

    rows=8192,codec=zlib,level=6,columns=flag+pos+name

``rows`` is the record-batch row target (frame segmentation — identical
between the file sink and the serve ``batch`` op so their bytes match),
``codec`` compresses the per-column buffers of the native container
("none" | "zlib" | "deflate"), ``columns`` is a ``+``-separated default
projection. ``deflate`` routes buffers through the write-path compressor
(compress/codec.py ``encode_zlib_stream``: device fixed-Huffman lanes
when ``SPARK_BAM_DEFLATE`` enables them) as spec-valid zlib streams —
the read side is unchanged. Literal-only fixed Huffman never beats raw
on binary planes, so the keep-only-when-smaller rule usually stores
those buffers uncompressed; the codec exists for write-path parity, not
ratio (docs/analytics.md).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

from spark_bam_tpu.columnar.schema import normalize_columns

_CODECS = ("none", "zlib", "deflate")


@dataclass(frozen=True)
class ColumnarConfig:
    batch_rows: int = 8192
    codec: str = "none"
    level: int = 6
    columns: "tuple[str, ...] | None" = None

    @staticmethod
    @functools.lru_cache(maxsize=64)
    def parse(spec: str) -> "ColumnarConfig":
        """Parse a ``rows=...,codec=...,level=...,columns=a+b`` spec
        ("" ⇒ defaults). Raises ``ValueError`` on unknown keys/values —
        the CLI validates before any work starts, like every other knob."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"Bad columnar spec {spec!r}: {part!r} is not key=value"
                )
            key, value = part.split("=", 1)
            key, value = key.strip(), value.strip()
            if key in ("rows", "batch_rows"):
                rows = int(value)
                if rows <= 0:
                    raise ValueError(f"columnar rows must be positive: {value}")
                kw["batch_rows"] = rows
            elif key == "codec":
                if value not in _CODECS:
                    raise ValueError(
                        f"Bad columnar codec {value!r}: expected "
                        f"{' | '.join(_CODECS)}"
                    )
                kw["codec"] = value
            elif key == "level":
                level = int(value)
                if not 0 <= level <= 9:
                    raise ValueError(f"columnar level must be 0..9: {value}")
                kw["level"] = level
            elif key == "columns":
                kw["columns"] = normalize_columns(value)
            else:
                raise ValueError(f"Unknown columnar key: {key!r}")
        return ColumnarConfig(**kw)
