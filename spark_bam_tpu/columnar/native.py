"""The native columnar container: the zero-dependency file format.

Mirrors the ``.sbi`` sidecar discipline (sbi/format.py): magic + version,
tagged CRC-framed sections, unknown-tag skip on read, typed structural
errors. Layout:

    magic   4s   b"SBCR"
    version u16  (1)
    flags   u16  (0, reserved)
    frame*  — each frame is
        tag         u8    (1 schema, 2 batch, 3 end; others skipped)
        payload_len u64
        payload     bytes
        crc32       u32   over tag+payload_len+payload

The schema frame's payload is deterministic JSON (sorted keys, no
whitespace) holding ``schema_version``/``columns``/``codec``/``level``/
``contigs`` — nothing run-specific (no paths, no timestamps), so the
same query produces the same bytes whether the producer is the file
sink or the serve daemon. A batch frame holds ``rows u32, ncols u16``
then per column (schema order) a kind byte (0 fixed / 1 var / 2
dictionary) and its buffer(s); each buffer is ``raw_len u64, enc_len
u64, bytes`` where ``enc_len == raw_len`` means stored raw (codec
"none") and anything else is zlib. Kind 2 (emitted for ``name``/
``cigar`` only when it is strictly smaller than kind 1) holds int32
per-row codes plus the dictionary's own offsets/values buffers, the
dictionary in first-occurrence order so the bytes stay a pure function
of the row stream. The end frame carries ``total_rows u64, n_batches u32``
so a reader detects truncation in O(1), like ``_Reader.count``.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator

import numpy as np

from spark_bam_tpu.columnar.schema import (
    COLUMNS,
    SCHEMA_VERSION,
    RecordBatch,
    VarColumn,
)
from spark_bam_tpu.core.guard import StructurallyInvalid

MAGIC = b"SBCR"
VERSION = 1

TAG_SCHEMA = 1
TAG_BATCH = 2
TAG_END = 3

_HEAD = struct.Struct("<4sHH")
_FRAME = struct.Struct("<BQ")
_CRC = struct.Struct("<I")
_BUF = struct.Struct("<QQ")
_BATCH = struct.Struct("<IH")
_END = struct.Struct("<QI")


class ColumnarFormatError(StructurallyInvalid):
    """Structurally invalid container (bad magic/CRC/framing/lengths)."""


def container_meta(columns, codec: str = "none", level: int = 6,
                   contigs=None) -> dict:
    """The schema-frame payload. Deterministic by construction: fixed key
    set, canonical column order, no environment-dependent values."""
    return {
        "schema_version": SCHEMA_VERSION,
        "columns": list(columns),
        "codec": codec,
        "level": int(level),
        "contigs": [[str(n), int(l)] for n, l in (contigs or [])],
    }


def _frame(tag: int, payload: bytes) -> bytes:
    head = _FRAME.pack(tag, len(payload))
    return head + payload + _CRC.pack(zlib.crc32(head + payload) & 0xFFFFFFFF)


def _encode_buffer(raw: bytes, codec: str, level: int) -> bytes:
    if codec == "zlib":
        enc = zlib.compress(raw, level)
        if len(enc) < len(raw):
            return _BUF.pack(len(raw), len(enc)) + enc
    elif codec == "deflate" and raw:
        # Write-path compressor (device fixed-Huffman lanes when enabled);
        # emits a plain zlib stream, so _decode_buffer needs no new code.
        from spark_bam_tpu.compress.codec import encode_zlib_stream

        enc = encode_zlib_stream(raw)
        if len(enc) < len(raw):
            return _BUF.pack(len(raw), len(enc)) + enc
    return _BUF.pack(len(raw), len(raw)) + raw


def container_head(meta: dict) -> bytes:
    """Magic + version + the schema frame — the first chunk of every
    container, file or wire."""
    payload = json.dumps(meta, separators=(",", ":"), sort_keys=True).encode()
    return _HEAD.pack(MAGIC, VERSION, 0) + _frame(TAG_SCHEMA, payload)


#: Var columns worth a dictionary pass: read names repeat their flowcell
#: prefix and CIGARs collapse to a handful of shapes, while seq/qual are
#: near-unique per row (the dict would only add bytes there).
_DICT_COLUMNS = frozenset({"name", "cigar"})


def _var_parts(col: VarColumn, codec: str, level: int) -> "list[bytes]":
    return [
        b"\x01",
        _encode_buffer(
            np.ascontiguousarray(col.offsets, dtype=np.int64).tobytes(),
            codec, level,
        ),
        _encode_buffer(
            np.ascontiguousarray(col.values, dtype=np.uint8).tobytes(),
            codec, level,
        ),
    ]


def _dict_parts(col: VarColumn, codec: str, level: int) -> "list[bytes]":
    """Kind-2 encoding: per-row int32 codes into a first-occurrence-order
    dictionary (deterministic — a pure function of the row stream, so
    the same query still produces the same bytes)."""
    offsets = np.ascontiguousarray(col.offsets, dtype=np.int64)
    values = np.ascontiguousarray(col.values, dtype=np.uint8)
    rows = len(offsets) - 1
    codes = np.empty(rows, dtype=np.int32)
    index: "dict[bytes, int]" = {}
    entries: "list[bytes]" = []
    for i in range(rows):
        s = values[offsets[i]: offsets[i + 1]].tobytes()
        code = index.get(s)
        if code is None:
            code = len(entries)
            index[s] = code
            entries.append(s)
        codes[i] = code
    d_off = np.zeros(len(entries) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in entries], out=d_off[1:])
    return [
        b"\x02",
        _encode_buffer(codes.tobytes(), codec, level),
        _encode_buffer(d_off.tobytes(), codec, level),
        _encode_buffer(b"".join(entries), codec, level),
    ]


def batch_frame(batch: RecordBatch, meta: dict) -> bytes:
    codec, level = meta["codec"], meta["level"]
    parts = [_BATCH.pack(batch.num_rows, len(meta["columns"]))]
    for name in meta["columns"]:
        col = batch.columns[name]
        if isinstance(col, VarColumn):
            var = _var_parts(col, codec, level)
            if name in _DICT_COLUMNS:
                # Keep-only-when-smaller: the dict section pays off only
                # when the column actually repeats.
                dct = _dict_parts(col, codec, level)
                if sum(map(len, dct)) < sum(map(len, var)):
                    var = dct
            parts.extend(var)
        else:
            parts.append(b"\x00")
            parts.append(_encode_buffer(
                np.ascontiguousarray(col, dtype=np.int32).tobytes(),
                codec, level,
            ))
    return _frame(TAG_BATCH, b"".join(parts))


def end_frame(total_rows: int, n_batches: int) -> bytes:
    return _frame(TAG_END, _END.pack(total_rows, n_batches))


# ------------------------------------------------------------------- reading
def _take(buf: memoryview, p: int, n: int, what: str) -> "tuple[memoryview, int]":
    if p + n > len(buf):
        raise ColumnarFormatError(
            f"truncated container: {what} needs {n} bytes at {p}, "
            f"have {len(buf) - p}"
        )
    return buf[p: p + n], p + n


def _decode_buffer(payload: memoryview, p: int) -> "tuple[bytes, int]":
    head, p = _take(payload, p, _BUF.size, "buffer header")
    raw_len, enc_len = _BUF.unpack(head)
    data, p = _take(payload, p, enc_len, "buffer body")
    if enc_len == raw_len:
        return bytes(data), p
    raw = zlib.decompress(bytes(data))
    if len(raw) != raw_len:
        raise ColumnarFormatError(
            f"buffer inflated to {len(raw)} bytes, header declared {raw_len}"
        )
    return raw, p


def _decode_batch(payload: memoryview, columns) -> RecordBatch:
    head, p = _take(payload, 0, _BATCH.size, "batch header")
    rows, ncols = _BATCH.unpack(head)
    if ncols != len(columns):
        raise ColumnarFormatError(
            f"batch has {ncols} columns, schema declares {len(columns)}"
        )
    cols: "dict[str, np.ndarray | VarColumn]" = {}
    for name in columns:
        kind, p = _take(payload, p, 1, "column kind")
        if kind[0] == 0:
            raw, p = _decode_buffer(payload, p)
            arr = np.frombuffer(raw, dtype=np.int32)
            if len(arr) != rows:
                raise ColumnarFormatError(
                    f"column {name!r}: {len(arr)} values for {rows} rows"
                )
            cols[name] = arr
        elif kind[0] == 1:
            raw_off, p = _decode_buffer(payload, p)
            raw_val, p = _decode_buffer(payload, p)
            offsets = np.frombuffer(raw_off, dtype=np.int64)
            values = np.frombuffer(raw_val, dtype=np.uint8)
            if len(offsets) != rows + 1:
                raise ColumnarFormatError(
                    f"column {name!r}: {len(offsets)} offsets for {rows} rows"
                )
            if rows and (int(offsets[-1]) != len(values) or int(offsets[0]) != 0
                         or (np.diff(offsets) < 0).any()):
                raise ColumnarFormatError(
                    f"column {name!r}: offsets inconsistent with "
                    f"{len(values)} value bytes"
                )
            cols[name] = VarColumn(offsets, values)
        elif kind[0] == 2:
            # Dictionary section (name/cigar): int32 codes + the dict's
            # own offsets/values. Reconstructs the full VarColumn so
            # consumers never see the encoding.
            raw_codes, p = _decode_buffer(payload, p)
            raw_off, p = _decode_buffer(payload, p)
            raw_val, p = _decode_buffer(payload, p)
            codes = np.frombuffer(raw_codes, dtype=np.int32)
            d_off = np.frombuffer(raw_off, dtype=np.int64)
            d_val = np.frombuffer(raw_val, dtype=np.uint8)
            if len(codes) != rows:
                raise ColumnarFormatError(
                    f"column {name!r}: {len(codes)} codes for {rows} rows"
                )
            ndict = len(d_off) - 1
            if ndict < 0 or (len(d_off) and (
                    int(d_off[0]) != 0
                    or (ndict and int(d_off[-1]) != len(d_val))
                    or (np.diff(d_off) < 0).any())):
                raise ColumnarFormatError(
                    f"column {name!r}: dictionary offsets inconsistent "
                    f"with {len(d_val)} value bytes"
                )
            if rows and (ndict == 0 or codes.min() < 0
                         or codes.max() >= ndict):
                raise ColumnarFormatError(
                    f"column {name!r}: code out of range for "
                    f"{ndict}-entry dictionary"
                )
            lens = np.diff(d_off)
            row_lens = lens[codes] if rows else np.zeros(0, dtype=np.int64)
            offsets = np.zeros(rows + 1, dtype=np.int64)
            np.cumsum(row_lens, out=offsets[1:])
            values = (
                np.concatenate([
                    d_val[d_off[c]: d_off[c + 1]] for c in codes
                ]) if rows and int(offsets[-1])
                else np.zeros(0, dtype=np.uint8)
            )
            cols[name] = VarColumn(offsets, values)
        else:
            raise ColumnarFormatError(
                f"column {name!r}: unknown kind byte {kind[0]}"
            )
    return RecordBatch(cols, rows)


class NativeReader:
    """Validating reader over a container's bytes or file path.

    ``meta`` is decoded eagerly (so schema errors surface at open);
    batches stream via :meth:`iter_batches`. Unknown frame tags are
    skipped (CRC still checked) — the forward-compatibility contract the
    ``.sbi`` reader set.
    """

    def __init__(self, src):
        if isinstance(src, (bytes, bytearray, memoryview)):
            self._data = memoryview(src)
        else:
            with open(src, "rb") as f:
                self._data = memoryview(f.read())
        head, p = _take(self._data, 0, _HEAD.size, "container header")
        magic, version, _flags = _HEAD.unpack(head)
        if magic != MAGIC:
            raise ColumnarFormatError(
                f"bad magic {bytes(magic)!r}: not a columnar container"
            )
        if version != VERSION:
            raise ColumnarFormatError(f"unsupported container version {version}")
        tag, payload, p = self._frame_at(p)
        if tag != TAG_SCHEMA:
            raise ColumnarFormatError(
                f"first frame has tag {tag}, expected schema ({TAG_SCHEMA})"
            )
        try:
            self.meta = json.loads(bytes(payload))
        except Exception as exc:
            raise ColumnarFormatError(f"schema frame is not JSON: {exc}") from exc
        if self.meta.get("schema_version") != SCHEMA_VERSION:
            raise ColumnarFormatError(
                f"unsupported schema_version {self.meta.get('schema_version')}"
            )
        cols = self.meta.get("columns")
        if (not isinstance(cols, list) or not cols
                or any(c not in COLUMNS for c in cols)):
            raise ColumnarFormatError(f"schema declares bad columns: {cols!r}")
        self.columns = tuple(cols)
        self._body_at = p

    def _frame_at(self, p: int) -> "tuple[int, memoryview, int]":
        head, q = _take(self._data, p, _FRAME.size, "frame header")
        tag, length = _FRAME.unpack(head)
        payload, q = _take(self._data, q, length, f"frame tag={tag} payload")
        crc_raw, q = _take(self._data, q, _CRC.size, "frame crc")
        want = zlib.crc32(self._data[p: p + _FRAME.size + length]) & 0xFFFFFFFF
        if _CRC.unpack(crc_raw)[0] != want:
            raise ColumnarFormatError(f"frame tag={tag} at {p}: CRC mismatch")
        return tag, payload, q

    def iter_batches(self) -> Iterator[RecordBatch]:
        p = self._body_at
        total = 0
        n_batches = 0
        saw_end = False
        while p < len(self._data):
            tag, payload, p = self._frame_at(p)
            if tag == TAG_BATCH:
                if saw_end:
                    raise ColumnarFormatError("batch frame after end frame")
                batch = _decode_batch(payload, self.columns)
                total += batch.num_rows
                n_batches += 1
                yield batch
            elif tag == TAG_END:
                if len(payload) != _END.size:
                    raise ColumnarFormatError("end frame has wrong size")
                want_rows, want_batches = _END.unpack(bytes(payload))
                if want_rows != total or want_batches != n_batches:
                    raise ColumnarFormatError(
                        f"end frame declares {want_rows} rows / "
                        f"{want_batches} batches, read {total} / {n_batches}"
                    )
                saw_end = True
            # unknown tags: CRC validated by _frame_at, content skipped
        if not saw_end:
            raise ColumnarFormatError("container has no end frame (truncated?)")


def read_container(src) -> "tuple[dict, list[RecordBatch]]":
    """Convenience: (meta, all batches) of a container path or bytes."""
    reader = NativeReader(src)
    return reader.meta, list(reader.iter_batches())
