"""Arrow IPC *stream format* as a serve wire schema (``wire=arrow``).

The SBCR container (native.py) stays the default ``batch`` payload;
this module renders the same record batches as Arrow IPC **stream**
messages instead, so an ``[arrow]`` client needs zero deserialization:
concatenate the frames (or map them straight out of the shm segment —
docs/serving.md "Transport") and hand the buffer to
``pa.ipc.open_stream``; the columns come back as zero-copy Arrow
arrays.

Framing is unchanged — each IPC message is one transport frame, the
response's ``binary_frames``/``resume_from`` mean exactly what they
mean for SBCR: frame 0 is the schema message, frames ``1..n`` are the
record-batch messages, the last frame is the 8-byte end-of-stream
marker. The sequence is deterministic for an unchanged file + query
(pyarrow's IPC encoding is), so the resume token and streaming
failover carry over untouched.

pyarrow is optional everywhere in this repo: :func:`arrow_available`
gates the path and the service answers ``Unsupported`` without it.
"""

from __future__ import annotations

from spark_bam_tpu.columnar.schema import (
    VAR_BYTES_COLUMNS,
    VAR_STR_COLUMNS,
)
from spark_bam_tpu.columnar.sink import _pyarrow, to_arrow_batch

#: Arrow IPC stream end-of-stream marker (continuation sentinel + zero
#: metadata length) — the final frame of every ``wire=arrow`` response.
EOS = b"\xff\xff\xff\xff\x00\x00\x00\x00"


def arrow_available() -> bool:
    try:
        _pyarrow()
    except Exception:
        return False
    return True


def arrow_schema(columns):
    """The projection's Arrow schema from the STATIC type tables —
    independent of any data, so an empty result still opens as a valid
    (zero-batch) stream. Types mirror ``sink.to_arrow_batch``: int32
    fixed planes, ``large_utf8``/``large_binary`` var planes."""
    pa = _pyarrow()
    fields = []
    for name in columns:
        if name in VAR_STR_COLUMNS:
            typ = pa.large_utf8()
        elif name in VAR_BYTES_COLUMNS:
            typ = pa.large_binary()
        else:
            typ = pa.int32()
        fields.append(pa.field(name, typ))
    return pa.schema(fields)


def stream_frames(batch, batch_rows: int,
                  columns) -> "tuple[list[bytes], int]":
    """Render ``batch``'s valid rows as IPC stream frames:
    ``[schema, record-batch..., EOS]``. Returns ``(frames, rows)``."""
    from spark_bam_tpu.columnar.from_parser import (
        read_batch_to_record_batches,
    )

    frames = [bytes(arrow_schema(columns).serialize())]
    rows = 0
    for rb in read_batch_to_record_batches(batch, batch_rows, columns):
        frames.append(bytes(to_arrow_batch(rb).serialize()))
        rows += rb.num_rows
    frames.append(EOS)
    return frames, rows


def open_stream(buf):
    """Convenience reader: ``open_stream(b"".join(frames))`` (bytes or
    a mapped memoryview — kept zero-copy either way)."""
    pa = _pyarrow()
    return pa.ipc.open_stream(pa.py_buffer(buf))
