"""File sinks: native container, Arrow IPC, Parquet.

All three write streamingly — one record batch at a time, O(batch) host
memory — into a same-directory temp file that is atomically renamed
into place on close (``core/atomic.AtomicFile``, the idiom shared with
``write_bam`` and the rewrite CLI), so a crashed export never leaves a
half-written output at the target path.

Arrow and Parquet need the optional ``pyarrow`` extra
(``pip install spark-bam-tpu[arrow]``); the native container has zero
dependencies and is the default. Conversion to Arrow is zero-copy: the
schema's large-offset layout is exactly ``large_utf8``/``large_binary``.
"""

from __future__ import annotations

import contextlib

from spark_bam_tpu.core.atomic import AtomicFile as _AtomicFile
from spark_bam_tpu.core.guard import map_write_error
from spark_bam_tpu.columnar.native import (
    batch_frame,
    container_head,
    end_frame,
)
from spark_bam_tpu.columnar.schema import (
    VAR_STR_COLUMNS,
    RecordBatch,
    VarColumn,
)

FORMATS = ("native", "arrow", "parquet")


class ColumnarUnavailable(RuntimeError):
    """Requested an Arrow/Parquet sink without pyarrow installed."""


@contextlib.contextmanager
def _guarded(what: str, path: str):
    """Classify OSErrors escaping a sink write/commit: exhaustion errnos
    (ENOSPC/EDQUOT/EIO) become the guard taxonomy's retryable
    ``ResourceExhausted`` instead of bypassing fault classification."""
    try:
        yield
    except OSError as exc:
        raise map_write_error(exc, what, path=path) from exc


def _pyarrow():
    try:
        import pyarrow
    except ImportError as exc:
        raise ColumnarUnavailable(
            "pyarrow is not installed: arrow/parquet sinks need the "
            "optional extra (pip install spark-bam-tpu[arrow]); the "
            "'native' format has no dependencies"
        ) from exc
    return pyarrow


class NativeSink:
    """Streaming writer of the native container (native.py frames)."""

    def __init__(self, out_path: str, meta: dict):
        self.meta = meta
        self.out_path = str(out_path)
        self._file = _AtomicFile(out_path)
        head = container_head(meta)
        with _guarded("container write", self.out_path):
            self._file.f.write(head)
        self.rows = 0
        self.batches = 0
        self.bytes_out = len(head)

    def write(self, batch: RecordBatch) -> None:
        frame = batch_frame(batch, self.meta)
        with _guarded("container write", self.out_path):
            self._file.f.write(frame)
        self.rows += batch.num_rows
        self.batches += 1
        self.bytes_out += len(frame)

    def close(self) -> None:
        tail = end_frame(self.rows, self.batches)
        with _guarded("container commit", self.out_path):
            self._file.f.write(tail)
            self.bytes_out += len(tail)
            self._file.commit()

    def abort(self) -> None:
        self._file.abort()


def to_arrow_batch(batch: RecordBatch):
    """Zero-copy RecordBatch → ``pyarrow.RecordBatch``."""
    pa = _pyarrow()
    arrays = []
    fields = []
    for name, col in batch.columns.items():
        if isinstance(col, VarColumn):
            typ = pa.large_utf8() if name in VAR_STR_COLUMNS else pa.large_binary()
            arrays.append(pa.Array.from_buffers(
                typ, batch.num_rows,
                [None, pa.py_buffer(col.offsets), pa.py_buffer(col.values)],
            ))
            fields.append(pa.field(name, typ))
        else:
            arrays.append(pa.array(col, type=pa.int32()))
            fields.append(pa.field(name, pa.int32()))
    return pa.record_batch(arrays, schema=pa.schema(fields))


class ArrowSink:
    """Arrow IPC file (Feather v2 container) via RecordBatchFileWriter."""

    def __init__(self, out_path: str, meta: dict):
        self.pa = _pyarrow()
        self.meta = meta
        self.out_path = str(out_path)
        self._file = _AtomicFile(out_path)
        self._writer = None
        self.rows = 0
        self.batches = 0
        self.bytes_out = 0

    def write(self, batch: RecordBatch) -> None:
        ab = to_arrow_batch(batch)
        with _guarded("arrow write", self.out_path):
            if self._writer is None:
                self._writer = self.pa.ipc.new_file(self._file.f, ab.schema)
            self._writer.write_batch(ab)
        self.rows += batch.num_rows
        self.batches += 1

    def close(self) -> None:
        with _guarded("arrow commit", self.out_path):
            if self._writer is None:
                # Zero batches: still a valid (empty) IPC file with the
                # schema.
                from spark_bam_tpu.columnar.schema import BatchBuilder

                empty = BatchBuilder(self.meta["columns"]).build()
                self._writer = self.pa.ipc.new_file(
                    self._file.f, to_arrow_batch(empty).schema
                )
            self._writer.close()
            self.bytes_out = self._file.f.tell()
            self._file.commit()

    def abort(self) -> None:
        self._file.abort()


class ParquetSink:
    """Parquet via ``pyarrow.parquet.ParquetWriter``, one row group per
    record batch."""

    def __init__(self, out_path: str, meta: dict):
        self.pa = _pyarrow()
        import pyarrow.parquet as pq

        self.pq = pq
        self.meta = meta
        self.out_path = str(out_path)
        self._file = _AtomicFile(out_path)
        self._writer = None
        self.rows = 0
        self.batches = 0
        self.bytes_out = 0

    def write(self, batch: RecordBatch) -> None:
        ab = to_arrow_batch(batch)
        with _guarded("parquet write", self.out_path):
            if self._writer is None:
                self._writer = self.pq.ParquetWriter(self._file.f, ab.schema)
            self._writer.write_table(self.pa.Table.from_batches([ab]))
        self.rows += batch.num_rows
        self.batches += 1

    def close(self) -> None:
        with _guarded("parquet commit", self.out_path):
            if self._writer is None:
                from spark_bam_tpu.columnar.schema import BatchBuilder

                empty = BatchBuilder(self.meta["columns"]).build()
                ab = to_arrow_batch(empty)
                self._writer = self.pq.ParquetWriter(self._file.f, ab.schema)
                self._writer.write_table(self.pa.Table.from_batches([ab]))
            self._writer.close()
            self.bytes_out = self._file.f.tell()
            self._file.commit()

    def abort(self) -> None:
        self._file.abort()


def open_sink(out_path: str, fmt: str, meta: dict):
    """Format-dispatched sink; ``fmt`` is one of :data:`FORMATS`."""
    if fmt == "native":
        return NativeSink(out_path, meta)
    if fmt == "arrow":
        return ArrowSink(out_path, meta)
    if fmt == "parquet":
        return ParquetSink(out_path, meta)
    raise ValueError(
        f"unknown export format {fmt!r}: expected {' | '.join(FORMATS)}"
    )
