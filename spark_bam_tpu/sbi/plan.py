"""Split-plan build/consume — the bridge between ``.sbi`` sidecars and
the load path's per-split record-start resolution.

A plan is the *raw* per-boundary resolution for one split size: one
``PlanEntry`` per file split, pre-dedup, so warm consumers reconstruct
exactly what live resolution would have produced. Unresolvable
boundaries (``NoReadFoundException`` — scan budget exhausted mid-file)
are stored as ``PLAN_UNRESOLVED`` and re-resolved live on every load:
the cache must never convert an error into silence.
"""

from __future__ import annotations

from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.sbi.format import (
    PLAN_NONE,
    PLAN_POS,
    PLAN_UNRESOLVED,
    PlanEntry,
)


def build_split_plan(path, splits, header, config) -> list[PlanEntry]:
    """Resolve every split boundary driver-side into a raw plan."""
    from spark_bam_tpu.check.checker import NoReadFoundException
    from spark_bam_tpu.load.api import _resolve_split_start

    entries: list[PlanEntry] = []
    for split in splits:
        try:
            pos = _resolve_split_start(path, split, header, config)
        except NoReadFoundException:
            entries.append(PlanEntry(split.start, PLAN_UNRESOLVED, None))
            continue
        entries.append(
            PlanEntry(
                split.start,
                PLAN_NONE if pos is None else PLAN_POS,
                pos,
            )
        )
    return entries


def plan_to_starts(splits, entries: list[PlanEntry]) -> dict | None:
    """``{split: Pos | None}`` for the splits a plan covers.

    ``PLAN_UNRESOLVED`` boundaries are *absent* from the result — the
    consumer resolves those live (and re-raises what the build saw).
    Returns None when the plan doesn't line up with ``splits`` (e.g. a
    sidecar built under a different splitter): callers treat that as a
    miss rather than guess."""
    by_start = {e.file_start: e for e in entries}
    starts: dict = {}
    for split in splits:
        e = by_start.get(split.start)
        if e is None:
            return None
        if e.kind == PLAN_POS:
            starts[split] = e.pos
        elif e.kind == PLAN_NONE:
            starts[split] = None
    return starts


def plan_split_starts(entries: list[PlanEntry], file_size: int):
    """Deduped ``(starts, ends)`` the way ``cli/splits_util`` computes
    them live: consecutive boundaries resolving to the same position
    collapse, unresolved boundaries are skipped (matching the native
    splitter's per-boundary ``continue``), ends tile to the next start
    with EOF = ``Pos(file_size, 0)``."""
    starts: list[Pos] = []
    for e in entries:
        if e.kind != PLAN_POS:
            continue
        if not starts or starts[-1] != e.pos:
            starts.append(e.pos)
    ends = starts[1:] + [Pos(file_size, 0)]
    return starts, ends
