"""Persistent split-index cache (``.sbi`` sidecars).

The reference lineage's splitting-BAM index — hadoop-bam's ``.sbi``,
spark-bam's ``IndexBlocks``/``IndexRecords`` sidecars — turned repeated
loads of the same file into pure record streaming. This package is that
idea as a *validated, write-through cache*: a versioned binary format
(``sbi.format``) holding the file fingerprint, BGZF block metadata,
resolved split plans, and record-start virtual positions; and a
``CacheStore`` (``sbi.store``) that resolves sidecars next to the BAM or
content-addressed under ``SPARK_BAM_CACHE_DIR``, validates on read
(stale or corrupt ⇒ invalidate and recompute, never a wrong answer),
writes atomically, and evicts by LRU under a byte budget.

Wiring: ``load/api.py`` and ``load/tpu_load.py`` consult before split
computation and write through after, governed by ``Config.cache`` /
``SPARK_BAM_CACHE`` / ``--cache``; the ``index`` CLI subcommand builds
sidecars ahead of time. Semantics in ``docs/caching.md``.
"""

from spark_bam_tpu.sbi.format import (
    Fingerprint,
    PlanEntry,
    SbiFormatError,
    SbiIndex,
    config_digest,
    decode_sbi,
    encode_sbi,
    fingerprint_of,
)
from spark_bam_tpu.sbi.store import (
    CacheMode,
    CacheStore,
    StaleCacheError,
    cache_events,
    cache_status_line,
    reset_cache_events,
)

__all__ = [
    "CacheMode",
    "CacheStore",
    "Fingerprint",
    "PlanEntry",
    "SbiFormatError",
    "SbiIndex",
    "StaleCacheError",
    "cache_events",
    "cache_status_line",
    "config_digest",
    "decode_sbi",
    "encode_sbi",
    "fingerprint_of",
    "reset_cache_events",
]
