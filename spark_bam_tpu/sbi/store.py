"""Sidecar resolution, validation, atomic writes, LRU eviction.

``CacheStore`` is the one place ``.sbi`` sidecars are read and written:

- **Resolution**: next to the BAM (``<path>.sbi``) by default, or
  content-addressed under a shared ``SPARK_BAM_CACHE_DIR`` (set the env
  var, or pass ``cache_dir``) — the shared-dir mode is what read-only
  inputs and multi-tenant hosts want, and the only mode that can cache
  remote (URL) BAMs.
- **Validation**: every read re-fingerprints the BAM (size, mtime,
  head-CRC, checker-config digest) and CRC-checks the sidecar bytes.
  Any mismatch or corruption invalidates — the cache recomputes, it
  never changes results. Strict mode (``--cache readwrite,strict``)
  raises ``StaleCacheError`` instead, mirroring ``FaultPolicy``'s
  strict-vs-tolerant split for operators who want staleness loud.
- **Atomicity**: write-to-tmp + ``os.replace`` with a pid+sequence
  suffix (the ``bgzf/index_blocks.py`` pattern, hardened for in-process
  concurrency) — racing writers never yield a torn file.
- **Eviction**: shared-dir caches keep a byte budget
  (``SPARK_BAM_CACHE_BUDGET``, byte shorthand ok); least-recently-used
  sidecars are evicted after each write (reads touch mtime).

Remote sidecar reads go through ``core/faults.with_retries`` so a
transient transport blip costs a retry, not a cold load. Metrics:
``cache.hits`` / ``cache.misses`` / ``cache.invalidations`` /
``cache.evictions`` counters, a ``cache.bytes`` gauge, and
``cache.read_ms`` / ``cache.write_ms`` histograms.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import threading
import time
from dataclasses import dataclass

from spark_bam_tpu import obs
from spark_bam_tpu.core import faults
from spark_bam_tpu.core.channel import is_url, open_channel, path_exists
from spark_bam_tpu.core.faults import FaultPolicy, Unrecoverable, with_retries
from spark_bam_tpu.core.guard import ResourceExhausted, map_write_error
from spark_bam_tpu.sbi.format import (
    SbiFormatError,
    SbiIndex,
    decode_sbi,
    encode_sbi,
    fingerprint_of,
)

log = logging.getLogger(__name__)


class StaleCacheError(IOError, Unrecoverable):
    """Strict cache mode: the sidecar exists but is stale or corrupt.
    Deterministic (re-reading won't fix the fingerprint), hence
    ``Unrecoverable`` — the executor fails fast instead of retrying."""


@dataclass(frozen=True)
class CacheMode:
    """Parsed ``--cache`` / ``Config.cache`` / ``SPARK_BAM_CACHE`` spec."""

    read: bool = False
    write: bool = False
    strict: bool = False

    @property
    def enabled(self) -> bool:
        return self.read or self.write

    _NAMES = ("off", "read", "write", "readwrite")

    @staticmethod
    def parse(spec: str) -> "CacheMode":
        """``off | read | write | readwrite`` with an optional ``,strict``
        suffix; ``""`` ⇒ off."""
        tokens = [t.strip() for t in (spec or "").split(",") if t.strip()]
        mode, strict = "off", False
        for tok in tokens:
            if tok == "strict":
                strict = True
            elif tok in CacheMode._NAMES:
                mode = tok
            else:
                raise ValueError(
                    f"Unknown cache mode {tok!r}: expected one of "
                    f"{', '.join(CacheMode._NAMES)} (+ optional ',strict')"
                )
        return CacheMode(
            read=mode in ("read", "readwrite"),
            write=mode in ("write", "readwrite"),
            strict=strict,
        )


# ------------------------------------------------------------ status events
@dataclass(frozen=True)
class CacheEvent:
    """One cache interaction, kept for the CLI status line."""

    state: str   # hit | miss | invalidated | written | skipped | evicted
    reason: str
    path: str


_events: list[CacheEvent] = []
_events_lock = threading.Lock()


def _record(state: str, reason: str, path: str) -> None:
    with _events_lock:
        _events.append(CacheEvent(state, reason, path))


def cache_events() -> list[CacheEvent]:
    with _events_lock:
        return list(_events)


def reset_cache_events() -> None:
    with _events_lock:
        _events.clear()


def cache_status_line(path, config) -> str:
    """One operator-facing line: why this run's load was warm or cold.
    When the run never consulted the cache (e.g. check-bam), probes the
    sidecar so the line still says what a load *would* find."""
    mode = config.cache_mode
    if not mode.enabled:
        return "cache: off (enable with --cache readwrite; docs/caching.md)"
    events = cache_events()
    if not events:
        store = CacheStore.from_env()
        state, reason = store.probe(path, config)
        return f"cache: {state} ({reason})"
    parts = [f"{e.state} ({e.reason})" for e in events]
    return "cache: " + "; ".join(parts)


# ------------------------------------------------------------------- store
_TMP_SEQ = itertools.count()

# Process-wide cache-write degrade latch: after a ResourceExhausted write
# (ENOSPC/EDQUOT/EIO on the sidecar filesystem) further write-through is
# pointless churn, so the cache degrades to read-only until reset. A
# cache write must NEVER fail the load it rides on — the index is a pure
# acceleration.
_write_disabled = False
_write_disabled_lock = threading.Lock()


def cache_writes_disabled() -> bool:
    return _write_disabled


def reset_cache_write_degrade() -> None:
    """Re-arm write-through (tests; operators after freeing space)."""
    global _write_disabled
    with _write_disabled_lock:
        _write_disabled = False


class CacheStore:
    def __init__(
        self,
        cache_dir: str | None = None,
        budget_bytes: int | None = None,
        policy: FaultPolicy | None = None,
    ):
        self.cache_dir = str(cache_dir) if cache_dir else None
        self.budget_bytes = budget_bytes
        self.policy = policy or FaultPolicy()

    @staticmethod
    def from_env(env=None, policy: FaultPolicy | None = None) -> "CacheStore":
        from spark_bam_tpu.core.config import parse_bytes

        env = env if env is not None else os.environ
        budget = env.get("SPARK_BAM_CACHE_BUDGET")
        return CacheStore(
            cache_dir=env.get("SPARK_BAM_CACHE_DIR") or None,
            budget_bytes=parse_bytes(budget) if budget else None,
            policy=policy,
        )

    # ------------------------------------------------------------ locate
    def sidecar_path(self, bam_path) -> str:
        """Where ``bam_path``'s index lives: content-addressed under the
        shared dir when configured, else adjacent to the BAM."""
        s = str(bam_path)
        if self.cache_dir:
            if not is_url(s):
                s = os.path.abspath(s)
            digest = hashlib.sha256(s.encode()).hexdigest()[:32]
            return os.path.join(self.cache_dir, digest + ".sbi")
        return s + ".sbi"

    def _writable(self, bam_path) -> bool:
        # Adjacent writes need a local filesystem; URL BAMs cache only
        # under a shared local cache dir.
        return bool(self.cache_dir) or not is_url(str(bam_path))

    # -------------------------------------------------------------- read
    def _read_bytes(self, sidecar: str) -> bytes:
        """Sidecar bytes through the channel seam (chaos-injectable;
        remote reads retried under the fault policy)."""

        def read():
            with open_channel(sidecar) as ch:
                return bytes(ch.read_at(0, ch.size))

        if is_url(sidecar):
            return with_retries(read, self.policy, "read_sbi")
        return read()

    def load(
        self, bam_path, config, strict: bool = False, _quiet: bool = False
    ) -> SbiIndex | None:
        """The validated index for ``bam_path``, or None (miss / stale /
        corrupt — counted and recorded; ``strict`` raises on the latter
        two). A hit touches the sidecar's mtime so LRU eviction tracks
        use, and observes ``cache.read_ms``."""
        sidecar = self.sidecar_path(bam_path)
        t0 = time.perf_counter()
        if not path_exists(sidecar):
            if not _quiet:
                obs.count("cache.misses")
                _record("miss", "no .sbi sidecar", sidecar)
            return None
        try:
            index = decode_sbi(self._read_bytes(sidecar))
        except SbiFormatError as e:
            return self._invalid(
                f"corrupt sidecar: {e}", sidecar, strict, _quiet
            )
        current = with_retries(
            lambda: fingerprint_of(bam_path, config), self.policy,
            "fingerprint",
        )
        reason = index.fingerprint.mismatch(current)
        if reason is not None:
            return self._invalid(f"stale sidecar: {reason}", sidecar, strict,
                                 _quiet)
        if not _quiet:
            obs.count("cache.hits")
            obs.observe(
                "cache.read_ms", (time.perf_counter() - t0) * 1e3, unit="ms"
            )
            _record("hit", "fingerprint ok", sidecar)
            if self.cache_dir and not is_url(sidecar):
                try:
                    os.utime(sidecar)
                except OSError:
                    pass
        return index

    def _invalid(self, reason: str, sidecar: str, strict: bool,
                 quiet: bool) -> None:
        if not quiet:
            obs.count("cache.invalidations")
            _record("invalidated", reason, sidecar)
        if strict:
            raise StaleCacheError(f"{sidecar}: {reason}")
        log.info("split-index cache invalidated: %s (%s)", sidecar, reason)
        return None

    def probe(self, bam_path, config) -> tuple[str, str]:
        """Validation-only peek (no counters, no status events): the
        (state, reason) a real load would see — the check-bam status line."""
        sidecar = self.sidecar_path(bam_path)
        if not path_exists(sidecar):
            return "miss", f"no sidecar at {sidecar}; build with 'index'"
        try:
            index = decode_sbi(self._read_bytes(sidecar))
        except SbiFormatError as e:
            return "invalidated", f"corrupt sidecar: {e}"
        reason = index.fingerprint.mismatch(
            with_retries(
                lambda: fingerprint_of(bam_path, config), self.policy,
                "fingerprint",
            )
        )
        if reason is not None:
            return "invalidated", f"stale sidecar: {reason}"
        sections = []
        if index.blocks is not None:
            sections.append(f"{len(index.blocks)} blocks")
        if index.split_plans:
            sections.append(
                "split plans for "
                + "/".join(str(s) for s in sorted(index.split_plans))
            )
        if index.record_starts is not None:
            sections.append(f"{len(index.record_starts)} record starts")
        return "hit", "; ".join(sections) or "empty index"

    # ------------------------------------------------------------- write
    def store(self, bam_path, index: SbiIndex) -> str | None:
        """Atomic write-through; returns the sidecar path, or None when
        this store cannot hold ``bam_path`` (URL BAM without a shared
        cache dir). Evicts over-budget shared-dir entries afterwards."""
        if not self._writable(bam_path):
            _record(
                "skipped",
                "remote BAM needs SPARK_BAM_CACHE_DIR for caching",
                str(bam_path),
            )
            return None
        global _write_disabled
        if _write_disabled:
            _record(
                "skipped", "cache writes disabled after earlier write error",
                str(bam_path),
            )
            return None
        sidecar = self.sidecar_path(bam_path)
        t0 = time.perf_counter()
        blob = encode_sbi(index)
        # pid + in-process sequence: unique even for threads racing on the
        # same sidecar; os.replace keeps every reader's view untorn.
        tmp = f"{sidecar}.tmp{os.getpid()}.{next(_TMP_SEQ)}"
        try:
            if self.cache_dir:
                os.makedirs(self.cache_dir, exist_ok=True)
            with faults.wrap_disk(open(tmp, "wb")) as f:
                f.write(blob)
            faults.disk_replace(tmp, sidecar)
        except OSError as exc:
            # A cache write never fails the load it accelerates: count it,
            # and on resource exhaustion latch the cache to read-only so
            # a full disk doesn't get hammered once per load.
            obs.count("cache.write_errors")
            mapped = map_write_error(exc, "sidecar write", path=sidecar)
            if isinstance(mapped, ResourceExhausted):
                with _write_disabled_lock:
                    _write_disabled = True
                log.warning(
                    "split-index cache degraded to read-only: %s", mapped
                )
                _record("skipped", f"write degraded to cache-off: {mapped}",
                        sidecar)
            else:
                log.info("split-index cache write failed: %s", mapped)
                _record("skipped", f"write failed: {mapped}", sidecar)
            return None
        finally:
            if os.path.exists(tmp):  # failure path only; replace moved it
                os.unlink(tmp)
        obs.observe(
            "cache.write_ms", (time.perf_counter() - t0) * 1e3, unit="ms"
        )
        obs.gauge("cache.bytes").set(len(blob))
        _record("written", f"{len(blob)} bytes", sidecar)
        self._evict(keep=sidecar)
        return sidecar

    def merge_and_store(self, bam_path, config, index: SbiIndex) -> str | None:
        """Write-through that preserves sections an existing *valid*
        sidecar already holds (quiet reload: no hit/miss accounting)."""
        existing = None
        if self._writable(bam_path):
            try:
                existing = self.load(bam_path, config, _quiet=True)
            except Exception:  # unreadable existing index: overwrite it
                existing = None
        if existing is not None:
            index.merge_from(existing)
        return self.store(bam_path, index)

    # ----------------------------------------------------------- evict
    def _evict(self, keep: str | None = None) -> None:
        """Drop least-recently-used shared-dir sidecars past the budget.
        The entry just written is exempt — evicting it would make a
        too-small budget cache-bust every write it just did."""
        if not (self.cache_dir and self.budget_bytes):
            return
        try:
            entries = [
                (os.path.join(self.cache_dir, name))
                for name in os.listdir(self.cache_dir)
                if name.endswith(".sbi")
            ]
            stats = []
            for p in entries:
                try:
                    st = os.stat(p)
                    stats.append((st.st_mtime_ns, st.st_size, p))
                except OSError:
                    continue
            total = sum(s for _, s, _ in stats)
            obs.gauge("cache.bytes").set(total)
            if total <= self.budget_bytes:
                return
            for _, size, p in sorted(stats):
                if p == keep:
                    continue
                try:
                    os.unlink(p)
                except OSError:
                    continue
                obs.count("cache.evictions")
                _record("evicted", f"{size} bytes over budget", p)
                total -= size
                if total <= self.budget_bytes:
                    break
            obs.gauge("cache.bytes").set(total)
        except OSError:
            pass  # eviction is best-effort; the cache stays correct


# ------------------------------------------------------------ shared store
_shared: "CacheStore | None" = None
_shared_lock = threading.Lock()


def shared_store(policy: FaultPolicy | None = None) -> CacheStore:
    """The process-wide ``CacheStore`` (env-resolved once) — the serving
    daemon's shared index tier: every request consults ONE store instance
    instead of re-reading ``SPARK_BAM_CACHE_DIR``/budget per call. The
    store itself is stateless (sidecars live on disk), so sharing is safe;
    a caller-supplied ``policy`` on first use pins the retry policy for
    the daemon's lifetime."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = CacheStore.from_env(policy=policy)
        return _shared


def reset_shared_store() -> None:
    """Drop the memoized store (tests that repoint SPARK_BAM_CACHE_DIR)."""
    global _shared
    with _shared_lock:
        _shared = None


# ------------------------------------------------------- block-table tier
def cached_blocks(bam_path, config=None):
    """The ``.sbi`` block table for ``bam_path``, or None (cache off /
    miss / sidecar has no SECTION_BLOCKS). This is the data plane's warm
    path: a fleet load that has seen a BAM before derives its exact fetch
    plan without a metadata scan (docs/remote.md)."""
    from spark_bam_tpu.core.config import default_config

    config = config or default_config()
    mode = config.cache_mode
    if not (mode.enabled and mode.read):
        return None
    index = CacheStore.from_env(policy=config.fault_policy).load(
        bam_path, config, strict=mode.strict
    )
    if index is None or index.blocks is None:
        return None
    return list(index.blocks)


def store_blocks(bam_path, blocks, config=None) -> str | None:
    """Write-through of a freshly scanned block table into the ``.sbi``
    tier (preserving any other sections the sidecar holds); returns the
    sidecar path or None when caching is off / the store can't hold it."""
    from spark_bam_tpu.core.config import default_config

    config = config or default_config()
    mode = config.cache_mode
    if not (mode.enabled and mode.write):
        return None
    store = CacheStore.from_env(policy=config.fault_policy)
    index = SbiIndex(
        fingerprint=with_retries(
            lambda: fingerprint_of(bam_path, config), store.policy,
            "fingerprint",
        ),
        blocks=list(blocks),
    )
    return store.merge_and_store(bam_path, config, index)
