"""Versioned binary ``.sbi`` split-index format.

Layout (little-endian throughout)::

    magic   4s   b"SBTI"
    version u16  FORMAT_VERSION
    flags   u16  reserved (0)
    -- fingerprint -------------------------------------------------
    size          u64  compressed byte size of the BAM
    mtime_ns      u64  local-file mtime (0 for URLs — size+CRC carry it)
    header_crc    u32  CRC32 of the BAM's first min(64 KiB, size) bytes
    config_digest u32  CRC32 of the checker knobs that shape the index
    -- sections ----------------------------------------------------
    n_sections u32, then per section: tag u32, payload_len u64, payload
        tag 1 BLOCKS:        n u64, then n × (start u64, comp u32, uncomp u32)
        tag 2 SPLIT_PLANS:   n_plans u32, per plan: split_size u64,
                             n_entries u64, entries × (file_start u64,
                             kind u8, vpos u64)
        tag 3 RECORD_STARTS: n u64, then n × u64 HTSJDK virtual positions
    -- trailer -----------------------------------------------------
    crc32 u32 over every preceding byte

Any structural problem — bad magic, unknown version, truncated payload,
trailer-CRC mismatch — raises ``SbiFormatError``; the store treats that
as cache corruption (invalidate and recompute), never as data.

Plan-entry ``kind``: 0 = the boundary owns no record start (clean:
no blocks, or EOF); 1 = resolved to ``vpos``; 2 = unresolved (the
boundary scan exhausted ``max_read_size`` at build time — consumers
re-resolve live so the cached plan can never swallow that error).

The fingerprint binds the sidecar to (file bytes, checker config): the
checker-knob digest covers every knob that changes split positions, so
an index built under different knobs reads as stale, not as truth.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.core.channel import is_url, open_channel, path_size
from spark_bam_tpu.core.guard import StructurallyInvalid
from spark_bam_tpu.core.pos import Pos

MAGIC = b"SBTI"
FORMAT_VERSION = 1
#: Bumped whenever checker *semantics* change in a way that moves record
#: boundaries; part of the config digest so old indexes age out safely.
CHECKER_SEMANTICS_VERSION = 1

#: Bytes of file head covered by the fingerprint CRC — spans the BAM
#: header's BGZF blocks for any realistic contig dictionary.
HEADER_CRC_SPAN = 64 << 10

SECTION_BLOCKS = 1
SECTION_SPLIT_PLANS = 2
SECTION_RECORD_STARTS = 3

PLAN_NONE = 0        # boundary owns no record start
PLAN_POS = 1         # resolved virtual position
PLAN_UNRESOLVED = 2  # scan budget exhausted at build time; re-resolve live


class SbiFormatError(StructurallyInvalid):
    """The sidecar's bytes are not a well-formed ``.sbi`` index.

    A ``StructurallyInvalid`` (still a ValueError): the store treats it as
    cache corruption, and the fuzz harness classifies it with the rest of
    the malformed-input taxonomy (core/guard.py)."""


def config_digest(config) -> int:
    """CRC32 over the checker knobs that determine split/record positions."""
    spec = (
        f"v{CHECKER_SEMANTICS_VERSION};"
        f"z={config.bgzf_blocks_to_check};"
        f"r={config.reads_to_check};"
        f"m={config.max_read_size}"
    )
    return zlib.crc32(spec.encode()) & 0xFFFFFFFF


@dataclass(frozen=True)
class Fingerprint:
    size: int
    mtime_ns: int
    header_crc: int
    config_digest: int

    def mismatch(self, other: "Fingerprint") -> str | None:
        """First differing field as a human reason, or None when equal."""
        for name, label in (
            ("size", "file size changed"),
            ("mtime_ns", "file mtime changed"),
            ("header_crc", "file head bytes changed"),
            ("config_digest", "checker config changed"),
        ):
            if getattr(self, name) != getattr(other, name):
                return label
        return None


def fingerprint_of(bam_path, config) -> Fingerprint:
    """The current fingerprint of ``bam_path`` under ``config``. Remote
    paths have no stable mtime; size + head-CRC carry the freshness check
    there (callers wrap this in ``with_retries`` for remote transports)."""
    path = str(bam_path)
    if is_url(path):
        # Raw backend channel, ONE connection, head first: servers answer
        # the ranged GET with the object's total size in Content-Range
        # (RFC 9110 clamps a long range to EOF), so the usual freshness
        # probe is ONE round-trip — ``size`` only HEADs when the server
        # omitted the total. The prefetching wrapper ``open_channel``
        # installs would re-probe the size and read megabytes ahead of
        # the CRC span — pure waste at RTT prices.
        from spark_bam_tpu.core.channel import _raw_url_channel

        with _raw_url_channel(path) as ch:
            head = bytes(ch.read_at(0, HEADER_CRC_SPAN))
            size = ch.size
        mtime_ns = 0
    else:
        size = path_size(path)
        mtime_ns = os.stat(path).st_mtime_ns
        with open_channel(path) as ch:
            head = bytes(ch.read_at(0, min(HEADER_CRC_SPAN, size)))
    return Fingerprint(
        size, mtime_ns, zlib.crc32(head) & 0xFFFFFFFF, config_digest(config)
    )


@dataclass(frozen=True)
class PlanEntry:
    """One raw split boundary's resolution (pre-dedup: consecutive
    boundaries may resolve to the same position; consumers dedupe)."""

    file_start: int
    kind: int           # PLAN_NONE | PLAN_POS | PLAN_UNRESOLVED
    pos: Pos | None     # set iff kind == PLAN_POS


@dataclass
class SbiIndex:
    """In-memory form of one ``.sbi`` sidecar."""

    fingerprint: Fingerprint
    blocks: list[Metadata] | None = None
    #: split_size → raw per-boundary entries for that split size
    split_plans: dict[int, list[PlanEntry]] = field(default_factory=dict)
    #: HTSJDK-packed virtual positions of every record start (sorted)
    record_starts: np.ndarray | None = None

    def merge_from(self, other: "SbiIndex") -> None:
        """Adopt sections present in ``other`` and absent here (the
        read-modify-write half of write-through: a load that only computed
        a split plan must not drop a previously indexed record-start
        section, and vice versa)."""
        if self.blocks is None:
            self.blocks = other.blocks
        for size, plan in other.split_plans.items():
            self.split_plans.setdefault(size, plan)
        if self.record_starts is None:
            self.record_starts = other.record_starts


# ----------------------------------------------------------------- encode

def _encode_blocks(blocks: list[Metadata]) -> bytes:
    out = [struct.pack("<Q", len(blocks))]
    out.extend(
        struct.pack("<QII", m.start, m.compressed_size, m.uncompressed_size)
        for m in blocks
    )
    return b"".join(out)


def _encode_split_plans(plans: dict[int, list[PlanEntry]]) -> bytes:
    out = [struct.pack("<I", len(plans))]
    for split_size in sorted(plans):
        entries = plans[split_size]
        out.append(struct.pack("<QQ", split_size, len(entries)))
        for e in entries:
            vpos = e.pos.to_htsjdk() if e.kind == PLAN_POS else 0
            out.append(struct.pack("<QBQ", e.file_start, e.kind, vpos))
    return b"".join(out)


def _encode_record_starts(starts: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(starts, dtype=np.uint64)
    return struct.pack("<Q", len(arr)) + arr.tobytes()


def encode_sbi(index: SbiIndex) -> bytes:
    fp = index.fingerprint
    head = MAGIC + struct.pack(
        "<HHQQII", FORMAT_VERSION, 0, fp.size, fp.mtime_ns, fp.header_crc,
        fp.config_digest,
    )
    sections: list[tuple[int, bytes]] = []
    if index.blocks is not None:
        sections.append((SECTION_BLOCKS, _encode_blocks(index.blocks)))
    if index.split_plans:
        sections.append(
            (SECTION_SPLIT_PLANS, _encode_split_plans(index.split_plans))
        )
    if index.record_starts is not None:
        sections.append(
            (SECTION_RECORD_STARTS, _encode_record_starts(index.record_starts))
        )
    body = [head, struct.pack("<I", len(sections))]
    for tag, payload in sections:
        body.append(struct.pack("<IQ", tag, len(payload)))
        body.append(payload)
    blob = b"".join(body)
    return blob + struct.pack("<I", zlib.crc32(blob) & 0xFFFFFFFF)


# ----------------------------------------------------------------- decode

class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise SbiFormatError(
                f"truncated .sbi: wanted {n} bytes at {self.off}, "
                f"have {len(self.data) - self.off}"
            )
        out = self.data[self.off: self.off + n]
        self.off += n
        return out

    def unpack(self, fmt: str):
        return struct.unpack(fmt, self.take(struct.calcsize(fmt)))

    def count(self, n: int, what: str, item_size: int) -> int:
        """Validate an element count against the bytes actually present
        before it sizes a loop (a corrupt count must fail in O(1), not
        after ``n`` iterations)."""
        if n * item_size > len(self.data) - self.off:
            raise SbiFormatError(
                f"corrupt .sbi: {what} count {n} needs {n * item_size} "
                f"bytes at {self.off}, have {len(self.data) - self.off}"
            )
        return n


def _decode_blocks(r: _Reader) -> list[Metadata]:
    (n,) = r.unpack("<Q")
    r.count(n, "blocks", 16)
    return [Metadata(*r.unpack("<QII")) for _ in range(n)]


def _decode_split_plans(r: _Reader) -> dict[int, list[PlanEntry]]:
    (n_plans,) = r.unpack("<I")
    r.count(n_plans, "split plans", 16)
    plans: dict[int, list[PlanEntry]] = {}
    for _ in range(n_plans):
        split_size, n_entries = r.unpack("<QQ")
        r.count(n_entries, "plan entries", 17)
        entries = []
        for _ in range(n_entries):
            file_start, kind, vpos = r.unpack("<QBQ")
            if kind not in (PLAN_NONE, PLAN_POS, PLAN_UNRESOLVED):
                raise SbiFormatError(f"bad plan-entry kind {kind}")
            entries.append(
                PlanEntry(
                    file_start, kind,
                    Pos.from_htsjdk(vpos) if kind == PLAN_POS else None,
                )
            )
        plans[int(split_size)] = entries
    return plans


def _decode_record_starts(r: _Reader) -> np.ndarray:
    (n,) = r.unpack("<Q")
    raw = r.take(8 * r.count(n, "record starts", 8))
    return np.frombuffer(raw, dtype=np.uint64).copy()


def decode_sbi(data: bytes) -> SbiIndex:
    if len(data) < len(MAGIC) + 2 + 2 + 24 + 4 + 4:
        raise SbiFormatError(f"short .sbi: {len(data)} bytes")
    (trailer,) = struct.unpack("<I", data[-4:])
    if zlib.crc32(data[:-4]) & 0xFFFFFFFF != trailer:
        raise SbiFormatError("trailer CRC32 mismatch (corrupt sidecar)")
    r = _Reader(data[:-4])
    if r.take(4) != MAGIC:
        raise SbiFormatError("bad magic")
    version, _flags = r.unpack("<HH")
    if version != FORMAT_VERSION:
        raise SbiFormatError(f"unsupported .sbi version {version}")
    size, mtime_ns, header_crc, digest = r.unpack("<QQII")
    index = SbiIndex(Fingerprint(size, mtime_ns, header_crc, digest))
    (n_sections,) = r.unpack("<I")
    for _ in range(n_sections):
        tag, payload_len = r.unpack("<IQ")
        payload = _Reader(r.take(payload_len))
        if tag == SECTION_BLOCKS:
            index.blocks = _decode_blocks(payload)
        elif tag == SECTION_SPLIT_PLANS:
            index.split_plans = _decode_split_plans(payload)
        elif tag == SECTION_RECORD_STARTS:
            index.record_starts = _decode_record_starts(payload)
        # Unknown tags are skipped: newer writers stay readable.
    return index


# --------------------------------------------------- virtual ↔ flat offsets

def record_starts_to_virtual(view, flat_starts: np.ndarray) -> np.ndarray:
    """Flat record-start offsets → sorted HTSJDK virtual positions."""
    blocks, offs = view.pos_of_flat_many(np.asarray(flat_starts, dtype=np.int64))
    return (
        (blocks.astype(np.uint64) << np.uint64(16)) | offs.astype(np.uint64)
    )


def record_starts_to_flat(view, virtual: np.ndarray) -> np.ndarray:
    """HTSJDK virtual positions → flat offsets in ``view``. Raises
    ``SbiFormatError`` when a position names a block the file doesn't
    have (the fingerprint should make this impossible; defense anyway)."""
    v = np.asarray(virtual, dtype=np.uint64)
    blocks = (v >> np.uint64(16)).astype(np.int64)
    offs = (v & np.uint64(0xFFFF)).astype(np.int64)
    idx = np.searchsorted(view.block_starts, blocks)
    if len(v) and (
        idx.max(initial=0) >= len(view.block_starts)
        or not np.array_equal(view.block_starts[idx], blocks)
    ):
        raise SbiFormatError("record-start block not present in file")
    return view.block_flat[idx] + offs
