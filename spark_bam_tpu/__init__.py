"""spark-bam-tpu: TPU-native parallel BAM loading.

A from-scratch reimplementation of the capabilities of fnothaft/spark-bam
(Scala/Spark) as a TPU-first framework:

- ``core``     — virtual positions, config surface, byte ranges, channels
- ``bgzf``     — BGZF block layer: header parse, block streams, block-start search
- ``bam``      — BAM structure: header/contigs, record codec, .bai index, iterators
- ``check``    — record-boundary checkers (eager / full / indexed / seqdoop-semantics)
                 plus the vectorized host (NumPy) checker
- ``tpu``      — JAX/XLA vectorized checker + batched record parser (the hot path)
- ``parallel`` — host orchestration, device meshes, sharded multi-chip check step
- ``load``     — user-facing load API (load_reads / load_bam / intervals / splits)
- ``cli``      — the 10 operator commands (check-bam, compute-splits, ...)

The reference's Spark substrate (driver/executors, RDDs, broadcast, accumulators)
is replaced by a host-side orchestrator plus fixed-shape batched kernels that XLA
compiles for TPU; see SURVEY.md §7 in the repo root.
"""

from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.core.config import Config, default_config

__version__ = "0.1.0"

__all__ = [
    "Pos",
    "Config",
    "default_config",
    "load_bam",
    "load_reads",
    "load_sam",
    "load_bam_intervals",
    "load_splits_and_reads",
    "load_reads_and_positions",
    "export",
    "aggregate",
    "count_reads_tpu",
    "load_reads_columnar",
    "record_starts_streaming",
    "stream_read_batches",
    "full_check_summary_streaming",
    "count_reads_sharded",
    "check_bam_sharded",
]

# Lazy exports: the load API pulls in numpy/jax; keep `import spark_bam_tpu`
# cheap. One name → providing-module table serves every lazily-bound symbol.
_LAZY = {
    **{
        name: "spark_bam_tpu.load.api"
        for name in (
            "load_bam", "load_reads", "load_sam", "load_bam_intervals",
            "load_splits_and_reads", "load_reads_and_positions", "export",
            "aggregate",
        )
    },
    **{
        name: "spark_bam_tpu.load.tpu_load"
        for name in (
            "count_reads_tpu", "load_reads_columnar", "record_starts",
            "record_starts_streaming", "stream_read_batches",
        )
    },
    "full_check_summary_streaming": "spark_bam_tpu.tpu.stream_check",
    "count_reads_sharded": "spark_bam_tpu.parallel.stream_mesh",
    "check_bam_sharded": "spark_bam_tpu.parallel.stream_mesh",
}


def __getattr__(name):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
