"""spark-bam-tpu: TPU-native parallel BAM loading.

A from-scratch reimplementation of the capabilities of fnothaft/spark-bam
(Scala/Spark) as a TPU-first framework:

- ``core``     — virtual positions, config surface, byte ranges, channels
- ``bgzf``     — BGZF block layer: header parse, block streams, block-start search
- ``bam``      — BAM structure: header/contigs, record codec, .bai index, iterators
- ``check``    — record-boundary checkers (eager / full / indexed / seqdoop-semantics)
                 plus the vectorized host (NumPy) checker
- ``tpu``      — JAX/XLA vectorized checker + batched record parser (the hot path)
- ``parallel`` — host orchestration, device meshes, sharded multi-chip check step
- ``load``     — user-facing load API (load_reads / load_bam / intervals / splits)
- ``cli``      — the 10 operator commands (check-bam, compute-splits, ...)

The reference's Spark substrate (driver/executors, RDDs, broadcast, accumulators)
is replaced by a host-side orchestrator plus fixed-shape batched kernels that XLA
compiles for TPU; see SURVEY.md §7 in the repo root.
"""

from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.core.config import Config, default_config

__version__ = "0.1.0"

__all__ = [
    "Pos",
    "Config",
    "default_config",
    "load_bam",
    "load_reads",
    "load_sam",
    "load_bam_intervals",
    "load_splits_and_reads",
    "load_reads_and_positions",
    "count_reads_tpu",
    "load_reads_columnar",
    "record_starts_streaming",
    "stream_read_batches",
    "full_check_summary_streaming",
]

_LOAD_API = {
    "load_bam",
    "load_reads",
    "load_sam",
    "load_bam_intervals",
    "load_splits_and_reads",
    "load_reads_and_positions",
}
_TPU_API = {
    "count_reads_tpu",
    "load_reads_columnar",
    "record_starts",
    "record_starts_streaming",
    "stream_read_batches",
}
_STREAM_API = {"full_check_summary_streaming"}


def __getattr__(name):
    # Lazy: the load API pulls in numpy/jax; keep `import spark_bam_tpu` cheap.
    if name in _LOAD_API:
        from spark_bam_tpu.load import api

        return getattr(api, name)
    if name in _TPU_API:
        from spark_bam_tpu.load import tpu_load

        return getattr(tpu_load, name)
    if name in _STREAM_API:
        from spark_bam_tpu.tpu import stream_check

        return getattr(stream_check, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
