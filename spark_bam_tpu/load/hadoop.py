"""hadoop-bam-semantics loading: split computation + strict record reading.

The reference compares itself against hadoop-bam's ``BAMInputFormat`` split
computation and ``BAMRecordReader`` loading (cli/.../spark/LoadReads.scala:
176-207). Here those are emulated: splits resolve through the seqdoop
guesser (so its false positives surface as bad split starts), and records
decode with HTSJDK-style SAM validation so a bad start produces the same
class of failure the reference observes from hadoop-bam (CountReadsTest:
"hadoop-bam threw exception").
"""

from __future__ import annotations



from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.check.seqdoop import SeqdoopChecker
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.splits import Split


class BamFormatError(Exception):
    pass


def hadoop_bam_splits(
    path, split_size: int, checker: SeqdoopChecker | None = None,
    config: Config = Config(),
) -> list[Split]:
    """Splits the way hadoop-bam computes them: sequentially on the driver,
    one seqdoop guess per raw split boundary; ends are (rawEnd, 0xffff)."""
    checker = checker or SeqdoopChecker.open(path)
    splits: list[Split] = []
    with open_channel(path) as ch:
        size = ch.size
        for s in range(0, size, split_size):
            e = min(s + split_size, size)
            block = find_block_start(ch, s, config.bgzf_blocks_to_check, path=str(path))
            start = checker.next_read_start(Pos(block, 0), config.max_read_size)
            if start is None or start.block_pos >= e:
                continue
            splits.append(Split(start, Pos(e, 0xFFFF)))
    return splits


def validate_record(rec: BamRecord, num_contigs: int, index: int) -> None:
    """A few of HTSJDK's SAMRecord validations — enough that garbage split
    starts fail the same way they do under hadoop-bam."""
    def err(msg: str) -> BamFormatError:
        return BamFormatError(
            f"SAM validation error: ERROR: Record {index}, Read name {rec.read_name}, {msg}"
        )

    paired = rec.flag & 0x1
    if not paired:
        if rec.next_ref_id != -1:
            raise err("MRNM should not be set for unpaired read.")
        if rec.flag & 0x40 or rec.flag & 0x80:
            raise err("First/second of pair flag should not be set for unpaired read.")
    if rec.ref_id < -1 or rec.ref_id >= num_contigs:
        raise err("Reference index out of range.")
    if rec.next_ref_id < -1 or rec.next_ref_id >= num_contigs:
        raise err("Mate reference index out of range.")


def hadoop_bam_read_split(
    view, num_contigs: int, split: Split, strict: bool = True
):
    """Decode records of one hadoop-style split from a flat view."""
    flat = view.flat_of_pos(split.start.block_pos, split.start.offset)
    n = view.size
    index = 0
    while flat + 4 <= n:
        block, off = view.pos_of_flat(flat)
        if (block, off) >= (split.end.block_pos, split.end.offset):
            break
        index += 1
        try:
            rec, consumed = BamRecord.decode(view.data, flat)
        except Exception as e:
            raise BamFormatError(f"Failed to decode record {index} at {block}:{off}: {e}")
        if strict:
            validate_record(rec, num_contigs, index)
        yield Pos(block, off), rec
        flat += consumed


def hadoop_bam_count(path, split_size: int, config: Config = Config()) -> int:
    checker = SeqdoopChecker.open(path)
    splits = hadoop_bam_splits(path, split_size, checker, config)
    num_contigs = checker.num_contigs
    total = 0
    for split in splits:
        for _ in hadoop_bam_read_split(checker.view, num_contigs, split):
            total += 1
    return total
