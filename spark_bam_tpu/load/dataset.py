"""Partitioned lazy dataset — the RDD analog.

A ``Dataset`` is a list of partition descriptors plus a compute function;
actions (count/collect/first-per-partition) execute partitions through the
host orchestrator (parallel/executor.py). This replaces the reference's
Spark RDD surface for the load API.
"""

from __future__ import annotations

from typing import Callable, Generic, Iterable, Iterator, Sequence, TypeVar

from spark_bam_tpu import obs
from spark_bam_tpu.parallel.executor import ParallelConfig, map_partitions

T = TypeVar("T")
P = TypeVar("P")


class Dataset(Generic[P, T]):
    def __init__(
        self,
        partitions: Sequence[P],
        compute: Callable[[P], Iterable[T]],
        parallel: ParallelConfig = ParallelConfig(),
    ):
        self.partitions = list(partitions)
        self.compute = compute
        self.parallel = parallel

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def map_partitions(self, fn: Callable[[Iterable[T]], Iterable[T]]) -> "Dataset":
        compute = self.compute
        return Dataset(
            self.partitions, lambda p: fn(compute(p)), self.parallel
        )

    def map(self, fn: Callable[[T], object]) -> "Dataset":
        return self.map_partitions(lambda it: (fn(x) for x in it))

    def filter(self, pred: Callable[[T], bool]) -> "Dataset":
        return self.map_partitions(lambda it: (x for x in it if pred(x)))

    def count(self) -> int:
        with obs.span("load.count", partitions=len(self.partitions)):
            return sum(
                map_partitions(
                    lambda p: sum(1 for _ in self.compute(p)),
                    self.partitions,
                    self.parallel,
                )
            )

    def collect(self) -> list[T]:
        out: list[T] = []
        for part in map_partitions(
            lambda p: list(self.compute(p)), self.partitions, self.parallel
        ):
            out.extend(part)
        return out

    def partition_sizes(self) -> list[int]:
        return map_partitions(
            lambda p: sum(1 for _ in self.compute(p)), self.partitions, self.parallel
        )

    def first_per_partition(self) -> list[T | None]:
        def first(p):
            for x in self.compute(p):
                return x
            return None

        return map_partitions(first, self.partitions, self.parallel)

    def __iter__(self) -> Iterator[T]:
        for p in self.partitions:
            yield from self.compute(p)
