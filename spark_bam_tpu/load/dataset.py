"""Partitioned lazy dataset — the RDD analog.

A ``Dataset`` is a list of partition descriptors plus a compute function;
actions (count/collect/first-per-partition) execute partitions through the
host orchestrator (parallel/executor.py) under the dataset's ``FaultPolicy``
— retries, deadlines, hedging, and strict-vs-tolerant degradation come from
there, the way the reference's RDD actions inherited Spark's task-level
fault tolerance. After any action, ``last_report`` holds the ``JobReport``
of per-partition attempts/outcomes (quarantined partitions contribute
nothing to the action's result in tolerant mode).
"""

from __future__ import annotations

from typing import Callable, Generic, Iterable, Iterator, Sequence, TypeVar

from spark_bam_tpu import obs
from spark_bam_tpu.core.faults import FaultPolicy
from spark_bam_tpu.parallel.executor import (
    JobReport,
    ParallelConfig,
    run_partitions,
)

T = TypeVar("T")
P = TypeVar("P")


class Dataset(Generic[P, T]):
    def __init__(
        self,
        partitions: Sequence[P],
        compute: Callable[[P], Iterable[T]],
        parallel: ParallelConfig = ParallelConfig(),
        policy: FaultPolicy | None = None,
    ):
        self.partitions = list(partitions)
        self.compute = compute
        self.parallel = parallel
        self.policy = policy
        self.last_report: JobReport | None = None

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def _execute(self, fn: Callable[[P], object]) -> list:
        results, report = run_partitions(
            fn, self.partitions, self.parallel, self.policy
        )
        self.last_report = report
        return results

    def map_partitions(self, fn: Callable[[Iterable[T]], Iterable[T]]) -> "Dataset":
        compute = self.compute
        return Dataset(
            self.partitions, lambda p: fn(compute(p)), self.parallel,
            policy=self.policy,
        )

    def map(self, fn: Callable[[T], object]) -> "Dataset":
        return self.map_partitions(lambda it: (fn(x) for x in it))

    def filter(self, pred: Callable[[T], bool]) -> "Dataset":
        return self.map_partitions(lambda it: (x for x in it if pred(x)))

    def count(self) -> int:
        with obs.span("load.count", partitions=len(self.partitions)):
            return sum(
                n
                for n in self._execute(lambda p: sum(1 for _ in self.compute(p)))
                if n is not None
            )

    def collect(self) -> list[T]:
        out: list[T] = []
        for part in self._execute(lambda p: list(self.compute(p))):
            if part is not None:
                out.extend(part)
        return out

    def partition_sizes(self) -> list[int | None]:
        """Record count per partition (``None`` marks a quarantined one)."""
        return self._execute(lambda p: sum(1 for _ in self.compute(p)))

    def first_per_partition(self) -> list[T | None]:
        def first(p):
            for x in self.compute(p):
                return x
            return None

        return self._execute(first)

    def aggregate(self, plan, nc: int) -> dict:
        """Aggregate this dataset's records into ``plan``'s int64 metric
        vectors (agg/plan.py): each partition reduces through the numpy
        oracle (agg/host.py) and the partials merge with ``combine`` —
        the record-path twin of the device plane, byte-equal for the
        same records. Quarantined partitions contribute nothing in
        tolerant mode (their loss shows in ``last_report``)."""
        from spark_bam_tpu.agg.host import (
            columns_from_records,
            combine,
            host_aggregate,
        )
        from spark_bam_tpu.agg.plan import AggConfig

        if not isinstance(plan, AggConfig):
            plan = AggConfig.parse(plan)
        with obs.span("agg.reduce", partitions=len(self.partitions)):
            parts = self._execute(
                lambda p: host_aggregate(
                    columns_from_records(list(self.compute(p))), plan, nc
                )
            )
        return combine(parts, plan, nc)

    def to_batches(self, batch_rows: int = 8192, columns=None):
        """Lazy columnar record batches of this dataset's records
        (docs/analytics.md). Items may be bare ``BamRecord``s or tuples
        whose last element is one (the ``(Pos, rec)`` load shapes).
        Sequential by construction — batch boundaries are a pure function
        of the row stream; for a parallel, fault-tolerant export use
        ``load.api.export``."""
        from spark_bam_tpu.columnar.schema import batches_from_records

        return batches_from_records(iter(self), batch_rows, columns=columns)

    def __iter__(self) -> Iterator[T]:
        for p in self.partitions:
            yield from self.compute(p)
