"""User-facing load API.

Reference: ``spark_bam._`` enrichment of SparkContext
(load/src/main/scala/spark_bam/package.scala:123-131 and
load/.../load/CanLoadBam.scala). Functions return lazy ``Dataset``s of
``BamRecord`` (or ``(Pos, BamRecord)``) partitioned exactly the way the
reference partitions RDDs:

- ``load_bam``: file splits → per split find-block-start → find-record-start
  → stream records until the next split's range (CanLoadBam.scala:173-243)
- ``load_sam``: newline-aligned text splits + SAM line parse (:143-171)
- ``load_bam_intervals``: .bai chunk query → cost-packed partitions →
  seek + interval-overlap filter (:59-138)
- ``load_reads``: extension dispatch (:348-382)
"""

from __future__ import annotations

import os

from spark_bam_tpu import obs
from spark_bam_tpu.bam.bai import BaiIndex, Chunk
from spark_bam_tpu.bam.header import BamHeader, read_header
from spark_bam_tpu.bam.iterators import SeekableRecordStream
from spark_bam_tpu.bam.record import BamRecord, parse_sam_line
from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.bgzf.stream import SeekableBlockStream, SeekableUncompressedBytes
from spark_bam_tpu.check.eager import EagerChecker
from spark_bam_tpu.core.channel import open_channel, path_exists, path_size
from spark_bam_tpu.core.config import Config, parse_bytes
from spark_bam_tpu.core.faults import (
    BlockCorruptionError,
    BlockGapError,
    with_retries,
)
from spark_bam_tpu.core.guard import MalformedInputError, RecordGapError
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.dataset import Dataset
from spark_bam_tpu.load.intervals import LociSet
from spark_bam_tpu.load.splits import FileSplit, Split, file_splits
from spark_bam_tpu.parallel.executor import ParallelConfig


def _resolve_split_start(path, split: FileSplit, header: BamHeader, config: Config):
    """find-block-start → find-record-start for one file split; None if the
    split owns no blocks (its first boundary lies at/after its end).

    The record-start scan runs through the native eager checker when built
    (one C++ call over a bounded inflated window — ~900× the Python
    checker's position rate; at WGS scale with 2 MB splits the Python
    checker alone costs thousands of seconds). ``backend="python"`` pins
    the Python oracle; both produce identical positions.
    """
    # The warm-cache acceptance gate: a cache-served load must never get
    # here (tests assert this counter stays 0 on warm loads).
    obs.count("load.split_resolutions")
    # The split owning the header needs no inference: the first record
    # begins exactly at header.end_pos (read_header already validated the
    # bytes up to there). Running the checker here instead would *search*
    # for a provable chain — and on a file whose early records are damaged,
    # silently resolve past them, losing records even in strict mode.
    first = header.end_pos
    if split.start <= first.block_pos < split.end:
        return first
    with obs.span("bgzf.read", kind="find_block_start", split=split.start):
        with open_channel(path) as ch:
            block_start = find_block_start(
                ch, split.start, config.bgzf_blocks_to_check, path=str(path)
            )
    if block_start >= split.end:
        return None
    tolerant = config.fault_policy.tolerant
    with obs.span("check.find_record_start", block=block_start):
        # Tolerant mode pins the Python checker: it streams lazily (only
        # the records it actually checks), so damage beyond the boundary
        # scan can't fail resolution, and a damaged block *inside* it
        # surfaces as a BlockGapError we can resync past — the native
        # window scan eagerly inflates far ahead with no gap story.
        if config.backend != "python" and not tolerant:
            pos = _native_next_read_start(path, block_start, header, config)
            if pos is not NotImplemented:
                return pos
        checker = EagerChecker(
            SeekableUncompressedBytes(
                SeekableBlockStream(open_channel(path), tolerant=tolerant)
            ),
            header.contig_lengths,
            config.reads_to_check,
        )
        try:
            # None ⇒ EOF reached cleanly: this trailing split owns no record
            # starts (they all precede it) and loads empty. A mid-file scan
            # that exhausts max_read_size raises NoReadFoundException from
            # the checker.
            return checker.next_read_start(
                Pos(block_start, 0), config.max_read_size
            )
        except BlockGapError as gap:
            # Tolerant only: the boundary scan itself ran into a damaged
            # block; resume the search past the gap (None ⇒ the partition's
            # range is lost with the damage).
            pos = _tolerant_record_resync(path, gap, header, config)
            if pos is None or pos.block_pos >= split.end:
                return None
            return pos
        finally:
            checker.close()


#: Chain-lookahead growth bound: once an uncertain position has this much
#: in-window lookahead and its chain STILL reaches the window edge, hand
#: the split to the Python oracle (which streams with seek-skips) instead
#: of growing further — covers ten multi-MB ultralong records; only
#: adversarial size fields (e.g. a 2 GB ``remaining``) exceed it.
_NATIVE_SCAN_SLACK = 64 << 20


def _native_next_read_start(path, block_start: int, header: BamHeader, config: Config):
    """``EagerChecker.next_read_start(Pos(block_start, 0))`` semantics via
    the native tri-state scan: inflate a small geometrically-growing run of
    blocks and scan with ``sbt_find_record_start_window``, which separates
    *certain* verdicts (chain resolved on in-window bytes — exact
    regardless of what lies beyond) from *uncertain* ones (chain cut by
    the window edge — could err in either direction). Scanning never
    advances past an uncertain position: the window grows and resumes
    exactly there, so no cut-induced false-fail can be skipped. A certain
    pass is additionally confirmed with one exact streaming-checker
    evaluation (belt-and-braces; disagreement demotes to the Python
    oracle). Returns the ``Pos``, ``None`` at clean EOF, or
    ``NotImplemented`` when the native library isn't built or growth hits
    its bound (caller runs the Python checker, whose contract — including
    ``NoReadFoundException`` on mid-file budget exhaustion — is
    authoritative). Reference CanLoadBam.scala:173-243;
    FindRecordStart.scala:34-50."""
    import numpy as np

    from spark_bam_tpu.check.checker import NoReadFoundException
    from spark_bam_tpu.native.build import (
        find_record_start_window_native,
        load_native,
    )

    if load_native() is None:
        return NotImplemented
    lens = np.array(header.contig_lengths.lengths_list(), dtype=np.int32)
    budget = config.max_read_size
    target = 128 << 10
    stream = SeekableBlockStream(open_channel(path))
    parts: list[np.ndarray] = []
    block_starts: list[int] = []
    block_flats: list[int] = []
    total = 0
    at_eof = False
    scan_from = 0  # every position before this carries a certain-fail verdict
    confirm = None
    try:
        stream.seek(block_start)

        def grow(upto: int):
            nonlocal total, at_eof
            while total < upto and not at_eof:
                blk = next(stream, None)
                if blk is None:
                    at_eof = True
                    return
                block_starts.append(blk.start)
                block_flats.append(total)
                parts.append(np.frombuffer(blk.data, dtype=np.uint8))
                total += len(blk.data)

        while True:
            grow(target)
            buf = (
                np.concatenate(parts) if parts else np.empty(0, dtype=np.uint8)
            )
            if scan_from >= budget:
                # Certain fails filled the whole scan budget mid-file.
                raise NoReadFoundException(str(path), block_start, budget)
            res = find_record_start_window_native(
                buf, scan_from, lens, config.reads_to_check,
                budget - scan_from, exact_eof=at_eof,
            )
            if res is None:
                return NotImplemented
            off, uncertain_at = res
            if off >= 0:
                i = int(np.searchsorted(block_flats, off, side="right")) - 1
                pos = Pos(block_starts[i], off - block_flats[i])
                if confirm is None:
                    confirm = EagerChecker(
                        SeekableUncompressedBytes(
                            SeekableBlockStream(open_channel(path))
                        ),
                        header.contig_lengths,
                        config.reads_to_check,
                    )
                return pos if confirm(pos) else NotImplemented
            if uncertain_at >= 0:
                # All of [scan_from, uncertain_at) is certainly not a
                # boundary; the uncertain chain needs more lookahead.
                scan_from = uncertain_at
                if total - uncertain_at >= _NATIVE_SCAN_SLACK:
                    return NotImplemented  # pathological chain: oracle decides
                target = max(total * 2, uncertain_at + (256 << 10))
                continue
            # (-1, -1): certain fails through min(budget, window) — at real
            # EOF that is the exact answer; otherwise near-edge positions
            # would have reported uncertainty, so the scan must have been
            # budget-limited (handled above on the next loop) or the window
            # is stale — grow defensively.
            if at_eof:
                if budget >= total:
                    return None  # clean EOF: trailing split owns nothing
                raise NoReadFoundException(str(path), block_start, budget)
            scan_from = max(scan_from, min(total, budget))
            target = max(total * 2, 128 << 10)
    finally:
        stream.close()
        if confirm is not None:
            confirm.close()


def _tolerant_next_start(path, start: Pos, header: BamHeader, config: Config):
    """First provable record boundary at or past ``start`` on a tolerant
    stream, or None when the damage runs to EOF or no boundary can be
    proven (the rest of the partition is lost with it)."""
    from spark_bam_tpu.check.checker import NoReadFoundException

    checker = EagerChecker(
        SeekableUncompressedBytes(
            SeekableBlockStream(open_channel(path), tolerant=True)
        ),
        header.contig_lengths,
        config.reads_to_check,
    )
    try:
        return checker.next_read_start(start, config.max_read_size)
    except BlockGapError as nxt:
        # The scan region is damaged too; chase the next gap (resync
        # offsets strictly increase, so this terminates).
        if nxt.resync is None or nxt.resync <= start.block_pos:
            return None
        return _tolerant_next_start(path, Pos(nxt.resync, 0), header, config)
    except (NoReadFoundException, BlockCorruptionError, MalformedInputError,
            EOFError):
        # MalformedInputError covers HeaderParseException and the structural
        # decode guards (core/guard.py).
        return None
    finally:
        checker.close()


def _tolerant_record_resync(path, gap: BlockGapError, header: BamHeader,
                            config: Config):
    """After a quarantined block gap: the first provable record boundary at
    or past the resynced block. Mirrors split resolution — find-block-start
    already happened in the stream's resync; this is the find-record-start
    half."""
    if gap.resync is None:
        return None
    return _tolerant_next_start(path, Pos(gap.resync, 0), header, config)


#: "no cached verdict for this boundary" — distinct from None, which is a
#: *cached* "this split owns no record start".
_UNRESOLVED = object()


def _iter_split_records(
    path, split: FileSplit, header: BamHeader, config: Config,
    start_pos=_UNRESOLVED,
):
    if start_pos is _UNRESOLVED:
        with obs.span("load.partition", split=split.start):
            start_pos = _resolve_split_start(path, split, header, config)
    if start_pos is None:
        return
    tolerant = config.fault_policy.tolerant
    stream = SeekableRecordStream(
        SeekableUncompressedBytes(
            SeekableBlockStream(open_channel(path), tolerant=tolerant)
        ),
        header,
    )
    records = 0
    try:
        stream.seek(start_pos)
        it = iter(stream)
        while True:
            try:
                pos, rec = next(it)
            except StopIteration:
                break
            except BlockGapError as gap:
                # Tolerant mode only (strict streams don't raise it): the
                # damaged block is quarantined; resume at the next provable
                # record boundary past the gap. Records overlapping the
                # damage are dropped with it.
                resume = _tolerant_record_resync(path, gap, header, config)
                if resume is None or resume.block_pos >= split.end:
                    break
                stream.seek(resume)
                it = iter(stream)
                continue
            except RecordGapError as gap:
                # Tolerant mode only: a record's length prefix is garbage,
                # so the local skip-one-record recovery can't size the skip;
                # re-prove a boundary with the checker just past the
                # damaged prefix (the BlockGapError analog one layer up).
                resume = _tolerant_next_start(
                    path, Pos(gap.pos.block_pos, gap.pos.offset + 1),
                    header, config,
                )
                if resume is None or resume.block_pos >= split.end:
                    break
                stream.seek(resume)
                it = iter(stream)
                continue
            if pos.block_pos >= split.end:
                break
            records += 1
            yield pos, rec
    finally:
        stream.close()
        # One counter bump per partition, not per record — the no-exporter
        # contract stays allocation-free inside the record loop.
        obs.count("load.records", records)
        obs.count("load.partitions")


def _consult_split_cache(path, splits, header, config: Config, size: int):
    """``{split: Pos | None}`` of cache-served (or freshly built and
    written-through) record starts; ``{}`` when the cache is off or can't
    serve these splits — absent splits resolve live, the cold path.
    Governed by ``Config.cache`` (docs/caching.md)."""
    mode = config.cache_mode
    if not mode.enabled:
        return {}
    from spark_bam_tpu import sbi
    from spark_bam_tpu.sbi import plan as sbi_plan

    store = sbi.CacheStore.from_env(policy=config.fault_policy)
    if mode.read:
        index = store.load(path, config, strict=mode.strict)
        if index is not None and size in index.split_plans:
            starts = sbi_plan.plan_to_starts(splits, index.split_plans[size])
            if starts is not None:
                return starts
    if not mode.write:
        return {}
    # Miss with write-through: resolve the whole plan driver-side (the
    # same work the partitions would each do lazily) and persist it.
    entries = sbi_plan.build_split_plan(path, splits, header, config)
    store.merge_and_store(
        path, config,
        sbi.SbiIndex(
            sbi.fingerprint_of(path, config), split_plans={size: entries}
        ),
    )
    return sbi_plan.plan_to_starts(splits, entries) or {}


def split_starts(
    path,
    split_size=None,
    config: Config = Config(),
    pool=None,
) -> "list[tuple[FileSplit, Pos | None]]":
    """Resolved first-record positions for every file split of ``path`` —
    the split-plan product without materializing a record ``Dataset``
    (what the serve/ daemon answers ``plan`` requests with).

    Cache-first: a warm ``.sbi`` split plan serves every split with ZERO
    ``_resolve_split_start`` calls (the ``load.split_resolutions`` counter
    stays flat — the daemon's repeat-plan fast path). Cold splits resolve
    in parallel through ``run_partitions`` under the config's fault
    policy; ``pool`` lends a persistent executor (the daemon's) so
    per-request pool spin-up never lands on the hot path. ``None``
    positions mark splits that own no record start (reference
    ``PLAN_NONE``) or whose scan could not prove one.
    """
    from spark_bam_tpu.check.checker import NoReadFoundException
    from spark_bam_tpu.parallel.executor import run_partitions

    size = (
        parse_bytes(split_size) if split_size is not None
        else config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    )
    policy = config.fault_policy
    header = with_retries(lambda: read_header(path), policy, "read_header")
    splits = with_retries(lambda: file_splits(path, size), policy, "file_splits")
    resolved = dict(_consult_split_cache(path, splits, header, config, size))
    missing = [s for s in splits if s not in resolved]
    if missing:
        def resolve(split):
            try:
                return _resolve_split_start(path, split, header, config)
            except NoReadFoundException:
                return None

        results, _ = run_partitions(
            resolve, missing,
            ParallelConfig("threads", workers=min(len(missing), 8)),
            policy, pool=pool,
        )
        resolved.update(zip(missing, results))
    return [(s, resolved.get(s)) for s in splits]


def load_reads_and_positions(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> Dataset:
    """(Pos, BamRecord) pairs, partitioned by file splits (ref :281-334)."""
    config = config.replace(split_size=split_size) if split_size else config
    size = config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    policy = config.fault_policy
    # Driver-side reads run before any partition exists; retry them under
    # the same policy so a transient fault here doesn't kill the job.
    header = with_retries(lambda: read_header(path), policy, "read_header")
    splits = with_retries(lambda: file_splits(path, size), policy, "file_splits")
    starts_by_split = _consult_split_cache(path, splits, header, config, size)
    return Dataset(
        splits,
        lambda split: _iter_split_records(
            path, split, header, config,
            start_pos=starts_by_split.get(split, _UNRESOLVED),
        ),
        parallel,
        policy=config.fault_policy,
    )


def load_bam(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> Dataset:
    """Records of a BAM, partitioned by file splits (ref :173-243)."""
    ds = load_reads_and_positions(path, split_size, config, parallel)
    compute = ds.compute
    return Dataset(
        ds.partitions,
        lambda p: (rec for _, rec in compute(p)),
        parallel,
        policy=ds.policy,
    )


def load_fleet(
    paths,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> Dataset:
    """Many BAMs as ONE dataset, partitioned by file — fleet mode
    (docs/remote.md). Each partition opens its own channels inside the
    worker (zero serial driver-side remote reads — hadoop-bam's original
    sin), rides the resilient executor's retry/hedge ledger, and shares
    the process-wide remote GET quota (core/remote_plan.py) plus the
    ``.sbi`` cache tier, so 64+ concurrent objects cannot stampede the
    store. Yields (path, Pos, BamRecord) triples."""
    paths = [str(p) for p in paths]

    def compute(path):
        # Header/split resolution happens HERE, in the partition, under
        # the sequential inner executor — the outer pool is the only
        # parallelism, so attempts stay independently retryable.
        ds = load_reads_and_positions(
            path, split_size, config, ParallelConfig("sequential")
        )
        for split in ds.partitions:
            for pos, rec in ds.compute(split):
                yield path, pos, rec

    obs.gauge("load.fleet_files").set(len(paths))
    return Dataset(paths, compute, parallel, policy=config.fault_policy)


def load_splits_and_reads(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> tuple[list[Split], Dataset]:
    """Resolved splits + the records dataset (ref :245-279)."""
    ds = load_reads_and_positions(path, split_size, config, parallel)
    firsts = ds.first_per_partition()
    starts = [pos for item in firsts if item is not None for pos in [item[0]]]
    eof = Pos(path_size(path), 0)
    splits = [
        Split(start, starts[i + 1] if i + 1 < len(starts) else eof)
        for i, start in enumerate(starts)
    ]
    return splits, load_bam(path, split_size, config, parallel)


def _scan_sam_header(path):
    """One pass over a SAM text header → the @SQ contig dictionary
    (the single parse shared by load_sam and the interval degrade path)."""
    from spark_bam_tpu.bam.header import ContigLengths

    entries: dict[int, tuple[str, int]] = {}
    with open(path, "rt") as f:
        for line in f:
            if not line.startswith("@"):
                break
            if line.startswith("@SQ"):
                fields = dict(
                    kv.split(":", 1)
                    for kv in line.rstrip("\n").split("\t")[1:]
                    if ":" in kv
                )
                if "SN" in fields:
                    entries[len(entries)] = (
                        fields["SN"], int(fields.get("LN", "0"))
                    )
    return ContigLengths(entries)


def load_sam(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> Dataset:
    """SAM text file → records, newline-aligned byte-range partitions."""
    size = (
        config.replace(split_size=split_size).split_size
        if split_size
        else config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    )
    contigs = _scan_sam_header(path)
    contigs_by_name = {name: idx for idx, (name, _) in contigs.items()}
    file_size = os.path.getsize(path)
    ranges = [(s, min(s + size, file_size)) for s in range(0, file_size, size)]

    def compute(rng):
        start, end = rng
        with open(path, "rb") as f:
            f.seek(start)
            if start > 0:
                f.readline()  # skip the partial line owned by the previous split
            while f.tell() < end:
                line = f.readline()
                if not line:
                    break
                text = line.decode("latin-1")
                if text.startswith("@"):
                    continue
                yield parse_sam_line(text, contigs_by_name)

    return Dataset(ranges, compute, parallel, policy=config.fault_policy)


def load_cram(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
    reference=None,
) -> Dataset:
    """Records of a CRAM, partitioned by container byte ranges.

    The reference delegates .cram to hadoop-bam's ``CRAMInputFormat``
    (CanLoadBam.scala:354-366), whose splits are container-aligned; here
    the built-in CRAM reader (cram/) supplies the container table and the
    decode. ``reference`` (FASTA path or {name: bytes}) is needed only for
    files with reference-based sequence encoding (``RR=true``)."""
    from spark_bam_tpu.cram import CramReader

    reference = _resolve_reference(reference)
    config = config.replace(split_size=split_size) if split_size else config
    size = config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    with CramReader(path) as r:
        infos = r.container_infos()
    groups = _group_by_size(infos, size)

    def compute(group):
        with CramReader(path, reference=reference) as r:
            yield from r.records(group[0].offset, group[-1].offset + 1)

    return Dataset(groups, compute, parallel, policy=config.fault_policy)


def _resolve_reference(reference):
    """FASTA path → {name: bytes}, parsed once (not per partition)."""
    if isinstance(reference, (str, bytes)) or hasattr(reference, "__fspath__"):
        from spark_bam_tpu.cram.fasta import read_fasta

        return read_fasta(reference)
    return reference


def _group_by_size(infos, size: int) -> list[list]:
    """Greedy size-capped grouping of container infos by compressed bytes
    (the container analog of pack_chunks)."""
    groups: list[list] = []
    cur: list = []
    cur_bytes = 0
    for info in infos:
        length = info.end - info.offset
        if cur and cur_bytes + length > size:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(info)
        cur_bytes += length
    if cur:
        groups.append(cur)
    return groups


def load_cram_intervals(
    path,
    loci: LociSet | str,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
    reference=None,
) -> Dataset:
    """Indexed random access on a CRAM: only records overlapping ``loci``.

    The ``.crai`` sidecar (one line per slice×reference with container
    offsets — cram/crai.py) plays the role the ``.bai`` plays for
    ``load_bam_intervals``; without one, every container is scanned and
    the overlap filter alone narrows the result."""
    from spark_bam_tpu.cram import CramReader
    from spark_bam_tpu.cram.crai import read_crai

    reference = _resolve_reference(reference)
    with CramReader(path) as r:
        header = r.bam_header
        infos = r.container_infos()
    if isinstance(loci, str):
        loci = LociSet.parse(loci, header.contig_lengths)
    name_to_idx = {
        name: idx for idx, (name, _) in header.contig_lengths.items()
    }
    crai_path = str(path) + ".crai"
    selected = infos
    if path_exists(crai_path):
        # ref id → 0-based intervals, whole-contig expanded, computed once.
        by_ref = {
            name_to_idx[contig]: ivs or [(0, header.contig_lengths[name_to_idx[contig]][1])]
            for contig, ivs in loci.intervals.items()
            if contig in name_to_idx
        }
        wanted = set()
        for entry in read_crai(crai_path):
            ivs = by_ref.get(entry.ref_seq_id)
            if ivs and any(entry.overlaps(entry.ref_seq_id, s, e) for s, e in ivs):
                wanted.add(entry.container_offset)
        selected = [info for info in infos if info.offset in wanted]

    config = config.replace(split_size=split_size) if split_size else config
    size = config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    groups = _group_by_size(selected, size)

    def overlaps(rec: BamRecord) -> bool:
        if rec.ref_id < 0 or rec.is_unmapped:
            return False
        return loci.overlaps(
            header.contig_lengths.name(rec.ref_id), rec.pos, rec.end_pos()
        )

    def compute(group):
        with CramReader(path, reference=reference) as r:
            for offset, end in _contiguous_runs(group):
                for rec in r.records(offset, end):
                    if overlaps(rec):
                        yield rec

    return Dataset(groups, compute, parallel, policy=config.fault_policy)


def _contiguous_runs(group):
    """Collapse container infos into (offset, end) runs so non-adjacent
    selections don't decode the containers between them."""
    runs = []
    for info in group:
        if runs and runs[-1][1] == info.offset:
            runs[-1][1] = info.end
        else:
            runs.append([info.offset, info.end])
    return [(s, e) for s, e in runs]


def load_reads(
    path,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
    reference=None,
) -> Dataset:
    """Extension dispatch: .sam / .bam / .cram (ref CanLoadBam.scala:348-382;
    the reference delegates .cram to hadoop-bam, here it's built in).
    ``reference`` is forwarded to the CRAM loader for reference-based
    (RR=true) files; other formats ignore it."""
    s = str(path)
    if s.endswith(".sam"):
        return load_sam(path, split_size, config, parallel)
    if s.endswith(".bam"):
        return load_bam(path, split_size, config, parallel)
    if s.endswith(".cram"):
        return load_cram(path, split_size, config, parallel, reference=reference)
    raise ValueError(f"Can't tell format of path: {s}")


# ---------------------------------------------------------------- columnar
def export(
    path,
    out,
    loci: "LociSet | str | None" = None,
    fmt: str = "native",
    columns=None,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
    reference=None,
    flags_required: int = 0,
    flags_forbidden: int = 0,
) -> dict:
    """Export a BAM/CRAM/SAM's records as columnar record batches
    (docs/analytics.md): ``fmt`` is ``native`` (zero-dependency container,
    columnar/native.py), ``arrow`` (IPC file) or ``parquet`` (the latter
    two need the ``pyarrow`` extra). ``loci`` restricts to overlapping
    records via the indexed interval loaders; ``columns`` projects the
    schema. Partition work runs through the fault-tolerant executor, so
    retries/quarantine apply and the returned summary carries the loss
    accounting. Output bytes are a pure function of (query, columnar
    config): the serve daemon's ``batch`` op streams the identical native
    frames for the same query."""
    from spark_bam_tpu.columnar.export import export_dataset

    s = str(path)
    if s.endswith(".cram"):
        from spark_bam_tpu.cram import CramReader

        with CramReader(path) as r:
            contig_lengths = r.bam_header.contig_lengths
        ds = (
            load_cram_intervals(path, loci, split_size, config, parallel,
                                reference=reference)
            if loci
            else load_cram(path, split_size, config, parallel,
                           reference=reference)
        )
    elif s.endswith(".sam"):
        contig_lengths = _scan_sam_header(path)
        ds = (
            _load_sam_intervals(path, loci, split_size, config, parallel)
            if loci
            else load_sam(path, split_size, config, parallel)
        )
    else:
        contig_lengths = with_retries(
            lambda: read_header(path), config.fault_policy, "read_header"
        ).contig_lengths
        ds = (
            load_bam_intervals(path, loci, split_size, config, parallel)
            if loci
            else load_bam(path, split_size, config, parallel)
        )
    if flags_required or flags_forbidden:
        # Pure flag predicate — same semantics as the device filter's
        # flag half (_apply_filter): unmapped reads pass unless a flag
        # bit excludes them.
        ds = ds.filter(
            lambda rec: (rec.flag & flags_required) == flags_required
            and (rec.flag & flags_forbidden) == 0
        )
    contigs = [
        (name, length) for _, (name, length) in sorted(contig_lengths.items())
    ]
    return export_dataset(
        ds, out, fmt=fmt, columns=columns, ccfg=config.columnar_config,
        contigs=contigs,
    )


def aggregate(
    path,
    agg: str = "",
    loci: "LociSet | str | None" = None,
    flags_required: int = 0,
    flags_forbidden: int = 0,
    tags_required=(),
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
    reference=None,
    chunk: "int | None" = None,
) -> dict:
    """Aggregate statistics for a query, without materializing records
    (docs/analytics.md "Aggregation"). ``agg`` is the compact
    :class:`~spark_bam_tpu.agg.plan.AggConfig` spec (``""`` = every
    metric at defaults, or ``config.agg``); predicates mirror
    ``export``'s (``loci`` intervals, flag masks, plus tag presence).

    BAM files reduce on device: flat view → parsed planes →
    ``_apply_filter`` pushdown → the fused jit carry
    (agg/kernels.py). CRAM/SAM fall back to the record path — the
    fault-tolerant partition executor runs the numpy oracle per
    partition and merges (``Dataset.aggregate``). Both paths return the
    identical structure: ``{"agg", "rows", "contigs", "metrics"}`` with
    int64 vectors byte-equal across paths for the same query.
    """
    from spark_bam_tpu.agg.plan import AggConfig
    from spark_bam_tpu.bam.record import render_tags

    plan = AggConfig.parse(agg or config.agg)
    tags_required = tuple(tags_required or ())
    for t in tags_required:
        if not isinstance(t, str) or len(t) != 2:
            raise ValueError(f"tag names are exactly two chars: {t!r}")
    s = str(path)
    if s.endswith(".bam"):
        import numpy as np

        from spark_bam_tpu.agg.kernels import aggregate_planes
        from spark_bam_tpu.bgzf.flat import flatten_file
        from spark_bam_tpu.load.tpu_load import _apply_filter, record_starts
        from spark_bam_tpu.tpu.parser import ReadBatch, parse_flat_records

        header = with_retries(
            lambda: read_header(path), config.fault_policy, "read_header"
        )
        contig_lengths = header.contig_lengths
        nc = len(contig_lengths.lengths_list())
        flat = flatten_file(path)
        starts = np.asarray(record_starts(path, config).starts, dtype=np.int64)
        batch = parse_flat_records(flat.data, starts)
        if loci or flags_required or flags_forbidden or tags_required:
            _apply_filter(
                batch, header, loci, flags_required, flags_forbidden,
                tags_required=tags_required,
            )
        rows = int(np.count_nonzero(batch.columns["valid"]))
        with obs.span("agg.reduce", path=s, rows=rows):
            metrics = aggregate_planes(batch.columns, plan, nc, chunk=chunk)
    else:
        if s.endswith(".cram"):
            from spark_bam_tpu.cram import CramReader

            with CramReader(path) as r:
                contig_lengths = r.bam_header.contig_lengths
            ds = (
                load_cram_intervals(path, loci, split_size, config, parallel,
                                    reference=reference)
                if loci
                else load_cram(path, split_size, config, parallel,
                               reference=reference)
            )
        elif s.endswith(".sam"):
            contig_lengths = _scan_sam_header(path)
            ds = (
                _load_sam_intervals(path, loci, split_size, config, parallel)
                if loci
                else load_sam(path, split_size, config, parallel)
            )
        else:
            raise ValueError(f"Can't tell format of path: {s}")
        if flags_required or flags_forbidden:
            ds = ds.filter(
                lambda rec: (rec.flag & flags_required) == flags_required
                and (rec.flag & flags_forbidden) == 0
            )
        if tags_required:
            # Presence via the total tag renderer: malformed tag blocks
            # render what they can, so a damaged entry reads as absent —
            # the same stop-clean semantics as the plane scan.
            prefixes = tuple(t + ":" for t in tags_required)

            def _has_tags(rec) -> bool:
                rendered = render_tags(rec.tags)
                return all(
                    any(r.startswith(p) for r in rendered) for p in prefixes
                )

            ds = ds.filter(_has_tags)
        nc = len(contig_lengths.lengths_list())
        metrics = ds.aggregate(plan, nc)
        # count[0] / flagstat[0] are both "records seen" — reuse either
        # rather than re-running the dataset for a side count.
        if "count" in metrics:
            rows = int(metrics["count"][0])
        elif "flagstat" in metrics:
            rows = int(metrics["flagstat"][0])
        else:
            rows = None
    contigs = [
        (name, length) for _, (name, length) in sorted(contig_lengths.items())
    ]
    return {
        "agg": plan.canonical(),
        "rows": rows,
        "contigs": contigs,
        "metrics": metrics,
    }


# --------------------------------------------------------------- intervals
def interval_chunks(
    path, loci: LociSet, header: BamHeader, config: Config = Config()
) -> list[Chunk]:
    """.bai chunks overlapping the loci (ref getIntevalChunks :387-421)."""
    bai = BaiIndex.read(str(path) + ".bai")
    name_to_idx = {name: idx for idx, (name, _) in header.contig_lengths.items()}
    chunks: list[Chunk] = []
    for contig, ivs in loci.intervals.items():
        if contig not in name_to_idx:
            continue
        ref = name_to_idx[contig]
        if not ivs:
            length = header.contig_lengths[ref][1]
            ivs = [(0, length)]
        for s, e in ivs:
            chunks.extend(bai.query(ref, s, e))
    chunks.sort(key=lambda c: (c.start, c.end))
    from spark_bam_tpu.bam.bai import merge_chunks

    return merge_chunks(chunks)


def pack_chunks(
    chunks: list[Chunk], split_size: int, ratio: float
) -> list[list[Chunk]]:
    """Greedy size-capped grouping (the reference's cappedCostGroups,
    CanLoadBam.scala:85-99)."""
    groups: list[list[Chunk]] = []
    cur: list[Chunk] = []
    cur_cost = 0
    for c in chunks:
        cost = max(c.size(ratio), 1)
        if cur and cur_cost + cost > split_size:
            groups.append(cur)
            cur, cur_cost = [], 0
        cur.append(c)
        cur_cost += cost
    if cur:
        groups.append(cur)
    return groups


def _load_sam_intervals(
    path,
    loci: LociSet | str,
    split_size,
    config: Config,
    parallel: ParallelConfig,
) -> Dataset:
    """SAM degrade path for interval loads: SAM text has no index, so the
    whole file is scanned and the interval-overlap filter alone narrows the
    result (reference CanLoadBam.scala:59-76 — SAM paths degrade to a
    full-scan filter inside loadBamIntervals)."""
    contigs = _scan_sam_header(path)
    if isinstance(loci, str):
        loci = LociSet.parse(loci, contigs)

    def overlaps(rec: BamRecord) -> bool:
        if rec.ref_id < 0 or rec.is_unmapped:
            return False
        return loci.overlaps(contigs.name(rec.ref_id), rec.pos, rec.end_pos())

    ds = load_sam(path, split_size, config, parallel)
    compute = ds.compute
    return Dataset(
        ds.partitions,
        lambda p: (rec for rec in compute(p) if overlaps(rec)),
        parallel,
        policy=ds.policy,
    )


def load_bam_intervals(
    path,
    loci: LociSet | str,
    split_size=None,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> Dataset:
    """Indexed random access: only records overlapping ``loci`` (ref :59-138).

    SAM paths degrade to a full scan + overlap filter, mirroring the
    reference's behavior for unindexed text input."""
    if str(path).endswith(".sam"):
        return _load_sam_intervals(path, loci, split_size, config, parallel)
    header = with_retries(
        lambda: read_header(path), config.fault_policy, "read_header"
    )
    if isinstance(loci, str):
        loci = LociSet.parse(loci, header.contig_lengths)
    config = config.replace(split_size=split_size) if split_size else config
    size = config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    chunks = interval_chunks(path, loci, header, config)
    groups = pack_chunks(chunks, size, config.estimated_compression_ratio)

    def overlaps(rec: BamRecord) -> bool:
        # Unmapped reads (even placed ones) have no genomic region.
        if rec.ref_id < 0 or rec.is_unmapped:
            return False
        return loci.overlaps(
            header.contig_lengths.name(rec.ref_id), rec.pos, rec.end_pos()
        )

    def compute(group):
        stream = SeekableRecordStream(
            SeekableUncompressedBytes(SeekableBlockStream(open_channel(path))),
            header,
        )
        try:
            for chunk in group:
                stream.seek(chunk.start)
                for pos, rec in stream:
                    if (pos.block_pos, pos.offset) >= (
                        chunk.end.block_pos,
                        chunk.end.offset,
                    ):
                        break
                    if overlaps(rec):
                        yield rec
        finally:
            stream.close()

    return Dataset(groups, compute, parallel, policy=config.fault_policy)
