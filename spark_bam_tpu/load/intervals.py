"""Loci sets: genomic interval collections for indexed loads.

Parses ``chr1:100-200,chr2,chr3:5k-10k`` style strings (the reference uses
hammerlab LociSet for ``loadBamIntervals``, load/.../CanLoadBam.scala:59-138).

Genomic coordinates get their own suffix table: ``k``/``m``/``g`` are
decimal (1e3/1e6/1e9) — ``chr1:5k-10k`` means positions 5 000–10 000,
not the 5 120–10 240 the *byte*-size shorthand (core/config.parse_bytes)
would produce. Malformed ranges (no ``-``, ``lo > hi``, negative or
non-integral coordinates) raise :class:`BadLociError`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class BadLociError(ValueError):
    """Malformed loci string (bad coordinate, bad range, lo > hi)."""


_LOCUS_RE = re.compile(r"^(\d+(?:\.\d+)?)([kKmMgG]?)$")

#: Decimal multipliers — genomic positions are base counts, not bytes.
_LOCUS_FACTORS = {"": 1, "k": 1_000, "m": 1_000_000, "g": 1_000_000_000}


def parse_locus(s: str) -> int:
    """One genomic coordinate: ``100``, ``5k``, ``1.5m``. Decimal suffixes;
    the value must come out a non-negative integer."""
    m = _LOCUS_RE.match(str(s).strip())
    if not m:
        raise BadLociError(
            f"bad genomic coordinate {s!r}: expected an integer with an "
            "optional decimal k/m/g suffix (e.g. 100, 5k, 1.5m)"
        )
    value, unit = m.groups()
    n = float(value) * _LOCUS_FACTORS[unit.lower()]
    if n != int(n):
        raise BadLociError(
            f"bad genomic coordinate {s!r}: {value}{unit} is not a whole "
            "number of positions"
        )
    return int(n)


@dataclass
class LociSet:
    # contig name → list of half-open (start, end); empty list ⇒ whole contig
    intervals: dict[str, list[tuple[int, int]]] = field(default_factory=dict)

    @staticmethod
    def parse(s: str, contig_lengths=None) -> "LociSet":
        out: dict[str, list[tuple[int, int]]] = {}
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                name, rng = part.split(":", 1)
                if "-" not in rng:
                    raise BadLociError(
                        f"bad range {part!r}: expected contig:lo-hi"
                    )
                lo_s, hi_s = rng.split("-", 1)
                lo, hi = parse_locus(lo_s), parse_locus(hi_s)
                if lo > hi:
                    raise BadLociError(
                        f"bad range {part!r}: start {lo} is past end {hi}"
                    )
                out.setdefault(name, []).append((lo, hi))
            else:
                out.setdefault(part, [])
        if contig_lengths is not None:
            for name, ivs in out.items():
                if not ivs:
                    length = next(
                        (l for _, (n, l) in contig_lengths.items() if n == name), None
                    )
                    if length is not None:
                        ivs.append((0, length))
        return LociSet(out)

    def overlaps(self, contig: str, start: int, end: int) -> bool:
        if contig not in self.intervals:
            return False
        ivs = self.intervals[contig]
        if not ivs:
            return True  # whole contig
        return any(s < end and start < e for s, e in ivs)

    def ranges_for(self, contig: str) -> list[tuple[int, int]] | None:
        return self.intervals.get(contig)

    def __bool__(self) -> bool:
        return bool(self.intervals)
