"""Loci sets: genomic interval collections for indexed loads.

Parses ``chr1:100-200,chr2,chr3:5k-10k`` style strings (the reference uses
hammerlab LociSet for ``loadBamIntervals``, load/.../CanLoadBam.scala:59-138).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from spark_bam_tpu.core.config import parse_bytes


@dataclass
class LociSet:
    # contig name → list of half-open (start, end); empty list ⇒ whole contig
    intervals: dict[str, list[tuple[int, int]]] = field(default_factory=dict)

    @staticmethod
    def parse(s: str, contig_lengths=None) -> "LociSet":
        out: dict[str, list[tuple[int, int]]] = {}
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                name, rng = part.split(":", 1)
                lo, hi = rng.split("-", 1)
                out.setdefault(name, []).append((parse_bytes(lo), parse_bytes(hi)))
            else:
                out.setdefault(part, [])
        if contig_lengths is not None:
            for name, ivs in out.items():
                if not ivs:
                    length = next(
                        (l for _, (n, l) in contig_lengths.items() if n == name), None
                    )
                    if length is not None:
                        ivs.append((0, length))
        return LociSet(out)

    def overlaps(self, contig: str, start: int, end: int) -> bool:
        if contig not in self.intervals:
            return False
        ivs = self.intervals[contig]
        if not ivs:
            return True  # whole contig
        return any(s < end and start < e for s, e in ivs)

    def ranges_for(self, contig: str) -> list[tuple[int, int]] | None:
        return self.intervals.get(contig)

    def __bool__(self) -> bool:
        return bool(self.intervals)
