from spark_bam_tpu.load.api import (
    load_bam,
    load_bam_intervals,
    load_reads,
    load_reads_and_positions,
    load_sam,
    load_splits_and_reads,
)
from spark_bam_tpu.load.splits import Split
from spark_bam_tpu.load.dataset import Dataset

__all__ = [
    "load_bam",
    "load_bam_intervals",
    "load_reads",
    "load_reads_and_positions",
    "load_sam",
    "load_splits_and_reads",
    "Split",
    "Dataset",
]
