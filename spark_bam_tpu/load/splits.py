"""File splits and resolved record-boundary splits.

Reference: hadoop ``FileSplits`` → ``SplitRDD`` byte ranges
(load/.../load/SplitRDD.scala:37-79) and the resolved
``Split(start: Pos, end: Pos)`` (check/.../bam/spark/Split.scala:80-104).
"""

from __future__ import annotations

from dataclasses import dataclass

from spark_bam_tpu.core.channel import path_size
from spark_bam_tpu.core.pos import Pos


@dataclass(frozen=True)
class FileSplit:
    """A compressed byte range [start, end) of one file."""
    path: str
    start: int
    end: int

    @property
    def length(self) -> int:
        return self.end - self.start


@dataclass(frozen=True)
class Split:
    """A resolved split: record-boundary virtual positions [start, end)."""
    start: Pos
    end: Pos

    def length(self, estimated_compression_ratio: float = 3.0) -> int:
        return self.end.distance(self.start, estimated_compression_ratio)

    def __str__(self) -> str:
        return f"Split({self.start}-{self.end})"


def file_splits(path, split_size: int) -> list[FileSplit]:
    size = path_size(path)
    return [
        FileSplit(str(path), start, min(start + split_size, size))
        for start in range(0, size, split_size)
    ]
