"""File splits and resolved record-boundary splits — plus split locality.

Reference: hadoop ``FileSplits`` → ``SplitRDD`` byte ranges
(load/.../load/SplitRDD.scala:37-79) and the resolved
``Split(start: Pos, end: Pos)`` (check/.../bam/spark/Split.scala:80-104).

Locality: the reference's ``SplitRDD.preferredLocations`` surfaces HDFS
block hosts so Spark schedules tasks data-local. There is no HDFS here;
the analog is a pluggable provider — ``set_locality_provider`` registers
``fn(path, start, end) -> list[str]`` (e.g. a cache-affinity map for
remote objects, or a parallel-FS topology query) and
``preferred_hosts(split)`` consults it. The multi-host mesh analog is
``parallel.stream_mesh.host_shard_plan``: the exact per-host contiguous
block ranges the unified sharding engine will read, for co-locating
processes with data before bring-up.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from spark_bam_tpu.core.channel import path_size
from spark_bam_tpu.core.pos import Pos


@dataclass(frozen=True)
class FileSplit:
    """A compressed byte range [start, end) of one file."""
    path: str
    start: int
    end: int

    @property
    def length(self) -> int:
        return self.end - self.start


@dataclass(frozen=True)
class Split:
    """A resolved split: record-boundary virtual positions [start, end)."""
    start: Pos
    end: Pos

    def length(self, estimated_compression_ratio: float = 3.0) -> int:
        return self.end.distance(self.start, estimated_compression_ratio)

    def __str__(self) -> str:
        return f"Split({self.start}-{self.end})"


def file_splits(path, split_size: int) -> list[FileSplit]:
    size = path_size(path)
    return [
        FileSplit(str(path), start, min(start + split_size, size))
        for start in range(0, size, split_size)
    ]


# ------------------------------------------------------------------ locality

_LOCALITY_PROVIDER: Callable[[str, int, int], list] | None = None


def set_locality_provider(fn: Callable[[str, int, int], list] | None) -> None:
    """Register ``fn(path, start, end) -> [host, ...]`` (or None to clear)
    — the ``SplitRDD.preferredLocations`` analog for whatever storage
    topology the deployment has (reference SplitRDD.scala:43-79)."""
    global _LOCALITY_PROVIDER
    _LOCALITY_PROVIDER = fn


def preferred_hosts(split: FileSplit) -> list:
    """Hosts that hold (or cache) ``split``'s byte range; empty = anywhere."""
    if _LOCALITY_PROVIDER is None:
        return []
    return list(_LOCALITY_PROVIDER(split.path, split.start, split.end))
