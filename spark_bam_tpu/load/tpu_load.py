"""TPU-backed end-to-end loading: the production fast path.

Composes the pipeline the BASELINE north star describes: BGZF blocks →
flat windows in HBM → vectorized boundary checking → batched columnar
record parsing with on-device filters. The host only inflates, steers
windows, and re-checks the (rare) escaped candidates.

- ``record_starts``: every record-start flat offset of a file, from the
  checker's verdicts (positions ≥ the header end; the eager battery has no
  known false calls — SURVEY.md §6 "spark-bam miscalls: 0 known")
- ``count_reads_tpu``: boundary count — the count-reads workload with zero
  per-record host work
- ``load_reads_columnar``: ReadBatch columnar views of all (or
  interval/flag-filtered) records
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_bam_tpu import obs
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bgzf.flat import FlatView, flatten_file
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.intervals import LociSet
from spark_bam_tpu.tpu.checker import TpuChecker
from spark_bam_tpu.tpu.parser import (
    ReadBatch,
    _next_pow2,
    interval_flag_filter,
    parse_flat_records,
)


@dataclass
class TpuLoadResult:
    view: FlatView
    header: object
    starts: np.ndarray  # flat record-start offsets

    def positions(self) -> list[Pos]:
        blocks, offs = self.view.pos_of_flat_many(self.starts)
        return [Pos(int(b), int(o)) for b, o in zip(blocks, offs)]


def _cached_record_starts(view, path, config, store, strict):
    """Flat record-start offsets from a valid ``.sbi`` sidecar, or None.
    Cached positions are stored virtual (portable across re-flattenings);
    the conversion is vectorized against the view's block tables."""
    from spark_bam_tpu.sbi.format import SbiFormatError, record_starts_to_flat

    index = store.load(path, config, strict=strict)
    if index is None or index.record_starts is None:
        return None
    try:
        return record_starts_to_flat(view, index.record_starts)
    except SbiFormatError:
        # Position names a block the file lacks — the fingerprint should
        # preclude this; recompute rather than trust it.
        return None


def record_starts(
    path, config: Config = Config(), checker: TpuChecker | None = None
) -> TpuLoadResult:
    """Whole-file record starts with the flat view retained (small files /
    callers that need the bytes, e.g. columnar parsing). For inputs larger
    than memory use ``record_starts_streaming`` / ``count_reads_tpu``, which
    run in O(window) host memory. With ``Config.cache`` enabled, a valid
    ``.sbi`` sidecar supplies the starts with zero checker work."""
    header = read_header(path)
    view = flatten_file(path)
    mode = config.cache_mode
    store = None
    if mode.enabled:
        from spark_bam_tpu.sbi.store import CacheStore

        store = CacheStore.from_env(policy=config.fault_policy)
        if mode.read:
            starts = _cached_record_starts(
                view, path, config, store, mode.strict
            )
            if starts is not None:
                obs.count("load.record_starts", len(starts))
                return TpuLoadResult(view, header, starts)
    if checker is None:
        # Size the window to the input: a small file in one kernel call, big
        # files stream through config.window_size windows. Power-of-two sizes
        # keep the jit cache small across files.
        want = min(config.window_size, max(view.size, 1))
        window = 1 << max(20, (want - 1).bit_length())
        checker = TpuChecker(
            np.array(header.contig_lengths.lengths_list(), dtype=np.int32),
            window=window,
            halo=min(config.halo_size, window // 4),
            reads_to_check=config.reads_to_check,
        )
    with obs.span("check.window", kind="whole_file", bytes=view.size):
        res = checker.check_buffer(view.data, at_eof=True)
    header_end = view.flat_of_pos(header.end_pos.block_pos, header.end_pos.offset)
    starts = np.flatnonzero(res.verdict)
    starts = starts[starts >= header_end]
    obs.count("load.record_starts", len(starts))
    if store is not None and mode.write:
        from spark_bam_tpu.sbi.format import (
            SbiIndex,
            fingerprint_of,
            record_starts_to_virtual,
        )

        store.merge_and_store(
            path, config,
            SbiIndex(
                fingerprint_of(path, config),
                record_starts=record_starts_to_virtual(view, starts),
            ),
        )
    return TpuLoadResult(view, header, starts)


def record_starts_streaming(path, config: Config = Config()):
    """Absolute flat record-start offsets, streamed per window in O(window)
    host memory (the WGS-scale path; reference CanLoadBam.scala:173-243 is
    likewise streaming per split)."""
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    yield from StreamChecker(path, config).record_starts()


def _interval_table(header, loci: LociSet | str) -> np.ndarray:
    """(R, 3) int32 rows of (ref_id, start, end) for the device filter."""
    if isinstance(loci, str):
        loci = LociSet.parse(loci, header.contig_lengths)
    name_to_idx = {
        name: idx for idx, (name, _) in header.contig_lengths.items()
    }
    rows = []
    for contig, ivs in loci.intervals.items():
        if contig not in name_to_idx:
            continue
        ref = name_to_idx[contig]
        if not ivs:
            ivs = [(0, header.contig_lengths[ref][1])]
        rows.extend((ref, s, e) for s, e in ivs)
    return np.array(rows or [(-2, 0, 0)], dtype=np.int32)


#: tag value-type byte → fixed payload size; Z/H are NUL-terminated and
#: B is typed-array-counted — both handled inline by the scan.
_TAG_SIZES = {
    ord("A"): 1, ord("c"): 1, ord("C"): 1,
    ord("s"): 2, ord("S"): 2,
    ord("i"): 4, ord("I"): 4, ord("f"): 4,
}


def _tag_presence_mask(batch: ReadBatch, tags_required) -> np.ndarray:
    """Per-row mask: does the record's tag region contain every tag in
    ``tags_required`` (two-character names, e.g. ``("NM", "MD")``)?

    Guard-boundary-clean by construction (core/guard.py discipline for
    untrusted bytes, without the raise half): every offset is clamped to
    the buffer, the walk is bounded by the record's declared extent, and
    a malformed entry (unknown type byte, truncated payload, unbounded
    B-array count) STOPS the walk — the remaining tags read as absent,
    never as an exception or an over-extent read. No struct unpacks, no
    unbounded loops.
    """
    cols = batch.columns
    buf = batch.buf
    wanted = [t.encode("latin-1") for t in tags_required]
    mask = np.zeros(len(cols["valid"]), dtype=bool)
    if buf is None:
        raise ValueError(
            "tag filter needs the flat record buffer (batch.buf)"
        )
    nbuf = len(buf)
    starts = batch.starts
    name_off = cols["name_offset"]
    l_name = cols["l_read_name"]
    n_cigar = cols["n_cigar"]
    l_seq = cols["l_seq"]
    block_size = cols["block_size"]
    for i in np.flatnonzero(cols["valid"]):
        ls = int(l_seq[i])
        p = (int(name_off[i]) + int(l_name[i]) + 4 * int(n_cigar[i])
             + (ls + 1) // 2 + ls)
        end = int(starts[i]) + 4 + int(block_size[i])
        end = max(0, min(end, nbuf))
        p = max(0, min(p, end))
        present = set()
        while p + 3 <= end:
            tag = bytes(buf[p: p + 2])
            typ = int(buf[p + 2])
            p += 3
            if typ in _TAG_SIZES:
                q = p + _TAG_SIZES[typ]
            elif typ in (ord("Z"), ord("H")):
                nuls = np.flatnonzero(buf[p:end] == 0)
                if len(nuls) == 0:
                    break                     # unterminated: stop clean
                q = p + int(nuls[0]) + 1
            elif typ == ord("B"):
                if p + 5 > end:
                    break
                elem = _TAG_SIZES.get(int(buf[p]))
                count = (int(buf[p + 1]) | (int(buf[p + 2]) << 8)
                         | (int(buf[p + 3]) << 16) | (int(buf[p + 4]) << 24))
                if elem is None or count < 0 or count > end - p:
                    break                     # malformed: stop clean
                q = p + 5 + elem * count
            else:
                break                         # unknown type byte: stop clean
            if q > end:
                break                         # truncated payload: stop clean
            present.add(tag)
            p = q
        mask[i] = all(t in present for t in wanted)
    return mask


def _apply_filter(
    batch: ReadBatch,
    header,
    loci: LociSet | str | None,
    flags_required: int,
    flags_forbidden: int,
    tags_required=None,
) -> ReadBatch:
    """Narrow a batch's ``valid`` mask by loci/flags/tag-presence (the
    pushdown shared by the whole-file and streaming loads and the serve
    ``batch``/``aggregate`` ops). Flag-only filtering is a pure flag
    predicate — unmapped reads pass unless a flag excludes them; only a
    loci filter imposes the reference's unmapped-reads-never-overlap
    rule (CanLoadBam.scala:109-133). ``tags_required`` is an iterable of
    two-character tag names that must ALL be present in a record's tag
    region (e.g. ``("NM",)``)."""
    if tags_required:
        for t in tags_required:
            if not isinstance(t, str) or len(t) != 2:
                raise ValueError(
                    f"Bad tag name {t!r}: expected two characters (e.g. 'NM')"
                )
        batch.columns["valid"] = (
            batch.columns["valid"] & _tag_presence_mask(batch, tags_required)
        )
    if loci is None:
        flag = batch.columns["flag"]
        ok = ((flag & flags_required) == flags_required) & (
            (flag & flags_forbidden) == 0
        )
        batch.columns["valid"] = batch.columns["valid"] & ok
        return batch
    import jax.numpy as jnp

    # Only the columns the device filter reads make the trip; rows pad to
    # a power of two (valid=False ⇒ masked out) so the jit sees at most
    # log2 distinct shapes across batches, not one compile per batch size.
    m = len(batch.columns["valid"])
    m_pad = _next_pow2(m)

    def padded(k):
        col = batch.columns[k]
        if m_pad == m:
            return jnp.asarray(col)
        out = np.zeros(m_pad, dtype=col.dtype)
        out[:m] = col
        return jnp.asarray(out)

    cols = {
        k: padded(k) for k in ("pos", "ref_span", "ref_id", "flag", "valid")
    }
    mask = np.asarray(
        interval_flag_filter(
            cols, jnp.asarray(_interval_table(header, loci)),
            jnp.int32(flags_required), jnp.int32(flags_forbidden),
        )
    )[:m]
    batch.columns["valid"] = batch.columns["valid"] & mask
    return batch


def stream_read_batches(
    path,
    config: Config = Config(),
    loci: LociSet | str | None = None,
    flags_required: int = 0,
    flags_forbidden: int = 0,
):
    """Columnar ``ReadBatch``es per streaming window: the load path in
    O(window) host memory (WGS scale), with interval/flag filters applied
    on device per window. Yields ``(abs_base, batch)``; ``(-1, batch)``
    entries carry records longer than the window lookahead, decoded exactly
    from the seekable stream."""
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    checker = StreamChecker(path, config)
    gen = checker.read_batches()
    if loci is None and not flags_required and not flags_forbidden:
        yield from gen
        return
    for base, batch in gen:
        yield base, _apply_filter(
            batch, checker.header, loci, flags_required, flags_forbidden
        )


def count_reads_tpu(path, config: Config = Config()) -> int:
    """count-reads via the streaming checker: O(window) host memory, device
    windows double-buffered, per-window counts reduced on device. This is
    the same code path bench.py measures."""
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    with obs.span("load.count", path=str(path)):
        n = StreamChecker(path, config).count_reads()
    obs.count("load.records", n)
    return n


def load_reads_columnar(
    path,
    loci: LociSet | str | None = None,
    flags_required: int = 0,
    flags_forbidden: int = 0,
    config: Config = Config(),
) -> ReadBatch:
    """All records of a BAM as columnar arrays; filters applied on device."""
    result = record_starts(path, config)
    with obs.span("load.parse", records=len(result.starts)):
        batch = parse_flat_records(result.view.data, result.starts)
    if loci is None and not flags_required and not flags_forbidden:
        return batch
    return _apply_filter(
        batch, result.header, loci, flags_required, flags_forbidden
    )
