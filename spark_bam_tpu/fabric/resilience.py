"""Fleet resilience primitives: retry budgets, circuit breakers, brownout.

Three small, pure-ish mechanisms the router composes so a worker storm
degrades the fleet gracefully instead of amplifying into one
(docs/robustness.md "Fleet resilience"):

- :class:`RetryBudget` — a router-wide token bucket every failover
  re-dispatch and paced shed-retry round must spend from. The bucket
  refills proportionally to *admitted* request volume
  (``budget_rate`` tokens per routed request, capped at ``budget``),
  so steady-state retry amplification is bounded by ``1 + budget_rate``
  no matter how hard the chaos layer pushes — retries can't outnumber
  the traffic that earned them.

- :class:`CircuitBreaker` — per-worker-link closed/open/half-open state
  unifying fabric/health.py's previously ad-hoc ejection + doubling
  re-probe: a failure opens the breaker for ``eject_ms`` (doubling to
  the ``eject_max_ms`` ceiling), expiry admits exactly ONE half-open
  probe, and its outcome either closes the breaker or re-opens it with
  a longer delay. Flap suppression rides on top: ``flap_k`` openings
  within ``flap_window_ms`` put the breaker in hold-down
  (``holddown_ms`` floor on the re-probe delay), so a crash-looping
  worker can't oscillate in and out of rotation taking a slice of live
  traffic down with it on every lap.

- :func:`brownout_level` — the shed-by-admission-class decision: when
  the healthy fraction of the fleet falls under ``brownout_frac`` the
  router sheds ``scan``-class ops (the expensive ones) at the edge with
  a typed ``Overloaded`` before their queues collapse; under half that
  fraction — or when the retry budget is simultaneously exhausted — it
  sheds every work op. Cheap control-plane ops keep answering so
  operators can see the brownout they are in.

Everything here runs on the router's single event loop, so no locks;
the breaker takes an injectable clock for deterministic tests.
"""

from __future__ import annotations

import time
from collections import deque

#: circuit-breaker states (stringly-typed on purpose: they appear in
#: flight-recorder events and ``stats`` payloads).
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class RetryBudget:
    """Router-wide token bucket gating retry/failover amplification.

    ``note_request()`` on every admitted request earns ``rate`` tokens
    (capped at ``capacity``); ``try_spend()`` before every re-dispatch
    consumes one. A bucket that starts at ``capacity`` lets a cold
    fleet absorb an initial burst of failovers (worker respawn storms)
    while the steady-state amplification bound stays ``1 + rate``.
    """

    def __init__(self, capacity: int, rate: float):
        self.capacity = float(capacity)
        self.rate = float(rate)
        self.tokens = float(capacity)
        self.spent = 0
        self.denied = 0

    def note_request(self) -> None:
        self.tokens = min(self.capacity, self.tokens + self.rate)

    def try_spend(self, n: float = 1.0) -> bool:
        if self.tokens >= n:
            self.tokens -= n
            self.spent += 1
            return True
        self.denied += 1
        return False

    @property
    def exhausted(self) -> bool:
        return self.tokens < 1.0


class CircuitBreaker:
    """Closed/open/half-open breaker for one worker link.

    State machine (driven by fabric/health.py's monitor loop):

    - ``record_failure`` → OPEN until ``now + backoff``; backoff doubles
      per consecutive failure, capped at ``eject_max_ms``. When the
      recent-openings window shows ``flap_k`` openings inside
      ``flap_window_ms``, the backoff is floored at ``holddown_ms``
      (flap suppression) and ``holddowns`` increments.
    - ``allow_probe`` → True exactly once per OPEN period after the
      backoff expires, moving the breaker HALF_OPEN (probe in flight).
    - ``record_success`` → CLOSED, backoff reset to ``eject_ms``.
    """

    def __init__(self, fcfg, clock=time.monotonic):
        self._clock = clock
        self._eject_s = fcfg.eject_ms / 1000.0
        self._eject_max_s = fcfg.eject_max_ms / 1000.0
        self._flap_k = int(fcfg.flap_k)
        self._flap_window_s = fcfg.flap_window_ms / 1000.0
        self._holddown_s = fcfg.holddown_ms / 1000.0
        self.state = CLOSED
        self.backoff_s = self._eject_s
        self.open_until = 0.0
        self.opened = 0
        self.holddowns = 0
        self._recent: "deque[float]" = deque(maxlen=max(1, self._flap_k))

    def record_failure(self, cause: str = "probe") -> str:
        """Open (or re-open) the breaker; returns the new state. The
        first failure opens at ``eject_ms``; consecutive failures double
        toward the cap; flapping floors the delay at ``holddown_ms``."""
        now = self._clock()
        if self.state == CLOSED:
            self.backoff_s = self._eject_s
        else:
            self.backoff_s = min(self.backoff_s * 2, self._eject_max_s)
        self._recent.append(now)
        delay = self.backoff_s
        if (len(self._recent) == self._flap_k
                and now - self._recent[0] <= self._flap_window_s
                and delay < self._holddown_s):
            delay = self._holddown_s
            self.holddowns += 1
        self.state = OPEN
        self.open_until = now + delay
        self.opened += 1
        return self.state

    def allow_probe(self) -> bool:
        """True when an OPEN breaker's delay has expired — transitions to
        HALF_OPEN so only one probe flies per open period."""
        if self.state == OPEN and self._clock() >= self.open_until:
            self.state = HALF_OPEN
            return True
        return False

    def record_success(self) -> str:
        self.state = CLOSED
        self.backoff_s = self._eject_s
        self.open_until = 0.0
        return self.state

    def delay_s(self) -> float:
        """Seconds until the next probe may fly (0 when due/closed)."""
        if self.state != OPEN:
            return 0.0
        return max(0.0, self.open_until - self._clock())


def brownout_level(healthy: int, total: int, fcfg,
                   budget_exhausted: bool = False) -> int:
    """Shed level for the current fleet state: 0 = serve everything,
    1 = shed ``scan``-class work ops, 2 = shed all work ops. Pure — the
    router evaluates it per routed request from live link state."""
    if not fcfg.brownout or total <= 0 or healthy <= 0:
        return 0
    frac = healthy / total
    if frac > fcfg.brownout_frac:
        return 0
    if frac <= fcfg.brownout_frac / 2 or budget_exhausted:
        return 2
    return 1
