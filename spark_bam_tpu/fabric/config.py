"""Fabric control-plane knobs: pool size, SLO target, probe/eject pacing.

Parsed from the same compact ``k=v,...`` spec pattern as ``ServeConfig``/
``FaultPolicy`` so it threads through ``Config.fabric`` /
``SPARK_BAM_FABRIC`` / ``--fabric`` unchanged. The floors/ceilings bound
what the autoscaler may ``tune`` on each worker; the worker applies
whatever it is told, so the bounds live HERE, in the controller.
Tuning notes in docs/fabric.md.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache


@dataclass(frozen=True)
class FabricConfig:
    """Knobs for the serve fabric (router + health + autoscaler)."""

    workers: int = 3              # serve workers to launch (local pool mode)
    slo_p99_ms: float = 500.0     # autoscaler target for per-worker p99
    probe_ms: float = 500.0       # health-probe period per healthy worker
    probe_timeout_ms: float = 3000.0  # ping timeout before ejection
    eject_ms: float = 250.0       # first re-probe delay after ejection
    eject_max_ms: float = 8000.0  # re-probe backoff ceiling (doubles)
    autoscale_ms: float = 1000.0  # control-loop period per worker
    spill: int = 8                # affinity target inflight before spillover
    # --- resilience (fabric/resilience.py; docs/robustness.md) ---
    budget: int = 32              # retry-budget token-bucket capacity
    budget_rate: float = 0.1      # tokens earned per admitted request
    flap_k: int = 4               # breaker openings within flap_window_ms ...
    flap_window_ms: float = 10_000.0  # ... that trigger hold-down
    holddown_ms: float = 5000.0   # re-probe floor while flapping
    brownout: int = 0             # opt-in: shed by class when unhealthy
    brownout_frac: float = 0.5    # healthy fraction at/below which to shed
    # --- streaming failover + chaos (both opt-in; zero cost unset) ---
    stream: int = 0               # relay batch frames as they arrive
    chaos: str = ""               # "SEED:SPEC" (fabric/chaos.py grammar)
    # --- zero-copy descriptor relay (serve/shm.py; needs stream=1) ---
    shm: int = 1                  # offer transport=shm to router clients
    # --- autoscaler actuation bounds (per worker, via the ``tune`` op) ---
    batch_floor: int = 1          # batch_rows floor (mesh-rounded upward)
    batch_ceil: int = 64          # batch_rows ceiling
    tick_floor: float = 0.0       # tick_ms floor
    tick_ceil: float = 20.0       # tick_ms ceiling
    scanq_floor: int = 4          # scan admission-cap floor
    scanq_ceil: int = 256         # scan admission-cap ceiling
    planq_floor: int = 4          # plan admission-cap floor
    planq_ceil: int = 256         # plan admission-cap ceiling

    def __post_init__(self):
        if self.workers < 1:
            raise ValueError(f"fabric workers must be >= 1: {self.workers}")
        if self.slo_p99_ms <= 0:
            raise ValueError(f"fabric slo must be > 0 ms: {self.slo_p99_ms}")
        for name in ("probe_ms", "probe_timeout_ms", "eject_ms",
                     "eject_max_ms", "autoscale_ms"):
            if getattr(self, name) <= 0:
                raise ValueError(
                    f"fabric {name} must be > 0: {getattr(self, name)}"
                )
        if self.eject_max_ms < self.eject_ms:
            raise ValueError(
                f"fabric eject_max {self.eject_max_ms} must be >= "
                f"eject {self.eject_ms}"
            )
        if self.spill < 1:
            raise ValueError(f"fabric spill must be >= 1: {self.spill}")
        if self.budget < 0 or self.budget_rate < 0:
            raise ValueError(
                f"fabric budget/budget_rate must be >= 0: "
                f"{self.budget}/{self.budget_rate}"
            )
        if self.flap_k < 1:
            raise ValueError(f"fabric flap_k must be >= 1: {self.flap_k}")
        if self.flap_window_ms <= 0 or self.holddown_ms <= 0:
            raise ValueError(
                f"fabric flap_window/holddown must be > 0 ms: "
                f"{self.flap_window_ms}/{self.holddown_ms}"
            )
        if not 0.0 < self.brownout_frac <= 1.0:
            raise ValueError(
                f"fabric brownout_frac must be in (0, 1]: {self.brownout_frac}"
            )
        if self.chaos:
            # Validate the sub-spec eagerly so a typo'd --fabric fails at
            # parse time, not mid-storm (local import: chaos.py imports
            # nothing from here, but keep the unconfigured path lean).
            from spark_bam_tpu.fabric.chaos import parse_fabric_chaos
            parse_fabric_chaos(self.chaos)
        for lo, hi in (("batch_floor", "batch_ceil"),
                       ("tick_floor", "tick_ceil"),
                       ("scanq_floor", "scanq_ceil"),
                       ("planq_floor", "planq_ceil")):
            if getattr(self, lo) > getattr(self, hi):
                raise ValueError(
                    f"fabric {lo} {getattr(self, lo)} exceeds "
                    f"{hi} {getattr(self, hi)}"
                )
        if self.batch_floor < 1 or self.scanq_floor < 1 or self.planq_floor < 1:
            raise ValueError("fabric batch/scanq/planq floors must be >= 1")
        if self.tick_floor < 0:
            raise ValueError(f"fabric tick_floor must be >= 0: {self.tick_floor}")

    _KEYS = {
        "workers": "workers",
        "slo": "slo_p99_ms",
        "slo_p99_ms": "slo_p99_ms",
        "probe": "probe_ms",
        "probe_ms": "probe_ms",
        "probe_timeout": "probe_timeout_ms",
        "probe_timeout_ms": "probe_timeout_ms",
        "eject": "eject_ms",
        "eject_ms": "eject_ms",
        "eject_max": "eject_max_ms",
        "eject_max_ms": "eject_max_ms",
        "autoscale": "autoscale_ms",
        "autoscale_ms": "autoscale_ms",
        "spill": "spill",
        "budget": "budget",
        "budget_rate": "budget_rate",
        "flap_k": "flap_k",
        "flap_window": "flap_window_ms",
        "flap_window_ms": "flap_window_ms",
        "holddown": "holddown_ms",
        "holddown_ms": "holddown_ms",
        "brownout": "brownout",
        "brownout_frac": "brownout_frac",
        "stream": "stream",
        "chaos": "chaos",
        "shm": "shm",
        "batch_floor": "batch_floor",
        "batch_ceil": "batch_ceil",
        "tick_floor": "tick_floor",
        "tick_ceil": "tick_ceil",
        "scanq_floor": "scanq_floor",
        "scanq_ceil": "scanq_ceil",
        "planq_floor": "planq_floor",
        "planq_ceil": "planq_ceil",
    }
    _FLOAT_KEYS = ("slo_p99_ms", "probe_ms", "probe_timeout_ms", "eject_ms",
                   "eject_max_ms", "autoscale_ms", "tick_floor", "tick_ceil",
                   "budget_rate", "flap_window_ms", "holddown_ms",
                   "brownout_frac")
    _STR_KEYS = ("chaos",)

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "FabricConfig":
        """``"workers=3,slo=200,probe=500,spill=8,batch_ceil=32"`` (any
        subset; ``""`` ⇒ defaults)."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad fabric-config entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            field = FabricConfig._KEYS.get(key.replace("-", "_"))
            if field is None:
                raise ValueError(
                    f"Unknown fabric-config key {key!r}: expected one of "
                    f"{', '.join(sorted(set(FabricConfig._KEYS)))}"
                )
            if field in FabricConfig._STR_KEYS:
                kw[field] = value
            elif field in FabricConfig._FLOAT_KEYS:
                kw[field] = float(value)
            else:
                kw[field] = int(value)
        return FabricConfig(**kw)

    @staticmethod
    def from_env(env=None) -> "FabricConfig":
        return FabricConfig.parse(
            (env or os.environ).get("SPARK_BAM_FABRIC", "")
        )
