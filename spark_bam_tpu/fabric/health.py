"""Per-worker health: ping probes, ejection, exponential re-probe.

One :func:`monitor_worker` task per link runs forever on the router's
loop. Healthy workers get a ``ping`` every ``probe_ms``; a probe that
times out (``probe_timeout_ms``) or errors ejects the worker — placement
stops immediately, pending requests on the link fail over. Ejected
workers are re-probed on a doubling backoff (``eject_ms`` →
``eject_max_ms``); the first successful reconnect+ping reinstates them.

Connection-level death (reader EOF on a kill) does NOT wait for a probe:
the link marks itself unhealthy the moment the socket dies
(``WorkerLink._fail``), so failover latency is bounded by TCP teardown,
not the probe period. The monitor's job is then just reinstatement.
"""

from __future__ import annotations

import asyncio

from spark_bam_tpu.obs import flight


async def _ping(link, timeout_s: float) -> None:
    await asyncio.wait_for(link.request({"op": "ping"}), timeout=timeout_s)


async def monitor_worker(link, fcfg, count) -> None:
    """Probe loop for one worker link; ``count`` is the router's counter
    hook (``ejected`` / ``reinstated``). Ejections and reinstatements
    also land in the flight-recorder ring — a postmortem dump shows the
    health history around the death, not just the death itself."""
    backoff_ms = fcfg.eject_ms
    timeout_s = fcfg.probe_timeout_ms / 1000.0
    while True:
        if link.healthy:
            await asyncio.sleep(fcfg.probe_ms / 1000.0)
            if not link.healthy:
                # Died between probes (connection-level ejection).
                count("ejected")
                flight.record("ejected", worker=link.wid, cause="connection")
                backoff_ms = fcfg.eject_ms
                continue
            try:
                await _ping(link, timeout_s)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                link.healthy = False
                link._teardown()
                count("ejected")
                flight.record("ejected", worker=link.wid, cause="probe",
                              error=str(exc))
                backoff_ms = fcfg.eject_ms
        else:
            await asyncio.sleep(backoff_ms / 1000.0)
            try:
                await link.connect()
                await _ping(link, timeout_s)
                backoff_ms = fcfg.eject_ms
                count("reinstated")
                flight.record("reinstated", worker=link.wid)
            except asyncio.CancelledError:
                raise
            except Exception:
                link.healthy = False
                link._teardown()
                backoff_ms = min(backoff_ms * 2, fcfg.eject_max_ms)
