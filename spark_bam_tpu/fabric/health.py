"""Per-worker health: ping probes, circuit breakers, flap suppression.

One :func:`monitor_worker` task per link runs forever on the router's
loop, driving the link's :class:`~spark_bam_tpu.fabric.resilience.
CircuitBreaker`. Healthy workers (breaker CLOSED) get a ``ping`` every
``probe_ms``; a probe that times out (``probe_timeout_ms``) or errors
ejects the worker — the breaker OPENs, placement stops immediately, and
pending requests on the link fail with ``WorkerLost`` so they can fail
over instead of hanging on a wedged (SIGSTOP'd) worker. An OPEN breaker
admits exactly one HALF_OPEN reconnect+ping probe after its delay
(``eject_ms`` doubling to ``eject_max_ms``); success reinstates the
worker (breaker CLOSED), failure re-opens with a longer delay. A worker
that flaps — ``flap_k`` openings inside ``flap_window_ms`` — is held
down for at least ``holddown_ms`` per re-probe so a crash-looping
process can't oscillate in and out of rotation.

Connection-level death (reader EOF on a kill) does NOT wait for a probe:
the link marks itself unhealthy the moment the socket dies
(``WorkerLink._fail``), so failover latency is bounded by TCP teardown,
not the probe period. The monitor's job is then just reinstatement.
"""

from __future__ import annotations

import asyncio

from spark_bam_tpu.fabric.resilience import CLOSED, CircuitBreaker
from spark_bam_tpu.obs import flight


async def _ping(link, timeout_s: float) -> None:
    await asyncio.wait_for(link.request({"op": "ping"}), timeout=timeout_s)


async def monitor_worker(link, fcfg, count) -> None:
    """Probe loop for one worker link; ``count`` is the router's counter
    hook (``ejected`` / ``reinstated`` / ``breaker.*``). Ejections and
    reinstatements also land in the flight-recorder ring — a postmortem
    dump shows the health history around the death, not just the death
    itself."""
    breaker = link.breaker = CircuitBreaker(fcfg)
    timeout_s = fcfg.probe_timeout_ms / 1000.0

    def _opened(cause: str, exc=None) -> None:
        breaker.record_failure(cause)
        count("ejected")
        count("breaker.opened")
        if breaker.holddowns > _opened.holddowns:
            _opened.holddowns = breaker.holddowns
            count("breaker.holddowns")
            flight.record("breaker_holddown", worker=link.wid,
                          delay_ms=round(breaker.delay_s() * 1000, 1))
        flight.record("ejected", worker=link.wid, cause=cause,
                      **({"error": str(exc)} if exc is not None else {}))

    _opened.holddowns = 0

    while True:
        if link.healthy:
            await asyncio.sleep(fcfg.probe_ms / 1000.0)
            if not link.healthy:
                # Died between probes (connection-level ejection).
                _opened("connection")
                continue
            try:
                await _ping(link, timeout_s)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # eject() fails pending futures with WorkerLost — a
                # wedged worker holds requests forever otherwise.
                link.eject(exc)
                _opened("probe", exc)
        else:
            if breaker.state == CLOSED:
                # _fail() marked the link dead but nothing opened the
                # breaker yet (death raced the healthy-branch sleep).
                _opened("connection")
            await asyncio.sleep(max(breaker.delay_s(), 0.001))
            if not breaker.allow_probe():
                continue  # still not due (clock granularity); re-sleep
            count("breaker.half_open")
            try:
                await link.connect()
                # connect() marks the link healthy for the request path;
                # a HALF_OPEN probe must not re-admit placement before
                # the ping proves the worker ANSWERS — a wedged
                # (SIGSTOP'd) worker accepts connections happily.
                link.healthy = False
                await _ping(link, timeout_s)
                link.healthy = True
                breaker.record_success()
                count("reinstated")
                count("breaker.closed")
                flight.record("reinstated", worker=link.wid)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                link.healthy = False
                link._teardown()
                _opened("reprobe", exc)
