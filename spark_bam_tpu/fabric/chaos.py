"""Fleet-level deterministic chaos: faults at the fabric/protocol seam.

The data plane has had seeded chaos since PR 2 (``core/faults.
ChaosChannel`` — byte-level faults under the decoder); this module
attacks the *fleet* plane with the same splitmix64 discipline: every
fault decision is a pure function of ``(seed, kind, event index)``, so
one seed replays one fault schedule and a chaos-run artifact carries
everything needed to reproduce it (the seed/spec lands in every
flight-recorder dump and SLO ledger entry via ``obs.flight``'s dump
context).

Installed via the fabric spec — ``--fabric "...,chaos=SEED:SPEC"`` —
where SPEC is ``+``-separated ``k=v`` entries (``+`` because the outer
fabric spec already splits on commas; ``,`` also works when the spec is
parsed standalone):

    chaos=42:drop=0.05+delay=0.1x20+trunc=0.02+dup=0.05+slow=0.1x5+accept=0.05

Faults at the router↔worker link (:class:`ChaosWorkerLink`, substituted
for ``WorkerLink`` at router construction — the plain link class carries
ZERO chaos branches, so an unconfigured fabric pays nothing):

- ``drop``   — sever the connection before a send: every request pending
  on the link fails with ``WorkerLost`` (failover/budget path).
- ``delay``  — hold a response ``delay_ms`` before resolving it: delayed
  responses complete after later-arriving peers, i.e. reordering (safe
  because responses are id-keyed to futures — the property under test).
- ``trunc``  — kill the connection mid-response-stream: the router sees
  a frame sequence cut short (the resume-token path for streaming ops).
- ``dup``    — deliver a response twice: the second copy must fall on
  the floor (its future was already popped).
- ``slow``   — slow-link throttle: ``slow_ms`` extra latency per send.
- ``accept`` — delay at the client↔router accept loop (edge latency).

Faults at the shared-memory transport seam (rolled by the SERVE accept
loop per frame record when any rate is set — serve/server.py builds a
:class:`FabricChaos` from the same ``chaos=`` spec):

- ``shm_crc``    — corrupt a descriptor's guard crc: the client must
  detect the mismatch and resume, never trust the frame.
- ``shm_trunc``  — cut the connection mid-descriptor: a half-written
  record then a hard abort (the resume-token path).
- ``shm_unlink`` — unlink the ring segment mid-stream: frames already
  described stay readable; later frames fall back to inline records.

Process-level storms (:func:`storm_schedule` + :class:`ChaosStorm`,
driving a ``WorkerPool``): seeded rolling SIGKILL (**crash** — the
worker vanishes, TCP resets, the router fails over instantly) and
SIGSTOP (**wedge** — the worker stays connected but answers nothing;
only the probe timeout can eject it, the strictly harder failure). Dead
workers respawn on their original port after ``revive_ms`` so a long
storm rolls across the fleet instead of annihilating it.
"""

from __future__ import annotations

import asyncio
import json
import struct
import threading
import time
from dataclasses import dataclass

from spark_bam_tpu import obs
from spark_bam_tpu.core.faults import _mix, _roll
from spark_bam_tpu.fabric.router import WorkerLink, WorkerLost
from spark_bam_tpu.obs import flight

#: distinct splitmix64 streams per fault kind (core/faults.py keeps
#: 1..4 for the byte-channel kinds; the fleet kinds extend the space).
_KINDS = {
    "drop": 11, "delay": 12, "trunc": 13, "dup": 14, "slow": 15,
    "accept": 16, "storm": 17, "shm_crc": 18, "shm_trunc": 19,
    "shm_unlink": 20,
}


@dataclass(frozen=True)
class FabricChaosSpec:
    """Which fleet faults to inject and how often. Rates are per event
    (request sent / response received / connection accepted); the storm
    fields size the :func:`storm_schedule` a bench/test drives."""

    drop: float = 0.0      # connection-drop rate (per request send)
    delay: float = 0.0     # response-delay rate (per response)
    delay_ms: float = 20.0
    trunc: float = 0.0     # mid-stream truncation rate (per response)
    dup: float = 0.0       # duplicate-delivery rate (per response)
    slow: float = 0.0      # slow-link rate (per request send)
    slow_ms: float = 5.0
    accept: float = 0.0    # accept-loop delay rate (per request)
    # shm-transport seam (serve/shm.py; rolled per frame RECORD by the
    # serve accept loop, not the router — the faults live where the
    # descriptors are minted):
    shm_crc: float = 0.0     # stale/corrupt descriptor crc rate
    shm_trunc: float = 0.0   # descriptor truncated mid-record rate
    shm_unlink: float = 0.0  # segment unlinked mid-stream rate
    kills: int = 0         # storm: SIGKILL events
    wedges: int = 0        # storm: SIGSTOP (wedge) events
    storm_ms: float = 500.0   # storm: pacing between events
    revive_ms: float = 400.0  # storm: kill→respawn / wedge→SIGCONT delay

    _FLOAT = ("drop", "delay", "trunc", "dup", "slow", "accept",
              "shm_crc", "shm_trunc", "shm_unlink",
              "storm_ms", "revive_ms")
    _INT = ("kills", "wedges")

    @staticmethod
    def parse(spec: str) -> "FabricChaosSpec":
        """``"drop=0.05+delay=0.1x20+kills=5+wedges=1"`` — entries split
        on ``+`` (or ``,`` standalone); ``delay``/``slow`` take the same
        optional ``xMS`` suffix as the byte-channel chaos grammar."""
        kw: dict = {}
        norm = (spec or "").replace("+", ",")
        for part in norm.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad fabric-chaos entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            key = {"storm": "storm_ms", "revive": "revive_ms"}.get(key, key)
            if key in ("delay", "slow") and "x" in value:
                rate, ms = value.split("x", 1)
                kw[key], kw[f"{key}_ms"] = float(rate), float(ms)
            elif key in FabricChaosSpec._FLOAT:
                kw[key] = float(value)
            elif key in FabricChaosSpec._INT:
                kw[key] = int(value)
            else:
                raise ValueError(
                    f"Unknown fabric-chaos key {key!r}: expected one of "
                    f"{', '.join(FabricChaosSpec._FLOAT + FabricChaosSpec._INT)}"
                )
        return FabricChaosSpec(**kw)


def parse_fabric_chaos(arg: str) -> "tuple[int, FabricChaosSpec]":
    """``"SEED:SPEC"`` — the ``chaos=`` value inside a fabric spec."""
    seed, _, spec = arg.partition(":")
    try:
        seed_i = int(seed)
    except ValueError:
        raise ValueError(
            f"Bad fabric-chaos seed {seed!r} in {arg!r} (want SEED:SPEC)"
        ) from None
    return seed_i, FabricChaosSpec.parse(spec)


class FabricChaos:
    """One installation's decision source + injected-fault tallies.

    Decisions key each fault kind's own monotone event counter into the
    splitmix64 roll, so the *set* of faulty event indices is a pure
    function of the seed. All rolls happen on the router's event loop —
    no locks. Tallies mirror into ``fabric.chaos.*`` obs counters."""

    def __init__(self, seed: int, spec: FabricChaosSpec):
        self.seed = int(seed)
        self.spec = spec
        self.injected: "dict[str, int]" = {k: 0 for k in _KINDS}
        self._n: "dict[str, int]" = {k: 0 for k in _KINDS}

    def roll(self, kind: str) -> bool:
        """Deterministic per-event fault decision for ``kind``."""
        rate = getattr(self.spec, kind)
        i = self._n[kind]
        self._n[kind] = i + 1
        if _roll(self.seed, _KINDS[kind], i, rate):
            self.injected[kind] += 1
            return True
        return False

    def describe(self) -> str:
        """Compact ``seed:spec`` string for artifacts/announcements."""
        s = self.spec
        parts = []
        for k in FabricChaosSpec._FLOAT + FabricChaosSpec._INT:
            v = getattr(s, k)
            if v and k not in ("storm_ms", "revive_ms", "delay_ms", "slow_ms"):
                parts.append(f"{k}={v}")
        return f"{self.seed}:{'+'.join(parts)}"


class ChaosWorkerLink(WorkerLink):
    """A ``WorkerLink`` with seeded faults at the protocol seam. The
    router constructs these INSTEAD of plain links when ``chaos=`` is
    set — the base class keeps zero chaos branches.

    Send side: ``drop`` severs the connection (everything pending fails
    with ``WorkerLost``, exactly like a worker crash); ``slow`` adds
    ``slow_ms`` before the send. Receive side (overridden ``_read_loop``):
    ``trunc`` kills the connection mid-response-stream, ``delay`` holds a
    complete response ``delay_ms`` before resolving it (later responses
    on the link overtake it — reordering), ``dup`` resolves a response a
    second time (the duplicate must fall on the floor via id-dedup)."""

    def __init__(self, wid: str, address: str, chaos: "FabricChaos"):
        super().__init__(wid, address)
        self.chaos = chaos

    async def request(self, req: dict) -> dict:
        c = self.chaos
        if c.roll("drop"):
            # lint: allow[obs-contract] literal name in obs/names.py
            obs.count("fabric.chaos.drops")
            self._fail(ConnectionError("chaos: connection dropped"))
            raise WorkerLost(f"worker {self.wid}: chaos connection drop")
        if c.roll("slow"):
            # lint: allow[obs-contract] literal name in obs/names.py
            obs.count("fabric.chaos.slowed")
            await asyncio.sleep(c.spec.slow_ms / 1000.0)
        return await super().request(req)

    async def _read_loop(self) -> None:
        c = self.chaos
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    raise ConnectionError("worker closed the connection")
                resp = json.loads(line)
                n = int(resp.get("binary_frames") or 0)
                if n:
                    frames = []
                    for _ in range(n):
                        if c.roll("trunc"):
                            # lint: allow[obs-contract] in obs/names.py
                            obs.count("fabric.chaos.truncs")
                            raise ConnectionError(
                                "chaos: response truncated mid-frame"
                            )
                        hdr = await self._reader.readexactly(8)
                        (length,) = struct.unpack("<Q", hdr)
                        frames.append(
                            await self._reader.readexactly(length)
                        )
                    resp["_binary"] = frames
                if c.roll("delay"):
                    # lint: allow[obs-contract] literal in obs/names.py
                    obs.count("fabric.chaos.delays")
                    # Resolve later WITHOUT blocking the reader: the next
                    # response overtakes this one — reordering, which the
                    # id-keyed futures must absorb.
                    asyncio.get_running_loop().call_later(
                        c.spec.delay_ms / 1000.0, self._resolve, resp
                    )
                    continue
                self._resolve(resp)
                if c.roll("dup"):
                    # lint: allow[obs-contract] literal in obs/names.py
                    obs.count("fabric.chaos.dups")
                    self._resolve(dict(resp))   # must fall on the floor
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self._fail(exc)


def install_context(chaos: "FabricChaos") -> None:
    """Stamp the chaos seed/spec into the flight-recorder dump context
    (and thereby every SLO alert-ledger entry): any artifact a chaos run
    leaves behind is reproducible from the artifact alone."""
    flight.set_context(chaos_seed=chaos.seed, chaos_spec=chaos.describe())


# ------------------------------------------------------------------ storms
def storm_schedule(seed: int, workers: int,
                   spec: FabricChaosSpec) -> "list[tuple[float, int, str]]":
    """Deterministic rolling storm: ``(at_s, victim, action)`` events,
    ``action`` ∈ {``kill``, ``wedge``}. Victims and the wedge positions
    are splitmix64-drawn from the seed; events pace ``storm_ms`` apart
    so the fleet is hit *rolling*, not all at once."""
    total = spec.kills + spec.wedges
    if total <= 0 or workers <= 0:
        return []
    k = _KINDS["storm"]
    # Draw wedge slots without replacement from the event indices.
    order = sorted(range(total), key=lambda i: _mix(seed, k, 1000 + i))
    wedge_slots = set(order[:spec.wedges])
    out = []
    for i in range(total):
        victim = _mix(seed, k, i) % workers
        action = "wedge" if i in wedge_slots else "kill"
        out.append(((i + 1) * spec.storm_ms / 1000.0, victim, action))
    return out


class ChaosStorm:
    """Drive a :func:`storm_schedule` against a ``WorkerPool`` from a
    background thread (bench/tests are synchronous). Each ``kill`` is a
    SIGKILL followed by a same-port respawn after ``revive_ms``; each
    ``wedge`` is a SIGSTOP followed by SIGCONT — the wedged worker keeps
    its sockets open and says nothing, so only the router's probe
    timeout (breaker path) can get traffic off it."""

    def __init__(self, pool, seed: int, spec: FabricChaosSpec):
        self.pool = pool
        self.seed = int(seed)
        self.spec = spec
        self.schedule = storm_schedule(self.seed, len(pool.procs), spec)
        self.events: "list[dict]" = []
        self._thread = threading.Thread(
            target=self._run, name="chaos-storm", daemon=True
        )

    def start(self) -> "ChaosStorm":
        self._thread.start()
        return self

    def join(self, timeout_s: float = 120.0) -> None:
        self._thread.join(timeout=timeout_s)
        if self._thread.is_alive():
            raise TimeoutError("chaos storm did not finish in time")

    def _note(self, action: str, victim: int) -> None:
        ev = {"t": round(time.time(), 3), "victim": victim,
              "action": action}
        self.events.append(ev)
        flight.record("chaos_storm", **ev)
        # lint: allow[obs-contract] two-value suffix; both names registered
        obs.count(f"fabric.chaos.{'kills' if action == 'kill' else 'wedges'}")

    def _run(self) -> None:
        t0 = time.monotonic()
        revive_s = self.spec.revive_ms / 1000.0
        for at_s, victim, action in self.schedule:
            time.sleep(max(0.0, t0 + at_s - time.monotonic()))
            if action == "kill":
                self.pool.kill(victim, hard=True)
                self._note("kill", victim)
                time.sleep(revive_s)
                try:
                    self.pool.respawn(victim)
                    flight.record("chaos_respawn", victim=victim)
                except Exception as exc:   # storm must not kill the driver
                    flight.record("chaos_respawn_failed", victim=victim,
                                  error=str(exc))
            else:
                self.pool.wedge(victim)
                self._note("wedge", victim)
                time.sleep(revive_s)
                self.pool.unwedge(victim)
                flight.record("chaos_unwedge", victim=victim)
