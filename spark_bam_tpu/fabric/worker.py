"""Runnable serve worker + local pool supervisor for the fabric.

One worker = one ``SplitService`` accept loop over THIS process's local
devices. Run directly (one per host, the ``jax.distributed`` bring-up
mirroring parallel/multihost.py) or let :class:`WorkerPool` launch N
local processes on a dev box:

    python -m spark_bam_tpu.fabric.worker \
        --listen tcp:127.0.0.1:0 [--devices 2] [--serve SPEC] \
        [--coordinator HOST0:port --num-processes N --process-id K]

On start the worker prints ONE JSON line on stdout —
``{"fabric_worker": true, "address": "tcp:host:port", ...}`` — which is
how the pool (and operators scripting attach mode) learn the bound
address when the listen spec asked for port 0. SIGTERM/SIGINT trigger a
graceful drain: new work is refused with a typed ``Draining`` error,
in-flight requests and queued batcher ticks finish unshed, then the
process exits.

The mesh is built over ``jax.local_devices()`` — NOT the global mesh —
because a serving worker answers only its own requests: a collective
step compiled over the global mesh would deadlock waiting for dispatches
the other hosts never make. Multi-host fabric = one local serving loop
per host, with the router doing the cross-host fan-out.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time


def serve_worker(
    listen: str = "tcp:127.0.0.1:0",
    devices: int = 0,
    serve: str = "",
    columnar: str = "",
    slo: str = "",
    coordinator: "str | None" = None,
    num_processes: int = 1,
    process_id: int = 0,
    announce: bool = True,
    drain_wait_s: float = 30.0,
    ready: "threading.Event | None" = None,
) -> int:
    """Bring up one serve worker and block until SIGTERM-drained."""
    from spark_bam_tpu.core.platform import enable_compile_cache

    if devices:
        from spark_bam_tpu.core.platform import force_cpu_devices

        force_cpu_devices(devices, defer_init=num_processes > 1)
    # Pool workers respawn per fabric bring-up; the persistent compile
    # cache turns the serve step's first compile into a disk hit.
    enable_compile_cache()
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )

    from spark_bam_tpu import obs
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.obs import flight
    from spark_bam_tpu.parallel.mesh import local_mesh
    from spark_bam_tpu.serve.server import ServerThread
    from spark_bam_tpu.serve.service import SplitService

    # Keep the platform-is-experimental banner (and nothing else) out of
    # worker stderr — N workers each re-import jax.
    obs.install_noise_filter()
    # A live registry regardless of --metrics-out: the stats op's
    # split_resolutions (the per-worker warm-tier proof) reads it.
    if not obs.enabled():
        obs.configure()

    # Disk-fault chaos rides the environment into pool workers exactly
    # like fabric chaos rides SPARK_BAM_FABRIC: the storm tests set
    # SPARK_BAM_DISK_CHAOS before spawning, every worker injects the
    # same seeded fault schedule, and the flight context names it.
    from spark_bam_tpu.core.faults import maybe_install_disk_chaos_from_env

    maybe_install_disk_chaos_from_env()

    config = Config.from_env()
    if serve:
        config = config.replace(serve=serve)
    if columnar:
        config = config.replace(columnar=columnar)
    if slo:
        config = config.replace(slo=slo)
    try:
        # Under a chaos run (SPARK_BAM_FABRIC carries chaos=SEED:SPEC)
        # the worker's own dumps must name the seed too — a postmortem
        # from EITHER side of the fabric seam reproduces the run.
        chaos_spec = config.fabric_config.chaos
    except Exception:
        chaos_spec = ""
    if chaos_spec:
        flight.set_context(chaos=chaos_spec)
    # A SIGKILL'd predecessor can't unlink its ring segments; sweep any
    # whose creating pid is dead so a storm can't leak /dev/shm.
    from spark_bam_tpu.serve.shm import sweep_orphans

    sweep_orphans()
    service = SplitService(config, mesh=local_mesh())

    stop = threading.Event()

    def _drain_and_stop(signum, frame):
        flight.record("sigterm", signum=int(signum))
        service.drain()
        stop.set()

    signal.signal(signal.SIGTERM, _drain_and_stop)
    signal.signal(signal.SIGINT, _drain_and_stop)

    srv = ServerThread(service, listen).start()
    addr = srv.address
    spec = addr if isinstance(addr, str) else f"tcp:{addr[0]}:{addr[1]}"
    flight.record("worker_start", address=spec,
                  devices=int(service.mesh.devices.size))
    if announce:
        print(json.dumps({
            "fabric_worker": True,
            "address": spec,
            "pid": os.getpid(),
            "process_id": int(process_id),
            "devices": int(service.mesh.devices.size),
        }), flush=True)
    if ready is not None:
        ready.set()
    try:
        stop.wait()
        # Drained: let in-flight ticks finish unshed before detaching.
        deadline = time.monotonic() + drain_wait_s
        while (sum(service.gate.inflight().values()) > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
    except BaseException as exc:
        # The one crash the worker CAN narrate: dump the ring before
        # the exception unwinds the process.
        flight.dump_auto("crash", extra={"address": spec,
                                         "error": repr(exc)})
        raise
    finally:
        srv.stop()
        service.close()
        # Postmortem + trace artifacts on the graceful path: the drain
        # dump names the requests this worker saw; the JSONL trace is
        # what metrics-report merges across the fleet by trace_id.
        flight.dump_auto("drain", extra={"address": spec})
        out = obs.resolve_metrics_path(
            os.environ.get("SPARK_BAM_METRICS_OUT")
        )
        if out:
            try:
                obs.export_jsonl(out)
            except OSError:
                pass
    return 0


class WorkerPool:
    """Launch (or attach to) the fabric's serve workers.

    Launch mode spawns N ``fabric.worker`` subprocesses on this host and
    reads each one's announce line for its bound address; attach mode
    takes addresses of already-running workers (other hosts' loops) and
    supervises nothing. ``kill(i, hard=True)`` exists for the failover
    bench/tests; ``terminate()`` SIGTERMs for graceful drains. The chaos
    layer (fabric/chaos.py ``ChaosStorm``) adds three more verbs:
    ``respawn(i)`` relaunches a killed worker on its ORIGINAL port (the
    router's link re-probes the same address and reinstates it), and
    ``wedge(i)``/``unwedge(i)`` SIGSTOP/SIGCONT a live worker — the
    wedged state keeps every socket open while answering nothing, which
    only a probe timeout can detect.
    """

    def __init__(self, workers: int = 3, devices: int = 1, serve: str = "",
                 columnar: str = "", slo: str = "",
                 attach: "list[str] | None" = None,
                 env: "dict | None" = None, stderr=None):
        self.workers = int(workers)
        self.devices = int(devices)
        self.serve = serve
        self.columnar = columnar
        self.slo = slo
        self.attach = list(attach or [])
        self.env = env
        self.stderr = stderr
        self.procs: list = []
        self.addresses: "list[str]" = []

    def _spawn(self, listen: str):
        import subprocess

        env = dict(os.environ if self.env is None else self.env)
        # -c (not -m): runpy would import the fabric package first and
        # warn about the worker module being re-executed as __main__.
        cmd = [sys.executable, "-c",
               "import sys; from spark_bam_tpu.fabric.worker import main;"
               " sys.exit(main(sys.argv[1:]))",
               "--listen", listen]
        if self.devices:
            cmd += ["--devices", str(self.devices)]
        if self.serve:
            cmd += ["--serve", self.serve]
        if self.columnar:
            cmd += ["--columnar", self.columnar]
        if self.slo:
            cmd += ["--slo", self.slo]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=self.stderr,
            env=env, text=True,
        )

    def start(self, timeout_s: float = 120.0) -> "list[str]":
        if self.attach:
            self.addresses = list(self.attach)
            return self.addresses
        for _ in range(self.workers):
            self.procs.append(self._spawn("tcp:127.0.0.1:0"))
        deadline = time.monotonic() + timeout_s
        for p in self.procs:
            line = self._read_announce(p, deadline)
            self.addresses.append(line["address"])
        return self.addresses

    @staticmethod
    def _read_announce(proc, deadline: float) -> dict:
        # The worker prints exactly one JSON line once it is listening;
        # anything else on stdout before it (warnings) is skipped.
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fabric worker exited rc={proc.returncode} before "
                    "announcing its address"
                )
            line = proc.stdout.readline()
            if not line:
                time.sleep(0.05)
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and obj.get("fabric_worker"):
                return obj
        raise TimeoutError("fabric worker did not announce in time")

    def kill(self, i: int, hard: bool = False) -> None:
        p = self.procs[i]
        if p.poll() is None:
            p.send_signal(signal.SIGKILL if hard else signal.SIGTERM)

    def respawn(self, i: int, timeout_s: float = 120.0) -> str:
        """Relaunch worker ``i`` on its ORIGINAL port. The router's link
        for that address stays in place; its health monitor reinstates
        the worker on the first successful re-probe — a rolling storm
        leaves the fleet exactly as it found it."""
        old = self.procs[i]
        if old.poll() is None:
            old.kill()
        old.wait(timeout=timeout_s)
        if old.stdout is not None:
            old.stdout.close()
        addr = self.addresses[i]
        deadline = time.monotonic() + timeout_s
        while True:
            # The dying process may hold the port through TCP teardown;
            # retry the bind until the OS releases it.
            proc = self._spawn(addr)
            try:
                line = self._read_announce(proc, deadline)
                break
            except RuntimeError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self.procs[i] = proc
        if line["address"] != addr:
            raise RuntimeError(
                f"respawned worker bound {line['address']}, wanted {addr}"
            )
        return addr

    def wedge(self, i: int) -> None:
        """SIGSTOP worker ``i``: sockets stay open, nothing answers —
        the failure mode only a probe timeout can detect."""
        p = self.procs[i]
        if p.poll() is None:
            p.send_signal(signal.SIGSTOP)

    def unwedge(self, i: int) -> None:
        p = self.procs[i]
        if p.poll() is None:
            p.send_signal(signal.SIGCONT)

    def terminate(self, timeout_s: float = 30.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout_s
        for p in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except Exception:
                p.kill()
        for p in self.procs:
            if p.stdout is not None:
                p.stdout.close()

    def __enter__(self) -> "WorkerPool":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", default="tcp:127.0.0.1:0",
                    help="accept-loop address (tcp:host:port or unix:path; "
                         "port 0 binds an ephemeral port, announced on stdout)")
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual CPU devices (dev boxes / pool "
                         "mode); 0 = this host's real devices")
    ap.add_argument("--serve", default="", help="ServeConfig spec override")
    ap.add_argument("--columnar", default="",
                    help="ColumnarConfig spec override")
    ap.add_argument("--slo", default="",
                    help="SloConfig spec override (objectives + burn-rate "
                         "alerting, obs/slo.py)")
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    a = ap.parse_args(argv)
    return serve_worker(
        listen=a.listen, devices=a.devices, serve=a.serve,
        columnar=a.columnar, slo=a.slo, coordinator=a.coordinator,
        num_processes=a.num_processes, process_id=a.process_id,
    )


if __name__ == "__main__":
    raise SystemExit(main())
