"""Fabric router: affinity placement, spillover, failover, admin fan-out.

The front end of the serve fabric (docs/fabric.md). It speaks the SAME
newline-JSON (+ ``batch`` frame) protocol as a single worker, so clients
cannot tell a router from a daemon — and it reuses the serve accept loop
unchanged (``server._handle_connection`` duck-types on ``submit``).

Placement: requests carrying a ``path`` go to the worker that wins a
rendezvous (highest-random-weight) hash over ``(worker id, path)`` —
repeat queries for a file land on the worker whose flat-view LRU and
``.sbi`` store are already warm. When the affinity target already has
``FabricConfig.spill`` requests in flight, the request spills to the
least-loaded healthy worker instead (counted ``fabric.spilled``).
Path-less ops (``fleet``) always go least-loaded.

Failover: a worker dying mid-request fails every request pending on its
link with :class:`WorkerLost`; idempotent ops (``plan`` /
``record_starts`` / ``count`` / ``batch``) are re-dispatched to another
worker exactly ONCE per request, everything else surfaces a typed
``WorkerLost`` error. The router buffers a worker's complete response
(JSON + all binary frames) before relaying it, so a mid-stream death
never leaks partial frames to the client — the failover answer is
byte-identical to a healthy worker's.

Upstream ``Overloaded``/``Draining`` answers spill across the remaining
workers; only when EVERY healthy worker sheds does the router pace a
jittered ``FaultPolicy`` retry round, and after the retry budget it
relays the shed response for the client's own retry loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import struct
import time
from collections import deque

from spark_bam_tpu import obs
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.faults import FaultPolicy
from spark_bam_tpu.fabric.config import FabricConfig
from spark_bam_tpu.obs import flight
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.serve.protocol import error_response, ok_response
from spark_bam_tpu.serve.server import MAX_LINE, ServeAddress

#: ops safe to re-dispatch after a mid-request worker death: pure reads
#: whose answers are deterministic for unchanged files.
IDEMPOTENT_OPS = frozenset({"plan", "record_starts", "count", "batch"})


class WorkerLost(ConnectionError):
    """The worker died (or its link closed) with this request pending."""


def rendezvous_weight(wid: str, path: str) -> int:
    """Stable highest-random-weight score for (worker, path). blake2b,
    not ``hash()`` — placement must agree across processes and runs."""
    h = hashlib.blake2b(f"{wid}|{path}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class WorkerLink:
    """One multiplexed upstream connection to a serve worker.

    Requests are re-keyed to router-assigned ids so many client
    connections share the link; one reader task resolves responses
    (JSON line + in-order binary frames) back to their futures. A dead
    connection fails every pending future with :class:`WorkerLost` and
    marks the link unhealthy immediately — the health monitor owns
    re-probe and reinstatement.
    """

    def __init__(self, wid: str, address: str):
        self.wid = wid
        self.address = ServeAddress(
            address if str(address).startswith(("unix:", "tcp:"))
            else str(address)
        )
        self.healthy = False
        self.draining = False
        self._reader = None
        self._writer = None
        self._reader_task = None
        self._pending: "dict[int, asyncio.Future]" = {}
        # uid → (original client id, op): the postmortem ledger — when
        # the link dies, the flight dump names exactly what was in
        # flight on it (the dead worker can't dump for itself).
        self._pending_meta: "dict[int, tuple]" = {}
        self._next_id = 0
        self._conn_lock = asyncio.Lock()

    @property
    def inflight(self) -> int:
        return len(self._pending)

    async def connect(self) -> None:
        async with self._conn_lock:
            if self._writer is not None:
                return
            if self.address.kind == "unix":
                r, w = await asyncio.open_unix_connection(
                    self.address.path, limit=MAX_LINE
                )
            else:
                r, w = await asyncio.open_connection(
                    self.address.host, self.address.port, limit=MAX_LINE
                )
            self._reader, self._writer = r, w
            self._reader_task = asyncio.ensure_future(self._read_loop())
            self.healthy = True

    async def request(self, req: dict) -> dict:
        """Send ``req`` upstream and await its COMPLETE response (frames
        included). Raises :class:`WorkerLost` if the link dies first."""
        if self._writer is None:
            try:
                await self.connect()
            except (ConnectionError, OSError) as exc:
                self.healthy = False
                raise WorkerLost(f"worker {self.wid}: {exc}") from exc
        self._next_id += 1
        uid = self._next_id
        orig_id = req.get("id")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[uid] = fut
        self._pending_meta[uid] = (orig_id, req.get("op"))
        try:
            self._writer.write(
                (json.dumps({**req, "id": uid}) + "\n").encode()
            )
            await self._writer.drain()
        except (ConnectionError, OSError) as exc:
            self._pending.pop(uid, None)
            self._pending_meta.pop(uid, None)
            self._fail(exc)
            raise WorkerLost(f"worker {self.wid}: {exc}") from exc
        resp = await fut
        resp["id"] = orig_id
        return resp

    async def _read_loop(self) -> None:
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    raise ConnectionError("worker closed the connection")
                resp = json.loads(line)
                n = int(resp.get("binary_frames") or 0)
                if n:
                    frames = []
                    for _ in range(n):
                        hdr = await self._reader.readexactly(8)
                        (length,) = struct.unpack("<Q", hdr)
                        frames.append(await self._reader.readexactly(length))
                    resp["_binary"] = frames
                fut = self._pending.pop(resp.get("id"), None)
                self._pending_meta.pop(resp.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self._fail(exc)

    def _fail(self, exc: BaseException, expected: bool = False) -> None:
        """Connection-level death: mark down NOW (placement must stop
        choosing this link before any probe runs) and fail all pending.

        Unexpected deaths (everything but a deliberate ``close``) are the
        router-observed ``WorkerLost``: the flight recorder notes the
        lost worker and the request ids in flight on the link, and — when
        ``SPARK_BAM_FLIGHT_DIR`` is set — dumps a postmortem JSONL,
        because a SIGKILL'd worker leaves no artifact of its own."""
        self.healthy = False
        pending, self._pending = self._pending, {}
        meta, self._pending_meta = self._pending_meta, {}
        if not expected:
            inflight = [
                {"id": orig_id, "op": op} for orig_id, op in meta.values()
            ]
            flight.record(
                "worker_lost", worker=self.wid, address=self.address.spec,
                error=str(exc), inflight=inflight,
            )
            flight.dump_auto(
                "worker_lost", who=self.wid,
                extra={"worker": self.wid, "address": self.address.spec,
                       "error": str(exc), "inflight": inflight},
            )
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    WorkerLost(f"worker {self.wid} died: {exc}")
                )
        self._teardown()

    def _teardown(self) -> None:
        w, self._writer = self._writer, None
        self._reader = None
        if w is not None:
            try:
                w.close()
            except Exception:
                pass

    async def close(self) -> None:
        self.healthy = False
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        self._fail(ConnectionError("link closed"), expected=True)


class Router:
    """Fabric front end; see the module docstring. Lives on one event
    loop (the serve accept loop's); ``submit`` returns an awaitable, so
    it slots into ``server._handle_connection`` where a
    :class:`~spark_bam_tpu.serve.service.SplitService` otherwise goes.
    """

    def __init__(self, addresses: "list[str]",
                 config: "Config | None" = None, pool=None):
        self.config = config if config is not None else Config()
        self.fcfg: FabricConfig = self.config.fabric_config
        self.policy: FaultPolicy = self.config.fault_policy
        self.links = [
            WorkerLink(f"w{i}", addr) for i, addr in enumerate(addresses)
        ]
        self.pool = pool            # optional WorkerPool (drain → terminate)
        self.draining = False
        self.counters: "dict[str, int]" = {}
        # Autoscale move ledger: {t, worker, move, reason} — the reason
        # cites the firing SLO objective when one drove the move, so the
        # ``alerts`` op answers "why did the fleet downscale" by itself.
        self.moves: "deque[dict]" = deque(maxlen=256)
        self._tasks: "list[asyncio.Task]" = []
        self._start_task: "asyncio.Task | None" = None
        self._loop: "asyncio.AbstractEventLoop | None" = None

    # ------------------------------------------------------------ lifecycle
    async def ensure_started(self) -> None:
        """Connect links and spawn health/autoscale loops on the RUNNING
        loop — lazily, because the serve accept loop owns the loop and
        only enters async context once a request arrives. Concurrent
        first requests all await the SAME bring-up task: routing before
        the links connect would misread every worker as unhealthy."""
        if self._start_task is None:
            self._start_task = asyncio.ensure_future(self._start())
        await self._start_task

    async def _start(self) -> None:
        # Captured for cross-thread read-side callers (the dashboard's
        # provider thread schedules coroutines onto this loop).
        self._loop = asyncio.get_running_loop()
        for link in self.links:
            try:
                await link.connect()
            except Exception:
                link.healthy = False   # monitor takes it from here
        from spark_bam_tpu.fabric.autoscaler import autoscale_worker
        from spark_bam_tpu.fabric.health import monitor_worker

        for link in self.links:
            self._tasks.append(asyncio.ensure_future(
                monitor_worker(link, self.fcfg, self._count)
            ))
            self._tasks.append(asyncio.ensure_future(
                autoscale_worker(link, self.fcfg, self._count,
                                 note_move=self._note_move)
            ))

    async def aclose(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        for link in self.links:
            await link.close()

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        # lint: allow[obs-contract] name bounded by Router's literal
        # _count call sites, all enumerated in obs/names.py
        obs.count(f"fabric.{name}", n)

    def _note_move(self, entry: dict) -> None:
        """Autoscaler move-ledger hook: stamp and retain the move (with
        its cited reason — the firing objective when an alert drove it)
        and mirror it into the flight recorder."""
        entry = dict(entry, t=round(time.time(), 3))
        self.moves.append(entry)
        flight.record("autoscale_move", **entry)

    # ------------------------------------------------------------ placement
    def healthy_links(self, exclude=()) -> "list[WorkerLink]":
        return [l for l in self.links
                if l.healthy and not l.draining and l.wid not in exclude]

    def pick(self, path: "str | None",
             exclude=()) -> "WorkerLink | None":
        """Affinity target (rendezvous winner) unless saturated, else
        least-loaded; path-less requests always go least-loaded."""
        cands = self.healthy_links(exclude)
        if not cands:
            return None
        if path:
            primary = max(
                cands, key=lambda l: rendezvous_weight(l.wid, str(path))
            )
            if primary.inflight < self.fcfg.spill:
                return primary
            spill = min(cands, key=lambda l: l.inflight)
            if spill is not primary:
                self._count("spilled")
            return spill
        return min(cands, key=lambda l: l.inflight)

    # -------------------------------------------------------------- serving
    async def submit(self, req: dict) -> dict:
        """The accept loop's entry point (awaitable counterpart of
        ``SplitService.submit``)."""
        await self.ensure_started()
        op = req.get("op")
        if op == "ping":
            return ok_response(
                req, pong=True, fabric=True,
                workers=len(self.healthy_links()),
            )
        if op == "stats":
            return await self._stats(req)
        if op == "drain":
            return await self._drain(req)
        if op == "tune":
            return await self._tune(req)
        if op == "telemetry":
            return await self._telemetry(req)
        if op == "alerts":
            return await self._alerts(req)
        if self.draining:
            return error_response(
                req, "Draining", "fabric is draining; route elsewhere",
            )
        return await self._route(req)

    async def _relay(self, link: WorkerLink, req: dict,
                     ctx: "obs_trace.TraceContext | None") -> dict:
        """One upstream attempt, carrying (and spanning) the trace: the
        worker's spans parent under this router's ``fabric.relay`` span,
        so the merged report reads client → router → worker as one tree."""
        if ctx is None:
            return await link.request(req)
        if not obs.enabled():
            # Relay the caller's carrier untouched — the router adds no
            # span of its own when its metrics are off.
            return await link.request(
                dict(req, trace=obs_trace.carrier(ctx))
            )
        with obs_trace.bind(ctx):
            with obs.span("fabric.relay", op=req.get("op"),
                          worker=link.wid) as sp:
                fwd = dict(req, trace={"id": sp.trace_id, "span": sp.span_id})
                return await link.request(fwd)

    async def _route(self, req: dict) -> dict:
        op = req.get("op")
        path = req.get("path")
        # Mint a trace on behalf of bare clients (the router is the fleet
        # edge); clients that already sent one keep theirs.
        ctx = obs_trace.from_carrier(req.get("trace"))
        if ctx is None and obs.enabled():
            ctx = obs_trace.mint()
        idempotent = op in IDEMPOTENT_OPS
        failed_over = False
        shed_resp = None
        for round_no in range(self.policy.max_retries + 1):
            tried: set = set()
            while True:
                link = self.pick(path, exclude=tried)
                if link is None:
                    break           # every healthy worker tried this round
                tried.add(link.wid)
                try:
                    resp = await self._relay(link, req, ctx)
                except WorkerLost:
                    if not idempotent or failed_over:
                        self._count("lost")
                        return error_response(
                            req, "WorkerLost",
                            f"worker {link.wid} died mid-{op}; "
                            "op is not re-dispatchable"
                            if not idempotent else
                            f"worker {link.wid} died mid-{op} after failover",
                        )
                    failed_over = True
                    self._count("failovers")
                    continue        # exactly one re-dispatch
                if (resp.get("ok") is False
                        and resp.get("error") in ("Overloaded", "Draining")):
                    shed_resp = resp
                    continue        # spill to the next-best worker
                self._count("routed")
                return resp
            if shed_resp is None:
                return error_response(
                    req, "WorkerLost", "no healthy workers in the fabric",
                )
            if round_no >= self.policy.max_retries:
                break
            hint_ms = float(shed_resp.get("retry_after_ms") or 0.0)
            await asyncio.sleep(
                max(hint_ms / 1000.0, self.policy.backoff_delay(round_no))
            )
        self._count("relayed_overload")
        return shed_resp

    # ------------------------------------------------------------ admin ops
    def _admin_targets(self, req: dict) -> "list[WorkerLink]":
        wid = req.get("worker")
        if wid is None:
            return list(self.links)
        links = [l for l in self.links if l.wid == wid]
        if not links:
            raise KeyError(f"unknown worker {wid!r}")
        return links

    async def _forward_admin(self, req: dict,
                             links: "list[WorkerLink]") -> dict:
        fwd = {k: v for k, v in req.items() if k != "worker"}

        async def one(link):
            try:
                resp = await link.request(dict(fwd))
                return {k: v for k, v in resp.items() if k != "id"}
            except Exception as exc:
                return {"ok": False, "error": "WorkerLost", "message": str(exc)}

        results = await asyncio.gather(*(one(l) for l in links))
        return {l.wid: r for l, r in zip(links, results)}

    async def _drain(self, req: dict) -> dict:
        """Router-level graceful drain: stop routing new work, forward
        ``drain`` so each worker refuses its own new arrivals, report the
        remaining inflight so the operator can watch it reach zero. A
        ``worker`` field narrows the drain to one worker (the router just
        stops placing work there)."""
        try:
            links = self._admin_targets(req)
        except KeyError as exc:
            return error_response(req, "ProtocolError", str(exc))
        if req.get("worker") is None:
            self.draining = True
        for link in links:
            link.draining = True
        self._count("drained", len(links))
        per_worker = await self._forward_admin({"op": "drain"}, links)
        return ok_response(
            req, draining=True,
            workers={w: r.get("inflight") for w, r in per_worker.items()},
        )

    async def _tune(self, req: dict) -> dict:
        """Fan a ``tune`` out to one worker (``worker`` field) or all —
        the autoscaler uses the per-worker form; operators may broadcast."""
        try:
            links = self._admin_targets(req)
        except KeyError as exc:
            return error_response(req, "ProtocolError", str(exc))
        per_worker = await self._forward_admin(req, links)
        ok = all(r.get("ok") for r in per_worker.values())
        if not ok:
            return error_response(
                req, "Internal", "tune failed on some workers",
                workers=per_worker,
            )
        return ok_response(req, workers=per_worker)

    async def _stats(self, req: dict) -> dict:
        links = list(self.links)

        async def one(link):
            if not link.healthy:
                return None
            try:
                resp = await link.request({"op": "stats"})
            except Exception:
                return None
            return {k: v for k, v in resp.items() if k not in ("id", "ok")}

        upstream = await asyncio.gather(*(one(l) for l in links))
        workers = {
            l.wid: {
                "address": l.address.spec,
                "healthy": bool(l.healthy),
                "draining": bool(l.draining),
                "inflight": int(l.inflight),
                "stats": stats,
            }
            for l, stats in zip(links, upstream)
        }
        return ok_response(
            req, fabric=True, draining=bool(self.draining),
            counters=dict(sorted(self.counters.items())),
            moves=list(self.moves),
            workers=workers,
        )

    async def _alerts(self, req: dict) -> dict:
        """Fleet alert view: every healthy worker's SLO status plus the
        router's autoscale move ledger — the one payload that answers
        "what is firing and what did the fleet do about it" (the CI
        failure artifact and the dashboard's /slo both read this)."""
        links = [l for l in self.links if l.healthy]
        per_worker = await self._forward_admin({"op": "alerts"}, links)
        firing = sorted({
            name
            for r in per_worker.values()
            for name in (r.get("slo") or {}).get("firing", ())
        })
        ledger = sorted(
            (dict(e, worker=w)
             for w, r in per_worker.items()
             for e in (r.get("slo") or {}).get("ledger", ())),
            key=lambda e: e.get("t", 0.0),
        )
        return ok_response(
            req, fabric=True, firing=firing, ledger=ledger,
            moves=list(self.moves), workers=per_worker,
        )

    async def _telemetry(self, req: dict) -> dict:
        """Fleet telemetry collector: scrape every healthy worker's
        ``telemetry`` op, merge their obs snapshots into one fleet view,
        and attach the router's own counters + flight ring. With
        ``prometheus: true`` the merged snapshot is also rendered in the
        exposition text format (one scrape endpoint for the whole
        fabric)."""
        from spark_bam_tpu.obs.account import merge_accounting
        from spark_bam_tpu.obs.exporters import (
            merge_snapshots,
            prometheus_text,
        )
        from spark_bam_tpu.obs.timeseries import merge_series

        links = list(self.links)
        fwd = {"op": "telemetry"}
        if req.get("max_spans") is not None:
            fwd["max_spans"] = req["max_spans"]

        async def one(link):
            if not link.healthy:
                return None
            try:
                resp = await link.request(dict(fwd))
            except Exception:
                return None
            if not resp.get("ok"):
                return None
            return {k: v for k, v in resp.items() if k not in ("id", "ok")}

        upstream = await asyncio.gather(*(one(l) for l in links))
        workers = {
            l.wid: {
                "address": l.address.spec,
                "healthy": bool(l.healthy),
                "draining": bool(l.draining),
                "inflight": int(l.inflight),
                "telemetry": t,
            }
            for l, t in zip(links, upstream)
        }
        merged = merge_snapshots([
            t["snapshot"] for t in upstream
            if t and t.get("snapshot")
        ])
        out = {
            "fabric": True,
            "draining": bool(self.draining),
            "counters": dict(sorted(self.counters.items())),
            "moves": list(self.moves),
            "workers": workers,
            "fleet": merged,
            # Fleet-wide time-series rings (cadence-bucketed sums) and
            # per-op/per-tenant cost rollups, merged across workers.
            "series": merge_series([
                t["series"] for t in upstream if t and t.get("series")
            ]),
            "accounting": merge_accounting([
                t.get("accounting") for t in upstream if t
            ]),
            "flight": flight.recorder().events(),
        }
        if req.get("prometheus"):
            out["prometheus"] = prometheus_text(merged)
        return ok_response(req, **out)
