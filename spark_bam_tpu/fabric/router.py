"""Fabric router: affinity placement, spillover, failover, admin fan-out.

The front end of the serve fabric (docs/fabric.md). It speaks the SAME
newline-JSON (+ ``batch`` frame) protocol as a single worker, so clients
cannot tell a router from a daemon — and it reuses the serve accept loop
unchanged (``server._handle_connection`` duck-types on ``submit``).

Placement: requests carrying a ``path`` go to the worker that wins a
rendezvous (highest-random-weight) hash over ``(worker id, path)`` —
repeat queries for a file land on the worker whose flat-view LRU and
``.sbi`` store are already warm. When the affinity target already has
``FabricConfig.spill`` requests in flight, the request spills to the
least-loaded healthy worker instead (counted ``fabric.spilled``).
Path-less ops (``fleet``) always go least-loaded.

Failover: a worker dying mid-request fails every request pending on its
link with :class:`WorkerLost`; idempotent ops (``plan`` /
``record_starts`` / ``count`` / ``batch`` / ``rewrite``) are
re-dispatched to another worker while the router-wide
:class:`~spark_bam_tpu.fabric.resilience.RetryBudget` holds tokens —
retries can't amplify into a storm because every re-dispatch spends from
a bucket refilled only by admitted traffic. Everything else surfaces a
typed ``WorkerLost`` error. By default the router buffers a worker's
complete response (JSON + all binary frames) before relaying it, so a
mid-stream death never leaks partial frames to the client; with
``stream=1`` the ``batch`` op instead relays frames AS THEY ARRIVE over
a dedicated upstream connection and, on a mid-stream death, resumes on a
replacement worker from a frame-sequence token (``resume_from=N``) —
byte-identical output without ever holding a full response in router
memory (docs/robustness.md "Resumable streaming failover").

Upstream ``Overloaded``/``Draining`` answers spill across the remaining
workers; only when EVERY healthy worker sheds does the router pace a
jittered ``FaultPolicy`` retry round (shed responses without a
``retry_after_ms`` hint are paced by the router's own rolling latency
median, jittered), and after the retry rounds it relays the shed
response for the client's own retry loop. With ``brownout=1`` the router
itself sheds by admission class while the healthy fraction of the fleet
sits at/below ``brownout_frac`` — scan-class first, everything at half
that fraction — so queues on the survivors don't collapse.

Chaos: ``chaos=SEED:SPEC`` in the fabric spec swaps the links for
``fabric/chaos.py``'s :class:`ChaosWorkerLink` and (with ``accept>0``)
the accept-loop entry point for a delaying wrapper — both chosen at
CONSTRUCTION, so an unconfigured router runs the exact same hot path as
before this layer existed.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import struct
import time
from collections import deque

from spark_bam_tpu import obs
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.faults import FaultPolicy, LatencyTracker
from spark_bam_tpu.fabric.config import FabricConfig
from spark_bam_tpu.fabric.resilience import RetryBudget, brownout_level
from spark_bam_tpu.obs import flight
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.serve import shm
from spark_bam_tpu.serve.admission import CLASS_OF
from spark_bam_tpu.serve.protocol import error_response, ok_response
from spark_bam_tpu.serve.server import MAX_LINE, ServeAddress

#: ops safe to re-dispatch after a mid-request worker death: pure reads
#: whose answers are deterministic for unchanged files, plus ``rewrite``
#: (its output commit is atomic — a re-run overwrites, never interleaves)
#: and the durable-job control ops (``submit`` keys jobs by a
#: deterministic spec hash and resumes from the journal, so a replayed
#: submit re-attaches instead of double-running; status/cancel are pure
#: table lookups).
IDEMPOTENT_OPS = frozenset(
    {"plan", "record_starts", "count", "batch", "aggregate", "rewrite",
     "submit", "job_status", "job_cancel"}
)

#: job states the orphan watchdog stops tracking.
_JOB_TERMINAL = frozenset({"done", "failed", "cancelled"})


class WorkerLost(ConnectionError):
    """The worker died (or its link closed) with this request pending."""


def rendezvous_weight(wid: str, path: str) -> int:
    """Stable highest-random-weight score for (worker, path). blake2b,
    not ``hash()`` — placement must agree across processes and runs."""
    h = hashlib.blake2b(f"{wid}|{path}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class WorkerLink:
    """One multiplexed upstream connection to a serve worker.

    Requests are re-keyed to router-assigned ids so many client
    connections share the link; one reader task resolves responses
    (JSON line + in-order binary frames) back to their futures. A dead
    connection fails every pending future with :class:`WorkerLost` and
    marks the link unhealthy immediately — the health monitor owns
    re-probe and reinstatement.
    """

    def __init__(self, wid: str, address: str):
        self.wid = wid
        self.address = ServeAddress(
            address if str(address).startswith(("unix:", "tcp:"))
            else str(address)
        )
        self.healthy = False
        self.draining = False
        self.breaker = None      # attached by fabric/health.monitor_worker
        self._reader = None
        self._writer = None
        self._reader_task = None
        self._pending: "dict[int, asyncio.Future]" = {}
        # uid → (original client id, op): the postmortem ledger — when
        # the link dies, the flight dump names exactly what was in
        # flight on it (the dead worker can't dump for itself).
        self._pending_meta: "dict[int, tuple]" = {}
        self._next_id = 0
        self._conn_lock = asyncio.Lock()

    @property
    def inflight(self) -> int:
        return len(self._pending)

    async def connect(self) -> None:
        async with self._conn_lock:
            if self._writer is not None:
                return
            if self.address.kind == "unix":
                r, w = await asyncio.open_unix_connection(
                    self.address.path, limit=MAX_LINE
                )
            else:
                r, w = await asyncio.open_connection(
                    self.address.host, self.address.port, limit=MAX_LINE
                )
            self._reader, self._writer = r, w
            self._reader_task = asyncio.ensure_future(self._read_loop())
            self.healthy = True

    async def request(self, req: dict) -> dict:
        """Send ``req`` upstream and await its COMPLETE response (frames
        included). Raises :class:`WorkerLost` if the link dies first."""
        if self._writer is None:
            try:
                await self.connect()
            except (ConnectionError, OSError) as exc:
                self.healthy = False
                raise WorkerLost(f"worker {self.wid}: {exc}") from exc
        self._next_id += 1
        uid = self._next_id
        orig_id = req.get("id")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[uid] = fut
        self._pending_meta[uid] = (orig_id, req.get("op"))
        try:
            self._writer.write(
                (json.dumps({**req, "id": uid}) + "\n").encode()
            )
            await self._writer.drain()
        except (ConnectionError, OSError) as exc:
            self._pending.pop(uid, None)
            self._pending_meta.pop(uid, None)
            self._fail(exc)
            raise WorkerLost(f"worker {self.wid}: {exc}") from exc
        resp = await fut
        resp["id"] = orig_id
        return resp

    async def _read_loop(self) -> None:
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    raise ConnectionError("worker closed the connection")
                resp = json.loads(line)
                n = int(resp.get("binary_frames") or 0)
                if n:
                    frames = []
                    for _ in range(n):
                        hdr = await self._reader.readexactly(8)
                        (length,) = struct.unpack("<Q", hdr)
                        frames.append(await self._reader.readexactly(length))
                    resp["_binary"] = frames
                self._resolve(resp)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self._fail(exc)

    def _resolve(self, resp: dict) -> None:
        """Hand a complete response to its waiting future. A second
        delivery of the same id (duplicate under chaos) finds the future
        already popped and falls on the floor — id-dedup is structural."""
        uid = resp.get("id")
        fut = self._pending.pop(uid, None)
        self._pending_meta.pop(uid, None)
        if fut is not None and not fut.done():
            fut.set_result(resp)

    def eject(self, exc: BaseException) -> None:
        """Forcibly eject the worker: fail every pending future with
        :class:`WorkerLost` and tear the connection down. The health
        monitor calls this on probe timeout — a WEDGED (SIGSTOP'd)
        worker keeps its socket open and never answers, so requests in
        flight on it would otherwise hang forever."""
        self._fail(exc)

    def _fail(self, exc: BaseException, expected: bool = False) -> None:
        """Connection-level death: mark down NOW (placement must stop
        choosing this link before any probe runs) and fail all pending.

        Unexpected deaths (everything but a deliberate ``close``) are the
        router-observed ``WorkerLost``: the flight recorder notes the
        lost worker and the request ids in flight on the link, and — when
        ``SPARK_BAM_FLIGHT_DIR`` is set — dumps a postmortem JSONL,
        because a SIGKILL'd worker leaves no artifact of its own."""
        self.healthy = False
        pending, self._pending = self._pending, {}
        meta, self._pending_meta = self._pending_meta, {}
        if not expected:
            inflight = [
                {"id": orig_id, "op": op} for orig_id, op in meta.values()
            ]
            flight.record(
                "worker_lost", worker=self.wid, address=self.address.spec,
                error=str(exc), inflight=inflight,
            )
            flight.dump_auto(
                "worker_lost", who=self.wid,
                extra={"worker": self.wid, "address": self.address.spec,
                       "error": str(exc), "inflight": inflight},
            )
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    WorkerLost(f"worker {self.wid} died: {exc}")
                )
        self._teardown()

    def _teardown(self) -> None:
        w, self._writer = self._writer, None
        self._reader = None
        if w is not None:
            try:
                w.close()
            except Exception:
                pass

    async def close(self) -> None:
        self.healthy = False
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        self._fail(ConnectionError("link closed"), expected=True)


class Router:
    """Fabric front end; see the module docstring. Lives on one event
    loop (the serve accept loop's); ``submit`` returns an awaitable, so
    it slots into ``server._handle_connection`` where a
    :class:`~spark_bam_tpu.serve.service.SplitService` otherwise goes.
    """

    def __init__(self, addresses: "list[str]",
                 config: "Config | None" = None, pool=None):
        self.config = config if config is not None else Config()
        self.fcfg: FabricConfig = self.config.fabric_config
        self.policy: FaultPolicy = self.config.fault_policy
        # Chaos is decided HERE, once: a configured fabric gets chaos
        # link subclasses and (for accept>0) a delaying submit wrapper;
        # an unconfigured fabric gets the plain classes — zero chaos
        # branches anywhere on its hot path.
        self.chaos = None
        if self.fcfg.chaos:
            from spark_bam_tpu.fabric.chaos import (
                ChaosWorkerLink,
                FabricChaos,
                install_context,
                parse_fabric_chaos,
            )
            seed, spec = parse_fabric_chaos(self.fcfg.chaos)
            self.chaos = FabricChaos(seed, spec)
            install_context(self.chaos)
            self.links = [
                ChaosWorkerLink(f"w{i}", addr, self.chaos)
                for i, addr in enumerate(addresses)
            ]
            if spec.accept > 0:
                self.submit = self._chaos_submit
        else:
            self.links = [
                WorkerLink(f"w{i}", addr) for i, addr in enumerate(addresses)
            ]
        self.budget = RetryBudget(self.fcfg.budget, self.fcfg.budget_rate)
        # Zero-copy descriptor relay (docs/serving.md "Transport"): the
        # accept loop reads these to answer ``hello`` exactly as it does
        # for a worker, so a local client maps the ROUTER's ring; ring
        # sizing comes from the serve config the fleet already carries.
        scfg = self.config.serve_config
        self.shm_enabled = bool(self.fcfg.shm) and bool(scfg.shm)
        self.shm_bytes = int(scfg.shm_bytes)
        self.shm_wait_ms = float(scfg.shm_wait_ms)
        self.shm_chaos = None   # fleet chaos hits links, not the client ring
        self._latency = LatencyTracker(window=128)
        self.pool = pool            # optional WorkerPool (drain → terminate)
        self.draining = False
        self.counters: "dict[str, int]" = {}
        # Autoscale move ledger: {t, worker, move, reason} — the reason
        # cites the firing SLO objective when one drove the move, so the
        # ``alerts`` op answers "why did the fleet downscale" by itself.
        self.moves: "deque[dict]" = deque(maxlen=256)
        # Durable-job ownership: job_id → {"req": original submit,
        # "wid": owning worker, "state": last seen}. The watchdog
        # re-dispatches jobs whose owner died (journal resume on the
        # survivor makes that safe); status/cancel route to the owner.
        self._job_owners: "dict[str, dict]" = {}
        self._tasks: "list[asyncio.Task]" = []
        self._start_task: "asyncio.Task | None" = None
        self._loop: "asyncio.AbstractEventLoop | None" = None

    # ------------------------------------------------------------ lifecycle
    async def ensure_started(self) -> None:
        """Connect links and spawn health/autoscale loops on the RUNNING
        loop — lazily, because the serve accept loop owns the loop and
        only enters async context once a request arrives. Concurrent
        first requests all await the SAME bring-up task: routing before
        the links connect would misread every worker as unhealthy."""
        if self._start_task is None:
            self._start_task = asyncio.ensure_future(self._start())
        await self._start_task

    async def _start(self) -> None:
        # Captured for cross-thread read-side callers (the dashboard's
        # provider thread schedules coroutines onto this loop).
        self._loop = asyncio.get_running_loop()
        for link in self.links:
            try:
                await link.connect()
            except Exception:
                link.healthy = False   # monitor takes it from here
        from spark_bam_tpu.fabric.autoscaler import autoscale_worker
        from spark_bam_tpu.fabric.health import monitor_worker

        for link in self.links:
            self._tasks.append(asyncio.ensure_future(
                monitor_worker(link, self.fcfg, self._count)
            ))
            self._tasks.append(asyncio.ensure_future(
                autoscale_worker(link, self.fcfg, self._count,
                                 note_move=self._note_move,
                                 hold=self._autoscale_hold)
            ))
        self._tasks.append(asyncio.ensure_future(self._job_watchdog()))

    async def aclose(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        for link in self.links:
            await link.close()

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        # lint: allow[obs-contract] name bounded by Router's literal
        # _count call sites, all enumerated in obs/names.py
        obs.count(f"fabric.{name}", n)

    def _note_move(self, entry: dict) -> None:
        """Autoscaler move-ledger hook: stamp and retain the move (with
        its cited reason — the firing objective when an alert drove it)
        and mirror it into the flight recorder."""
        entry = dict(entry, t=round(time.time(), 3))
        self.moves.append(entry)
        flight.record("autoscale_move", **entry)

    # ------------------------------------------------------------ placement
    def healthy_links(self, exclude=()) -> "list[WorkerLink]":
        return [l for l in self.links
                if l.healthy and not l.draining and l.wid not in exclude]

    def pick(self, path: "str | None",
             exclude=()) -> "WorkerLink | None":
        """Affinity target (rendezvous winner) unless saturated, else
        least-loaded; path-less requests always go least-loaded."""
        cands = self.healthy_links(exclude)
        if not cands:
            return None
        if path:
            primary = max(
                cands, key=lambda l: rendezvous_weight(l.wid, str(path))
            )
            if primary.inflight < self.fcfg.spill:
                return primary
            spill = min(cands, key=lambda l: l.inflight)
            if spill is not primary:
                self._count("spilled")
            return spill
        return min(cands, key=lambda l: l.inflight)

    # ----------------------------------------------------------- resilience
    def _shed_hint_ms(self, hint_ms: float = 0.0) -> float:
        """Pacing hint for a shed response: the upstream worker's own
        ``retry_after_ms`` when it sent one, else the router's rolling
        relay-latency median — a worker too overloaded to even attach a
        hint shouldn't earn an IMMEDIATE retry. Jittered (``FaultPolicy.
        jitter``) so a thundering herd of pacing clients decorrelates."""
        if hint_ms > 0:
            return hint_ms
        med = self._latency.median()
        if med is None:
            return 0.0
        j = self.policy.jitter
        return med * (1.0 - j + 2.0 * j * random.random())

    def _brownout(self) -> int:
        return brownout_level(
            len(self.healthy_links()), len(self.links), self.fcfg,
            self.budget.exhausted,
        )

    def _autoscale_hold(self) -> bool:
        """The autoscaler must not retune workers from brownout traffic —
        shed-heavy stats would read as idleness and downscale the exact
        capacity the fleet is trying to win back."""
        return self._brownout() > 0

    async def _chaos_submit(self, req: dict, conn=None) -> dict:
        """Accept-loop chaos (installed as ``self.submit`` when the spec
        sets ``accept>0``): delay a seeded subset of client requests at
        the fleet edge before normal routing."""
        chaos = self.chaos
        if chaos.roll("accept"):
            # lint: allow[obs-contract] literal name in obs/names.py
            obs.count("fabric.chaos.accept_delays")
            await asyncio.sleep(chaos.spec.delay_ms / 1000.0)
        return await Router.submit(self, req, conn=conn)

    # -------------------------------------------------------------- serving
    async def submit(self, req: dict, conn=None) -> dict:
        """The accept loop's entry point (awaitable counterpart of
        ``SplitService.submit``). ``conn`` is the accept loop's
        per-connection transport state: when the CLIENT negotiated shm,
        the streaming relay forwards same-host workers' frame
        descriptors instead of re-copying bytes (docs/serving.md
        "Transport")."""
        await self.ensure_started()
        op = req.get("op")
        if op == "ping":
            return ok_response(
                req, pong=True, fabric=True,
                workers=len(self.healthy_links()),
            )
        if op == "stats":
            return await self._stats(req)
        if op == "drain":
            return await self._drain(req)
        if op == "tune":
            return await self._tune(req)
        if op == "telemetry":
            return await self._telemetry(req)
        if op == "alerts":
            return await self._alerts(req)
        if self.draining:
            return error_response(
                req, "Draining", "fabric is draining; route elsewhere",
            )
        if op in ("submit", "job_status", "job_cancel"):
            return await self._route_job(req)
        return await self._route(req, conn=conn)

    async def _relay(self, link: WorkerLink, req: dict,
                     ctx: "obs_trace.TraceContext | None") -> dict:
        """One upstream attempt, carrying (and spanning) the trace: the
        worker's spans parent under this router's ``fabric.relay`` span,
        so the merged report reads client → router → worker as one tree."""
        if ctx is None:
            return await link.request(req)
        if not obs.enabled():
            # Relay the caller's carrier untouched — the router adds no
            # span of its own when its metrics are off.
            return await link.request(
                dict(req, trace=obs_trace.carrier(ctx))
            )
        with obs_trace.bind(ctx):
            with obs.span("fabric.relay", op=req.get("op"),
                          worker=link.wid) as sp:
                fwd = dict(req, trace={"id": sp.trace_id, "span": sp.span_id})
                return await link.request(fwd)

    async def _route(self, req: dict, conn=None) -> dict:
        op = req.get("op")
        path = req.get("path")
        # Mint a trace on behalf of bare clients (the router is the fleet
        # edge); clients that already sent one keep theirs.
        ctx = obs_trace.from_carrier(req.get("trace"))
        if ctx is None and obs.enabled():
            ctx = obs_trace.mint()
        self.budget.note_request()
        level = self._brownout()
        if level and (level >= 2 or CLASS_OF.get(op) == "scan"):
            # Shed at the edge, BEFORE placement: brownout exists to keep
            # the survivors' queues from collapsing under full load.
            self._count("brownout_shed")
            return error_response(
                req, "Overloaded",
                f"fabric brownout (level {level}): shedding "
                f"{CLASS_OF.get(op, op)}-class work",
                retry_after_ms=round(self._shed_hint_ms(), 3),
            )
        if op in ("batch", "aggregate") and self.fcfg.stream:
            return await self._stream_route(req, ctx, conn=conn)
        idempotent = op in IDEMPOTENT_OPS
        shed_resp = None
        for round_no in range(self.policy.max_retries + 1):
            tried: set = set()
            while True:
                link = self.pick(path, exclude=tried)
                if link is None:
                    break           # every healthy worker tried this round
                tried.add(link.wid)
                t0 = time.monotonic()
                try:
                    resp = await self._relay(link, req, ctx)
                except WorkerLost:
                    if not idempotent:
                        self._count("lost")
                        return error_response(
                            req, "WorkerLost",
                            f"worker {link.wid} died mid-{op}; "
                            "op is not re-dispatchable",
                        )
                    if not self.budget.try_spend():
                        # Budget empty: surfacing the loss beats joining
                        # a retry storm. The client owns the next retry.
                        self._count("lost")
                        self._count("budget_exhausted")
                        return error_response(
                            req, "WorkerLost",
                            f"worker {link.wid} died mid-{op}; "
                            "retry budget exhausted",
                        )
                    self._count("failovers")
                    self._count("budget_spent")
                    continue        # re-dispatch (budget-gated)
                if (resp.get("ok") is False
                        and resp.get("error") in ("Overloaded", "Draining")):
                    shed_resp = resp
                    continue        # spill to the next-best worker
                self._latency.record((time.monotonic() - t0) * 1000.0)
                self._count("routed")
                return resp
            if shed_resp is None:
                return error_response(
                    req, "WorkerLost", "no healthy workers in the fabric",
                )
            if round_no >= self.policy.max_retries:
                break
            if not self.budget.try_spend():
                self._count("budget_exhausted")
                break               # relay the shed answer; client paces
            self._count("budget_spent")
            hint_ms = self._shed_hint_ms(
                float(shed_resp.get("retry_after_ms") or 0.0)
            )
            await asyncio.sleep(
                max(hint_ms / 1000.0, self.policy.backoff_delay(round_no))
            )
        self._count("relayed_overload")
        return shed_resp

    # ------------------------------------------------------------ job plane
    def _link_by_wid(self, wid: str) -> "WorkerLink | None":
        return next((l for l in self.links if l.wid == wid), None)

    def _note_job(self, jid: str, resp: dict, req=None, wid=None) -> None:
        """Update the ownership table from a job response."""
        entry = self._job_owners.get(jid)
        if entry is None:
            if req is None or wid is None:
                return
            entry = self._job_owners[jid] = {"req": dict(req), "wid": wid}
        if wid is not None:
            entry["wid"] = wid
        state = resp.get("state")
        if state:
            entry["state"] = state

    async def _route_job(self, req: dict) -> dict:
        """Durable-job control routing: ``submit`` places by path
        affinity (failing over across workers — the deterministic job id
        + shared journal dir make a re-dispatch resume, not restart);
        ``job_status``/``job_cancel`` go to the job's owning worker."""
        op = req.get("op")
        ctx = obs_trace.from_carrier(req.get("trace"))
        if ctx is None and obs.enabled():
            ctx = obs_trace.mint()
        self.budget.note_request()
        if op == "submit":
            tried: set = set()
            while True:
                link = self.pick(req.get("path"), exclude=tried)
                if link is None:
                    return error_response(
                        req, "WorkerLost",
                        "no healthy workers in the fabric",
                    )
                tried.add(link.wid)
                try:
                    resp = await self._relay(link, req, ctx)
                except WorkerLost:
                    if not self.budget.try_spend():
                        self._count("lost")
                        self._count("budget_exhausted")
                        return error_response(
                            req, "WorkerLost",
                            f"worker {link.wid} died mid-submit; "
                            "retry budget exhausted",
                        )
                    self._count("failovers")
                    self._count("budget_spent")
                    continue
                if resp.get("ok") and resp.get("job_id"):
                    self._note_job(
                        resp["job_id"], resp, req=req, wid=link.wid
                    )
                self._count("routed")
                return resp
        # status / cancel: prefer the owner; any healthy worker can
        # answer after a rescue re-homed the job.
        jid = req.get("job_id")
        entry = self._job_owners.get(jid) if jid else None
        link = None
        if entry is not None:
            owner = self._link_by_wid(entry["wid"])
            if owner is not None and owner.healthy and not owner.draining:
                link = owner
        if link is None:
            link = self.pick(None)
        if link is None:
            return error_response(
                req, "WorkerLost", "no healthy workers in the fabric",
            )
        try:
            resp = await self._relay(link, req, ctx)
        except WorkerLost:
            return error_response(
                req, "WorkerLost", f"worker {link.wid} died mid-{op}",
            )
        if resp.get("ok") and jid:
            self._note_job(jid, resp)
        self._count("routed")
        return resp

    async def _job_watchdog(self) -> None:
        """Orphan rescue: a tracked, non-terminal job whose owning link
        is down gets its original ``submit`` re-dispatched to a
        survivor, which resumes it from the journal (shared jobs dir).
        Budget-gated like any failover."""
        interval = max(self.fcfg.probe_ms / 1000.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            for jid, entry in list(self._job_owners.items()):
                if entry.get("state") in _JOB_TERMINAL:
                    continue
                owner = self._link_by_wid(entry["wid"])
                if owner is not None and owner.healthy:
                    continue
                nxt = self.pick(entry["req"].get("path"),
                                exclude={entry["wid"]})
                if nxt is None or not self.budget.try_spend():
                    continue
                self._count("budget_spent")
                try:
                    resp = await self._relay(nxt, dict(entry["req"]), None)
                except WorkerLost:
                    continue
                if resp.get("ok"):
                    self._count("job_rescues")
                    flight.record("job_rescue", job_id=jid,
                                  worker=nxt.wid, was=entry["wid"])
                    self._note_job(jid, resp, wid=nxt.wid)

    # ------------------------------------------------------------ streaming
    @staticmethod
    def _link_local(link: WorkerLink) -> bool:
        """Whether the worker plausibly shares this host — the only
        placement where relaying its shm descriptors can work (the
        client must be able to map the segment path)."""
        addr = link.address
        if addr.kind == "unix":
            return True
        host = str(addr.host)
        return host.startswith("127.") or host in ("::1", "localhost")

    async def _stream_open(self, link: WorkerLink, req: dict,
                           ctx, resume_from: int, shm_offer: bool = False):
        """Open a DEDICATED upstream connection for one streaming
        response and read its head. The multiplexed link must buffer
        complete responses (frames from different requests would
        interleave); a stream gets its own socket so the router can relay
        frames the moment they arrive. With ``shm_offer`` a ``hello``
        rides the SAME buffered write as the request (one syscall, no
        extra round-trip); a granted upstream answers with frame
        descriptors the relay forwards without touching the bytes.
        Returns ``(head, reader, writer, up_shm)`` — ``up_shm`` is the
        granted ``{"segment", "segment_id"}`` or None; raises
        :class:`WorkerLost` when the worker can't be reached or dies
        before the head."""
        addr = link.address
        try:
            if addr.kind == "unix":
                reader, writer = await asyncio.open_unix_connection(
                    addr.path, limit=MAX_LINE
                )
            else:
                reader, writer = await asyncio.open_connection(
                    addr.host, addr.port, limit=MAX_LINE
                )
        except (ConnectionError, OSError) as exc:
            raise WorkerLost(f"worker {link.wid}: {exc}") from exc
        fwd = {k: v for k, v in req.items() if k != "id"}
        fwd["id"] = 1
        if resume_from:
            fwd["resume_from"] = int(resume_from)
        if ctx is not None:
            fwd["trace"] = obs_trace.carrier(ctx)
        try:
            payload = b""
            if shm_offer:
                payload += (json.dumps(
                    {"op": "hello", "transport": "shm", "id": 0}
                ) + "\n").encode()
            payload += (json.dumps(fwd) + "\n").encode()
            writer.write(payload)
            await writer.drain()
            up_shm = None
            if shm_offer:
                hline = await reader.readline()
                if not hline:
                    raise ConnectionError("worker closed during hello")
                h = json.loads(hline)
                if h.get("ok") and h.get("transport") == "shm":
                    up_shm = {"segment": str(h["segment"]),
                              "segment_id": int(h["segment_id"])}
            line = await reader.readline()
            if not line:
                raise ConnectionError("worker closed before the stream head")
            head = json.loads(line)
        except (ConnectionError, OSError, ValueError, KeyError,
                asyncio.IncompleteReadError) as exc:
            try:
                writer.close()
            except Exception:
                pass
            raise WorkerLost(f"worker {link.wid}: {exc}") from exc
        return head, reader, writer, up_shm

    async def _stream_route(self, req: dict, ctx, conn=None) -> dict:
        """Streaming relay for ``batch`` (``stream=1``): forward the head
        as soon as the first worker answers, then hand the accept loop an
        async frame iterator (``_binary_iter``) that relays each frame as
        it arrives. A mid-stream :class:`WorkerLost` at frame N re-opens
        on a replacement worker with ``resume_from = N`` (plus whatever
        resume base the CLIENT sent — the token composes end-to-end), so
        the delivered frame sequence is byte-identical to an undisturbed
        run without the router ever buffering the response.

        When the CLIENT negotiated shm (``conn.transport == "shm"``) and
        the chosen worker is same-host and grants shm upstream, the
        relay switches to DESCRIPTOR mode (``_records_iter``): the
        worker's segment is announced downstream under a router-assigned
        id and its descriptors are remapped and forwarded — the frame
        bytes never enter router memory, and the client acks straight
        into the worker's ring. Any other combination (socket client,
        remote worker, shm-less worker, failover onto one) degrades to
        byte relay per frame — inline records downstream cost one copy,
        exactly the classic path."""
        path = req.get("path")
        client_base = int(req.get("resume_from") or 0)
        want_shm = (conn is not None
                    and getattr(conn, "transport", "socket") == "shm"
                    and bool(self.fcfg.shm))
        tried: set = set()
        shed_resp = None
        while True:
            link = self.pick(path, exclude=tried)
            if link is None:
                if shed_resp is not None:
                    self._count("relayed_overload")
                    return shed_resp
                return error_response(
                    req, "WorkerLost", "no healthy workers in the fabric",
                )
            tried.add(link.wid)
            try:
                head, reader, writer, up_shm = await self._stream_open(
                    link, req, ctx, client_base,
                    shm_offer=want_shm and self._link_local(link),
                )
            except WorkerLost:
                if not self.budget.try_spend():
                    self._count("lost")
                    self._count("budget_exhausted")
                    return error_response(
                        req, "WorkerLost",
                        f"worker {link.wid} died opening stream; "
                        "retry budget exhausted",
                    )
                self._count("failovers")
                self._count("budget_spent")
                continue
            if head.get("ok") is False:
                try:
                    writer.close()
                except Exception:
                    pass
                if head.get("error") in ("Overloaded", "Draining"):
                    shed_resp = dict(head, id=req.get("id"))
                    continue        # spill to the next-best worker
                return dict(head, id=req.get("id"))   # typed worker error
            break
        total = int(head.get("binary_frames") or 0)
        self._count("routed")
        self._count("streamed")

        async def frames():
            nonlocal reader, writer
            delivered = 0
            cur_wid = link.wid
            chaos = self.chaos
            try:
                while delivered < total:
                    try:
                        if chaos is not None and chaos.roll("trunc"):
                            # lint: allow[obs-contract] in obs/names.py
                            obs.count("fabric.chaos.truncs")
                            raise ConnectionError("chaos: stream truncated")
                        hdr = await reader.readexactly(8)
                        (length,) = struct.unpack("<Q", hdr)
                        frame = await reader.readexactly(length)
                    except (ConnectionError, OSError,
                            asyncio.IncompleteReadError) as exc:
                        flight.record(
                            "stream_lost", worker=cur_wid,
                            op=req.get("op", "batch"),
                            delivered=delivered, total=total,
                            error=str(exc),
                        )
                        reader, writer, cur_wid, _ = (
                            await self._stream_resume(
                                req, ctx, cur_wid,
                                client_base + delivered, total - delivered,
                                writer,
                            )
                        )
                        continue
                    delivered += 1
                    self._count("stream_frames")
                    yield frame
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def records():
            # Descriptor relay: upstream RECORDS in, remapped records
            # out. ``segmap`` translates worker segment ids into this
            # downstream connection's id space (drawn from the same
            # allocator as the connection's own ring, so they can never
            # collide); a failover onto a shm-less upstream downgrades
            # to wrapping its plain frames as inline records mid-stream.
            nonlocal reader, writer
            delivered = 0
            cur_wid = link.wid
            chaos = self.chaos
            up_mode = "records"
            segmap: "dict[int, int]" = {}
            ds = conn.alloc_seg_id()
            segmap[int(up_shm["segment_id"])] = ds
            obs.count("transport.segment_announces")
            yield shm.pack_segment(ds, up_shm["segment"])
            try:
                while delivered < total:
                    try:
                        if chaos is not None and chaos.roll("trunc"):
                            # lint: allow[obs-contract] in obs/names.py
                            obs.count("fabric.chaos.truncs")
                            raise ConnectionError("chaos: stream truncated")
                        if up_mode == "frames":
                            hdr = await reader.readexactly(8)
                            (length,) = struct.unpack("<Q", hdr)
                            rec = shm.pack_inline(
                                await reader.readexactly(length)
                            )
                        else:
                            kb = await reader.readexactly(1)
                            kind = kb[0]
                            if kind == shm.REC_SEGMENT:
                                body = await reader.readexactly(
                                    shm.SEG.size
                                )
                                up_id, plen = shm.SEG.unpack(body)
                                spath = (
                                    await reader.readexactly(plen)
                                ).decode()
                                nds = conn.alloc_seg_id()
                                segmap[up_id] = nds
                                obs.count("transport.segment_announces")
                                yield shm.pack_segment(nds, spath)
                                continue    # announces aren't frames
                            if kind == shm.REC_INLINE:
                                hdr = await reader.readexactly(8)
                                (length,) = struct.unpack("<Q", hdr)
                                rec = kb + hdr + (
                                    await reader.readexactly(length)
                                )
                            elif kind == shm.REC_SHM:
                                body = await reader.readexactly(
                                    shm.DESC.size
                                )
                                up_id, offset, length, crc = (
                                    shm.DESC.unpack(body)
                                )
                                mapped = segmap.get(up_id)
                                if mapped is None:
                                    raise ConnectionError(
                                        "descriptor for unannounced "
                                        f"segment {up_id}"
                                    )
                                obs.count("transport.relay_descriptors")
                                rec = shm.pack_desc(
                                    mapped, offset, length, crc
                                )
                            else:
                                raise ConnectionError(
                                    f"unknown record kind {kind}"
                                )
                    except (ConnectionError, OSError,
                            asyncio.IncompleteReadError) as exc:
                        flight.record(
                            "stream_lost", worker=cur_wid,
                            op=req.get("op", "batch"),
                            delivered=delivered, total=total,
                            error=str(exc),
                        )
                        reader, writer, cur_wid, new_shm = (
                            await self._stream_resume(
                                req, ctx, cur_wid,
                                client_base + delivered, total - delivered,
                                writer, shm_offer=True,
                            )
                        )
                        if new_shm is not None:
                            # Replacement worker's segment, fresh id —
                            # the failover re-announce of docs/serving.md.
                            up_mode = "records"
                            segmap = {}
                            nds = conn.alloc_seg_id()
                            segmap[int(new_shm["segment_id"])] = nds
                            obs.count("transport.segment_announces")
                            yield shm.pack_segment(nds, new_shm["segment"])
                        else:
                            up_mode = "frames"
                        continue
                    delivered += 1
                    self._count("stream_frames")
                    yield rec
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        resp = {k: v for k, v in head.items()
                if k not in ("resume_from", "total_frames")}
        resp["id"] = req.get("id")
        resp["binary_frames"] = total
        if want_shm and up_shm is not None:
            resp["_records_iter"] = records()
        else:
            resp["_binary_iter"] = frames()
        return resp

    async def _stream_resume(self, req: dict, ctx, dead_wid: str,
                             resume_from: int, need: int, old_writer,
                             shm_offer: bool = False):
        """Find a replacement worker mid-stream and re-open from the
        resume token. Budget-gated like any failover; raises
        :class:`WorkerLost` when the budget or the fleet runs out (the
        accept loop then ABORTS the client connection — a half-delivered
        frame sequence must never look complete). Returns ``(reader,
        writer, wid, up_shm)`` — ``up_shm`` is the replacement's granted
        segment when ``shm_offer`` held and the worker is same-host."""
        try:
            old_writer.close()
        except Exception:
            pass
        exclude = {dead_wid}
        while True:
            if not self.budget.try_spend():
                self._count("budget_exhausted")
                raise WorkerLost(
                    f"stream lost at resume_from={resume_from}; "
                    "retry budget exhausted"
                )
            self._count("failovers")
            self._count("budget_spent")
            nxt = self.pick(req.get("path"), exclude=exclude)
            if nxt is None:
                raise WorkerLost("no healthy workers to resume the stream")
            try:
                head, reader, writer, up_shm = await self._stream_open(
                    nxt, req, ctx, resume_from,
                    shm_offer=shm_offer and self._link_local(nxt),
                )
            except WorkerLost:
                exclude.add(nxt.wid)
                continue
            if head.get("ok") is False:
                try:
                    writer.close()
                except Exception:
                    pass
                if head.get("error") in ("Overloaded", "Draining"):
                    await asyncio.sleep(max(
                        self._shed_hint_ms(
                            float(head.get("retry_after_ms") or 0.0)
                        ) / 1000.0,
                        self.policy.backoff_delay(0),
                    ))
                    continue
                raise WorkerLost(
                    f"worker {nxt.wid} refused stream resume: "
                    f"{head.get('error')}"
                )
            got = int(head.get("binary_frames") or 0)
            if got != need:
                try:
                    writer.close()
                except Exception:
                    pass
                raise WorkerLost(
                    f"resume mismatch: worker {nxt.wid} offered {got} "
                    f"frames at resume_from={resume_from}, need {need}"
                )
            self._count("resumed")
            flight.record("stream_resume", worker=nxt.wid,
                          resume_from=resume_from, frames=need)
            return reader, writer, nxt.wid, up_shm

    # ------------------------------------------------------------ admin ops
    def _admin_targets(self, req: dict) -> "list[WorkerLink]":
        wid = req.get("worker")
        if wid is None:
            return list(self.links)
        links = [l for l in self.links if l.wid == wid]
        if not links:
            raise KeyError(f"unknown worker {wid!r}")
        return links

    async def _forward_admin(self, req: dict,
                             links: "list[WorkerLink]") -> dict:
        fwd = {k: v for k, v in req.items() if k != "worker"}

        async def one(link):
            try:
                resp = await link.request(dict(fwd))
                return {k: v for k, v in resp.items() if k != "id"}
            except Exception as exc:
                return {"ok": False, "error": "WorkerLost", "message": str(exc)}

        results = await asyncio.gather(*(one(l) for l in links))
        return {l.wid: r for l, r in zip(links, results)}

    async def _drain(self, req: dict) -> dict:
        """Router-level graceful drain: stop routing new work, forward
        ``drain`` so each worker refuses its own new arrivals, report the
        remaining inflight so the operator can watch it reach zero. A
        ``worker`` field narrows the drain to one worker (the router just
        stops placing work there)."""
        try:
            links = self._admin_targets(req)
        except KeyError as exc:
            return error_response(req, "ProtocolError", str(exc))
        if req.get("worker") is None:
            self.draining = True
        for link in links:
            link.draining = True
        self._count("drained", len(links))
        per_worker = await self._forward_admin({"op": "drain"}, links)
        return ok_response(
            req, draining=True,
            workers={w: r.get("inflight") for w, r in per_worker.items()},
        )

    async def _tune(self, req: dict) -> dict:
        """Fan a ``tune`` out to one worker (``worker`` field) or all —
        the autoscaler uses the per-worker form; operators may broadcast."""
        try:
            links = self._admin_targets(req)
        except KeyError as exc:
            return error_response(req, "ProtocolError", str(exc))
        per_worker = await self._forward_admin(req, links)
        ok = all(r.get("ok") for r in per_worker.values())
        if not ok:
            return error_response(
                req, "Internal", "tune failed on some workers",
                workers=per_worker,
            )
        return ok_response(req, workers=per_worker)

    async def _stats(self, req: dict) -> dict:
        links = list(self.links)

        async def one(link):
            if not link.healthy:
                return None
            try:
                resp = await link.request({"op": "stats"})
            except Exception:
                return None
            return {k: v for k, v in resp.items() if k not in ("id", "ok")}

        upstream = await asyncio.gather(*(one(l) for l in links))
        workers = {
            l.wid: {
                "address": l.address.spec,
                "healthy": bool(l.healthy),
                "draining": bool(l.draining),
                "inflight": int(l.inflight),
                "breaker": (l.breaker.state if l.breaker is not None
                            else None),
                "stats": stats,
            }
            for l, stats in zip(links, upstream)
        }
        extra = {}
        if self.chaos is not None:
            extra["chaos"] = {
                "seed": self.chaos.seed,
                "spec": self.chaos.describe(),
                "injected": dict(self.chaos.injected),
            }
        return ok_response(
            req, fabric=True, draining=bool(self.draining),
            counters=dict(sorted(self.counters.items())),
            budget={
                "tokens": round(self.budget.tokens, 3),
                "capacity": self.budget.capacity,
                "spent": self.budget.spent,
                "denied": self.budget.denied,
            },
            brownout=self._brownout(),
            moves=list(self.moves),
            workers=workers,
            **extra,
        )

    async def _alerts(self, req: dict) -> dict:
        """Fleet alert view: every healthy worker's SLO status plus the
        router's autoscale move ledger — the one payload that answers
        "what is firing and what did the fleet do about it" (the CI
        failure artifact and the dashboard's /slo both read this)."""
        links = [l for l in self.links if l.healthy]
        per_worker = await self._forward_admin({"op": "alerts"}, links)
        firing = sorted({
            name
            for r in per_worker.values()
            for name in (r.get("slo") or {}).get("firing", ())
        })
        ledger = sorted(
            (dict(e, worker=w)
             for w, r in per_worker.items()
             for e in (r.get("slo") or {}).get("ledger", ())),
            key=lambda e: e.get("t", 0.0),
        )
        return ok_response(
            req, fabric=True, firing=firing, ledger=ledger,
            moves=list(self.moves), workers=per_worker,
        )

    async def _telemetry(self, req: dict) -> dict:
        """Fleet telemetry collector: scrape every healthy worker's
        ``telemetry`` op, merge their obs snapshots into one fleet view,
        and attach the router's own counters + flight ring. With
        ``prometheus: true`` the merged snapshot is also rendered in the
        exposition text format (one scrape endpoint for the whole
        fabric)."""
        from spark_bam_tpu.obs.account import merge_accounting
        from spark_bam_tpu.obs.exporters import (
            merge_snapshots,
            prometheus_text,
        )
        from spark_bam_tpu.obs.timeseries import merge_series

        links = list(self.links)
        fwd = {"op": "telemetry"}
        if req.get("max_spans") is not None:
            fwd["max_spans"] = req["max_spans"]

        async def one(link):
            if not link.healthy:
                return None
            try:
                resp = await link.request(dict(fwd))
            except Exception:
                return None
            if not resp.get("ok"):
                return None
            return {k: v for k, v in resp.items() if k not in ("id", "ok")}

        upstream = await asyncio.gather(*(one(l) for l in links))
        workers = {
            l.wid: {
                "address": l.address.spec,
                "healthy": bool(l.healthy),
                "draining": bool(l.draining),
                "inflight": int(l.inflight),
                "telemetry": t,
            }
            for l, t in zip(links, upstream)
        }
        merged = merge_snapshots([
            t["snapshot"] for t in upstream
            if t and t.get("snapshot")
        ])
        out = {
            "fabric": True,
            "draining": bool(self.draining),
            "counters": dict(sorted(self.counters.items())),
            "moves": list(self.moves),
            "workers": workers,
            "fleet": merged,
            # Fleet-wide time-series rings (cadence-bucketed sums) and
            # per-op/per-tenant cost rollups, merged across workers.
            "series": merge_series([
                t["series"] for t in upstream if t and t.get("series")
            ]),
            "accounting": merge_accounting([
                t.get("accounting") for t in upstream if t
            ]),
            "flight": flight.recorder().events(),
        }
        if req.get("prometheus"):
            out["prometheus"] = prometheus_text(merged)
        return ok_response(req, **out)
