"""SLO autoscaler: per-worker control loop steering on burn rate.

Every ``autoscale_ms`` the loop reads the worker's ``stats`` op — the
SAME per-op p50/p99 ledger operators read, not a private side channel.
When the worker runs an SLO engine (``--slo``, obs/slo.py), its stats
carry a compact ``slo`` block (``max_burn_fast`` + firing objective
names) and the loop steers on THAT — a windowed burn rate is a far
steadier signal than one p99 sample, and a move taken during an alert
cites the firing objective in the router's move ledger (the operator can
answer "why did the fleet downscale" from the ``alerts`` op alone):

- burn ≥ 1 on the fast window (or any objective firing) → step every
  knob toward its floor (halve ``batch_rows`` and ``tick_ms``, halve the
  scan/plan admission caps): smaller ticks finish sooner, lower caps
  shed earlier so queue wait stops compounding the tail.
- burn under 0.5 → step gently toward the ceilings (+25%): reclaim
  batching throughput when the budget has headroom.
- otherwise, or when no new requests were served since the last look
  (no fresh samples), hold — hysteresis against flapping on stale tails.

Without an SLO engine the loop falls back to the PR 13 behavior:
``latency_p99_ms`` against ``FabricConfig.slo_p99_ms`` with the same
above/half thresholds.

Decisions are pure (:func:`decide_with_reason` — unit-testable);
actuation is one ``tune`` op per move (counted ``autoscale_moves``, each
reported to the router's ledger via ``note_move``). Floors/ceilings live
in :class:`~spark_bam_tpu.fabric.config.FabricConfig`; the worker
applies whatever it is told (serve/service.py ``tune``).
"""

from __future__ import annotations

import asyncio


def _down(value, floor):
    return max(floor, min(value, floor) if value <= floor else value / 2)


def _up(value, ceil):
    return min(ceil, max(value + 1, value * 1.25))


def _direction(stats: dict, fcfg) -> "tuple[int, str | None]":
    """(+1 scale up, -1 scale down, 0 hold) plus the cited reason.

    Burn rate wins when the worker reports an SLO block with data (any
    measured value burns > 0, so burn == 0 means "no samples yet" and
    falls through to the p99 path)."""
    slo = stats.get("slo") or {}
    burn = float(slo.get("max_burn_fast") or 0.0)
    firing = list(slo.get("firing") or ())
    if firing:
        return -1, f"slo_alert:{firing[0]} burn={round(burn, 2)}"
    if burn > 0.0:
        worst = slo.get("worst")
        if burn >= 1.0:
            return -1, f"burn={round(burn, 2)} worst={worst}"
        if burn < 0.5:
            return 1, f"burn={round(burn, 2)}<0.5"
        return 0, None
    p99 = stats.get("latency_p99_ms")
    if p99 is None:
        return 0, None
    if p99 > fcfg.slo_p99_ms:
        return -1, f"p99={p99}ms>slo={fcfg.slo_p99_ms}ms"
    if p99 < 0.5 * fcfg.slo_p99_ms:
        return 1, f"p99={p99}ms<0.5*slo"
    return 0, None


def decide_with_reason(stats: dict,
                       fcfg) -> "tuple[dict | None, str | None]":
    """The tune fields (if any) for one worker given its ``stats``
    payload, plus the human-readable reason the move cites (the router's
    move ledger / flight entries).

    Returns (None, None) to hold. Values are already clamped to the
    config's floors/ceilings; ints stay ints (batch_rows/caps), tick
    stays float.
    """
    direction, reason = _direction(stats, fcfg)
    if direction == 0:
        return None, None
    batch = int(stats.get("batch_rows") or 1)
    tick = float(stats.get("tick_ms") or 0.0)
    limits = stats.get("limits") or {}
    scanq = int(limits.get("scan") or fcfg.scanq_ceil)
    planq = int(limits.get("plan") or fcfg.planq_ceil)
    move: dict = {}
    if direction < 0:
        new_batch = int(_down(min(batch, fcfg.batch_ceil), fcfg.batch_floor))
        new_tick = float(_down(min(tick, fcfg.tick_ceil), fcfg.tick_floor))
        new_scanq = int(_down(min(scanq, fcfg.scanq_ceil), fcfg.scanq_floor))
        new_planq = int(_down(min(planq, fcfg.planq_ceil), fcfg.planq_floor))
    else:
        new_batch = int(_up(batch, fcfg.batch_ceil))
        new_tick = min(float(_up(tick, fcfg.tick_ceil)), fcfg.tick_ceil)
        new_scanq = int(_up(scanq, fcfg.scanq_ceil))
        new_planq = int(_up(planq, fcfg.planq_ceil))
    if new_batch != batch:
        move["batch_rows"] = new_batch
    if abs(new_tick - tick) > 1e-9:
        move["tick_ms"] = round(new_tick, 3)
    if new_scanq != scanq:
        move["scan_queue"] = new_scanq
    if new_planq != planq:
        move["plan_queue"] = new_planq
    return (move, reason) if move else (None, None)


def decide(stats: dict, fcfg) -> "dict | None":
    """Back-compat wrapper: just the move dict (or None to hold)."""
    move, _ = decide_with_reason(stats, fcfg)
    return move


async def autoscale_worker(link, fcfg, count, note_move=None,
                           hold=None) -> None:
    """Control loop for one worker link; ``count`` is the router's
    counter hook (``autoscale_moves``), ``note_move`` its move-ledger
    hook — called with ``{worker, move, reason}`` per actuated move.
    ``hold`` (optional callable → bool) freezes actuation while true:
    the router holds during brownout, because stats measured under
    edge-shed traffic would read as idleness and downscale the exact
    capacity the fleet needs back."""
    prev_served = None
    while True:
        await asyncio.sleep(fcfg.autoscale_ms / 1000.0)
        if not link.healthy or link.draining:
            continue
        if hold is not None and hold():
            continue
        try:
            stats = await link.request({"op": "stats"})
        except asyncio.CancelledError:
            raise
        except Exception:
            continue
        served = stats.get("served")
        if prev_served is not None and served == prev_served:
            continue                 # no fresh samples → hold
        prev_served = served
        move, reason = decide_with_reason(stats, fcfg)
        if not move:
            continue
        try:
            await link.request({"op": "tune", **move})
            count("autoscale_moves")
            if note_move is not None:
                note_move({"worker": link.wid, "move": move,
                           "reason": reason})
        except asyncio.CancelledError:
            raise
        except Exception:
            continue
