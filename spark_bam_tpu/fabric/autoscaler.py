"""SLO autoscaler: per-worker control loop holding a target p99.

Every ``autoscale_ms`` the loop reads the worker's ``stats`` op — the
SAME per-op p50/p99 ledger operators read, not a private side channel —
and compares the overall ``latency_p99_ms`` against
``FabricConfig.slo_p99_ms``:

- p99 ABOVE the SLO → step every knob toward its floor (halve
  ``batch_rows`` and ``tick_ms``, halve the scan/plan admission caps):
  smaller ticks finish sooner, lower caps shed earlier so queue wait
  stops compounding the tail.
- p99 under HALF the SLO → step gently toward the ceilings (+25%):
  reclaim batching throughput when latency headroom is back.
- otherwise, or when no new requests were served since the last look
  (no fresh samples), hold — hysteresis against flapping on stale tails.

Decisions are pure (:func:`decide` — unit-testable); actuation is one
``tune`` op per move (counted ``autoscale_moves``). Floors/ceilings live
in :class:`~spark_bam_tpu.fabric.config.FabricConfig`; the worker
applies whatever it is told (serve/service.py ``tune``).
"""

from __future__ import annotations

import asyncio


def _down(value, floor):
    return max(floor, min(value, floor) if value <= floor else value / 2)


def _up(value, ceil):
    return min(ceil, max(value + 1, value * 1.25))


def decide(stats: dict, fcfg) -> "dict | None":
    """The tune fields (if any) for one worker given its ``stats`` payload.

    Returns None to hold. Values are already clamped to the config's
    floors/ceilings; ints stay ints (batch_rows/caps), tick stays float.
    """
    p99 = stats.get("latency_p99_ms")
    if p99 is None:
        return None
    batch = int(stats.get("batch_rows") or 1)
    tick = float(stats.get("tick_ms") or 0.0)
    limits = stats.get("limits") or {}
    scanq = int(limits.get("scan") or fcfg.scanq_ceil)
    planq = int(limits.get("plan") or fcfg.planq_ceil)
    move: dict = {}
    if p99 > fcfg.slo_p99_ms:
        new_batch = int(_down(min(batch, fcfg.batch_ceil), fcfg.batch_floor))
        new_tick = float(_down(min(tick, fcfg.tick_ceil), fcfg.tick_floor))
        new_scanq = int(_down(min(scanq, fcfg.scanq_ceil), fcfg.scanq_floor))
        new_planq = int(_down(min(planq, fcfg.planq_ceil), fcfg.planq_floor))
    elif p99 < 0.5 * fcfg.slo_p99_ms:
        new_batch = int(_up(batch, fcfg.batch_ceil))
        new_tick = min(float(_up(tick, fcfg.tick_ceil)), fcfg.tick_ceil)
        new_scanq = int(_up(scanq, fcfg.scanq_ceil))
        new_planq = int(_up(planq, fcfg.planq_ceil))
    else:
        return None
    if new_batch != batch:
        move["batch_rows"] = new_batch
    if abs(new_tick - tick) > 1e-9:
        move["tick_ms"] = round(new_tick, 3)
    if new_scanq != scanq:
        move["scan_queue"] = new_scanq
    if new_planq != planq:
        move["plan_queue"] = new_planq
    return move or None


async def autoscale_worker(link, fcfg, count) -> None:
    """Control loop for one worker link; ``count`` is the router's
    counter hook (``autoscale_moves``)."""
    prev_served = None
    while True:
        await asyncio.sleep(fcfg.autoscale_ms / 1000.0)
        if not link.healthy or link.draining:
            continue
        try:
            stats = await link.request({"op": "stats"})
        except asyncio.CancelledError:
            raise
        except Exception:
            continue
        served = stats.get("served")
        if prev_served is not None and served == prev_served:
            continue                 # no fresh samples → hold
        prev_served = served
        move = decide(stats, fcfg)
        if not move:
            continue
        try:
            await link.request({"op": "tune", **move})
            count("autoscale_moves")
        except asyncio.CancelledError:
            raise
        except Exception:
            continue
