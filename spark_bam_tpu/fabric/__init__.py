"""Serve fabric: the control plane above the single-host serve daemon.

A router with file-path affinity fronts N serve workers (one per host
over ``jax.distributed``, or N local processes), each running its own
accept loop, compiled ``MeshSteps``, flat-view LRU and ``.sbi`` warm
tier. Health probes eject dead workers with exponential re-probe; a
per-worker SLO control loop retunes ``batch_rows``/``tick_ms`` and the
admission caps from the same ``stats`` percentiles operators read; a
worker dying mid-request fails idempotent ops over to another worker
exactly once, byte-identically. See docs/fabric.md.
"""

from spark_bam_tpu.fabric.autoscaler import autoscale_worker, decide
from spark_bam_tpu.fabric.config import FabricConfig
from spark_bam_tpu.fabric.health import monitor_worker
from spark_bam_tpu.fabric.router import (
    IDEMPOTENT_OPS,
    Router,
    WorkerLink,
    WorkerLost,
    rendezvous_weight,
)
from spark_bam_tpu.fabric.worker import WorkerPool, serve_worker

__all__ = [
    "FabricConfig",
    "IDEMPOTENT_OPS",
    "Router",
    "WorkerLink",
    "WorkerLost",
    "WorkerPool",
    "autoscale_worker",
    "decide",
    "monitor_worker",
    "rendezvous_weight",
    "serve_worker",
]
