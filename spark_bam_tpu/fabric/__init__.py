"""Serve fabric: the control plane above the single-host serve daemon.

A router with file-path affinity fronts N serve workers (one per host
over ``jax.distributed``, or N local processes), each running its own
accept loop, compiled ``MeshSteps``, flat-view LRU and ``.sbi`` warm
tier. Health probes drive a per-link circuit breaker
(closed/open/half-open with flap hold-down); a worker dying mid-request
fails idempotent ops over to another worker under a router-wide retry
budget, byte-identically — with ``stream=1``, even mid-frame-stream via
``resume_from`` tokens. A seeded chaos layer (``chaos=SEED:SPEC``,
fabric/chaos.py) attacks all of it deterministically. See
docs/fabric.md and docs/robustness.md ("Fleet resilience").
"""

from spark_bam_tpu.fabric.autoscaler import autoscale_worker, decide
from spark_bam_tpu.fabric.chaos import (
    ChaosStorm,
    ChaosWorkerLink,
    FabricChaos,
    FabricChaosSpec,
    parse_fabric_chaos,
    storm_schedule,
)
from spark_bam_tpu.fabric.config import FabricConfig
from spark_bam_tpu.fabric.health import monitor_worker
from spark_bam_tpu.fabric.resilience import (
    CircuitBreaker,
    RetryBudget,
    brownout_level,
)
from spark_bam_tpu.fabric.router import (
    IDEMPOTENT_OPS,
    Router,
    WorkerLink,
    WorkerLost,
    rendezvous_weight,
)
from spark_bam_tpu.fabric.worker import WorkerPool, serve_worker

__all__ = [
    "ChaosStorm",
    "ChaosWorkerLink",
    "CircuitBreaker",
    "FabricChaos",
    "FabricChaosSpec",
    "FabricConfig",
    "IDEMPOTENT_OPS",
    "RetryBudget",
    "Router",
    "WorkerLink",
    "WorkerLost",
    "WorkerPool",
    "autoscale_worker",
    "brownout_level",
    "decide",
    "monitor_worker",
    "parse_fabric_chaos",
    "rendezvous_weight",
    "serve_worker",
    "storm_schedule",
]
