from spark_bam_tpu.utils.timer import Timer, heartbeat, profile_trace

__all__ = ["Timer", "heartbeat", "profile_trace"]
