"""Timers, heartbeats, and profiler hooks.

The reference's observability is wall-clock ``Timer.time`` blocks and
heartbeat logging (SURVEY.md §5: ComputeSplits.scala:74-106,
IndexBlocks.scala:34-45; its docs admit "no profiling having been done").
Per the survey's recommendation we wire stage timers + the JAX profiler in
from day one: ``profile_trace`` wraps any block in a TensorBoard-viewable
device trace when ``SPARK_BAM_PROFILE_DIR`` is set, and is a no-op
otherwise.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

log = logging.getLogger(__name__)


class Timer:
    """Named stage timer: ``with Timer() as t: ...; t.ms``."""

    def __init__(self, name: str = "", echo=None):
        self.name = name
        self.echo = echo
        self.ms = 0

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.ms = int((time.perf_counter() - self._t0) * 1000)
        if self.echo is not None and self.name:
            self.echo(f"{self.name}: {self.ms}ms")


@contextlib.contextmanager
def heartbeat(what: str, interval_seconds: float = 10.0):
    """Yields a callable ``beat(progress)``; logs at most every interval."""
    last = time.monotonic()

    def beat(progress):
        nonlocal last
        now = time.monotonic()
        if now - last >= interval_seconds:
            log.info("%s: %s", what, progress)
            last = now

    yield beat


@contextlib.contextmanager
def heartbeat_progress(
    what: str, unit: str = "step", interval_seconds: float = 10.0
):
    """Heartbeat shaped as the streaming APIs' ``progress`` callback
    (``(k, done, total)`` — StreamChecker windows / sharded steps): yields
    a callable suitable for their ``progress=`` kwarg."""
    with heartbeat(what, interval_seconds) as beat:
        yield lambda k, done, total: beat(
            f"{unit} {k}, {done}/{total} positions"
        )


@contextlib.contextmanager
def profile_trace(name: str = "spark-bam-tpu"):
    """JAX device trace when SPARK_BAM_PROFILE_DIR is set; else no-op."""
    trace_dir = os.environ.get("SPARK_BAM_PROFILE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield
