"""Timers, heartbeats, and profiler hooks — thin shims over ``obs``.

The reference's observability is wall-clock ``Timer.time`` blocks and
heartbeat logging (SURVEY.md §5: ComputeSplits.scala:74-106,
IndexBlocks.scala:34-45; its docs admit "no profiling having been done").
These helpers predate the unified observability layer
(``spark_bam_tpu.obs``) and are kept as shims: a named ``Timer`` feeds
its duration into the live registry's ``timer.<name>`` histogram, and
heartbeats bump ``progress.beats``. New instrumentation should use
``obs.span``/``obs.counter`` directly. ``profile_trace`` wraps any block
in a TensorBoard-viewable device trace when ``SPARK_BAM_PROFILE_DIR`` is
set, and is a no-op otherwise — it composes with ``--metrics-out``
(wall-clock spans and a device trace can capture the same run).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

from spark_bam_tpu import obs

log = logging.getLogger(__name__)


class Timer:
    """Named stage timer: ``with Timer() as t: ...; t.seconds / t.ms``.

    ``seconds`` is the measured float duration; ``ms`` derives from it
    (also float — the old int truncation erased sub-millisecond stages
    entirely).
    """

    def __init__(self, name: str = "", echo=None):
        self.name = name
        self.echo = echo
        self.seconds = 0.0

    @property
    def ms(self) -> float:
        return self.seconds * 1e3

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        if self.name:
            # lint: allow[obs-contract] timer names are literal strings at
            # Timer(...) construction sites — a fixed, code-reviewed set
            obs.observe(f"timer.{self.name}", self.ms, unit="ms")
        if self.echo is not None and self.name:
            self.echo(f"{self.name}: {self.ms:.3f}ms")


@contextlib.contextmanager
def heartbeat(what: str, interval_seconds: float = 10.0):
    """Yields a callable ``beat(progress)``; logs at most every interval."""
    last = time.monotonic()

    def beat(progress):
        nonlocal last
        obs.count("progress.beats")
        now = time.monotonic()
        if now - last >= interval_seconds:
            log.info("%s: %s", what, progress)
            last = now

    yield beat


@contextlib.contextmanager
def heartbeat_progress(
    what: str, unit: str = "step", interval_seconds: float = 10.0
):
    """Heartbeat shaped as the streaming APIs' ``progress`` callback
    (``(k, done, total)`` — StreamChecker windows / sharded steps): yields
    a callable suitable for their ``progress=`` kwarg."""
    with heartbeat(what, interval_seconds) as beat:
        yield lambda k, done, total: beat(
            f"{unit} {k}, {done}/{total} positions"
        )


@contextlib.contextmanager
def profile_trace(name: str = "spark-bam-tpu"):
    """JAX device trace when SPARK_BAM_PROFILE_DIR is set; else no-op."""
    trace_dir = os.environ.get("SPARK_BAM_PROFILE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield
