#!/usr/bin/env python
"""Repo-level runner for the decode-boundary mutation fuzzer.

Thin wrapper over ``spark_bam_tpu.tools.fuzz_decode`` so the harness can
be launched without installing the package::

    python tools/fuzz_decode.py --seed 42 --mutants 500 --formats bam,cram

Exits nonzero iff any mutant violated the decode contract (hang,
allocation blow-up, or untyped error). See docs/robustness.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_bam_tpu.tools.fuzz_decode import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
