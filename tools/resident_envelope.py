#!/usr/bin/env python3
"""Find the largest working resident-scan configuration on the live TPU.

The r05 live window showed the auto (max-HBM) resident chunk crashes the
TPU worker at 32 MB windows x 32-window chunks; this probe walks a
ladder of (window_mb, chunk_windows) configurations from large to small,
each in its own ``bench.py --child-resident`` subprocess (a worker crash
poisons the client process, never the ladder), and reports the first
configuration that completes with an exact count plus its throughput.

Results append to ``RESIDENT_ENVELOPE.jsonl`` at the repo root so live
windows accumulate evidence across sessions.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BENCH_DIR = Path("/tmp/spark_bam_bench")

LADDER = ((32, 32), (32, 8), (16, 8), (8, 8), (8, 2))


def main():
    big = BENCH_DIR / "big_64mb.bam"
    manifest_path = BENCH_DIR / "big_64mb.manifest.json"
    if not big.exists():
        sys.path.insert(0, str(REPO))
        from spark_bam_tpu.benchmarks.synth import ensure_big_bam

        p, man = ensure_big_bam(64 << 20)
        big, reads = Path(p), man["reads"]
    else:
        reads = json.loads(manifest_path.read_text())["reads"]

    out_path = REPO / "RESIDENT_ENVELOPE.jsonl"
    for window_mb, chunk_windows in LADDER:
        t0 = time.time()
        entry = {
            "ts": t0, "window_mb": window_mb,
            "chunk_windows": chunk_windows, "file": str(big),
        }
        try:
            proc = subprocess.run(
                [sys.executable, str(REPO / "bench.py"), "--child-resident",
                 str(window_mb), str(big), str(reads), str(chunk_windows)],
                capture_output=True, text=True, timeout=900,
            )
            result = None
            for line in proc.stdout.splitlines():
                if line.startswith("##RESULT "):
                    payload = json.loads(line[len("##RESULT "):])
                    if payload.get("leg") == "e2e_resident":
                        result = payload
            stages = [ln for ln in proc.stdout.splitlines()
                      if ln.startswith("##STAGE")]
            if result is not None:
                entry.update(ok=True, **{
                    k: result[k] for k in
                    ("pps", "wall_s", "count_ok", "positions")
                })
            else:
                entry.update(ok=False, stages=stages[-3:])
        except subprocess.TimeoutExpired:
            entry.update(ok=False, stages=["timeout"])
        print(json.dumps(entry), flush=True)
        with open(out_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        if entry["ok"]:
            break  # largest working configuration found


if __name__ == "__main__":
    main()
