#!/usr/bin/env python3
"""Find the largest working resident-scan configuration on the live TPU.

The r05 live window showed the auto (max-HBM) resident chunk crashes the
TPU worker at 32 MB windows; this probe walks a ladder of
(window_mb, chunk_windows) configurations from large to small, each in
its own ``bench.py --child-resident`` subprocess (a worker crash poisons
the client process, never the ladder), and reports the first
configuration that completes with an exact count plus its throughput.

Child management (spawn, dead-tunnel init kill, ##STAGE/##RESULT
parsing) reuses ``bench._run_child`` so marker changes can't desync.

Results append to ``RESIDENT_ENVELOPE.jsonl`` at the repo root so live
windows accumulate evidence across sessions.
"""

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402
from spark_bam_tpu.benchmarks.synth import ensure_big_bam  # noqa: E402

LADDER = ((32, 32), (32, 8), (16, 8), (8, 8), (8, 2))


def main():
    # ensure_big_bam reuses a valid cached file and re-synthesizes a
    # missing/stale one — no hand-rolled cache checks here.
    big, manifest = ensure_big_bam(64 << 20)
    reads = manifest["reads"]

    out_path = REPO / "RESIDENT_ENVELOPE.jsonl"
    for window_mb, chunk_windows in LADDER:
        entry = {
            "ts": time.time(), "window_mb": window_mb,
            "chunk_windows": chunk_windows, "file": str(big),
        }
        results, stages, err = bench._run_child(
            ["--child-resident", str(window_mb), str(big), str(reads),
             str(chunk_windows)],
            900,
        )
        result = results.get("e2e_resident")
        if result is not None:
            entry.update(ok=True, **{
                k: result[k]
                for k in ("pps", "wall_s", "count_ok", "positions")
            })
        else:
            entry.update(ok=False, stages=stages[-3:], err=err)
        print(json.dumps(entry), flush=True)
        with open(out_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        if entry["ok"]:
            break  # largest working configuration found
        if not any(s.startswith("backend_ok:tpu") for s in stages):
            break  # tunnel dark or CPU fallback; rungs are irrelevant


if __name__ == "__main__":
    main()
