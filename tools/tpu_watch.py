#!/usr/bin/env python3
"""Tunnel watcher: retry TPU bench captures whenever the chip answers.

The axon TPU tunnel is intermittently dark (r03-r05: most capture
attempts found it down; the two live windows so far lasted ~15 min).
This watcher turns that intermittency into artifacts: every
``--interval`` seconds it probes ``jax.devices()`` in a throwaway
subprocess (a wedged tunnel hangs the probe — the timeout contains it),
and when the probe answers it runs ``bench.py`` (which appends every
capture to BENCH_HISTORY.jsonl itself) and optionally a follow-up
command (e.g. a resident-scan envelope probe).

Usage:
    python tools/tpu_watch.py [--interval 300] [--max-captures 2] \
        [--follow "python tools/resident_envelope.py"]

Runs until ``--max-captures`` benches complete (a capture that reaches
the TPU backend counts; CPU-fallback runs do not) or until killed.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

PROBE = (
    "import jax; d = jax.devices(); "
    "print('ALIVE' if d and d[0].platform != 'cpu' else 'CPU')"
)


def _bench_running() -> bool:
    """True iff some OTHER process is executing bench.py (an interpreter
    whose script argument is bench.py — not a process that merely mentions
    it in some argument string)."""
    me = str(os.getpid())
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or pid == me:
            continue
        try:
            argv = Path(f"/proc/{pid}/cmdline").read_bytes().split(b"\0")
        except OSError:
            continue
        if any(
            a.endswith(b"/bench.py") or a == b"bench.py" for a in argv[:3]
        ):
            return True
    return False


def tunnel_alive(timeout_s: int = 90) -> bool:
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return "ALIVE" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def run_bench(timeout_s: int, trace_path: Path) -> dict | None:
    """One bench.py capture; returns the parsed JSON record (or None).

    The child runs with ``SPARK_BAM_METRICS_OUT`` pointing at
    ``trace_path`` so bench.py's per-stage obs registry also lands on
    disk as a JSONL trace (renderable with ``metrics-report``)."""
    env = dict(os.environ, SPARK_BAM_METRICS_OUT=str(trace_path))
    try:
        out = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
        for line in reversed(out.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    except subprocess.TimeoutExpired:
        pass
    return None


def _stage_line(trace_path: Path) -> str:
    """Per-stage digest of the capture's obs trace (heaviest spans first);
    degrades to a note when the child wrote no trace (old bench.py, crash
    before export)."""
    if not trace_path.exists():
        return "(no trace written)"
    sys.path.insert(0, str(REPO))
    from spark_bam_tpu.obs.report import stage_summary_line

    try:
        return stage_summary_line(trace_path)
    except (OSError, ValueError, KeyError) as e:
        return f"(trace unreadable: {e})"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--max-captures", type=int, default=2)
    ap.add_argument("--bench-timeout", type=int, default=4500)
    ap.add_argument("--follow", default="",
                    help="shell command to run after each TPU capture")
    args = ap.parse_args()

    captures = 0
    while captures < args.max_captures:
        # Never contend with an already-running bench (e.g. the driver's
        # round-end capture) for the single chip — both would degrade.
        # argv-precise: a plain `pgrep -f bench.py` also matches unrelated
        # processes that merely MENTION bench.py in an argument string.
        busy = _bench_running()
        if busy:
            print(f"[{time.strftime('%H:%M:%S')}] bench already running; "
                  "standing down", flush=True)
        elif tunnel_alive():
            print(f"[{time.strftime('%H:%M:%S')}] tunnel ALIVE — capturing",
                  flush=True)
            trace = REPO / f"BENCH_TRACE_{time.strftime('%Y%m%d_%H%M%S')}.jsonl"
            rec = run_bench(args.bench_timeout, trace)
            if rec is not None and rec.get("backend") == "tpu":
                captures += 1
                print(f"[{time.strftime('%H:%M:%S')}] capture {captures}: "
                      f"value={rec.get('value')} "
                      f"vs_baseline={rec.get('vs_baseline')} "
                      f"source={rec.get('value_source')}", flush=True)
                print(f"[{time.strftime('%H:%M:%S')}] stages: "
                      f"{_stage_line(trace)}", flush=True)
                if args.follow:
                    try:
                        subprocess.run(args.follow, shell=True,
                                       timeout=2 * args.bench_timeout)
                    except subprocess.TimeoutExpired:
                        print(f"[{time.strftime('%H:%M:%S')}] follow "
                              "command timed out", flush=True)
            else:
                print(f"[{time.strftime('%H:%M:%S')}] capture fell back to "
                      f"CPU or failed; will retry", flush=True)
        else:
            print(f"[{time.strftime('%H:%M:%S')}] tunnel dark", flush=True)
        time.sleep(args.interval)
    print("done: capture budget reached", flush=True)


if __name__ == "__main__":
    main()
