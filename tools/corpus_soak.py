#!/usr/bin/env python3
"""Corpus-scale soak: a multi-GB PacBio-class BAM through the sharded engines.

The reference's correctness story rests on ~20 TB of corpus runs
(reference docs/benchmarks.md:5-15); this repo's equivalent evidence is
synthesized corpora validated end-to-end. This soak builds (or reuses) a
multi-GB long-read BAM whose ultra records exceed the streaming halo —
the regime where hadoop-bam mis-split GiaB PacBio data
(docs/benchmarks.md:24-38) — and validates it through the PRODUCTION
sharded paths on the virtual 8-device CPU mesh:

1. ``count_reads_sharded``  == the synth manifest's exact read count;
2. ``index_records`` (sequential truth walk) → ``check_bam_sharded``
   vs that sidecar == zero false positives / zero false negatives at
   every uncompressed position.

Writes one JSON line to ``CORPUS_SOAK.jsonl`` at the repo root.

Usage: python tools/corpus_soak.py [gigabytes]   (default 4)
"""

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from spark_bam_tpu.core.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import jax  # noqa: E402

from spark_bam_tpu.bam.index_records import index_records  # noqa: E402
from spark_bam_tpu.benchmarks.synth import ensure_longread_bam  # noqa: E402
from spark_bam_tpu.core.config import Config  # noqa: E402
from spark_bam_tpu.parallel.mesh import make_mesh  # noqa: E402
from spark_bam_tpu.parallel.stream_mesh import (  # noqa: E402
    check_bam_sharded,
    count_reads_sharded,
)


def main():
    gb = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    t0 = time.time()
    path, manifest = ensure_longread_bam(gb << 30, seed=11)
    synth_s = time.time() - t0
    entry = {
        "ts": time.time(), "file": str(path), "gb": gb,
        "reads": manifest["reads"],
        "compressed_bytes": path.stat().st_size,
        "synth_s": round(synth_s, 1),
    }

    mesh = make_mesh(jax.devices("cpu")[:8])
    cfg = Config()

    t0 = time.time()
    stats: dict = {}
    count = count_reads_sharded(path, cfg, mesh=mesh, stats_out=stats)
    entry["count_s"] = round(time.time() - t0, 1)
    entry["count"] = count
    entry["count_ok"] = count == manifest["reads"]
    entry["count_stats"] = stats

    t0 = time.time()
    sidecar, n_indexed = index_records(path)
    entry["index_records_s"] = round(time.time() - t0, 1)
    entry["indexed_records"] = n_indexed

    t0 = time.time()
    cb = check_bam_sharded(path, cfg, mesh=mesh, records_path=sidecar)
    entry["check_bam_s"] = round(time.time() - t0, 1)
    entry["check_bam"] = {
        k: int(cb[k]) for k in
        ("true_positives", "false_positives", "false_negatives", "positions")
    }
    entry["check_ok"] = (
        cb["false_positives"] == 0 and cb["false_negatives"] == 0
        and cb["true_positives"] == manifest["reads"]
    )

    entry["ok"] = bool(entry["count_ok"] and entry["check_ok"])
    print(json.dumps(entry), flush=True)
    with open(REPO / "CORPUS_SOAK.jsonl", "a") as f:
        f.write(json.dumps(entry) + "\n")
    sys.exit(0 if entry["ok"] else 1)


if __name__ == "__main__":
    main()
