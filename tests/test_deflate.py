"""Device-side BGZF compression (spark_bam_tpu/compress/): member
builders, kernel/host byte parity, codec demotion, writer round-trips,
rewrite sidecars + warm loads, the serve ``rewrite`` op, the columnar
``deflate`` codec, and fuzz-consumer cleanliness on device-written
files. docs/design.md, "The write path"."""

import os
import struct
import zlib

import numpy as np
import pytest

from spark_bam_tpu import obs
from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bam.writer import (
    BGZF_EOF,
    compress_block,
    write_bam_result,
)
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.compress.codec import (
    DeviceDeflateCodec,
    HostZlibCodec,
    encode_zlib_stream,
    make_codec,
)
from spark_bam_tpu.compress.config import DeflateConfig
from spark_bam_tpu.compress.huffman import (
    MAX_STORED_PAYLOAD,
    fixed_member,
    fixed_pack,
    stored_member,
    zlib_member,
    zlib_stream,
)
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.guard import LimitExceeded
from tests.bam_factories import random_bam

pytestmark = pytest.mark.deflate

RNG = np.random.default_rng(0xDEF1A7E)

#: Shared by every payload-level test: empty, tiny, text (every byte
#: <144 — the fixed alphabet's 8-bit half), binary (9-bit bytes mixed
#: in), and both sides of the stored-member boundary.
PAYLOADS = {
    "empty": b"",
    "one": b"\x00",
    "text": bytes(RNG.integers(32, 127, 5000, dtype=np.uint8)),
    "binary": RNG.integers(0, 256, 4000, dtype=np.uint8).tobytes(),
    "runs": b"ACGT" * 4000,
    "boundary": RNG.integers(0, 256, MAX_STORED_PAYLOAD,
                             dtype=np.uint8).tobytes(),
}


def gunzip_member(member: bytes) -> bytes:
    """Decode one complete BGZF member with stdlib zlib (the external
    referee — never our own reader)."""
    d = zlib.decompressobj(31)
    out = d.decompress(member)
    assert d.eof and not d.unconsumed_tail
    return out


def member_fields(member: bytes):
    """(BSIZE+1, CRC32, ISIZE) from the BGZF framing."""
    bsize = struct.unpack("<H", member[16:18])[0] + 1
    crc, isize = struct.unpack("<II", member[-8:])
    return bsize, crc, isize


@pytest.fixture(scope="module")
def src_bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("deflate") / "src.bam")
    random_bam(path, seed=77, n_records=(400, 401))
    return path


def read_back(path):
    """(header_text, [(Pos, encoded_record)]) via our own reader."""
    with open_channel(path) as ch:
        rs = RecordStream.open(ch)
        return rs.header.text, [(pos, rec.encode()) for pos, rec in rs]


# -------------------------------------------------------------- config


def test_deflate_config_parse():
    cfg = DeflateConfig.parse("mode=fixed,level=4,lanes=8,device=off")
    assert (cfg.mode, cfg.level, cfg.lanes, cfg.device) == (
        "fixed", 4, 8, "off")
    assert DeflateConfig.parse("stored").mode == "stored"
    assert DeflateConfig.parse("").mode == "off"
    assert not DeflateConfig.parse("").enabled
    assert DeflateConfig.parse("mode=stored").deterministic
    assert not DeflateConfig.parse("mode=auto").deterministic
    for bad in ("mode=lz77", "level=10", "lanes=0", "device=maybe",
                "nope=1"):
        with pytest.raises(ValueError):
            DeflateConfig.parse(bad)


def test_deflate_env_reaches_config(monkeypatch):
    monkeypatch.setenv("SPARK_BAM_DEFLATE", "mode=stored,lanes=4")
    cfg = Config.from_env()
    assert cfg.deflate == "mode=stored,lanes=4"
    assert cfg.deflate_config.mode == "stored"
    assert cfg.deflate_config.lanes == 4


# ------------------------------------------------------ member builders


@pytest.mark.parametrize("name", list(PAYLOADS))
def test_stored_member_roundtrip(name):
    p = PAYLOADS[name]
    m = stored_member(p)
    assert gunzip_member(m) == p
    bsize, crc, isize = member_fields(m)
    assert bsize == len(m)
    assert crc == zlib.crc32(p)
    assert isize == len(p)


@pytest.mark.parametrize("name", list(PAYLOADS))
def test_fixed_member_roundtrip(name):
    p = PAYLOADS[name]
    m = fixed_member(p)
    assert gunzip_member(m) == p
    _, crc, isize = member_fields(m)
    assert crc == zlib.crc32(p)
    assert isize == len(p)


@pytest.mark.parametrize("name", list(PAYLOADS))
def test_fixed_pack_is_valid_deflate(name):
    p = PAYLOADS[name]
    packed, total_bits = fixed_pack(p)
    assert len(packed) == (total_bits + 7) // 8
    assert zlib.decompress(packed, wbits=-15) == p


def test_fixed_wins_on_text_stored_on_binary():
    # Every text byte is an 8-bit code, so fixed beats stored's 5-byte
    # framing on any text payload past ~40 bytes; high-entropy binary
    # mixes in 9-bit codes and stored wins — zlib's own policy.
    text, binary = PAYLOADS["text"], PAYLOADS["boundary"]
    assert len(fixed_member(text)) < len(stored_member(text))
    assert fixed_member(binary) == stored_member(binary)


def test_member_size_limits():
    over = b"x" * (MAX_STORED_PAYLOAD + 1)
    for builder in (stored_member, fixed_member):
        with pytest.raises(LimitExceeded):
            builder(over)
    # compress_block's zlib body may still fit an oversize-but-
    # compressible payload; only one that needs the stored fallback is a
    # true LimitExceeded.
    incompressible = RNG.integers(
        0, 256, MAX_STORED_PAYLOAD + 1, dtype=np.uint8).tobytes()
    with pytest.raises(LimitExceeded):
        compress_block(incompressible)


def test_compress_block_stored_fallback_exactly_fits():
    # Incompressible max-size payload: zlib output would overflow BSIZE;
    # the stored fallback lands on the format's exact 64 KiB ceiling.
    p = PAYLOADS["boundary"]
    m = compress_block(p)
    assert len(m) == 0x10000
    assert member_fields(m)[0] == 0x10000  # BSIZE field is 0xFFFF
    assert gunzip_member(m) == p
    # A compressible payload still takes the zlib body.
    assert compress_block(b"a" * 1000) == zlib_member(b"a" * 1000)


# ------------------------------------------------------- device kernels


def test_kernel_crc32_parity():
    from spark_bam_tpu.compress import kernels as k
    import jax.numpy as jnp

    payloads = [PAYLOADS["text"], b"", PAYLOADS["binary"],
                PAYLOADS["boundary"]]
    data, lengths, _ = k.pack_lanes(payloads)
    crc = np.asarray(k.crc32_lanes(jnp.asarray(data), jnp.asarray(lengths)))
    for i, p in enumerate(payloads):
        assert int(crc[i]) == zlib.crc32(p), f"lane {i}"


def test_kernel_fixed_pack_parity():
    from spark_bam_tpu.compress import kernels as k
    import jax.numpy as jnp

    payloads = [PAYLOADS["text"], PAYLOADS["runs"], b"", b"\xff" * 1000]
    data, lengths, _ = k.pack_lanes(payloads)
    packed, total_bits, crc = k.deflate_fixed_lanes(
        jnp.asarray(data), jnp.asarray(lengths))
    packed, total_bits = np.asarray(packed), np.asarray(total_bits)
    for i, p in enumerate(payloads):
        want, want_bits = fixed_pack(p)
        assert int(total_bits[i]) == want_bits, f"lane {i}"
        assert packed[i, : len(want)].tobytes() == want, f"lane {i}"
        assert int(np.asarray(crc)[i]) == zlib.crc32(p)


# -------------------------------------------------------------- codecs


def test_make_codec_selection():
    assert isinstance(make_codec(None), HostZlibCodec)
    assert isinstance(make_codec(""), HostZlibCodec)
    assert isinstance(make_codec("mode=off"), HostZlibCodec)
    assert isinstance(make_codec("mode=stored"), DeviceDeflateCodec)
    assert make_codec("mode=off", level=1).level == 1


@pytest.mark.parametrize("mode", ["stored", "fixed", "auto"])
def test_codec_members_decode(mode):
    codec = DeviceDeflateCodec(DeflateConfig.parse(f"mode={mode}"))
    payloads = [PAYLOADS["text"], PAYLOADS["binary"], b"z"]
    members = codec.encode_blocks(payloads)
    assert [gunzip_member(m) for m in members] == payloads


@pytest.mark.parametrize("mode", ["stored", "fixed"])
def test_device_off_is_byte_identical(mode):
    on = DeviceDeflateCodec(DeflateConfig.parse(f"mode={mode}"))
    off = DeviceDeflateCodec(DeflateConfig.parse(f"mode={mode},device=off"))
    payloads = [PAYLOADS["text"], PAYLOADS["binary"], PAYLOADS["boundary"]]
    assert on.encode_blocks(payloads) == off.encode_blocks(payloads)


@pytest.mark.parametrize("mode", ["stored", "fixed"])
def test_demote_to_host_is_byte_identical(mode, monkeypatch):
    """A device failure mid-batch demotes that window to host with
    byte-identical output (the host builders are the byte authority)."""
    from spark_bam_tpu.compress import kernels as k

    payloads = [PAYLOADS["text"], PAYLOADS["binary"]]
    want = DeviceDeflateCodec(
        DeflateConfig.parse(f"mode={mode},device=off")).encode_blocks(payloads)

    def boom(*a, **kw):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(k, "crc32_lanes", boom)
    monkeypatch.setattr(k, "deflate_fixed_lanes", boom)
    obs.shutdown()
    reg = obs.configure()
    try:
        codec = DeviceDeflateCodec(DeflateConfig.parse(f"mode={mode}"))
        got = codec.encode_blocks(payloads)
        counters = {c["name"]: c["value"]
                    for c in reg.snapshot()["counters"]}
    finally:
        obs.shutdown()
    assert got == want
    assert counters.get("deflate.demotions", 0) >= 1


def test_limit_exceeded_never_demotes():
    codec = DeviceDeflateCodec(DeflateConfig.parse("mode=stored"))
    with pytest.raises(LimitExceeded):
        codec.dispatch([b"x" * (MAX_STORED_PAYLOAD + 1)])


# ------------------------------------------------------------- writer


WRITE_SPECS = ["", "mode=stored", "mode=fixed", "mode=auto",
               "mode=fixed,lanes=3", "mode=stored,device=off"]


@pytest.mark.parametrize("spec", WRITE_SPECS)
def test_write_bam_roundtrip(spec, src_bam, tmp_path):
    header, want = read_back(src_bam)
    out = str(tmp_path / "out.bam")
    with open_channel(src_bam) as ch:
        rs = RecordStream.open(ch)
        res = write_bam_result(
            out, rs.header, (rec for _, rec in rs),
            block_payload=0x4000, deflate=spec,
        )
    got_header, got = read_back(out)
    assert got_header == header
    assert [r for _, r in got] == [r for _, r in want]
    assert res.count == len(want)
    data = open(out, "rb").read()
    assert data.endswith(BGZF_EOF)
    assert res.bytes_out == len(data)
    # The writer's in-memory block table IS what a scan reads back.
    with open_channel(out) as ch:
        assert res.blocks == list(MetadataStream(ch))
    # Every member independently valid, footer fields truthful.
    off = 0
    flat = b""
    for m in res.blocks:
        member = data[m.start: m.start + m.compressed_size]
        payload = gunzip_member(member)
        _, crc, isize = member_fields(member)
        assert crc == zlib.crc32(payload) and isize == len(payload)
        assert m.start == off and m.uncompressed_size == len(payload)
        off += m.compressed_size
        flat += payload
    assert data[off:] == BGZF_EOF
    # record_flats index the uncompressed stream exactly.
    for f, (_, rec) in zip(res.record_flats, want):
        assert flat[f: f + len(rec)] == rec


def test_write_bam_empty_records(tmp_path, src_bam):
    out = str(tmp_path / "empty.bam")
    with open_channel(src_bam) as ch:
        res = write_bam_result(out, RecordStream.open(ch).header, [],
                               deflate="mode=fixed")
    assert res.count == 0 and len(res.blocks) >= 1
    _, got = read_back(out)
    assert got == []


def test_write_is_atomic_on_failure(tmp_path, src_bam):
    out = str(tmp_path / "crash.bam")

    def exploding():
        with open_channel(src_bam) as ch:
            for i, (_, rec) in enumerate(RecordStream.open(ch)):
                if i == 50:
                    raise RuntimeError("mid-write crash")
                yield rec

    with open_channel(src_bam) as ch:
        header = RecordStream.open(ch).header
    with pytest.raises(RuntimeError):
        write_bam_result(out, header, exploding())
    assert not os.path.exists(out)
    assert not [f for f in os.listdir(tmp_path) if f.startswith("crash")]


def test_stored_and_fixed_record_parity(src_bam, tmp_path):
    """Different specs, same decoded stream — the format-level property
    that lets ``--deflate`` change without touching any reader."""
    outs = {}
    for spec in ("mode=stored", "mode=fixed"):
        out = str(tmp_path / f"{spec[5:]}.bam")
        with open_channel(src_bam) as ch:
            rs = RecordStream.open(ch)
            write_bam_result(out, rs.header, (rec for _, rec in rs),
                             deflate=spec)
        outs[spec] = read_back(out)
    assert outs["mode=stored"] == outs["mode=fixed"]


# ---------------------------------------------------- rewrite + sidecars


def test_rewrite_sidecars_and_warm_load(src_bam, tmp_path, monkeypatch):
    from spark_bam_tpu.bgzf.index_blocks import format_block_line
    from spark_bam_tpu.cli.rewrite import rewrite_bam
    from spark_bam_tpu.load.api import split_starts

    monkeypatch.setenv("SPARK_BAM_CACHE_DIR", str(tmp_path / "cache"))
    out = str(tmp_path / "out.bam")
    cfg = Config(split_size=64 << 10, cache="readwrite")
    res = rewrite_bam(src_bam, out, deflate="mode=fixed", index=True,
                      config=cfg)
    assert sorted(res.sidecars) == ["blocks", "records", "sbi"]
    # .blocks matches a scan of the output byte-for-byte.
    with open_channel(out) as ch:
        scan = [format_block_line(m) for m in MetadataStream(ch)]
    assert open(res.sidecars["blocks"]).read().splitlines() == scan
    assert len(scan) == res.n_blocks
    # Live truth vs the synthesized plan: identical splits, and the warm
    # load does ZERO checker invocations — the acceptance gate.
    cold = split_starts(out, config=Config(split_size=64 << 10))
    obs.shutdown()
    reg = obs.configure()
    try:
        warm = split_starts(out, config=Config(split_size=64 << 10,
                                               cache="read"))
        counters = {c["name"]: c["value"]
                    for c in reg.snapshot()["counters"]}
    finally:
        obs.shutdown()
    assert warm == cold
    assert counters.get("load.split_resolutions", 0) == 0
    assert counters.get("cache.hits") == 1


def test_rewrite_records_match_source(src_bam, tmp_path):
    from spark_bam_tpu.cli.rewrite import rewrite_bam

    out = str(tmp_path / "re.bam")
    res = rewrite_bam(src_bam, out, block_payload=0x2000,
                      deflate="mode=stored")
    _, src = read_back(src_bam)
    _, got = read_back(out)
    assert [r for _, r in got] == [r for _, r in src]
    assert res.count == len(src)
    # Re-blocking actually re-blocked: different payload size, different
    # member layout than the source.
    with open_channel(out) as ch:
        blocks = list(MetadataStream(ch))
    assert all(m.uncompressed_size <= 0x2000 for m in blocks)


@pytest.mark.fuzz
def test_fuzz_consumers_clean_on_device_written(src_bam, tmp_path):
    """The mutation-fuzz consumers (strict AND tolerant) read a
    device-written file clean — device output joins the fuzz corpus's
    idea of well-formed input."""
    from spark_bam_tpu.cli.rewrite import rewrite_bam
    from spark_bam_tpu.tools.fuzz_decode import _consume_bam, _run_case

    out = str(tmp_path / "fz.bam")
    res = rewrite_bam(src_bam, out, deflate="mode=fixed")
    for tolerant in (False, True):
        case = _run_case(_consume_bam, out, tolerant)
        assert case["outcome"] == "clean", case
    assert _consume_bam(out, tolerant=False) == res.count


# ------------------------------------------------------------ serve op


@pytest.mark.serve
def test_serve_rewrite_op(src_bam, tmp_path, monkeypatch):
    from spark_bam_tpu.serve.protocol import OPS, decode_request
    from spark_bam_tpu.serve.service import SplitService

    assert "rewrite" in OPS
    monkeypatch.setenv("SPARK_BAM_CACHE_DIR", str(tmp_path / "cache"))
    out = str(tmp_path / "served.bam")
    svc = SplitService(Config(
        serve="window=64KB,halo=8KB,batch=8,tick=5,workers=4",
        cache="write"))
    try:
        req = decode_request(
            '{"op":"rewrite","id":1,"path":"%s","out":"%s",'
            '"deflate":"mode=fixed","index":true}' % (src_bam, out))
        resp = svc.submit(req).result(timeout=120)
        assert resp["ok"], resp
        assert resp["count"] == len(read_back(src_bam)[1])
        assert os.path.exists(out)
        assert sorted(resp["sidecars"]) == ["blocks", "records", "sbi"]
        # Typed errors, not crashes.
        bad = svc.submit({"op": "rewrite", "id": 2, "path": src_bam,
                          "out": out, "deflate": "mode=bogus"}
                         ).result(timeout=30)
        assert not bad["ok"] and bad["error"] == "ProtocolError"
        noout = svc.submit({"op": "rewrite", "id": 3, "path": src_bam}
                           ).result(timeout=30)
        assert not noout["ok"] and noout["error"] == "ProtocolError"
    finally:
        svc.close()


# ------------------------------------------------- zlib streams/columnar


@pytest.mark.parametrize("name", ["empty", "text", "binary", "boundary"])
def test_zlib_stream_roundtrip_and_parity(name):
    raw = PAYLOADS[name] * (3 if name != "empty" else 1)
    host = zlib_stream(raw)
    assert zlib.decompress(host) == raw
    assert encode_zlib_stream(raw, spec="mode=fixed") == host
    assert encode_zlib_stream(raw, spec="mode=fixed,device=off") == host
    assert encode_zlib_stream(raw, spec="") == host


def test_columnar_deflate_codec_roundtrip():
    from spark_bam_tpu.columnar.config import ColumnarConfig
    from spark_bam_tpu.columnar.native import _decode_buffer, _encode_buffer

    assert ColumnarConfig.parse("codec=deflate").codec == "deflate"
    with pytest.raises(ValueError):
        ColumnarConfig.parse("codec=lz4")
    for raw in (b"", PAYLOADS["text"].ljust(200_000, b"n"),
                PAYLOADS["binary"]):
        buf = _encode_buffer(raw, "deflate", 6)
        got, p = _decode_buffer(memoryview(buf), 0)
        assert got == raw and p == len(buf)
