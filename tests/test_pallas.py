"""Pallas field-check kernel vs the NumPy engine (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_bam_tpu.bam.header import contig_lengths
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.vectorized import compute_flags
from spark_bam_tpu.tpu.pallas_kernels import (
    FIELD_CHECK_BITS,
    HALO,
    TILE,
    field_check_flags,
)


def test_field_check_kernel_matches_numpy(bam2):
    flat = flatten_file(bam2)
    lens_list = contig_lengths(bam2).lengths_list()
    lengths = np.zeros(128, dtype=np.int32)
    lengths[: len(lens_list)] = lens_list

    w = 4 * TILE
    padded = np.zeros(w + HALO, dtype=np.uint8)
    padded[:w] = flat.data[:w]

    got = np.asarray(
        field_check_flags(
            jnp.asarray(padded),
            jnp.asarray(lengths),
            jnp.asarray(np.array([len(lens_list)], dtype=np.int32)),
            interpret=True,
        )
    )

    # The NumPy engine on the *same* padded buffer (identical zero halo),
    # restricted to the kernel's neighborhood-check bits.
    ref = compute_flags(padded, np.array(lens_list, np.int32))
    want = ref.F[:w] & FIELD_CHECK_BITS
    np.testing.assert_array_equal(got & FIELD_CHECK_BITS, want)
