"""Pallas full flag kernel: parity, wiring, CLI reachability."""

import numpy as np

import jax.numpy as jnp

from spark_bam_tpu.bam.header import contig_lengths
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.tpu.pallas_kernels import TILE


def test_full_flags_kernel_matches_xla_flag_pass(bam2):
    """All 19 bits: the Pallas full kernel must equal the XLA flag pass
    (the component it replaces under backend=pallas) bit-for-bit,
    including EOF-dependent bits at a mid-buffer valid count."""
    from spark_bam_tpu.tpu import checker as tc
    from spark_bam_tpu.tpu.pallas_kernels import FULL_HALO, full_check_flags

    assert FULL_HALO == tc.PAD  # one padded buffer serves both paths

    flat = flatten_file(bam2)
    lens_list = contig_lengths(bam2).lengths_list()
    lengths = np.zeros(128, dtype=np.int32)
    lengths[: len(lens_list)] = lens_list

    w = 4 * TILE
    padded = np.zeros(w + tc.PAD, dtype=np.uint8)
    padded[: w + tc.PAD] = flat.data[: w + tc.PAD]

    for n in (w, w - 12345):
        want = tc._compute_flags(
            jnp.asarray(padded), jnp.asarray(lengths),
            jnp.int32(len(lens_list)), jnp.int32(n),
        )
        got = full_check_flags(
            jnp.asarray(padded), jnp.asarray(lengths),
            jnp.asarray(np.array([len(lens_list)], dtype=np.int32)),
            jnp.asarray(np.array([n], dtype=np.int32)),
            interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=f"n={n}"
        )


def test_pallas_backend_checker_parity(bam2):
    """backend=pallas wiring: TpuChecker with the Pallas flag pass must
    produce the same verdicts as the XLA flag pass on real data."""
    from spark_bam_tpu.tpu.checker import TpuChecker

    flat = flatten_file(bam2)
    lens = np.array(contig_lengths(bam2).lengths_list(), dtype=np.int32)
    buf = flat.data[: 256 << 10]

    xla = TpuChecker(lens, window=1 << 18, halo=1 << 16)
    pal = TpuChecker(lens, window=1 << 18, halo=1 << 16, flags_impl="pallas")
    a = xla.check_buffer(buf, at_eof=True)
    b = pal.check_buffer(buf, at_eof=True)
    np.testing.assert_array_equal(a.verdict, b.verdict)
    np.testing.assert_array_equal(a.fail_mask, b.fail_mask)
    np.testing.assert_array_equal(a.reads_parsed, b.reads_parsed)


def test_pallas_backend_cli_reachable(tmp_path, monkeypatch):
    """Explicit SPARK_BAM_BACKEND values must flow through the CLI to the
    device engines (tpu → jit kernel, pallas → Pallas flag pass; on this
    CI backend both run on the virtual-CPU jax platform) and reproduce the
    numpy backend's output byte-for-byte (VERDICT r3 weak #5: the device
    engine must be CLI-reachable in tests)."""
    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.bam.index_records import index_records
    from spark_bam_tpu.cli.main import main
    from spark_bam_tpu.core.pos import Pos

    path = tmp_path / "tiny.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 1_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000000\n",
    )
    write_bam(
        path, header,
        (
            BamRecord(
                ref_id=0, pos=10 + 7 * i, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"t{i}", cigar=[(20, 0)], seq="A" * 20,
                qual=bytes([30]) * 20,
            )
            for i in range(200)
        ),
    )
    index_records(path)

    outs = {}
    for backend in ("numpy", "tpu", "pallas"):
        monkeypatch.setenv("SPARK_BAM_BACKEND", backend)
        out = tmp_path / f"out_{backend}.txt"
        assert main(["check-bam", "-s", str(path), "-o", str(out)]) == 0
        outs[backend] = out.read_text()
    assert outs["pallas"] == outs["numpy"] == outs["tpu"]
    assert "All calls matched!" in outs["pallas"]


def test_pallas_streaming_path(tmp_path):
    """backend=pallas must reach the streaming production path too
    (StreamChecker builds its kernel from config.backend)."""
    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.core.pos import Pos
    from spark_bam_tpu.tpu.stream_check import count_reads_streaming

    path = tmp_path / "tiny.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 1_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000000\n",
    )
    write_bam(
        path, header,
        (
            BamRecord(
                ref_id=0, pos=10 + 7 * i, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"t{i}", cigar=[(20, 0)], seq="A" * 20,
                qual=bytes([30]) * 20,
            )
            for i in range(200)
        ),
    )
    assert count_reads_streaming(path, Config(backend="pallas")) == 200
