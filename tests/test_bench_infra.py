"""bench.py's parent-side plumbing: the pieces whose misbehavior has cost
whole benchmark rounds (stage forensics, history append). Pure-host tests —
no device work, no child processes."""

import json

import bench


def test_forensics_no_windows():
    assert bench._e2e_forensics(["start", "backend_ok:tpu", "compiled"]) == (
        "no e2e window completed (last stage: compiled)"
    )


def test_forensics_skips_completed_legs():
    """A finished leg's window markers must not be blamed for a later leg's
    stall (the r05 live artifact attributed the 1 GB warm-up wedge to the
    completed e2e_quick)."""
    stages = [
        "e2e_win:e2e_quick:6:180904186:180904186:28.0s",
        "e2e_quick_done",
        "e2e_plan",
        "e2e_warm",
    ]
    out = bench._e2e_forensics(stages, {"e2e_quick", "steady"})
    assert out == "no e2e window completed (last stage: e2e_warm)"


def test_forensics_last_window():
    stages = [
        "e2e_plan",
        "e2e_win:e2e:8:268435456:2883176122:41.2s",
        "e2e_win:e2e:16:536870912:2883176122:83.9s",
    ]
    assert bench._e2e_forensics(stages) == (
        "e2e stalled after window 16, 536870912/2883176122 positions in 83.9s"
    )


def test_forensics_projection_abort():
    stages = [
        "e2e_win:e2e:8:268435456:2883176122:41.2s",
        "e2e_projection:443s projected > 420s budget (4/395 in 4s)",
    ]
    out = bench._e2e_forensics(stages)
    assert out.startswith(
        "projection-aborted (443s projected > 420s budget (4/395 in 4s))"
    )
    assert "stalled after window 8" in out


def _fake_synth(tmp_path, monkeypatch):
    """Stub the synth-BAM builder + CPU baselines so _main_measure's
    aggregation runs without device work or gigabyte files."""
    import spark_bam_tpu.benchmarks.synth as synth

    big = tmp_path / "big.bam"
    big.write_bytes(b"x")
    manifest = {
        "compressed_bytes": 1,
        "uncompressed_bytes": 3,
        "reads": 42,
    }
    monkeypatch.setattr(
        synth, "ensure_big_bam", lambda n, **kw: (big, manifest)
    )
    monkeypatch.setattr(bench, "baselines", lambda *a, **kw: (276508.0, 238975767.0))
    monkeypatch.setattr(bench, "cpu_e2e_rate", lambda *a, **kw: 231908717.0)
    # The resident/inflate extra children are real subprocesses; stub them
    # out (their aggregation is covered by the *_merges_legs tests).
    monkeypatch.setattr(
        bench, "_run_extra_child", lambda *a, **kw: ({}, [], None)
    )
    # _main_measure's fixture preamble (flatten/contig scan) is real but
    # cheap on the 600 KB fixture.


def _leg(pps, inflate, backend="tpu", count_ok=True, **kw):
    return {
        "pps": pps, "reads_per_s": pps / 640.0, "wall_s": 1.0,
        "boundaries": 42, "expected_reads": 42, "count_ok": count_ok,
        "backend": backend, "window_mb": 32, "inflate": inflate,
        "positions": int(pps), "file_bytes": 1 << 30, **kw,
    }


def test_headline_is_e2e_on_device_runs(tmp_path, monkeypatch):
    """A TPU run's value/vs_baseline come from the completed big-file e2e
    leg (the north star is e2e ≥ 10× native CPU eager), with the inflate
    A/B recorded per mode; steady stays as its own field."""
    _fake_synth(tmp_path, monkeypatch)
    results = {
        "steady": {
            "steady_pps": 9.0e10, "steady_fused_pps": 1.0e11,
            "transfer_pps": 1.28e9, "backend": "tpu", "window_mb": 32,
        },
        "e2e": _leg(3.1e9, "device"),
        "e2e_alt": _leg(2.5e9, "host"),
        "e2e_quick": _leg(2.9e9, "host", file_bytes=64 << 20),
    }
    monkeypatch.setattr(
        bench, "_device_ladder", lambda *a: (results, [], [], [])
    )
    record = {"value": 0, "vs_baseline": 0}
    bench._main_measure(record, [], [])
    assert record["value"] == round(3.1e9)
    assert record["vs_baseline"] == round(3.1e9 / 238975767.0, 2)
    assert record["value_source"] == "e2e_device_inflate"
    assert record["e2e_device_inflate_pps"] == round(3.1e9)
    assert record["e2e_host_inflate_pps"] == round(2.5e9)
    assert record["steady_pps"] == round(9.0e10)
    assert record["e2e_quick_pps"] == round(2.9e9)
    assert record["backend"] == "tpu"


def test_headline_quick_leg_stands_in(tmp_path, monkeypatch):
    """When only the quick e2e landed (child killed mid-big-leg), it is
    still a device e2e artifact and becomes the headline."""
    _fake_synth(tmp_path, monkeypatch)
    results = {"e2e_quick": _leg(2.0e9, "host", file_bytes=64 << 20)}
    monkeypatch.setattr(
        bench, "_device_ladder", lambda *a: (results, [], [], [])
    )
    record = {"value": 0, "vs_baseline": 0}
    errors = []
    bench._main_measure(record, [], errors)
    assert record["value"] == round(2.0e9)
    assert record["value_source"] == "e2e_quick_host_inflate"
    # the big leg's absence is still flagged for forensics
    assert any("e2e" in e for e in errors)


def test_headline_cpu_fallback_stays_steady(tmp_path, monkeypatch):
    """The CPU-backend fallback keeps the steady kernel number as value
    (no device e2e exists) and never claims an e2e source."""
    _fake_synth(tmp_path, monkeypatch)
    monkeypatch.setattr(bench, "_device_ladder", lambda *a: ({}, [], [], [{"window_mb": 32, "skipped": "timeout", "last_stage": None}]))
    cpu_results = {
        "steady": {
            "steady_pps": 1.25e7, "steady_fused_pps": 1.38e7,
            "transfer_pps": 1.2e7, "backend": "cpu", "window_mb": 8,
        },
    }
    monkeypatch.setattr(
        bench, "_run_child", lambda *a, **kw: (cpu_results, [], None)
    )
    record = {"value": 0, "vs_baseline": 0}
    errors = []
    bench._main_measure(record, [], errors)
    assert record["value"] == round(1.25e7)
    assert record["value_source"] == "steady_kernel"
    assert any("TPU unavailable" in e for e in errors)
    assert record["ladder_skips"] == [
        {"window_mb": 32, "skipped": "timeout", "last_stage": None}
    ]


def test_inflate_child_merges_legs(tmp_path, monkeypatch):
    """The isolated --child-inflate process's e2e_alt merges into the A/B
    fields and competes for the headline like any big-file e2e leg."""
    _fake_synth(tmp_path, monkeypatch)
    results = {
        "steady": {
            "steady_pps": 9.0e10, "steady_fused_pps": None,
            "transfer_pps": 1.28e9, "backend": "tpu", "window_mb": 32,
        },
        "e2e": _leg(2.5e9, "host"),
    }
    monkeypatch.setattr(bench, "_device_ladder", lambda *a: (results, [], [], []))

    def fake_extra(mode, *a, **kw):
        if mode == "inflate":
            return {"e2e_alt": _leg(3.4e9, "device")}, ["start"], None
        return {}, [], None

    monkeypatch.setattr(bench, "_run_extra_child", fake_extra)
    record = {"value": 0, "vs_baseline": 0}
    bench._main_measure(record, [], [])
    assert record["e2e_device_inflate_pps"] == round(3.4e9)
    assert record["e2e_host_inflate_pps"] == round(2.5e9)
    assert record["value"] == round(3.4e9)
    assert record["value_source"] == "e2e_device_inflate"


def test_headline_resident_leg_competes(tmp_path, monkeypatch):
    """e2e_resident (one dispatch per chunk) is a whole-workload leg: when
    it is the fastest completed big-file e2e it becomes the headline, with
    its own decomposition fields recorded."""
    _fake_synth(tmp_path, monkeypatch)
    results = {"e2e": _leg(2.5e9, "host")}
    monkeypatch.setattr(bench, "_device_ladder", lambda *a: (results, [], [], []))

    def fake_extra(mode, *a, **kw):
        if mode == "resident":
            return (
                {"e2e_resident": _leg(7.0e9, "host", mode="resident")},
                ["start"], None,
            )
        return {}, [], None

    monkeypatch.setattr(bench, "_run_extra_child", fake_extra)
    record = {"value": 0, "vs_baseline": 0}
    bench._main_measure(record, [], [])
    assert record["value"] == round(7.0e9)
    assert record["value_source"] == "e2e_resident_host_inflate"
    assert record["e2e_resident_pps"] == round(7.0e9)
    assert record["e2e_resident_count_ok"] is True


def test_history_append(tmp_path, monkeypatch, capsys):
    """main() with a missing fixture still prints its one JSON line and
    appends the same record to BENCH_HISTORY.jsonl next to bench.py."""
    monkeypatch.setattr(bench, "FIXTURE", tmp_path / "nope.bam")
    fake_file = tmp_path / "bench.py"
    fake_file.write_text("")
    monkeypatch.setattr(bench, "__file__", str(fake_file))
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["error"] == "fixture unavailable"
    hist = (tmp_path / "BENCH_HISTORY.jsonl").read_text().strip().splitlines()
    assert len(hist) == 1
    entry = json.loads(hist[0])
    assert entry["error"] == "fixture unavailable"
    assert "ts" in entry


def test_ladder_skips_when_probe_dead(monkeypatch):
    """A probe that never reaches backend_ok must skip the whole window
    ladder with one clear warning — not burn an init timeout per rung
    (the r05 window=32MB/16MB double-burn)."""
    calls = []

    def fake_child(args, timeout_s):
        calls.append(args)
        assert args == ["--child-probe"]
        return {}, ["start"], "timed out after 240s (last stage: start)"

    monkeypatch.setattr(bench, "_run_child", fake_child)
    results, stages, errors, _skips = bench._device_ladder("big.bam", 1, "q.bam", 1)
    assert results == {}
    assert len(calls) == 1  # probe only, no --child-all rungs
    assert any("skipping device window ladder" in e for e in errors)


def test_ladder_proceeds_past_healthy_probe(monkeypatch):
    calls = []

    def fake_child(args, timeout_s):
        calls.append(args)
        if args == ["--child-probe"]:
            return (
                {"probe": {"backend": "tpu"}},
                ["start", "backend_ok:tpu"], None,
            )
        return {"steady": {"pps": 1.0}}, ["start", "backend_ok:tpu"], None

    monkeypatch.setattr(bench, "_run_child", fake_child)
    results, _, errors, _skips = bench._device_ladder("big.bam", 1, "q.bam", 1)
    assert "steady" in results
    assert calls[0] == ["--child-probe"]
    assert calls[1][0] == "--child-all"
    assert not errors


def test_ladder_timeout_rungs_become_structured_skips(monkeypatch):
    """A rung that times out without landing a leg is a ladder fact, not a
    warning: it lands in the structured ``skips`` list (and from there in
    the record's ``ladder_skips``), keeping the errors field reserved for
    evidence someone must read."""

    def fake_child(args, timeout_s):
        if args == ["--child-probe"]:
            return (
                {"probe": {"backend": "tpu"}},
                ["start", "backend_ok:tpu"], None,
            )
        return {}, ["start", "backend_ok:tpu", "steady:warmup"], (
            "timeout after stages=['start', 'backend_ok:tpu']: wedged"
        )

    monkeypatch.setattr(bench, "_run_child", fake_child)
    results, _, errors, skips = bench._device_ladder("big.bam", 1, "q.bam", 1)
    assert results == {}
    assert len(skips) == len(bench.WINDOW_LADDER_MB)
    assert skips[0] == {
        "window_mb": bench.WINDOW_LADDER_MB[0], "skipped": "timeout",
        "last_stage": "steady:warmup",
    }
    # no free-text timeout warnings duplicate the structured record
    assert not any("timeout" in e for e in errors)


def test_ladder_probe_disabled_by_env(monkeypatch):
    """SB_BENCH_PROBE_S=0 removes the gate (escape hatch if the probe
    itself ever misbehaves)."""
    monkeypatch.setenv("SB_BENCH_PROBE_S", "0")
    calls = []

    def fake_child(args, timeout_s):
        calls.append(args)
        return {"steady": {"pps": 1.0}}, ["start", "backend_ok:tpu"], None

    monkeypatch.setattr(bench, "_run_child", fake_child)
    results, _, _, _skips = bench._device_ladder("big.bam", 1, "q.bam", 1)
    assert "steady" in results
    assert calls[0][0] == "--child-all"
