"""bench.py's parent-side plumbing: the pieces whose misbehavior has cost
whole benchmark rounds (stage forensics, history append). Pure-host tests —
no device work, no child processes."""

import json

import bench


def test_forensics_no_windows():
    assert bench._e2e_forensics(["start", "backend_ok:tpu", "compiled"]) == (
        "no e2e window completed"
    )


def test_forensics_last_window():
    stages = [
        "e2e_plan",
        "e2e_win:8:268435456:2883176122:41.2s",
        "e2e_win:16:536870912:2883176122:83.9s",
    ]
    assert bench._e2e_forensics(stages) == (
        "stalled after window 16, 536870912/2883176122 positions in 83.9s"
    )


def test_forensics_projection_abort():
    stages = [
        "e2e_win:8:268435456:2883176122:41.2s",
        "e2e_projection:443s projected > 420s budget (4/395 in 4s)",
    ]
    out = bench._e2e_forensics(stages)
    assert out.startswith(
        "projection-aborted (443s projected > 420s budget (4/395 in 4s))"
    )
    assert "stalled after window 8" in out


def test_history_append(tmp_path, monkeypatch, capsys):
    """main() with a missing fixture still prints its one JSON line and
    appends the same record to BENCH_HISTORY.jsonl next to bench.py."""
    monkeypatch.setattr(bench, "FIXTURE", tmp_path / "nope.bam")
    fake_file = tmp_path / "bench.py"
    fake_file.write_text("")
    monkeypatch.setattr(bench, "__file__", str(fake_file))
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["error"] == "fixture unavailable"
    hist = (tmp_path / "BENCH_HISTORY.jsonl").read_text().strip().splitlines()
    assert len(hist) == 1
    entry = json.loads(hist[0])
    assert entry["error"] == "fixture unavailable"
    assert "ts" in entry
