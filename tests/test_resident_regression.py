"""Regression coverage for the resident-mode worker crash (BENCH_r05
``e2e_resident_error``): ``count_reads_resident`` must complete and match
the streaming count — in-process on the CPU backend for tier-1, and
through the exact ``bench.py --child-resident … cpu`` child the bench
harness spawns, so the crash is reproducible in-harness rather than only
on a live TPU."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from spark_bam_tpu.core.config import Config
from spark_bam_tpu.native.build import load_native
from spark_bam_tpu.tpu.stream_check import StreamChecker

from tests.bam_factories import random_bam

pytestmark = pytest.mark.skipif(
    load_native() is None, reason="native runtime unavailable"
)

CFG = dict(window_uncompressed=128 << 10, halo=32 << 10)

BENCH = Path(__file__).resolve().parent.parent / "bench.py"


def _streaming_count(path, **cfg):
    return StreamChecker(
        path, Config(device_inflate=False, fused_count=False), **cfg
    ).count_reads()


def test_resident_matches_streaming_in_process(tmp_path):
    path = tmp_path / "r.bam"
    random_bam(path, 21, contigs=(("chr1", 5_000_000),), dup_rate=0.05)
    want = _streaming_count(path, **CFG)
    got = StreamChecker(path, Config(), **CFG).count_reads_resident(
        chunk_windows=4, first_chunk_windows=2
    )
    assert got == want


def test_resident_tiny_chunk_cap_still_exact(tmp_path):
    """A pathologically small ``resident_chunk_bytes`` (the r05 OOM fix
    knob at its floor) degrades chunk size, never correctness."""
    path = tmp_path / "tiny.bam"
    random_bam(path, 22, contigs=(("chr1", 5_000_000),), dup_rate=0.05)
    want = _streaming_count(path, **CFG)
    got = StreamChecker(
        path, Config(resident_chunk_bytes=1), **CFG
    ).count_reads_resident(chunk_windows=256)
    assert got == want


def _parse_protocol(out: str):
    stages, results = [], {}
    for line in out.splitlines():
        if line.startswith("##STAGE "):
            stages.append(line[len("##STAGE "):].strip())
        elif line.startswith("##RESULT "):
            payload = json.loads(line[len("##RESULT "):])
            results[payload.pop("leg")] = payload
    return stages, results


def test_bench_child_resident_cpu_completes(tmp_path):
    """The harness child itself: ``--child-resident <mb> <bam> <reads> <cw>
    cpu`` must emit an ``e2e_resident`` RESULT with ``count_ok`` true and
    no ``e2e_resident_error`` stage."""
    path = tmp_path / "child.bam"
    random_bam(path, 23, contigs=(("chr1", 5_000_000),), dup_rate=0.05)
    reads = _streaming_count(path, **CFG)
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--child-resident", "8", str(path),
         str(reads), "4", "cpu"],
        capture_output=True, text=True, timeout=570,
    )
    stages, results = _parse_protocol(proc.stdout)
    errors = [s for s in stages if s.startswith("e2e_resident_error")]
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert not errors, errors
    assert any(s.startswith("backend_ok:cpu") for s in stages), stages
    assert "e2e_resident" in results, (stages, proc.stdout[-2000:])
    leg = results["e2e_resident"]
    assert leg["count_ok"] is True, leg
    assert leg["boundaries"] == reads


def test_bench_child_resident_unrequested_cpu_skips(tmp_path):
    """Without the explicit cpu platform arg, a CPU backend still skips
    the device leg (it is a device benchmark) — but cleanly, via a
    RESULT line, not a silent empty child."""
    path = tmp_path / "skip.bam"
    random_bam(path, 24, contigs=(("chr1", 1_000_000),))
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--child-resident", "8", str(path), "1"],
        capture_output=True, text=True, timeout=570,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    _, results = _parse_protocol(proc.stdout)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert results.get("resident_child", {}).get("skipped") is True, results
