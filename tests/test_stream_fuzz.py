"""Property fuzz: streaming projections == whole-file engine on random BAMs.

Randomized record sets (lengths, flags, mapped/unmapped mixes) packed at
randomized block payloads, checked through deliberately tiny windows/halos
so every streaming mechanism (halo carry, deferral, spill decode) gets
exercised; each projection must equal the single-pass whole-file engine
bit-for-bit.
"""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import BamHeader, ContigLengths, read_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.tpu.stream_check import StreamChecker

CFG = dict(window_uncompressed=128 << 10, halo=32 << 10)


def _random_bam(path, seed: int):
    rng = np.random.default_rng(seed)
    header = BamHeader(
        ContigLengths({0: ("chr1", 5_000_000), 1: ("chr2", 3_000_000)}),
        Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:5000000\n@SQ\tSN:chr2\tLN:3000000\n",
    )

    def records():
        pos = 5
        for i in range(int(rng.integers(150, 400))):
            n = int(rng.integers(10, 3000))
            mapped = rng.random() < 0.8
            flag = (0 if mapped else 4) | (0x400 if rng.random() < 0.1 else 0)
            yield BamRecord(
                ref_id=int(rng.integers(0, 2)) if mapped else -1,
                pos=pos if mapped else -1,
                mapq=int(rng.integers(0, 61)), bin=0, flag=flag,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"f{seed}_{i}",
                cigar=[(n, 0)] if mapped else [],
                seq="".join(rng.choice(list("ACGT"), n)),
                qual=bytes(rng.integers(5, 40, n, dtype=np.uint8)),
            )
            pos += int(rng.integers(1, 900))

    write_bam(
        path, header, records(), block_payload=int(rng.integers(2000, 40000))
    )


@pytest.mark.parametrize("seed", range(5))
def test_streaming_projections_match_whole_file(tmp_path, seed):
    path = tmp_path / f"fuzz{seed}.bam"
    _random_bam(path, seed)

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size

    checker = StreamChecker(path, Config(), **CFG)

    # count_reads == whole-file verdict count past the header.
    assert checker.count_reads() == int(want.verdict[he:].sum())

    # spans reassemble the verdict array.
    got_v = np.zeros(flat.size, dtype=bool)
    for base, v in StreamChecker(path, Config(), **CFG).spans():
        got_v[base: base + len(v)] |= v
    np.testing.assert_array_equal(got_v, want.verdict)

    # full spans reassemble masks + reads_before.
    got_fm = np.full(flat.size, -1, dtype=np.int64)
    got_rb = np.full(flat.size, -1, dtype=np.int64)
    for base, fm, rb in StreamChecker(path, Config(), **CFG).full_spans():
        got_fm[base: base + len(fm)] = fm
        got_rb[base: base + len(rb)] = rb
    np.testing.assert_array_equal(got_fm, want.fail_mask)
    np.testing.assert_array_equal(got_rb, want.reads_before)

    # streamed batches cover exactly the true record starts.
    rows = 0
    for base, batch in StreamChecker(path, Config(), **CFG).read_batches():
        rows += len(batch)
    assert rows == int(want.verdict[he:].sum())
