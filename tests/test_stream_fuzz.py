"""Property fuzz: streaming projections == whole-file engine on random BAMs.

Randomized record sets (lengths, flags, mapped/unmapped mixes) packed at
randomized block payloads, checked through deliberately tiny windows/halos
so every streaming mechanism (halo carry, deferral, spill decode) gets
exercised; each projection must equal the single-pass whole-file engine
bit-for-bit.
"""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.tpu.stream_check import StreamChecker

from tests.bam_factories import random_bam

CFG = dict(window_uncompressed=128 << 10, halo=32 << 10)


@pytest.mark.parametrize("seed", range(5))
def test_streaming_projections_match_whole_file(tmp_path, seed):
    path = tmp_path / f"fuzz{seed}.bam"
    random_bam(path, seed, contigs=(("chr1", 5_000_000), ("chr2", 3_000_000)), dup_rate=0.1)

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size

    checker = StreamChecker(path, Config(), **CFG)

    # count_reads == whole-file verdict count past the header.
    assert checker.count_reads() == int(want.verdict[he:].sum())

    # spans reassemble the verdict array.
    got_v = np.zeros(flat.size, dtype=bool)
    for base, v in StreamChecker(path, Config(), **CFG).spans():
        got_v[base: base + len(v)] |= v
    np.testing.assert_array_equal(got_v, want.verdict)

    # full spans reassemble masks + reads_before.
    got_fm = np.full(flat.size, -1, dtype=np.int64)
    got_rb = np.full(flat.size, -1, dtype=np.int64)
    for base, fm, rb in StreamChecker(path, Config(), **CFG).full_spans():
        got_fm[base: base + len(fm)] = fm
        got_rb[base: base + len(rb)] = rb
    np.testing.assert_array_equal(got_fm, want.fail_mask)
    np.testing.assert_array_equal(got_rb, want.reads_before)

    # streamed batches cover exactly the true record starts.
    rows = 0
    for base, batch in StreamChecker(path, Config(), **CFG).read_batches():
        rows += len(batch)
    assert rows == int(want.verdict[he:].sum())


@pytest.mark.parametrize("seed", range(5))
def test_sharded_count_matches_whole_file(tmp_path, seed):
    """The mesh streaming count agrees with the whole-file oracle on the
    same adversarial random BAMs (tiny windows/halos force multi-batch
    assembly, seam carries, and — at halo=32K — occasional escapes)."""
    import jax

    from spark_bam_tpu.parallel.mesh import make_mesh
    from spark_bam_tpu.parallel.stream_mesh import count_reads_sharded

    path = tmp_path / f"fuzz{seed}.bam"
    random_bam(
        path, seed, contigs=(("chr1", 5_000_000), ("chr2", 3_000_000)),
        dup_rate=0.1,
    )
    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size

    mesh = make_mesh(jax.devices("cpu")[:8])
    got = count_reads_sharded(path, Config(), mesh=mesh, **CFG)
    assert got == int(want.verdict[he:].sum())


def test_sharded_check_bam_matches_whole_file(tmp_path):
    """check_bam_sharded's truth alignment (block→flat mapping via
    searchsorted against the sidecar) must reproduce the whole-file
    confusion exactly on a random BAM."""
    import jax

    from spark_bam_tpu.bam.index_records import index_records
    from spark_bam_tpu.parallel.mesh import make_mesh
    from spark_bam_tpu.parallel.stream_mesh import check_bam_sharded

    from spark_bam_tpu.core.pos import Pos

    path = tmp_path / "fuzz_cb.bam"
    random_bam(
        path, 7, contigs=(("chr1", 5_000_000), ("chr2", 3_000_000)),
        dup_rate=0.1,
    )
    index_records(path)

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    truth = np.zeros(flat.size, dtype=bool)
    he = hdr.uncompressed_size
    truth_idx = np.flatnonzero(want.verdict)
    truth[truth_idx[truth_idx >= he]] = True  # sidecar == real starts

    # Perturb: one bogus truth entry at a non-boundary position, so the
    # false-negative accounting is actually exercised (fn must come out 1,
    # not merely 0 == 0).
    bogus = int(truth_idx[len(truth_idx) // 2]) + 1
    assert not truth[bogus]
    truth[bogus] = True
    sidecar = tmp_path / "tampered.records"
    lines = [
        f"{b},{o}"
        for b, o in zip(*flat.pos_of_flat_many(np.flatnonzero(truth)))
    ]
    sidecar.write_text("\n".join(lines) + "\n")

    stats = check_bam_sharded(
        path, Config(), mesh=make_mesh(jax.devices("cpu")[:8]),
        records_path=sidecar, **CFG
    )
    tp = int((want.verdict & truth).sum())
    fp = int((want.verdict & ~truth).sum())
    fn = int((~want.verdict & truth).sum())
    assert fn == 1  # the perturbation is visible, not vacuous
    assert stats["true_positives"] == tp
    assert stats["false_positives"] == fp
    assert stats["false_negatives"] == fn
    assert stats["positions"] == flat.size
    assert stats["true_negatives"] == flat.size - tp - fp - fn


@pytest.mark.parametrize("seed", range(3))
def test_truncation_fuzz_device_vs_numpy_engines(tmp_path, seed):
    """Random cuts through a random BAM: the device and NumPy engines must
    agree byte-for-byte through the identical streaming control flow —
    same count when the cut reads cleanly, same error class when it
    doesn't (the pinned truncation semantics)."""
    rng = np.random.default_rng(1000 + seed)
    path = tmp_path / f"t{seed}.bam"
    random_bam(path, seed, contigs=(("chr1", 5_000_000),), dup_rate=0.05)
    data = path.read_bytes()

    for cut in sorted(rng.integers(100, len(data), 4).tolist()):
        trunc = tmp_path / f"t{seed}_{cut}.bam"
        trunc.write_bytes(data[:cut])

        def run(use_device):
            try:
                return StreamChecker(
                    trunc, Config(), use_device=use_device, **CFG
                ).count_reads()
            except (EOFError, IOError) as e:
                return type(e).__name__

        dev, host = run(True), run(False)
        assert dev == host, (cut, dev, host)


@pytest.mark.parametrize("seed", range(2))
def test_subrecord_window_projections_match_whole_file(tmp_path, seed):
    """Windows far smaller than one record: every owned position defers
    (the regime where ungated flags-path resolution was O(span^2) and
    re-emissions were per-position). The gated, run-batched deferral
    path must still reassemble every projection bit-for-bit."""
    from spark_bam_tpu.benchmarks.synth import synth_longread_bam

    path = tmp_path / f"lrfuzz{seed}.bam"
    synth_longread_bam(
        path, target_bytes=2 << 20, seed=seed,
        read_lens=(60_000, 140_000), ultra_seq_len=200_000,
    )
    cfg = dict(window_uncompressed=64 << 10, halo=32 << 10)

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size

    got_v = np.zeros(flat.size, dtype=bool)
    for base, v in StreamChecker(path, Config(), **cfg).spans():
        got_v[base: base + len(v)] |= v
    np.testing.assert_array_equal(got_v, want.verdict)

    got_fm = np.full(flat.size, -1, dtype=np.int64)
    got_rb = np.full(flat.size, -1, dtype=np.int64)
    for base, fm, rb in StreamChecker(path, Config(), **cfg).full_spans():
        got_fm[base: base + len(fm)] = fm
        got_rb[base: base + len(rb)] = rb
    np.testing.assert_array_equal(got_fm, want.fail_mask)
    np.testing.assert_array_equal(got_rb, want.reads_before)

    assert StreamChecker(path, Config(), **cfg).count_reads() == int(
        want.verdict[he:].sum()
    )


@pytest.mark.parametrize("seed,chunk_windows", [(0, 2), (1, 3), (2, 5)])
def test_resident_count_matches_whole_file(tmp_path, seed, chunk_windows):
    """count_reads_resident at odd chunk sizes (non-pow2 → bucketed with
    dummy rows) must equal the whole-file oracle on random BAMs — pins
    the chunk pack/bucket arithmetic under irregular window counts."""
    path = tmp_path / f"res{seed}.bam"
    random_bam(
        path, seed, contigs=(("chr1", 5_000_000), ("chr2", 3_000_000)),
        dup_rate=0.1,
    )
    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size

    got = StreamChecker(path, Config(), **CFG).count_reads_resident(
        chunk_windows=chunk_windows, first_chunk_windows=2,
    )
    assert got == int(want.verdict[he:].sum())
