"""Off-fixture fuzzing of the seqdoop oracle (VERDICT r3 item 6).

The fixture goldens pin the oracle at exactly two hand-picked files; this
property test exercises it on ≥10 *generated* BAMs — htsjdk-rewrite-style
repacks at adversarial block payloads (records stop being block-aligned,
reference HTSJDKRewrite.scala:347-418) plus fully randomized record sets —
and asserts, at every uncompressed position of every file:

- zero false negatives vs the ``.records`` truth (hadoop-bam only misses
  starts on ultra-long reads, which these short-read files don't contain);
- the eager engine stays perfect (0 FP / 0 FN) off-fixture too;
- the seqdoop false-positive rate stays inside the documented regime
  (reference docs/benchmarks.md:5-15: 1.60e-9 – 5.39e-5 per position; we
  allow headroom to 2e-4 since these files are tiny and adversarial —
  one hit on a 1.6M-position file is already 6e-7).
"""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.index_records import index_records, read_records_index
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.seqdoop import seqdoop_check_flat
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.cli import rewrite

FP_RATE_CEILING = 2e-4

# Adversarial payloads: tiny blocks force records to span blocks; odd sizes
# guarantee no record start is block-aligned after the first.
PAYLOADS_1BAM = (0xFF00, 30_011, 9_973)
PAYLOADS_2BAM = (50_021, 17_389, 4_999)


def _random_bam(path, seed: int, n_records: int = 400):
    from tests.bam_factories import random_bam

    random_bam(
        path, seed,
        n_records=(n_records, n_records + 1),
        read_len=(20, 400), mapped_rate=0.9, pos_step=(1, 500),
        block_payload=(3000, 60000), index=True,
    )


def _generate(tmp_path, bam1, bam2):
    files = []
    for i, payload in enumerate(PAYLOADS_1BAM):
        out = tmp_path / f"rw1_{i}.bam"
        rewrite.run(bam1, out, Printer(), block_payload=payload, reindex=True)
        files.append(out)
    for i, payload in enumerate(PAYLOADS_2BAM):
        out = tmp_path / f"rw2_{i}.bam"
        rewrite.run(bam2, out, Printer(), block_payload=payload, reindex=True)
        files.append(out)
    for seed in range(4):
        out = tmp_path / f"rand_{seed}.bam"
        _random_bam(out, seed)
        files.append(out)
    return files


def test_seqdoop_oracle_off_fixture(tmp_path, bam1, bam2):
    files = _generate(tmp_path, bam1, bam2)
    assert len(files) >= 10

    total_positions = 0
    total_fp = 0
    for path in files:
        view = flatten_file(path)
        hdr = read_header(path)
        lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)

        truth = np.zeros(view.size, dtype=bool)
        for p in read_records_index(str(path) + ".records"):
            truth[view.flat_of_pos(p.block_pos, p.offset)] = True

        # The eager engine must stay perfect off-fixture.
        eager = check_flat(view.data, lens, at_eof=True).verdict
        eager[: hdr.uncompressed_size] = False  # header region not indexed
        np.testing.assert_array_equal(eager, truth, err_msg=str(path))

        sd = seqdoop_check_flat(view, len(lens))
        sd[: hdr.uncompressed_size] = False
        fn = np.flatnonzero(truth & ~sd)
        assert len(fn) == 0, f"{path}: seqdoop missed {len(fn)} true starts"
        fp = int((sd & ~truth).sum())
        total_fp += fp
        total_positions += view.size
        assert fp / view.size <= FP_RATE_CEILING, (
            f"{path}: FP rate {fp / view.size:.2e} out of regime ({fp} FPs)"
        )

    # Aggregate rate sits inside (a generous ceiling of) the published band.
    assert total_positions > 5_000_000
    assert total_fp / total_positions <= FP_RATE_CEILING
