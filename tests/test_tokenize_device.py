"""Device-resident DEFLATE tokenization: the in-kernel bit-reader
(tpu/tokenize_device.py + the Pallas form) differentially tested against
the native host tokenizer and zlib — the permanent correctness oracles.

The contract under test (docs/design.md "Device-resident tokenization"):
byte-identical to the host entropy phase on every stream both accept, and
NEVER wrong bytes on a stream only one side takes — the device may only
reject (demote), not disagree. Plus the donation-flatness regression the
window ring relies on, the ``Config.inflate`` spec surface, and the
demote-to-host-zlib parity path.
"""

import zlib
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_bam_tpu.native.build import load_native, tokenize_deflate_native
from spark_bam_tpu.tpu.tokenize_device import STRIDE, tokenize_planes

pytestmark = pytest.mark.tokenize


def _deflate(data: bytes, level: int = 6,
             strategy: int = zlib.Z_DEFAULT_STRATEGY) -> bytes:
    co = zlib.compressobj(level, zlib.DEFLATED, -15, 8, strategy)
    return co.compress(data) + co.flush()


def _stage(comps: list[bytes], c_pad: int | None = None,
           b_pad: int | None = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The ``stage_run_payloads`` convention: pow2-padded rows with ≥ 8
    bytes of tail slack so the kernel's 4-byte bit reads stay in-row."""
    longest = max((len(c) for c in comps), default=0)
    if c_pad is None:
        c_pad = max(1 << max(longest + 8 - 1, 0).bit_length(), 1024)
    if b_pad is None:
        b_pad = max(1 << max(len(comps) - 1, 0).bit_length(), 1)
    staged = np.zeros((b_pad, c_pad), dtype=np.uint8)
    clens = np.zeros(b_pad, dtype=np.int32)
    for i, c in enumerate(comps):
        staged[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        clens[i] = len(c)
    return jnp.asarray(staged), jnp.asarray(clens)


def _native_one(comp: bytes):
    """Host-oracle planes for one stream, or None when it rejects."""
    try:
        return tokenize_deflate_native(
            np.frombuffer(comp, dtype=np.uint8),
            np.array([0], dtype=np.int64),
            np.array([len(comp)], dtype=np.int64),
            stride=STRIDE,
        )
    except IOError:
        return None


def _zlib_one(comp: bytes) -> bytes | None:
    """zlib's verdict on one raw stream: decoded bytes, or None. Uses a
    decompressobj so trailing garbage after BFINAL (which the tokenizers
    ignore, like the BGZF framing does) is not itself a rejection."""
    d = zlib.decompressobj(-15)
    try:
        out = d.decompress(comp)
    except zlib.error:
        return None
    return out if d.eof else None


class _BitWriter:
    """LSB-first DEFLATE bit emitter for hand-built edge-case streams."""

    def __init__(self):
        self.bits: list[int] = []

    def put(self, value: int, n: int):           # LSB-first fields
        for i in range(n):
            self.bits.append((value >> i) & 1)

    def put_code(self, code: int, n: int):       # Huffman codes: MSB-first
        for i in reversed(range(n)):
            self.bits.append((code >> i) & 1)

    def align(self):
        while len(self.bits) % 8:
            self.bits.append(0)

    def bytes(self) -> bytes:
        self.align()
        out = bytearray()
        for i in range(0, len(self.bits), 8):
            v = 0
            for j, b in enumerate(self.bits[i: i + 8]):
                v |= b << j
            out.append(v)
        return bytes(out)


def _fixed_lit_code(sym: int) -> tuple[int, int]:
    """RFC 1951 §3.2.6 fixed litlen code for ``sym`` → (code, nbits)."""
    if sym < 144:
        return 0x30 + sym, 8
    if sym < 256:
        return 0x190 + (sym - 144), 9
    if sym < 280:
        return sym - 256, 7
    return 0xC0 + (sym - 280), 8


# ------------------------------------------------------- plane parity


@pytest.mark.skipif(load_native() is None,
                    reason="native runtime unavailable")
def test_planes_match_native_tokenizer():
    """All three block types, all strategies: the device bit-reader must
    emit the native tokenizer's planes bit-for-bit (tails included)."""
    rng = np.random.default_rng(3)
    datas = [
        b"the quick brown fox " * 200,                       # fixed/dynamic
        rng.integers(0, 256, 8_000, dtype=np.uint8).tobytes(),  # stored-ish
        b"z" * 50_000,                                       # deep RLE
        b"tail",
        b"",                                                 # empty stream
    ]
    comps = [_deflate(d) for d in datas]
    comps.append(_deflate(datas[0], level=0))                # stored blocks
    comps.append(_deflate(datas[0], level=9, strategy=zlib.Z_FIXED))
    datas.append(datas[0])
    datas.append(datas[0])
    staged, clens = _stage(comps)
    lit, dist, olens, ok = map(np.asarray, tokenize_planes(staged, clens))
    for i, (d, c) in enumerate(zip(datas, comps)):
        n_lit, n_dist, n_olens = _native_one(c)
        assert bool(ok[i]) and int(olens[i]) == len(d) == int(n_olens[0])
        assert np.array_equal(lit[i], n_lit[0]), f"lit plane differs row {i}"
        assert np.array_equal(dist[i], n_dist[0]), f"dist plane differs row {i}"
    # Batch-pad rows (clen == 0) are vacuously rejected, never garbage.
    assert not ok[len(comps):].any() and not olens[len(comps):].any()


def test_dynamic_huffman_with_cl_runs():
    """A skewed alphabet at level 9 forces a dynamic-Huffman block whose
    code-length header uses the 16/17/18 run codes; the kernel's canonical
    rebuild + run expansion must reproduce the exact stream."""
    rng = np.random.default_rng(11)
    data = bytes(rng.choice([32, 101, 116, 97, 10, 200], size=20_000,
                            p=[.3, .25, .2, .15, .05, .05]).astype(np.uint8))
    comp = _deflate(data, level=9)
    assert (comp[0] >> 1) & 3 == 2  # first block really is dynamic
    staged, clens = _stage([comp])
    lit, dist, olens, ok = map(np.asarray, tokenize_planes(staged, clens))
    assert bool(ok[0]) and int(olens[0]) == len(data)
    from spark_bam_tpu.tpu.inflate import resolve_lz77

    resolved, _ = resolve_lz77(lit, dist)
    assert bytes(np.asarray(resolved)[0, : len(data)]) == data


@pytest.mark.parametrize("sym", [286, 287])
def test_invalid_litlen_symbols_rejected(sym):
    """286/287 have fixed-Huffman codes but are invalid litlen symbols
    (RFC 1951 §3.2.6) — the kernel must reject, exactly like zlib."""
    w = _BitWriter()
    w.put(1, 1)            # BFINAL
    w.put(1, 2)            # BTYPE = fixed
    w.put_code(*_fixed_lit_code(ord("A")))
    w.put_code(*_fixed_lit_code(sym))
    comp = w.bytes() + b"\x00" * 4
    assert _zlib_one(comp) is None
    staged, clens = _stage([comp])
    _, _, _, ok = tokenize_planes(staged, clens)
    assert not bool(np.asarray(ok)[0])


def test_distance_before_stream_rejected():
    """A match whose distance reaches before output position 0 is corrupt;
    accepting it would fabricate bytes."""
    w = _BitWriter()
    w.put(1, 1)
    w.put(1, 2)                          # fixed
    w.put_code(*_fixed_lit_code(ord("A")))
    w.put_code(*_fixed_lit_code(257))    # length 3
    w.put_code(3, 5)                     # dist sym 3 → distance 4 > pos 1
    w.put_code(*_fixed_lit_code(256))
    comp = w.bytes() + b"\x00" * 4
    assert _zlib_one(comp) is None
    staged, clens = _stage([comp])
    _, _, _, ok = tokenize_planes(staged, clens)
    assert not bool(np.asarray(ok)[0])


def test_zero_length_final_stored_block():
    """BGZF writers emit zero-length members and stored empty final
    blocks; a fixed block followed by an empty stored BFINAL block must
    tokenize with the stored block contributing nothing."""
    w = _BitWriter()
    w.put(0, 1)            # non-final
    w.put(1, 2)            # fixed
    for ch in b"abc":
        w.put_code(*_fixed_lit_code(ch))
    w.put_code(*_fixed_lit_code(256))
    w.put(1, 1)            # BFINAL
    w.put(0, 2)            # stored
    w.align()
    comp = w.bytes() + b"\x00\x00\xff\xff"      # LEN=0, NLEN=~0
    assert _zlib_one(comp) == b"abc"
    staged, clens = _stage([comp])
    lit, dist, olens, ok = map(np.asarray, tokenize_planes(staged, clens))
    assert bool(ok[0]) and int(olens[0]) == 3
    assert bytes(lit[0, :3]) == b"abc" and not dist[0].any()
    # The canonical empty stream (deflate of b"") is a zero-length final
    # block too — fixed-Huffman EOB only.
    staged, clens = _stage([_deflate(b"")])
    _, _, olens, ok = map(np.asarray, tokenize_planes(staged, clens))
    assert bool(ok[0]) and int(olens[0]) == 0


# ------------------------------------------------------- fuzz differential


def test_fuzz_differential_never_wrong_bytes():
    """fuzz-decode's structure-aware mutator over compressed payloads, the
    same 180-mutant corpus the host-path fuzz test walks: whatever a
    mutant does, the device tokenizer must either reject it or produce
    planes that resolve to zlib's exact bytes — NEVER wrong bytes. Where
    the native tokenizer also accepts, the planes must be identical."""
    from spark_bam_tpu.tools.fuzz_decode import _Rng, _mutate
    from spark_bam_tpu.tpu.inflate import resolve_lz77

    rng = np.random.default_rng(9)
    bases = [
        b"the quick brown fox " * 200,
        rng.integers(0, 256, 8_000, dtype=np.uint8).tobytes(),
        b"z" * 50_000,
    ]
    have_native = load_native() is not None
    checked = agreed = 0
    for bi, data in enumerate(bases):
        comp = _deflate(data)
        mutants = []
        for i in range(60):
            r = _Rng(1000 * bi + i)
            mutants.append(_mutate(comp, r.below(len(comp)), r))
        # One staged batch per base, padded to a SHARED shape so the jit
        # compiles once for the whole corpus.
        staged, clens = _stage(mutants, c_pad=16384, b_pad=64)
        lit, dist, olens, ok = tokenize_planes(staged, clens)
        resolved, _ = resolve_lz77(lit, dist)
        lit, dist, olens, ok, resolved = map(
            np.asarray, (lit, dist, olens, ok, resolved)
        )
        for i, mut in enumerate(mutants):
            checked += 1
            host = _zlib_one(mut)
            if not bool(ok[i]):
                continue                      # clean demote — always safe
            # Device accepted: zlib must agree byte-for-byte.
            assert host is not None and int(olens[i]) == len(host), (
                f"device tokenizer accepted a stream zlib rejects "
                f"(base={bi} i={i})"
            )
            assert bytes(resolved[i, : len(host)]) == host, (
                f"device tokenizer produced wrong bytes (base={bi} i={i})"
            )
            agreed += 1
            if have_native:
                nat = _native_one(mut)
                if nat is not None:
                    assert np.array_equal(lit[i], nat[0][0])
                    assert np.array_equal(dist[i], nat[1][0])
    assert checked == 180
    assert agreed > 0                         # benign mutants flow through


# ------------------------------------------------------- pallas parity


def test_pallas_interpret_parity():
    """The Pallas bit-reader (interpret mode on this backend) must agree
    with the XLA vmap form on planes, lengths, and verdicts."""
    from spark_bam_tpu.tpu.pallas_kernels import tokenize_pallas

    comps = [
        _deflate(b"abcabcabc repeat " * 4),
        _deflate(b""),
        _deflate(b"q" * 300),
        b"\x07" + b"\x00" * 8,               # garbage: must reject in both
    ]
    staged, clens = _stage(comps)
    want = [np.asarray(a) for a in tokenize_planes(staged, clens)]
    got = [np.asarray(a) for a in tokenize_pallas(staged, clens,
                                                  interpret=True)]
    for w, g, name in zip(want, got, ("lit", "dist", "olens", "ok")):
        assert np.array_equal(w, g), f"pallas {name} differs"


# ------------------------------------------------------- config surface


def test_inflate_config_parse():
    from spark_bam_tpu.core.inflate_config import InflateConfig

    cfg = InflateConfig.parse("")
    assert (cfg.tokenize, cfg.kernel, cfg.donate) == ("auto", "auto", "on")
    assert InflateConfig.parse("device").tokenize == "device"     # bare token
    assert InflateConfig.parse("host").tokenize == "host"
    full = InflateConfig.parse("tokenize=device,kernel=pallas,donate=off")
    assert full.tokenize == "device" and full.kernel == "pallas"
    assert not full.donate_enabled
    assert InflateConfig.parse("") is InflateConfig.parse("")     # lru cache
    # auto follows the backend: device iff TPU, host everywhere else.
    assert InflateConfig.parse("").resolve_tokenize(backend="tpu") == "device"
    assert InflateConfig.parse("").resolve_tokenize(backend="cpu") == "host"
    assert full.resolve_tokenize(backend="cpu") == "device"       # pinned
    with pytest.raises(ValueError):
        InflateConfig.parse("tokenize=maybe")
    with pytest.raises(ValueError):
        InflateConfig.parse("bogus_knob=1")


# ------------------------------------------------------- pipeline seams


@pytest.fixture
def synth_path(tmp_path) -> Path:
    from spark_bam_tpu.benchmarks.synth import synth_bam

    path = tmp_path / "synth.bam"
    synth_bam(path, 96 << 10)
    return path


@pytest.fixture
def reg():
    from spark_bam_tpu import obs

    obs.shutdown()
    r = obs.configure()
    yield r
    obs.shutdown()


def _pipeline_bytes(path, **kw) -> np.ndarray:
    from spark_bam_tpu.tpu.inflate import InflatePipeline

    views = list(InflatePipeline(path, window_uncompressed=32 << 10,
                                 device_copy=True, **kw))
    assert views[-1].at_eof
    return np.concatenate([v.data for v in views])


def test_pipeline_device_tokenize_matches_host(synth_path, reg):
    """End-to-end: raw payloads H2D, in-kernel tokenize, donated resolve —
    byte-identical to the host zlib flatten, with the re-scoped
    attribution series populated."""
    from spark_bam_tpu import obs
    from spark_bam_tpu.bgzf.flat import flatten_file

    host = flatten_file(synth_path)
    got = _pipeline_bytes(synth_path,
                          inflate_spec="tokenize=device,kernel=xla")
    assert np.array_equal(got, host.data)
    assert obs.counter("inflate.tokenize_blocks").value > 0
    assert obs.counter("inflate.tokenize_demotions").value == 0


def test_demote_parity_on_kernel_reject(synth_path, reg, monkeypatch):
    """A kernel that disavows every row (ok=False) must demote cleanly to
    host zlib at the materialize sync — bytes still exact, demotions
    counted. The never-wrong-bytes contract's last line of defense."""
    from spark_bam_tpu import obs
    from spark_bam_tpu.bgzf.flat import flatten_file
    from spark_bam_tpu.tpu import tokenize_device

    def reject_all(staged, clens):
        b = staged.shape[0]
        return (jnp.zeros((b, STRIDE), jnp.uint8),
                jnp.zeros((b, STRIDE), jnp.uint16),
                jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.bool_))

    monkeypatch.setattr(tokenize_device, "tokenize_planes", reject_all)
    host = flatten_file(synth_path)
    got = _pipeline_bytes(synth_path,
                          inflate_spec="tokenize=device,kernel=xla")
    assert np.array_equal(got, host.data)
    assert obs.counter("inflate.tokenize_demotions").value > 0


def test_demote_parity_on_kernel_raise(synth_path, monkeypatch):
    """A kernel that throws (Mosaic refusal stand-in) demotes at dispatch;
    the pipeline must still produce exact bytes."""
    from spark_bam_tpu.bgzf.flat import flatten_file
    from spark_bam_tpu.tpu import tokenize_device

    def boom(staged, clens):
        raise RuntimeError("mosaic said no")

    monkeypatch.setattr(tokenize_device, "tokenize_planes", boom)
    host = flatten_file(synth_path)
    got = _pipeline_bytes(synth_path,
                          inflate_spec="tokenize=device,kernel=xla")
    assert np.array_equal(got, host.data)


def test_donation_keeps_steady_state_allocations_flat(tmp_path):
    """The donated window ring's regression assert (ISSUE tentpole #2):
    with ``donate=on`` the resolve reuses the lit plane's buffer, so live
    device allocations must be FLAT across ≥ 8 steady-state windows — any
    upward drift means donation silently stopped aliasing."""
    from spark_bam_tpu.benchmarks.synth import synth_bam
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
    from spark_bam_tpu.core.channel import open_channel
    from spark_bam_tpu.tpu.inflate import dispatch_group_device, window_plan

    path = tmp_path / "ring.bam"
    synth_bam(path, 192 << 10)
    groups = window_plan(list(blocks_metadata(path)), 16 << 10)
    assert len(groups) >= 8, "need ≥ 8 windows to see the steady state"
    counts = []
    datas = []
    # Drive the dispatch → materialize cycle synchronously (no producer
    # thread racing the measurement) — the live-array census after each
    # materialize IS the window ring's footprint.
    with open_channel(path) as ch:
        for g in groups:
            view = dispatch_group_device(
                ch, g, inflate_spec="tokenize=device,kernel=xla"
            ).materialize()
            datas.append(np.asarray(view.data).copy())
            counts.append(len(jax.live_arrays()))
    steady = counts[2:]        # first windows pay compile-cache warmup
    assert max(steady) - min(steady) == 0, (
        f"device allocations drift across windows: {counts}"
    )
    from spark_bam_tpu.bgzf.flat import flatten_file

    host = flatten_file(path)
    assert np.array_equal(np.concatenate(datas), host.data)


@pytest.mark.slow
def test_fused_raw_count_matches_host(tmp_path):
    """The fused count kernel fed raw payloads (count_window_raw) must
    agree with the classic host-tokenize count exactly."""
    from spark_bam_tpu.benchmarks.synth import synth_bam
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    path = tmp_path / "count.bam"
    synth_bam(path, 128 << 10)
    host = StreamChecker(
        path, Config(), window_uncompressed=64 << 10
    ).count_reads()
    dev = StreamChecker(
        path,
        Config(device_inflate=True, inflate="tokenize=device,kernel=xla"),
        window_uncompressed=64 << 10,
    ).count_reads()
    assert dev == host
