"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware; set the XLA flags before jax is imported
anywhere.
"""

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# Force, not setdefault: the environment pins JAX_PLATFORMS to the real TPU
# tunnel; tests want the fast deterministic CPU backend with 8 virtual
# devices so multi-chip sharding is exercised. Real-TPU runs go through
# bench.py / __graft_entry__.py.
from spark_bam_tpu.core.platform import (  # noqa: E402
    enable_compile_cache,
    force_cpu_devices,
)

force_cpu_devices(8)
# Persistent XLA compile cache: repeat test sessions skip kernel recompiles.
enable_compile_cache("/tmp/spark_bam_jaxcache_cpu")

import pytest  # noqa: E402

# Reference test fixtures (small real BAMs + golden sidecars). Read-only.
FIXTURES = Path("/root/reference/test_bams/src/main/resources")


def fixture(name: str) -> Path:
    return FIXTURES / name


@pytest.fixture(scope="session")
def bam1():
    p = fixture("1.bam")
    if not p.exists():
        pytest.skip("reference fixtures unavailable")
    return p


@pytest.fixture(scope="session")
def bam2():
    p = fixture("2.bam")
    if not p.exists():
        pytest.skip("reference fixtures unavailable")
    return p


@pytest.fixture(scope="session")
def sam2():
    p = fixture("2.sam")
    if not p.exists():
        pytest.skip("reference fixtures unavailable")
    return p


@pytest.fixture(scope="session")
def bam5k():
    p = fixture("5k.bam")
    if not p.exists():
        pytest.skip("reference fixtures unavailable")
    return p
