"""Fault-tolerance layer: FaultPolicy parsing, the resilient executor
(retry / hedge / deadline / quarantine + JobReport), the deterministic
ChaosChannel harness, and chaos-driven end-to-end recovery through
``load_bam`` (docs/robustness.md)."""

import threading
import time

import pytest

from spark_bam_tpu import obs
from spark_bam_tpu.bam.header import BamHeader, ContigLengths
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.faults import (
    BlockCorruptionError,
    ChaosChannel,
    ChaosError,
    ChaosSpec,
    ChaosState,
    FaultPolicy,
    chaos,
    parse_chaos,
    retryable,
)
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.api import load_bam
from spark_bam_tpu.parallel.executor import (
    ParallelConfig,
    map_partitions,
    run_partitions,
)

# Zero-backoff policies so retry tests spend no wall-clock sleeping.
FAST = FaultPolicy(backoff_base=0.0, jitter=0.0)
FAST_TOLERANT = FaultPolicy(backoff_base=0.0, jitter=0.0, mode="tolerant")


# ------------------------------------------------------------ policy parsing


def test_fault_policy_parse_full_spec():
    p = FaultPolicy.parse(
        "retries=5,backoff=0.1,backoff_max=2,jitter=0,deadline=60,"
        "hedge=2.5,mode=tolerant"
    )
    assert p.max_retries == 5
    assert p.backoff_base == 0.1
    assert p.backoff_max == 2.0
    assert p.jitter == 0.0
    assert p.deadline == 60.0
    assert p.hedge_after == 2.5
    assert p.tolerant


def test_fault_policy_parse_empty_is_default():
    assert FaultPolicy.parse("") == FaultPolicy()
    assert FaultPolicy().mode == "strict"


def test_fault_policy_parse_off_disables():
    p = FaultPolicy.parse("deadline=off,hedge=none")
    assert p.deadline is None and p.hedge_after is None


@pytest.mark.parametrize(
    "spec", ["bogus=1", "mode=yolo", "retries", "retries=-1"]
)
def test_fault_policy_parse_rejects(spec):
    with pytest.raises(ValueError):
        FaultPolicy.parse(spec)


def test_fault_policy_from_config_env(monkeypatch):
    monkeypatch.setenv("SPARK_BAM_FAULTS", "retries=7,mode=tolerant")
    p = Config.from_env().fault_policy
    assert p.max_retries == 7 and p.tolerant


def test_backoff_is_capped_exponential():
    p = FaultPolicy(backoff_base=0.1, backoff_max=0.5, jitter=0.0)
    assert [p.backoff_delay(a) for a in range(4)] == [0.1, 0.2, 0.4, 0.5]


# ---------------------------------------------- ParallelConfig.parse (satellite)


def test_parallel_config_parse_modes():
    assert ParallelConfig.parse("threads=4") == ParallelConfig("threads", 4)
    assert ParallelConfig.parse("sequential") == ParallelConfig("sequential", 0)
    assert ParallelConfig.parse("processes") == ParallelConfig("processes", 0)


def test_parallel_config_parse_rejects_unknown_mode():
    with pytest.raises(ValueError, match="sequential, threads, processes"):
        ParallelConfig.parse("spark")


def test_parallel_config_parse_rejects_bad_workers():
    with pytest.raises(ValueError, match=">= 0"):
        ParallelConfig.parse("threads=-2")
    with pytest.raises(ValueError, match="integer"):
        ParallelConfig.parse("threads=four")


# --------------------------------------------- Retry-After clamp (satellite)


def test_parse_retry_after_past_http_date_clamped():
    from email.utils import formatdate

    from spark_bam_tpu.core.remote import _parse_retry_after

    past = formatdate(time.time() - 3600, usegmt=True)
    assert _parse_retry_after(past) == 0.0
    future = formatdate(time.time() + 30, usegmt=True)
    assert 0.0 < _parse_retry_after(future) <= 30.0
    assert _parse_retry_after("12") == 12.0
    assert _parse_retry_after(None) == 0.0


# ------------------------------------------------------------- retryability


def test_retryable_classification():
    assert retryable(OSError("transient"))
    assert retryable(TimeoutError())
    assert retryable(ChaosError("injected"))
    assert not retryable(FileNotFoundError())
    assert not retryable(PermissionError())
    assert not retryable(BlockCorruptionError())  # Unrecoverable marker
    assert not retryable(ValueError())
    assert not retryable(EOFError())


# ---------------------------------------------------------------- executor


@pytest.mark.parametrize("mode", ["sequential", "threads"])
def test_transient_errors_recover_within_budget(mode):
    calls = {}
    lock = threading.Lock()

    def flaky(i):
        with lock:
            calls[i] = calls.get(i, 0) + 1
            n = calls[i]
        if i % 2 == 0 and n <= 2:
            raise OSError(f"transient #{n} on {i}")
        return i * 10

    results, report = run_partitions(
        flaky, list(range(6)), ParallelConfig(mode, 3), FAST
    )
    assert results == [i * 10 for i in range(6)]
    assert report.retries == 6  # 3 even partitions × 2 retries each
    assert not report.quarantined
    for p in report.partitions:
        assert p.status == "ok"
        assert p.attempts[-1].outcome == "ok"


@pytest.mark.parametrize("mode", ["sequential", "threads"])
def test_strict_raises_when_budget_exhausted(mode):
    def always(i):
        raise OSError(f"always failing {i}")

    with pytest.raises(OSError, match="always failing"):
        run_partitions(always, [0, 1], ParallelConfig(mode, 2), FAST)


@pytest.mark.parametrize("mode", ["sequential", "threads"])
def test_tolerant_quarantines_and_continues(mode):
    def poisoned(i):
        if i == 1:
            raise OSError("always failing")
        return i

    results, report = run_partitions(
        poisoned, [0, 1, 2, 3], ParallelConfig(mode, 2), FAST_TOLERANT
    )
    assert results == [0, None, 2, 3]
    assert report.quarantined == [1]
    assert report.partitions[1].status == "quarantined"
    assert "always failing" in report.partitions[1].error
    # Budget was spent before giving up: 1 initial + max_retries attempts.
    assert len(report.partitions[1].attempts) == FAST.max_retries + 1


@pytest.mark.parametrize("mode", ["sequential", "threads"])
def test_nonretryable_error_fails_in_one_attempt(mode):
    def bad(i):
        raise ValueError("deterministic bug")

    _, report = run_partitions(
        bad, [0], ParallelConfig(mode, 2), FAST_TOLERANT
    )
    assert report.quarantined == [0]
    assert len(report.partitions[0].attempts) == 1


def test_unrecoverable_corruption_not_retried():
    attempts = []

    def corrupt(i):
        attempts.append(i)
        raise BlockCorruptionError("CRC mismatch")

    _, report = run_partitions(
        corrupt, [0], ParallelConfig("sequential"), FAST_TOLERANT
    )
    assert attempts == [0]  # no retry burned on deterministic damage
    assert report.quarantined == [0]


def test_map_partitions_wrapper_returns_results_only():
    assert map_partitions(
        lambda x: x + 1, [1, 2, 3], ParallelConfig("sequential")
    ) == [2, 3, 4]


def test_executor_rejects_unknown_mode():
    with pytest.raises(ValueError, match="Unknown parallel mode"):
        run_partitions(lambda x: x, [1, 2], ParallelConfig("spark", 2))


@pytest.mark.slow
def test_hedge_fires_on_straggler():
    """A partition exceeding hedge_after × median completed latency gets a
    speculative twin; the twin's fast finish resolves the partition without
    waiting out the straggler."""
    calls = {}
    lock = threading.Lock()

    def work(i):
        with lock:
            calls[i] = calls.get(i, 0) + 1
            first = calls[i] == 1
        if i == 3 and first:
            time.sleep(2.0)  # the straggler's primary attempt
        else:
            time.sleep(0.02)
        return i

    t0 = time.monotonic()
    results, report = run_partitions(
        work,
        list(range(4)),
        ParallelConfig("threads", 5),
        FaultPolicy(hedge_after=3.0, backoff_base=0.0),
    )
    wall = time.monotonic() - t0
    assert results == [0, 1, 2, 3]
    assert report.hedges == 1
    spec = [a for a in report.partitions[3].attempts if a.speculative]
    assert spec and spec[0].outcome == "ok"
    assert wall < 1.9, f"hedge did not cut the straggler wait ({wall:.2f}s)"


@pytest.mark.slow
def test_deadline_times_out_and_retries():
    """An attempt over the per-attempt deadline is written off as a timeout
    and a fresh attempt launched."""
    calls = {}
    lock = threading.Lock()

    def work(i):
        with lock:
            calls[i] = calls.get(i, 0) + 1
            first = calls[i] == 1
        if first:
            time.sleep(5.0)
        return i

    results, report = run_partitions(
        work, [0, 1], ParallelConfig("threads", 4),
        FaultPolicy(deadline=0.3, backoff_base=0.0),
    )
    assert results == [0, 1]
    outcomes = [a.outcome for a in report.partitions[0].attempts]
    assert "timeout" in outcomes and outcomes[-1] == "ok"


# ------------------------------------------------------------------- chaos


def test_parse_chaos_spec():
    seed, spec = parse_chaos("42:io=0.1,latency=0.05x25,short=0.02,corrupt=1e-6")
    assert seed == 42
    assert spec == ChaosSpec(
        io=0.1, latency=0.05, latency_ms=25.0, short=0.02, corrupt=1e-6
    )
    with pytest.raises(ValueError, match="SEED:SPEC"):
        parse_chaos("nope:io=1")
    with pytest.raises(ValueError, match="Unknown chaos key"):
        parse_chaos("1:fire=0.5")


class _MemChannel:
    """Minimal in-memory ByteChannel for chaos unit tests."""

    def __init__(self, data: bytes):
        self._data = data

    def read_at(self, pos, n):
        return self._data[pos: pos + n]

    @property
    def size(self):
        return len(self._data)

    def close(self):
        pass


def _drain(ch, step=100):
    """Read the channel range by range, retrying transient faults."""
    out = bytearray()
    pos = 0
    while pos < ch.size:
        try:
            out += ch.read_at(pos, min(step, ch.size - pos))
        except ChaosError:
            continue
        pos += step
    return bytes(out)


def test_chaos_channel_deterministic_replay():
    """Same seed ⇒ identical fault offsets, tallies, and corrupted bytes;
    different seed ⇒ a different fault set. The fast seeded smoke test of
    the chaos harness (default suite)."""
    data = bytes(range(256)) * 40
    runs = []
    for _ in range(2):
        state = ChaosState(7, ChaosSpec.parse("io=0.2,short=0.1,corrupt=1e-3"))
        ch = ChaosChannel(_MemChannel(data), 7, state.spec, state=state)
        runs.append((_drain(ch), dict(state.injected), sorted(state.consumed)))
    assert runs[0] == runs[1]
    assert runs[0][1]["io"] > 0 and runs[0][1]["corrupt"] > 0

    other = ChaosState(8, ChaosSpec.parse("io=0.2,short=0.1,corrupt=1e-3"))
    ch = ChaosChannel(_MemChannel(data), 8, other.spec, state=other)
    assert (_drain(ch), dict(other.injected)) != runs[0][:2]


def test_chaos_transient_faults_fire_once_per_region():
    """A transient fault consumes its 4 KiB region: the retry that re-reads
    the same offset succeeds (that's what makes it *transient*)."""
    data = b"x" * (64 << 10)
    state = ChaosState(3, ChaosSpec(io=1.0))  # every region faults once
    ch = ChaosChannel(_MemChannel(data), 3, state.spec, state=state)
    with pytest.raises(ChaosError):
        ch.read_at(0, 100)
    assert ch.read_at(0, 100) == data[:100]          # consumed
    assert ch.read_at(1000, 100) == data[:100]       # same region: clear
    with pytest.raises(ChaosError):
        ch.read_at(8192, 100)                        # next region: fresh fault


def test_chaos_corruption_is_persistent_and_pure():
    """Corruption is a pure per-byte function: every read of an offset sees
    the same damaged value — unlike transients, retries don't help."""
    data = bytes(1000)
    state = ChaosState(5, ChaosSpec(corrupt=0.01))
    ch = ChaosChannel(_MemChannel(data), 5, state.spec, state=state)
    a = ch.read_at(0, 1000)
    b = ch.read_at(0, 1000)
    assert a == b != data
    # Reading in pieces lands the same damage at the same offsets.
    assert b"".join(ch.read_at(p, 100) for p in range(0, 1000, 100)) == a


# ----------------------------------------------------- end-to-end via load


@pytest.fixture(scope="module")
def synth_bam(tmp_path_factory):
    path = tmp_path_factory.mktemp("faults") / "synth.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 1_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000000\n",
    )

    def records():
        for i in range(1200):
            yield BamRecord(
                ref_id=0, pos=100 + i * 50, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"r{i}", cigar=[(100, 0)],
                seq="ACGT" * 25, qual=bytes([30]) * 100,
            )

    write_bam(path, header, records(), block_payload=5000)
    return path


@pytest.mark.parametrize("mode", ["sequential", "threads"])
@pytest.mark.parametrize("seed", [7, 13, 23])
def test_load_bam_byte_identical_under_transient_chaos(synth_bam, mode, seed):
    """The acceptance bar: 10% injected transient-IOError rate, fixed seed,
    default FaultPolicy ⇒ byte-identical records to the fault-free run.
    (Seed 23 faults offset 0 — the driver-side header read — proving the
    pre-partition reads retry too.)"""
    baseline = [
        r.encode()
        for r in load_bam(synth_bam, split_size=4_000, config=Config()).collect()
    ]
    assert len(baseline) == 1200
    with chaos(f"{seed}:io=0.1") as state:
        ds = load_bam(
            synth_bam, split_size=4_000, config=Config(),
            parallel=ParallelConfig(mode, 4),
        )
        got = [r.encode() for r in ds.collect()]
    assert state.injected["io"] > 0, "chaos must actually have fired"
    assert got == baseline
    assert ds.last_report.retries >= 1
    assert not ds.last_report.quarantined


def test_load_bam_same_seed_same_story(synth_bam):
    """Deterministic replay through the whole stack: two runs with one seed
    inject the identical fault set and land identical bytes."""
    cfg = Config(faults="backoff=0.001,jitter=0")
    runs = []
    for _ in range(2):
        with chaos("7:io=0.1,latency=0.01x1") as state:
            ds = load_bam(
                synth_bam, split_size=4_000, config=cfg,
                parallel=ParallelConfig("sequential"),
            )
            runs.append((
                [r.encode() for r in ds.collect()],
                dict(state.injected),
                sorted(state.consumed),
            ))
    assert runs[0] == runs[1]


def test_faults_metrics_flow_to_registry(synth_bam):
    """faults.retries / chaos.io_errors counters and the attempt-latency
    histogram land in the PR-1 observability registry."""
    obs.shutdown()
    reg = obs.configure()
    try:
        with chaos("7:io=0.1"):
            load_bam(
                synth_bam, split_size=4_000,
                config=Config(faults="backoff=0.001,jitter=0"),
                parallel=ParallelConfig("sequential"),
            ).count()
        snap = reg.snapshot()
        counters = {c["name"]: c["value"] for c in snap["counters"]}
        assert counters.get("faults.retries", 0) >= 1
        assert counters.get("chaos.io_errors", 0) >= 1
        hists = {h["name"] for h in snap["hists"]}
        assert "faults.attempt_ms" in hists
    finally:
        obs.shutdown()


def test_cli_chaos_and_faults_flags(synth_bam, capsys):
    from spark_bam_tpu.cli.main import main

    rc = main([
        "count-reads", "-m", "4KB",
        "--chaos", "7:io=0.1", "--faults", "backoff=0.001,jitter=0",
        str(synth_bam),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Read counts matched: 1200" in out
    assert "fault tolerance:" in out and "retries" in out
    assert "chaos(seed=7): injected io=" in out


def test_cli_rejects_bad_fault_specs(synth_bam, capsys):
    from spark_bam_tpu.cli.main import main

    assert main(["count-reads", "--faults", "bogus=1", str(synth_bam)]) == 2
    assert "Unknown fault-policy key" in capsys.readouterr().err
    assert main(["count-reads", "--chaos", "x:io=1", str(synth_bam)]) == 2
    assert "Bad chaos seed" in capsys.readouterr().err
