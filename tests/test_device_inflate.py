"""Two-phase device inflate: host entropy tokenize + device LZ77 resolution.

Differential tests against zlib — the permanent correctness oracle
(SURVEY.md §7 hard-part #1: "keep host-zlib as the correctness fallback").
Covers all three DEFLATE block types (stored / fixed / dynamic Huffman),
deep overlapping-copy chains (RLE), multi-block streams, and a whole
reference BAM.
"""

import zlib

import numpy as np
import pytest

from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.native.build import load_native, tokenize_deflate_native
from spark_bam_tpu.tpu.inflate import (
    STRIDE,
    inflate_blocks_device,
    inflate_file_device,
)

pytestmark = pytest.mark.skipif(
    load_native() is None, reason="native runtime unavailable"
)


def _deflate(data: bytes, level: int = 6, strategy: int = zlib.Z_DEFAULT_STRATEGY):
    co = zlib.compressobj(level, zlib.DEFLATED, -15, 8, strategy)
    return co.compress(data) + co.flush()


def _roundtrip_one(data: bytes, **kw) -> None:
    comp = np.frombuffer(_deflate(data, **kw), dtype=np.uint8)
    out = inflate_blocks_device(
        comp,
        np.array([0], dtype=np.int64),
        np.array([len(comp)], dtype=np.int64),
        np.array([len(data)], dtype=np.int64),
    )
    assert out is not None
    assert out.tobytes() == data


def test_dynamic_huffman_roundtrip():
    rng = np.random.default_rng(0)
    # Compressible but non-trivial: repeated 64-byte motifs + noise.
    motifs = rng.integers(0, 256, (8, 64), dtype=np.uint8)
    picks = rng.integers(0, 8, 500)
    data = np.concatenate([motifs[p] for p in picks]).tobytes()
    _roundtrip_one(data)


def test_stored_blocks():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
    _roundtrip_one(data, level=0)


def test_fixed_huffman():
    _roundtrip_one(b"fixed huffman " * 200, strategy=zlib.Z_FIXED)


def test_deep_rle_chains():
    # dist=1 overlapping copies: every byte's chain points at the single
    # root literal through a ~64K-deep chain — the pointer-doubling
    # worst case.
    _roundtrip_one(b"a" * (STRIDE - 1))


def test_empty_payload():
    _roundtrip_one(b"")


def test_batched_blocks_roundtrip():
    rng = np.random.default_rng(2)
    datas = [
        b"x" * striped
        for striped in (1, 100, 65_535)
    ] + [rng.integers(0, 256, 10_000, dtype=np.uint8).tobytes()]
    comps = [np.frombuffer(_deflate(d), dtype=np.uint8) for d in datas]
    offsets = np.zeros(len(comps), dtype=np.int64)
    lengths = np.array([len(c) for c in comps], dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    out = inflate_blocks_device(
        np.concatenate(comps),
        offsets,
        lengths,
        np.array([len(d) for d in datas], dtype=np.int64),
    )
    assert out.tobytes() == b"".join(datas)


def test_no_distance_codes_stream():
    # RFC 1951 §3.2.7: a match-free block may declare a single distance
    # code of zero bits. Real encoders (libdeflate in htslib) emit this
    # shape; the tokenizer must accept it. Hand-assembled: dynamic block,
    # litlen lens {65:1, 256:1}, one zero-length dist code, data "AA".
    bits = []

    def put(value, n):  # LSB-first field
        bits.extend((value >> k) & 1 for k in range(n))

    def put_code(code, n):  # Huffman code, MSB-first
        bits.extend((code >> (n - 1 - k)) & 1 for k in range(n))

    put(1, 1)   # BFINAL
    put(2, 2)   # BTYPE = dynamic
    put(0, 5)   # HLIT  = 257 codes
    put(0, 5)   # HDIST = 1 code
    put(14, 4)  # HCLEN = 18 entries
    # Code-length code lens in the fixed order 16,17,18,0,8,7,...,1:
    # {0:2, 1:2, 17:2, 18:2}, canonical codes 00,01,10,11.
    for cl_len in [0, 2, 2, 2] + [0] * 13 + [2]:
        put(cl_len, 3)
    cl = {0: (0, 2), 1: (1, 2), 17: (2, 2), 18: (3, 2)}

    def put_cl(sym):
        put_code(*cl[sym])

    put_cl(18); put(65 - 11, 7)    # 65 zeros
    put_cl(1)                      # symbol 65 ('A') → len 1
    put_cl(18); put(138 - 11, 7)   # 138 zeros
    put_cl(18); put(52 - 11, 7)    # 52 zeros  (66..255 = 190 total)
    put_cl(1)                      # symbol 256 (EOB) → len 1
    put_cl(0)                      # the single dist code: len 0
    # Payload: 'A' 'A' EOB with litlen codes {65: 0, 256: 1}.
    put_code(0, 1); put_code(0, 1); put_code(1, 1)

    raw = bytearray()
    for i in range(0, len(bits), 8):
        raw.append(sum(b << k for k, b in enumerate(bits[i: i + 8])))
    raw = bytes(raw)
    assert zlib.decompress(raw, -15) == b"AA"  # the stream really is valid

    out = inflate_blocks_device(
        np.frombuffer(raw, dtype=np.uint8),
        np.array([0], dtype=np.int64),
        np.array([len(raw)], dtype=np.int64),
        np.array([2], dtype=np.int64),
    )
    assert out.tobytes() == b"AA"


def test_tokenizer_rejects_truncated_stream():
    comp = np.frombuffer(_deflate(b"hello world" * 50), dtype=np.uint8)
    with pytest.raises(IOError):
        inflate_blocks_device(
            comp[: len(comp) // 2],
            np.array([0], dtype=np.int64),
            np.array([len(comp) // 2], dtype=np.int64),
            np.array([550], dtype=np.int64),
        )


def test_size_mismatch_raises():
    comp = np.frombuffer(_deflate(b"hello world" * 50), dtype=np.uint8)
    with pytest.raises(IOError):
        inflate_blocks_device(
            comp,
            np.array([0], dtype=np.int64),
            np.array([len(comp)], dtype=np.int64),
            np.array([549], dtype=np.int64),  # footer lies about the size
        )


def test_tokenize_shapes():
    data = b"shape check " * 32
    comp = np.frombuffer(_deflate(data), dtype=np.uint8)
    lit, dist, out_lens = tokenize_deflate_native(
        comp,
        np.array([0], dtype=np.int64),
        np.array([len(comp)], dtype=np.int64),
        stride=STRIDE,
    )
    assert lit.shape == (1, STRIDE) and dist.shape == (1, STRIDE)
    assert dist.dtype == np.uint16  # 3 wire bytes per output byte total
    assert out_lens[0] == len(data)
    # Padded tail must be dist=0 identities.
    assert not dist[0, len(data):].any()
    # The repeated motif must actually produce back-references (dist>0)
    # whose implied parents point strictly backwards.
    used = dist[0, : len(data)].astype(np.int64)
    assert used.max() > 0
    idx = np.arange(len(data), dtype=np.int64)
    assert ((idx - used) >= 0).all()


def test_pipeline_device_copy_matches_host(bam2):
    from spark_bam_tpu.tpu.inflate import InflatePipeline

    host = flatten_file(bam2)
    views = list(InflatePipeline(bam2, window_uncompressed=256 << 10,
                                 device_copy=True))
    assert len(views) > 1  # multiple windows actually exercised
    got = np.concatenate([v.data for v in views])
    assert np.array_equal(got, host.data)
    assert views[-1].at_eof


def test_whole_bam_matches_host_inflate(bam2):
    host = flatten_file(bam2)
    dev = inflate_file_device(bam2)
    assert dev is not None
    assert np.array_equal(dev.data, host.data)
    assert np.array_equal(dev.block_starts, host.block_starts)
    assert np.array_equal(dev.block_flat, host.block_flat)
    assert dev.at_eof


def test_resolve_early_exit_rounds():
    """The early-exit resolve reports rounds-to-convergence: a literal-only
    batch costs exactly one gather (the convergence test itself), a
    block-spanning distance-1 run needs the full log2(64 Ki) doubling."""
    from spark_bam_tpu.tpu.inflate import _DOUBLING_ROUNDS, resolve_lz77

    data = b"a" * (STRIDE - 1)
    comp = np.frombuffer(_deflate(data), dtype=np.uint8)
    lit, dist, _ = tokenize_deflate_native(
        comp, np.array([0], dtype=np.int64),
        np.array([len(comp)], dtype=np.int64), stride=STRIDE,
    )
    deep, rounds_deep = resolve_lz77(lit, dist)
    assert bytes(np.asarray(deep)[0, : len(data)]) == data
    assert int(rounds_deep) == _DOUBLING_ROUNDS == 16

    lits_only, rounds_lit = resolve_lz77(lit, np.zeros_like(dist))
    assert np.array_equal(np.asarray(lits_only), np.asarray(lit))
    assert int(rounds_lit) == 1


def test_pack_unpack_roundtrip():
    """The packed single-buffer H2D layout must resolve identically to the
    two-array path (and the u16 dist plane must survive the bitcast)."""
    from spark_bam_tpu.tpu.inflate import (
        _resolve_packed, pack_tokens, resolve_lz77,
    )

    rng = np.random.default_rng(7)
    datas = [b"ab" * 20_000, rng.integers(0, 256, 5_000, dtype=np.uint8).tobytes()]
    comps = [np.frombuffer(_deflate(d), dtype=np.uint8) for d in datas]
    offsets = np.zeros(len(comps), dtype=np.int64)
    lengths = np.array([len(c) for c in comps], dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    lit, dist, _ = tokenize_deflate_native(
        np.concatenate(comps), offsets, lengths, stride=STRIDE,
    )
    want, rounds_a = resolve_lz77(lit, dist)
    got, rounds_b = _resolve_packed(pack_tokens(lit, dist))
    assert np.array_equal(np.asarray(got), np.asarray(want))
    assert int(rounds_a) == int(rounds_b)


def test_pallas_lz77_parity():
    """The fused Pallas kernel (interpret mode on this backend) must agree
    with the XLA resolve bit-for-bit, early exit included."""
    import jax.numpy as jnp

    from spark_bam_tpu.tpu.inflate import resolve_lz77
    from spark_bam_tpu.tpu.pallas_kernels import lz77_resolve_pallas

    rng = np.random.default_rng(8)
    datas = [
        b"a" * (STRIDE - 1),             # max-depth distance-1 chain
        b"xy" * 10_000,                  # distance-2 overlaps
        rng.integers(0, 256, 30_000, dtype=np.uint8).tobytes(),
        b"hello world " * 400,
    ]
    comps = [np.frombuffer(_deflate(d), dtype=np.uint8) for d in datas]
    offsets = np.zeros(len(comps), dtype=np.int64)
    lengths = np.array([len(c) for c in comps], dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    lit, dist, _ = tokenize_deflate_native(
        np.concatenate(comps), offsets, lengths, stride=STRIDE,
    )
    want, rounds_xla = resolve_lz77(lit, dist)
    got, rounds_pl = lz77_resolve_pallas(
        jnp.asarray(lit), jnp.asarray(dist), interpret=True
    )
    assert np.array_equal(np.asarray(got), np.asarray(want))
    assert int(rounds_pl) == int(rounds_xla)


@pytest.mark.parametrize("distance", [1, 2, 3, 7])
def test_overlapping_copy_distances(distance):
    """Overlapping copies at tiny distances (copy source overlaps its own
    destination — the serial-inflate special case) across a near-block-
    sized run."""
    motif = bytes(range(65, 65 + distance))
    reps = (STRIDE - 1) // distance
    _roundtrip_one(motif * reps)


def test_zero_length_final_block():
    """A batch whose FINAL block inflates to zero bytes (BGZF writers emit
    empty blocks mid-stream and the EOF sentinel is one): the zero-length
    row must occupy no output range."""
    datas = [b"payload " * 512, b"tail", b""]
    comps = [np.frombuffer(_deflate(d), dtype=np.uint8) for d in datas]
    offsets = np.zeros(len(comps), dtype=np.int64)
    lengths = np.array([len(c) for c in comps], dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    out = inflate_blocks_device(
        np.concatenate(comps), offsets, lengths,
        np.array([len(d) for d in datas], dtype=np.int64),
    )
    assert out.tobytes() == b"".join(datas)


def test_fuzz_mutant_corpus_never_wrong_bytes():
    """fuzz-decode's structure-aware mutator over compressed payloads:
    whatever a mutant does, the device inflate must return bytes identical
    to host zlib's decode or raise cleanly — NEVER wrong bytes. (The
    out_lengths footer is the original's, so mutants that change the
    decoded size must be rejected by the size check.)"""
    from spark_bam_tpu.tools.fuzz_decode import _Rng, _mutate

    rng = np.random.default_rng(9)
    bases = [
        b"the quick brown fox " * 200,
        rng.integers(0, 256, 8_000, dtype=np.uint8).tobytes(),
        b"z" * 50_000,
    ]
    checked = 0
    agreed = 0
    for bi, data in enumerate(bases):
        comp = _deflate(data)
        for i in range(60):
            r = _Rng(1000 * bi + i)
            mutant = _mutate(comp, r.below(len(comp)), r)
            try:
                host = zlib.decompress(mutant, -15)
            except zlib.error:
                host = None
            try:
                out = inflate_blocks_device(
                    np.frombuffer(mutant, dtype=np.uint8),
                    np.array([0], dtype=np.int64),
                    np.array([len(mutant)], dtype=np.int64),
                    np.array([len(data)], dtype=np.int64),
                )
            except (IOError, ValueError):
                out = "rejected"
            checked += 1
            if isinstance(out, np.ndarray):
                # Device accepted: zlib must agree byte-for-byte.
                assert host is not None and out.tobytes() == host, (
                    f"device inflate returned wrong bytes for mutant "
                    f"base={bi} i={i}"
                )
                agreed += 1
    assert checked == 180
    assert agreed > 0  # identity/benign mutants must flow through


def test_count_reads_with_device_inflate_config(bam1):
    """spark.bam.device.inflate=true must flow through the config surface
    into the streaming pipeline and still count exactly."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.load.tpu_load import count_reads_tpu

    cfg = Config.from_dict({"spark.bam.device.inflate": True})
    assert cfg.device_inflate is True
    assert count_reads_tpu(bam1, cfg) == 4917


def test_device_inflate_auto_resolution():
    """Default is auto (None): True only on a TPU backend with the native
    tokenizer built; False on this CPU-mesh backend and for host-only
    consumers; explicit settings always win."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.inflate import resolve_device_inflate

    cfg = Config()
    assert cfg.device_inflate is None
    assert resolve_device_inflate(cfg) is False  # CPU test backend
    assert resolve_device_inflate(cfg, use_device=False) is False
    assert resolve_device_inflate(Config(device_inflate=True)) is True
    assert resolve_device_inflate(
        Config(device_inflate=True), use_device=False
    ) is True  # explicit beats auto everywhere
    assert resolve_device_inflate(Config(device_inflate=False)) is False
    assert Config.from_dict({"spark.bam.device.inflate": "auto"}).device_inflate is None
    assert Config.from_dict({"spark.bam.device.inflate": "false"}).device_inflate is False
