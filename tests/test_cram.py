"""CRAM 3.0: varints, rANS, codecs, writer/reader round-trips, load_cram.

The reference's .cram support is delegation to hadoop-bam/htsjdk
(CanLoadBam.scala:348-382); here the format is built in, so the tests are
(a) primitive round-trips, (b) the canonical EOF sentinel byte-for-byte,
(c) full-fidelity record round-trips over the reference BAM fixtures, and
(d) container-partitioned loading.
"""

import numpy as np
import pytest

from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.stream import BlockStream, UncompressedBytes
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.cram import CramReader, CramWriter, rans
from spark_bam_tpu.cram.codecs import (
    BitReader,
    BitWriter,
    Decoders,
    beta,
    huffman,
)
from spark_bam_tpu.cram.container import eof_container
from spark_bam_tpu.cram.nums import Cursor, itf8, ltf8


def read_bam(path):
    stream = RecordStream(UncompressedBytes(BlockStream(open_channel(path))))
    header = stream.header
    recs = [rec for _, rec in stream]
    stream.close()
    return header, recs


# ------------------------------------------------------------- primitives
@pytest.mark.parametrize(
    "value",
    [0, 1, 127, 128, 0x3FFF, 0x4000, 0x1FFFFF, 0x200000, 0x0FFFFFFF,
     0x10000000, 2**31 - 1, -1, -2, -(2**31)],
)
def test_itf8_roundtrip(value):
    cur = Cursor(itf8(value))
    assert cur.itf8() == value
    assert cur.at_end()


@pytest.mark.parametrize(
    "value",
    [0, 127, 128, 0x3FFF, 0x200000 - 1, 0x10000000, 2**34, 2**41, 2**48,
     2**55, 2**62, -1, -(2**63)],
)
def test_ltf8_roundtrip(value):
    cur = Cursor(ltf8(value))
    assert cur.ltf8() == value
    assert cur.at_end()


def test_eof_container_is_canonical():
    # The spec's fixed 38-byte v3.0 EOF, reproduced structurally (both
    # CRCs computed, not pasted).
    assert eof_container().hex() == (
        "0f000000ffffffff0fe0454f4600000000010005bdd94f"
        "0001000606010001000100ee63014b"
    )


@pytest.mark.parametrize("order", [0, 1])
def test_rans_roundtrip(order):
    rng = np.random.default_rng(7)
    cases = [
        b"",
        b"a",
        b"skewed " * 500,
        bytes(rng.integers(0, 256, 10_000, dtype=np.uint8)),
        bytes(rng.integers(33, 43, 30_000, dtype=np.uint8)),  # qual-like
    ]
    for data in cases:
        assert rans.decompress(rans.compress(data, order)) == data


def test_rans_native_matches_python():
    from spark_bam_tpu.cram.rans import _decode_o0, _decode_o1
    from spark_bam_tpu.native.build import load_native, rans_decompress_native

    if load_native() is None:
        pytest.skip("native runtime unavailable")
    rng = np.random.default_rng(11)
    cases = [
        b"q" * 5000,
        bytes(rng.integers(0, 256, 20_000, dtype=np.uint8)),
        bytes(rng.integers(33, 43, 50_000, dtype=np.uint8)),
    ]
    for order in (0, 1):
        for data in cases:
            blob = rans.compress(data, order)
            cur = Cursor(blob)
            actual_order = cur.u8()
            cur.u32()
            out_sz = cur.u32()
            python = (
                _decode_o0(cur, out_sz) if actual_order == 0
                else _decode_o1(cur, out_sz)
            )
            assert rans_decompress_native(blob, out_sz) == python == data


def test_rans_native_rejects_malformed_input():
    from spark_bam_tpu.native.build import load_native, rans_decompress_native

    if load_native() is None:
        pytest.skip("native runtime unavailable")
    import struct

    # Over-subscribed frequency table: symbol 0x00 claims 0x7FFF twice via
    # the two-byte form — used to write ~32KB past the lookup table.
    evil_table = bytes([0x00, 0xFF, 0xFF, 0x01, 0xFF, 0xFF, 0x00])
    blob = bytes([0]) + struct.pack("<I", len(evil_table)) + struct.pack("<I", 100) + evil_table
    with pytest.raises(IOError):
        rans_decompress_native(blob, 100)
    # Truncated stream (no states) must error, not crash.
    short = bytes([0]) + struct.pack("<I", 1) + struct.pack("<I", 10) + b"\x00"
    with pytest.raises(IOError):
        rans_decompress_native(short, 10)
    # RLE symbol run extending past 255 must be rejected (the Python
    # decoder IndexErrors on it; wrapping would clobber low symbols).
    run_table = bytes([250, 1, 251, 10]) + bytes([1] * 11) + bytes([0])
    blob = bytes([0]) + struct.pack("<I", len(run_table)) + struct.pack("<I", 10) + run_table
    with pytest.raises(IOError):
        rans_decompress_native(blob, 10)


def test_core_block_codecs():
    # Huffman (multi-symbol + 0-bit constant), beta: encode with BitWriter,
    # decode through the Decoders dispatch.
    w = BitWriter()
    # canonical codes for values [5, 6, 7] lens [1, 2, 2]: 5→0, 6→10, 7→11
    for bits, n in [(0b0, 1), (0b10, 2), (0b11, 2), (0b0, 1)]:
        w.write_bits(bits, n)
    w.write_bits(37, 8)  # beta(offset=0, length=8)
    dec = Decoders(BitReader(w.getvalue()), {})
    h = dec.int_reader(huffman([5, 6, 7], [1, 2, 2]))
    assert [h(), h(), h(), h()] == [5, 6, 7, 5]
    b = dec.int_reader(beta(0, 8))
    assert b() == 37
    const = dec.int_reader(huffman([42], [0]))
    assert const() == 42  # zero-bit constant reads nothing


# ------------------------------------------------------------ round-trips
@pytest.mark.parametrize("method", ["gzip", "rans", "raw"])
def test_roundtrip_2bam(bam2, tmp_path, method):
    header, recs = read_bam(bam2)
    out = tmp_path / "2.cram"
    with CramWriter(out, header.contig_lengths, header.text, method=method) as w:
        w.write_all(recs)
    with CramReader(out) as r:
        back = list(r)
        assert r.contigs == header.contig_lengths
    assert back == recs  # full 13-field equality, bin/tags/quals included


def test_roundtrip_multi_container(bam1, tmp_path):
    header, recs = read_bam(bam1)
    out = tmp_path / "1.cram"
    with CramWriter(
        out, header.contig_lengths, header.text, records_per_container=1000
    ) as w:
        w.write_all(recs)
    with CramReader(out) as r:
        infos = r.container_infos()
        assert len(infos) == 5  # 4917 records / 1000 per container
        assert [i.n_records for i in infos] == [1000] * 4 + [917]
        assert [i.record_counter for i in infos] == [0, 1000, 2000, 3000, 4000]
        assert back_count(r) == len(recs)
        assert list(r) == recs


def back_count(reader):
    return sum(1 for _ in reader)


def test_roundtrip_5k_with_unmapped(bam5k, tmp_path):
    header, recs = read_bam(bam5k)
    assert any(r.flag & 4 for r in recs)  # fixture has unmapped reads
    out = tmp_path / "5k.cram"
    with CramWriter(out, header.contig_lengths, header.text) as w:
        w.write_all(recs)
    with CramReader(out) as r:
        assert list(r) == recs


def test_seqless_mapped_record(tmp_path):
    # Sequence '*' with a real cigar: CF_NO_SEQ path; cigar survives, seq
    # and qual stay empty.
    from spark_bam_tpu.bam.header import ContigLengths

    contigs = ContigLengths({0: ("chr1", 1000)})
    rec = BamRecord(
        ref_id=0, pos=99, mapq=7, bin=4681, flag=0, next_ref_id=-1,
        next_pos=-1, tlen=0, read_name="noseq",
        cigar=[(30, 0), (5, 2), (20, 0)], seq="", qual=b"", tags=b"",
    )
    out = tmp_path / "noseq.cram"
    with CramWriter(out, contigs) as w:
        w.write(rec)
    with CramReader(out) as r:
        back = list(r)
    assert len(back) == 1
    got = back[0]
    assert got.cigar == rec.cigar
    assert got.seq == "" and got.qual == b""
    assert got.read_name == "noseq" and got.mapq == 7


def test_reference_based_decode():
    # Feature reconstruction against a reference: match gaps pull bases
    # from the FASTA, X substitutions go through the substitution matrix.
    from spark_bam_tpu.bam.header import ContigLengths
    from spark_bam_tpu.cram.bam_bridge import subst_tables
    from spark_bam_tpu.cram.structure import DEFAULT_SUBST_MATRIX

    reader = CramReader.__new__(CramReader)
    reader.contigs = ContigLengths({0: ("c", 40)})
    reader.reference = {"c": b"ACGTACGTACGTACGTACGT"}
    sub = subst_tables(DEFAULT_SUBST_MATRIX)
    # Hand-rolled series readers: 2 features — X at read pos 3 (code 0),
    # D of 2 at read pos 5; rl=6, pos=0. Layout: gap M2, X, gap M1, D2, M2.
    seq_feats = [("X", 3, 0), ("D", 5, 2)]
    fi = iter(seq_feats)
    state = {}

    def r_fn():
        return len(seq_feats)

    def r_fc():
        c, p, payload = next(fi)
        state["payload"] = payload
        state["pos"] = p
        return ord(c)

    prev = [0]

    def r_fp():
        d = state["pos"] - prev[0]
        prev[0] = state["pos"]
        return d

    payload = lambda: state["payload"]  # noqa: E731
    rec = reader._decode_mapped(
        0, 0, 0, 6, 0, sub, None, 0, False,
        r_fn, r_fc, r_fp,
        payload, payload, payload, payload, payload,  # bb/in/sc/qq/bs
        payload, payload, payload, payload,           # dl/rs/hc/pd
        lambda: 60,                                   # mq
        lambda: ord("N"), lambda: 0xFF,               # ba/qs
        lambda n: b"#" * n,                           # qs bulk
    )
    # ref = ACGTACGT..; read: AC + subst(G→code0=A) + T + (del 2) + GT
    assert rec.seq == "ACATGT"
    assert rec.cigar == [(4, 0), (2, 2), (2, 0)]
    assert rec.mapq == 60


# ------------------------------------------------- foreign-writer surface
def _foreign_cram(tmp_path, with_embedded: bool):
    """Hand-assemble a container the way real writers shape them — core
    bit-stream codecs (HUFFMAN/BETA), AP-delta, a single-ref slice, names
    off, NF mate chaining, and (optionally) an embedded reference whose
    slice starts mid-contig — none of which our own writer emits."""
    from spark_bam_tpu.cram import codecs
    from spark_bam_tpu.cram.container import (
        COMPRESSION_HEADER,
        CORE,
        EXTERNAL,
        MAPPED_SLICE,
        RAW,
        Block,
        ContainerHeader,
        file_definition,
        sam_header_container,
    )
    from spark_bam_tpu.cram.structure import CompressionHeader, SliceHeader

    ds = {
        "BF": huffman([65, 129], [1, 1]),
        "CF": huffman([0, 4], [1, 1]),
        "RL": beta(0, 6),
        "AP": beta(0, 4),
        "NF": huffman([0], [0]),
        "TL": huffman([0], [0]),
        "FN": huffman([0, 1], [1, 1]),
        "FC": huffman([ord("X")], [0]),
        "FP": beta(0, 4),
        "BS": huffman([1], [0]),
        "MQ": beta(0, 7),
    }
    ch = CompressionHeader(
        read_names_included=False,
        ap_delta=True,
        reference_required=True,
        tag_dict=[[]],
        data_series=ds,
        tags={},
    )
    w = BitWriter()
    # rec A: BF=65, CF=4 (mate downstream), RL=8, AP∆=0, NF=0 (0 bits),
    #        TL (0 bits), FN=1, FC='X' (0 bits), FP∆=3, BS=1 (0 bits), MQ=30
    w.write_bits(0, 1); w.write_bits(1, 1); w.write_bits(8, 6)
    w.write_bits(0, 4); w.write_bits(1, 1); w.write_bits(3, 4)
    w.write_bits(30, 7)
    # rec B: BF=129, CF=0, RL=8, AP∆=5, TL, FN=0, MQ=30
    w.write_bits(1, 1); w.write_bits(0, 1); w.write_bits(8, 6)
    w.write_bits(5, 4); w.write_bits(0, 1); w.write_bits(30, 7)

    ref_bytes = b"AACCGGTTAACCGGTT"  # contig "c" positions 10..25 (0-based)
    blocks = [Block(CORE, 0, w.getvalue()).serialize(RAW)]
    content_ids: list[int] = []
    embedded_id = -1
    if with_embedded:
        blocks.append(Block(EXTERNAL, 100, ref_bytes).serialize(RAW))
        content_ids = [100]
        embedded_id = 100
    sh = SliceHeader(
        ref_seq_id=0, start=11, span=13, n_records=2, record_counter=0,
        n_blocks=len(blocks), content_ids=content_ids,
        embedded_ref_id=embedded_id,
    )
    ch_block = Block(COMPRESSION_HEADER, 0, ch.serialize()).serialize(RAW)
    sh_block = Block(MAPPED_SLICE, 0, sh.serialize()).serialize(RAW)
    body = ch_block + sh_block + b"".join(blocks)
    hdr = ContainerHeader(
        length=len(body), ref_seq_id=0, start=11, span=13, n_records=2,
        record_counter=0, bases=16, n_blocks=2 + len(blocks),
        landmarks=[len(ch_block)],
    )
    out = tmp_path / "foreign.cram"
    with open(out, "wb") as f:
        f.write(file_definition())
        f.write(sam_header_container("@HD\tVN:1.6\n@SQ\tSN:c\tLN:100\n"))
        f.write(hdr.serialize() + body)
        f.write(eof_container())
    return out


def _check_foreign_records(recs):
    a, b = recs
    assert (a.flag, a.pos, a.mapq, a.read_name) == (65, 10, 30, "q0")
    assert a.seq == "AAGCGGTT"  # gap M2 + X(code1: C→G) + gap M5 off the ref
    assert a.cigar == [(8, 0)]
    assert (b.flag, b.pos, b.seq) == (129, 15, "GTTAACCG")
    # NF mate chaining resolved both directions.
    assert (a.next_ref_id, a.next_pos, a.tlen) == (0, 15, 13)
    assert (b.next_ref_id, b.next_pos, b.tlen) == (0, 10, -13)


def test_foreign_shape_embedded_ref(tmp_path):
    path = _foreign_cram(tmp_path, with_embedded=True)
    with CramReader(path) as r:  # no external reference: embedded used
        _check_foreign_records(list(r))


def test_foreign_shape_external_reference(tmp_path):
    path = _foreign_cram(tmp_path, with_embedded=False)
    ref = {"c": b"??????????AACCGGTTAACCGGTT"}
    with CramReader(path, reference=ref) as r:
        _check_foreign_records(list(r))


def test_reference_required_raises_without_reference(tmp_path):
    path = _foreign_cram(tmp_path, with_embedded=False)
    with CramReader(path) as r:
        with pytest.raises(ValueError, match="RR=true"):
            list(r)


def test_count_reads_cli_with_reference_flag(tmp_path):
    # RR=true CRAM + external FASTA through the CLI's -F flag.
    from spark_bam_tpu.cli.main import main

    path = _foreign_cram(tmp_path, with_embedded=False)
    fasta = tmp_path / "c.fa"
    fasta.write_text(">c\nNNNNNNNNNNAACCGGTTAACCGGTT\n")
    out = tmp_path / "out.txt"
    assert main(["count-reads", "-F", str(fasta), str(path), "-o", str(out)]) == 0
    assert "Read count: 2" in out.read_text()


# ------------------------------------------------------------------ .crai
def test_crai_roundtrip_and_overlap(tmp_path):
    from spark_bam_tpu.cram.crai import CraiEntry, read_crai, write_crai

    entries = [
        CraiEntry(0, 101, 500, 1000, 50, 4000),
        CraiEntry(-1, 0, 0, 5000, 50, 2000),
    ]
    p = tmp_path / "x.cram.crai"
    write_crai(p, entries)
    assert read_crai(p) == entries
    e = entries[0]
    assert e.overlaps(0, 100, 101)       # touches first base (0-based 100)
    assert not e.overlaps(0, 0, 100)     # ends before it
    assert not e.overlaps(1, 100, 200)   # other ref
    assert not entries[1].overlaps(-1, 0, 10)  # unmapped line never matches


def test_load_cram_intervals_matches_bam(bam2, tmp_path):
    from spark_bam_tpu.load.api import load_bam_intervals, load_cram_intervals

    header, recs = read_bam(bam2)
    out = tmp_path / "2.cram"
    with CramWriter(
        out, header.contig_lengths, header.text, records_per_container=250
    ) as w:
        w.write_all(recs)
    assert (tmp_path / "2.cram.crai").exists()

    loci = "1:13000-14000,1:60000-61000"
    want = list(load_bam_intervals(bam2, loci))
    assert want  # the locus actually selects records
    got = list(load_cram_intervals(out, loci))
    assert got == want

    # The .crai actually prunes containers: indexed selection must decode
    # fewer containers than a full scan would.
    from spark_bam_tpu.cram import CramReader
    from spark_bam_tpu.cram.crai import read_crai

    with CramReader(out) as r:
        total = len(r.container_infos())
    hit = {e.container_offset for e in read_crai(str(out) + ".crai")
           if e.ref_seq_id == 0 and e.overlaps(0, 13000, 14000)
           or e.ref_seq_id == 0 and e.overlaps(0, 60000, 61000)}
    assert 0 < len(hit) < total

    # Without the sidecar the same records come back via full scan.
    (tmp_path / "2.cram.crai").unlink()
    assert list(load_cram_intervals(out, loci)) == want


# ---------------------------------------------------------------- loading
def test_load_cram_partitioned(bam2, tmp_path):
    from spark_bam_tpu.load.api import load_cram, load_reads

    header, recs = read_bam(bam2)
    out = tmp_path / "2.cram"
    with CramWriter(
        out, header.contig_lengths, header.text, records_per_container=500
    ) as w:
        w.write_all(recs)
    ds = load_cram(out, split_size=200 * 1024)
    assert len(ds.partitions) > 1
    got = list(ds)
    assert got == recs
    # Extension dispatch reaches the same loader.
    assert sum(1 for _ in load_reads(out)) == len(recs)


def test_rans_python_truncated_freq_table_errors_cleanly():
    """VERDICT r3 weak #6: a rANS stream truncated inside the frequency
    table must raise a clean EOFError from the Python decoder (like the
    native decoder's IOError), never a bare IndexError from an unguarded
    buffer peek. (Truncation deep in the state bytes decodes garbage by
    design — the spec stream carries no checksum.)"""
    from spark_bam_tpu.cram.nums import Cursor
    from spark_bam_tpu.cram.rans import _decode_o0, _decode_o1

    data = bytes(range(64)) * 8
    for order, decode in ((0, _decode_o0), (1, _decode_o1)):
        blob = rans.compress(data, order)
        body = blob[9:]  # strip the 9-byte (order, comp_sz, out_sz) header
        # Every cut inside the frequency table region must error cleanly.
        for cut in range(1, 12):
            with pytest.raises((EOFError, ValueError, IOError)):
                decode(Cursor(body[:cut]), len(data))


def test_nf_linked_mates_share_synthesized_qname():
    """CRAM without stored read names: NF-linked mates are one template and
    must share one generated QNAME (VERDICT r3 weak #6 / cram/reader.py)."""
    from spark_bam_tpu.bam.record import BamRecord

    def rec(name):
        return BamRecord(
            ref_id=0, pos=100, mapq=60, bin=0, flag=0x1,
            next_ref_id=-1, next_pos=-1, tlen=0,
            read_name=name, cigar=[], seq="ACGT", qual=b"####",
        )

    # links[0] = 0 ⇒ record 1 is record 0's mate.
    out = [rec("q0"), rec("q1"), rec("q2")]
    CramReader._resolve_mates(out, [0, None, None], names_included=False)
    assert out[0].read_name == out[1].read_name == "q0"
    assert out[2].read_name == "q2"

    # With stored names the reader must never overwrite them.
    out = [rec("a"), rec("b")]
    CramReader._resolve_mates(out, [0, None], names_included=True)
    assert out[1].read_name == "b"


@pytest.mark.parametrize("seed", range(3))
def test_roundtrip_random_bam(tmp_path, seed):
    """Randomized BAMs (mixed mapped/unmapped, duplicate flags, wide
    length spread) survive the CRAM round-trip with full field equality."""
    from tests.bam_factories import random_bam

    path = tmp_path / f"r{seed}.bam"
    random_bam(path, seed, dup_rate=0.15, read_len=(1, 5000))
    header, recs = read_bam(path)
    out = tmp_path / f"r{seed}.cram"
    with CramWriter(out, header.contig_lengths, header.text) as w:
        w.write_all(recs)
    with CramReader(out) as r:
        back = list(r)
    assert back == recs


def test_load_cram_intervals_fuzz_random(tmp_path):
    """Random sorted BAM → CRAM + .crai → interval loads equal the BAM
    interval loads (which the .bai fuzz pins against brute force)."""
    import numpy as np

    from tests.bam_factories import random_bam

    from spark_bam_tpu.bam.bai import index_bam
    from spark_bam_tpu.load.api import load_bam_intervals, load_cram_intervals

    rng = np.random.default_rng(77)
    bam = tmp_path / "s.bam"
    random_bam(
        bam, 77, contigs=(("chr1", 2_000_000),), n_records=(250, 251),
        pos_step=(1, 50), read_len=(10, 600), mapped_rate=0.9, sort=True,
    )
    index_bam(bam)
    header, recs = read_bam(bam)
    cram = tmp_path / "s.cram"
    with CramWriter(
        cram, header.contig_lengths, header.text, records_per_container=64
    ) as w:
        w.write_all(recs)

    def key(r):
        return (r.read_name, r.flag, r.pos)

    for _ in range(8):
        a = int(rng.integers(1, 10_000))
        b = a + int(rng.integers(1, 4_000))
        loci = f"chr1:{a}-{b}"
        want = sorted(key(r) for r in load_bam_intervals(bam, loci))
        got = sorted(key(r) for r in load_cram_intervals(cram, loci))
        assert got == want, loci
