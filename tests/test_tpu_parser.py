"""Device record parser vs the sequential codec on real fixture records."""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.tpu.parser import interval_flag_filter, parse_flat_records


@pytest.fixture(scope="module")
def parsed(bam2):
    flat = flatten_file(bam2)
    records = read_records_index(str(bam2) + ".records")
    starts = np.array(
        [flat.flat_of_pos(p.block_pos, p.offset) for p in records], dtype=np.int64
    )
    return flat, starts, parse_flat_records(flat.data, starts)


def test_parser_matches_codec(bam2, parsed):
    flat, starts, batch = parsed
    assert len(batch) == 2500
    rng = np.random.default_rng(3)
    for i in rng.integers(0, len(starts), 100).tolist():
        rec, _ = BamRecord.decode(flat.data, int(starts[i]))
        assert batch.columns["ref_id"][i] == rec.ref_id
        assert batch.columns["pos"][i] == rec.pos
        assert batch.columns["flag"][i] == rec.flag
        assert batch.columns["mapq"][i] == rec.mapq
        assert batch.columns["l_seq"][i] == rec.read_length
        assert batch.columns["n_cigar"][i] == len(rec.cigar)
        assert batch.columns["next_ref_id"][i] == rec.next_ref_id
        assert batch.columns["next_pos"][i] == rec.next_pos
        assert batch.columns["tlen"][i] == rec.tlen
        assert batch.columns["ref_span"][i] == rec.reference_span()
    assert batch.columns["span_exact"].all()


def test_interval_filter_matches_load_api(bam2, parsed):
    import jax.numpy as jnp

    flat, starts, batch = parsed
    # Whole-contig interval: the golden count is 2450 (50 unmapped excluded).
    intervals = jnp.asarray(np.array([[0, 0, 100_000_000]], dtype=np.int32))
    mask = np.asarray(
        interval_flag_filter(
            {k: jnp.asarray(v) for k, v in batch.columns.items()},
            intervals,
            jnp.int32(0),
            jnp.int32(0),
        )
    )
    assert int(mask.sum()) == 2450
    # Flag filter: forbidding the unmapped bit changes nothing here; requiring
    # read-paired keeps only paired reads.
    mask2 = np.asarray(
        interval_flag_filter(
            {k: jnp.asarray(v) for k, v in batch.columns.items()},
            intervals,
            jnp.int32(0x1),
            jnp.int32(0),
        )
    )
    paired = (batch.columns["flag"] & 1) == 1
    assert int(mask2.sum()) == int((mask & paired).sum())


def test_lazy_payloads_match_codec(bam2, parsed):
    flat, starts, batch = parsed
    rec, _ = BamRecord.decode(flat.data, int(starts[7]))
    assert batch.name(7) == rec.read_name
    assert batch.seq(7) == rec.seq
    assert batch.qual(7) == rec.qual


def test_shape_bucketing_bounds_compiles(bam2):
    """Streaming windows vary in size every step; the parser must bucket
    both buffer and row-count shapes to powers of two so the jit compiles
    O(log) variants, not one per window."""
    from spark_bam_tpu.tpu.parser import parse_records

    flat = flatten_file(bam2)
    records = read_records_index(str(bam2) + ".records")
    starts = np.array(
        [flat.flat_of_pos(p.block_pos, p.offset) for p in records[:40]],
        dtype=np.int64,
    )
    early = starts[starts < 90_000]
    # Different buffer lengths in the same pow2 bucket and different row
    # counts in the same pow2 bucket: the second call must be a full
    # cache hit (order-independent: the first call may itself hit).
    parse_flat_records(flat.data[:100_000], early[:5])
    mid = parse_records._cache_size()
    parse_flat_records(flat.data[:120_000], early[:7])
    assert parse_records._cache_size() == mid
