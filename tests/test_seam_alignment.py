"""Adversarial seam alignment: every window/block boundary lands EXACTLY
on a record start. Uniform-size records packed at a block payload that is
an exact multiple of the record size make every BGZF block boundary a
record boundary; streaming windows then put their ownership seams
(own_end) precisely on record starts — the off-by-one surface for
double-counting or dropping the seam record."""

import numpy as np

import jax

from spark_bam_tpu.bam.header import BamHeader, ContigLengths, read_header
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.parallel.mesh import make_mesh
from spark_bam_tpu.parallel.stream_mesh import count_reads_sharded
from spark_bam_tpu.tpu.stream_check import StreamChecker

N_RECORDS = 240


def _uniform_bam(path):
    """All records encode to one identical size."""
    sam = "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:10000000\n"
    header = BamHeader(ContigLengths({0: ("chr1", 10_000_000)}), Pos(0, 0), 0, sam)

    def records():
        for i in range(N_RECORDS):
            yield BamRecord(
                ref_id=0, pos=100 + 7 * i, mapq=30, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"u{i:04d}",  # fixed-width name
                cigar=[(64, 0)],
                seq="ACGT" * 16,
                qual=bytes([30] * 64),
            )

    recs = list(records())
    sizes = {len(r.encode()) for r in recs}
    assert len(sizes) == 1, sizes
    rec_size = sizes.pop()
    # Block payload = 4 records exactly ⇒ every block boundary is a
    # record boundary (after the header block, which write_bam emits
    # separately).
    write_bam(path, header, recs, block_payload=4 * rec_size)
    return rec_size


def test_seams_on_record_boundaries(tmp_path):
    path = tmp_path / "uniform.bam"
    rec_size = _uniform_bam(path)

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True)
    he = hdr.uncompressed_size
    expected = int(want.verdict[he:].sum())
    assert expected == N_RECORDS

    # Window = 2 blocks (8 records), halo = 1 block: own_end lands on a
    # record start at every single seam.
    win = 8 * rec_size
    halo = 4 * rec_size
    got = StreamChecker(
        path, Config(), window_uncompressed=win, halo=halo
    ).count_reads()
    assert got == N_RECORDS

    # Same alignment through the mesh tier (rows seam on record starts).
    got = count_reads_sharded(
        path, Config(), mesh=make_mesh(jax.devices("cpu")[:8]),
        window_uncompressed=win, halo=halo,
    )
    assert got == N_RECORDS

    # Degenerate: window = one block, minimum legal halo.
    got = StreamChecker(
        path, Config(), window_uncompressed=4 * rec_size, halo=2 * rec_size
    ).count_reads()
    assert got == N_RECORDS
