"""Third fixture (5k.bam): engines + loaders agree with its sidecars."""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import contig_lengths
from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.bgzf.index_blocks import read_blocks_index
from spark_bam_tpu.bgzf.stream import MetadataStream
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.load.api import load_bam, load_bam_intervals


def test_blocks_match_sidecar(bam5k):
    with open_channel(bam5k) as ch:
        metas = list(MetadataStream(ch))
    assert metas == read_blocks_index(str(bam5k) + ".blocks")


def test_vectorized_matches_records(bam5k):
    flat = flatten_file(bam5k)
    lens = np.array(contig_lengths(bam5k).lengths_list(), dtype=np.int32)
    result = check_flat(flat.data, lens, at_eof=True)
    truth = np.zeros(flat.size, dtype=bool)
    records = read_records_index(str(bam5k) + ".records")
    for pos in records:
        truth[flat.flat_of_pos(pos.block_pos, pos.offset)] = True
    np.testing.assert_array_equal(result.verdict, truth)
    assert len(records) == truth.sum()


def test_load_count(bam5k):
    records = read_records_index(str(bam5k) + ".records")
    assert load_bam(bam5k, split_size=200_000).count() == len(records)


def test_bai_interval_load(bam5k):
    # 5k.bam ships a .bai: indexed loads must run and agree with a full-scan
    # filter.
    header_count = load_bam(bam5k, split_size=1_000_000)
    from spark_bam_tpu.bam.header import read_header
    from spark_bam_tpu.load.intervals import LociSet

    header = read_header(bam5k)
    name0 = header.contig_lengths.name(0)
    loci = LociSet.parse(f"{name0}", header.contig_lengths)
    via_index = load_bam_intervals(bam5k, loci).count()
    full = [
        r
        for r in header_count
        if not r.is_unmapped and r.ref_id == 0
    ]
    assert via_index == len(full)
