"""The project-native static-analysis suite (``spark-bam-tpu lint``).

Three layers of coverage (docs/static-analysis.md):

1. per-rule fixtures — a MUST-trigger snippet and a near-miss MUST-NOT
   snippet for each registered rule, driven through ``lint_source``;
2. suppression mechanics — inline allows, the justified baseline,
   stale-entry reporting, content-addressed keys surviving line shifts;
3. the gate itself — the whole repo lints clean against the committed
   baseline, and injecting one canonical violation per rule fails it.

Plus regressions for the real findings this suite surfaced (corrupt
B-tag blobs in cram/bam_bridge.py, the unlocked ``Batcher.tick_s``
write), and the ``slow``-marked runtime lock-order harness that backs
the static ``shared-state`` pass with observed happens-before evidence.
"""

import json
import os
import struct
import threading
import time

import pytest

from spark_bam_tpu.analysis import (
    RULES,
    Baseline,
    Severity,
    lint_source,
    run_lint,
)
from spark_bam_tpu.analysis.findings import finding_key
from spark_bam_tpu.analysis.runtime_sync import LockOrderRecorder

pytestmark = pytest.mark.lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "lint-baseline.json")


def _findings(rel_path, source, rule_id):
    return [f for f in lint_source(rel_path, source) if f.rule == rule_id]


# ------------------------------------------------------------ jit-purity

JIT_TRIGGER = """\
import jax

@jax.jit
def count(window, n):
    if n > 0:                       # traced value in a Python branch
        return window.sum()
    return window.max()
"""

JIT_NEARMISS = """\
import jax

@jax.jit
def count(window, n=4):
    if window.shape[0] > 8:         # shapes are static at trace time
        return window.sum()
    if n > 2:                       # param with literal default: config
        return window.max()
    if window is None:              # host-level sentinel test
        return None
    return window.min()
"""


def test_jit_purity_triggers_on_traced_branch():
    found = _findings("tpu/fixture.py", JIT_TRIGGER, "jit-purity")
    assert found and found[0].severity == Severity.P1
    assert "n" in found[0].message


def test_jit_purity_ignores_shape_static_and_sentinel():
    assert _findings("tpu/fixture.py", JIT_NEARMISS, "jit-purity") == []


def test_jit_purity_flags_nonliteral_static_argnums():
    src = (
        "import jax\n"
        "def make(idx):\n"
        "    return jax.jit(step, static_argnums=idx)\n"
    )
    found = _findings("parallel/fixture.py", src, "jit-purity")
    assert found and "static_arg" in found[0].message


def test_jit_purity_out_of_scope_module_is_skipped():
    assert _findings("serve/fixture.py", JIT_TRIGGER, "jit-purity") == []


# -------------------------------------------------------- blocking-async

ASYNC_TRIGGER = """\
import time

async def handle(conn):
    time.sleep(0.1)                 # stalls the whole accept loop
    return conn
"""

ASYNC_NEARMISS = """\
import asyncio
import time

async def handle(conn, loop):
    await asyncio.sleep(0.1)
    def work():                     # run_in_executor target: fine
        time.sleep(0.1)
    return await loop.run_in_executor(None, work)
"""


def test_blocking_async_triggers_on_time_sleep():
    found = _findings("fabric/fixture.py", ASYNC_TRIGGER, "blocking-async")
    assert found and found[0].severity == Severity.P1
    assert "time.sleep" in found[0].message


def test_blocking_async_ignores_await_and_executor_targets():
    assert _findings("serve/fixture.py", ASYNC_NEARMISS, "blocking-async") == []


# -------------------------------------------------------- guard-boundary

GUARD_TRIGGER = """\
import struct

def parse(raw):
    return struct.unpack("<i", raw[:4])[0]
"""

GUARD_NEARMISS = """\
import struct

from spark_bam_tpu.core.guard import TruncatedInput

def parse(raw):
    if len(raw) < 4:
        raise TruncatedInput("need 4 bytes")
    return struct.unpack("<i", raw[:4])[0]

def parse_wrapped(raw):
    try:
        return struct.unpack("<q", raw[:8])[0]
    except struct.error as e:
        raise TruncatedInput(str(e)) from e
"""

GUARD_FEEDER = """\
import struct

from spark_bam_tpu.core.guard import TruncatedInput

class Reader:
    def take(self, n):
        if self.off + n > len(self.data):
            raise TruncatedInput("short read")
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def unpack(self, fmt):
        return struct.unpack(fmt, self.take(struct.calcsize(fmt)))
"""


def test_guard_boundary_triggers_on_bare_unpack():
    found = _findings("bam/fixture.py", GUARD_TRIGGER, "guard-boundary")
    assert found and found[0].severity == Severity.P1


def test_guard_boundary_accepts_validate_and_catch_idioms():
    assert _findings("cram/fixture.py", GUARD_NEARMISS, "guard-boundary") == []


def test_guard_boundary_accepts_guarded_feeder():
    assert _findings("sbi/fixture.py", GUARD_FEEDER, "guard-boundary") == []


# --------------------------------------------------------- shared-state

STATE_TRIGGER = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.rate = 1.0
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        while True:
            r = self.rate

    def set_rate(self, r):
        self.rate = r               # foreign-domain write, no lock
"""

STATE_NEARMISS = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.rate = 1.0
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                r = self.rate

    def set_rate(self, r):
        with self._lock:
            self.rate = r

    def stop(self):
        self._stop.set()            # Events ARE the synchronization
"""


def test_shared_state_triggers_on_unlocked_cross_thread_write():
    found = _findings("serve/fixture.py", STATE_TRIGGER, "shared-state")
    assert found and found[0].severity == Severity.P1
    assert "rate" in found[0].message
    assert "_lock" in (found[0].hint or "")


def test_shared_state_ignores_locked_writes_and_events():
    assert _findings("serve/fixture.py", STATE_NEARMISS, "shared-state") == []


# --------------------------------------------------------- obs-contract

OBS_TRIGGER = """\
from spark_bam_tpu import obs

def tick():
    obs.count("serve.totally_unregistered")
"""

OBS_NEARMISS = """\
from spark_bam_tpu import obs

def tick(r):
    obs.count("serve.batches")
    r.count(4, "blocks", 16)        # not the obs module: out of scope
"""


def test_obs_contract_triggers_on_unregistered_name():
    found = _findings("serve/fixture.py", OBS_TRIGGER, "obs-contract")
    assert found and "not in the registered catalog" in found[0].message


def test_obs_contract_ignores_registered_and_foreign_receivers():
    assert _findings("serve/fixture.py", OBS_NEARMISS, "obs-contract") == []


def test_obs_contract_dynamic_name_severity_split():
    bounded = (
        "from spark_bam_tpu import obs\n"
        "def f(name):\n"
        "    obs.count(f\"serve.{name}\")\n"
    )
    unbounded = (
        "from spark_bam_tpu import obs\n"
        "def f(name):\n"
        "    obs.count(f\"{name}.total\")\n"
    )
    b = _findings("serve/fixture.py", bounded, "obs-contract")
    u = _findings("serve/fixture.py", unbounded, "obs-contract")
    assert b and b[0].severity == Severity.P2
    assert u and u[0].severity == Severity.P1


# ------------------------------------------------- suppression mechanics


def test_inline_allow_suppresses_with_reason():
    src = OBS_TRIGGER.replace(
        'obs.count("serve.totally_unregistered")',
        'obs.count("serve.totally_unregistered")'
        "  # lint: allow[obs-contract] fixture",
    )
    assert _findings("serve/fixture.py", src, "obs-contract") == []


def test_inline_allow_without_reason_stays_live():
    src = OBS_TRIGGER.replace(
        'obs.count("serve.totally_unregistered")',
        'obs.count("serve.totally_unregistered")  # lint: allow[obs-contract]',
    )
    found = _findings("serve/fixture.py", src, "obs-contract")
    assert found and "no reason" in found[0].message


def test_inline_allow_comment_line_carries_past_continuations():
    src = OBS_TRIGGER.replace(
        '    obs.count("serve.totally_unregistered")',
        "    # lint: allow[obs-contract] the reason wraps onto a\n"
        "    # second comment line before the flagged statement\n"
        '    obs.count("serve.totally_unregistered")',
    )
    assert _findings("serve/fixture.py", src, "obs-contract") == []


def test_finding_keys_survive_line_shifts():
    base = lint_source("bam/fixture.py", GUARD_TRIGGER)
    shifted = lint_source("bam/fixture.py", "import os\n\n" + GUARD_TRIGGER)
    assert base and shifted
    assert base[0].key == shifted[0].key
    assert base[0].line != shifted[0].line


def test_finding_key_distinguishes_identical_lines():
    assert finding_key("r", "x = 1", 0) != finding_key("r", "x = 1", 1)


def test_baseline_requires_justification(tmp_path):
    bad = tmp_path / "serve" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(OBS_TRIGGER)
    rep = run_lint(paths=[str(tmp_path)])
    assert len(rep.failing) == 1
    f = rep.failing[0]
    entry = {"rule": f.rule, "path": f.path, "key": f.key}

    silent = Baseline([dict(entry, justification="")])
    rep2 = run_lint(paths=[str(tmp_path)], baseline=silent)
    assert len(rep2.failing) == 1   # unjustified entry does not suppress

    justified = Baseline([dict(entry, justification="fixture")])
    rep3 = run_lint(paths=[str(tmp_path)], baseline=justified)
    assert rep3.ok and len(rep3.suppressed) == 1


def test_baseline_stale_entry_fails_the_gate(tmp_path):
    clean = tmp_path / "serve" / "clean.py"
    clean.parent.mkdir()
    clean.write_text("x = 1\n")
    stale = Baseline([{
        "rule": "obs-contract", "path": "serve/clean.py",
        "key": "obs-contract:deadbeef:0", "justification": "long fixed",
    }])
    # Stale entries only fail a FULL-scope run (root=...): a --rules or
    # paths subset never visits the other entries.
    rep = run_lint(root=str(tmp_path), baseline=stale)
    assert not rep.ok and len(rep.stale_baseline) == 1
    rep2 = run_lint(root=str(tmp_path), rule_ids=["obs-contract"],
                    baseline=stale)
    assert rep2.stale_baseline == []


def test_baseline_write_round_trip(tmp_path):
    bad = tmp_path / "serve" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(OBS_TRIGGER)
    rep = run_lint(paths=[str(tmp_path)])
    path = tmp_path / "baseline.json"
    n = Baseline.write(str(path), rep.findings, "bootstrap fixture")
    assert n == 1
    rep2 = run_lint(paths=[str(tmp_path)], baseline=str(path))
    assert rep2.ok


def test_unknown_rule_id_is_an_error():
    with pytest.raises(ValueError, match="unknown rule"):
        run_lint(rule_ids=["no-such-rule"])


# ------------------------------------------------------------- the gate

CANONICAL_VIOLATIONS = {
    "jit-purity": ("tpu/injected.py", JIT_TRIGGER),
    "blocking-async": ("fabric/injected.py", ASYNC_TRIGGER),
    "guard-boundary": ("bam/injected.py", GUARD_TRIGGER),
    "shared-state": ("serve/injected.py", STATE_TRIGGER),
    "obs-contract": ("serve/injected_obs.py", OBS_TRIGGER),
}


def test_all_registered_rules_have_fixture_coverage():
    assert set(CANONICAL_VIOLATIONS) == set(RULES)


def test_whole_repo_lints_clean_against_committed_baseline():
    rep = run_lint(baseline=BASELINE)
    assert rep.errors == []
    assert rep.stale_baseline == []
    assert rep.failing == [], "\n".join(f.render() for f in rep.failing)
    # Every committed suppression carries a justification by construction
    # (unjustified entries never index), and none is stale.
    assert all(f.justification for f in rep.suppressed)


@pytest.mark.parametrize("rule_id", sorted(CANONICAL_VIOLATIONS))
def test_injected_violation_fails_the_gate(rule_id, tmp_path):
    rel, src = CANONICAL_VIOLATIONS[rule_id]
    target = tmp_path / rel
    target.parent.mkdir(parents=True)
    target.write_text(src)
    rep = run_lint(paths=[str(tmp_path)], baseline=BASELINE)
    assert not rep.ok
    assert any(f.rule == rule_id for f in rep.failing)


# ------------------------------------------------------------------ CLI


def test_cli_lint_exits_zero_on_clean_repo(capsys):
    from spark_bam_tpu.cli.main import main

    assert main(["lint"]) == 0
    out = capsys.readouterr().out
    assert "0 failing" in out


def test_cli_lint_fails_and_writes_artifact_on_violation(tmp_path, capsys):
    from spark_bam_tpu.cli.main import main

    bad = tmp_path / "serve" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(OBS_TRIGGER)
    artifact = tmp_path / "findings.json"
    rc = main(["lint", str(tmp_path), "--no-baseline",
               "--json", str(artifact)])
    assert rc == 1
    data = json.loads(artifact.read_text())
    assert data["ok"] is False
    assert any(f["rule"] == "obs-contract" for f in data["findings"])


def test_cli_lint_unknown_rule_is_usage_error(capsys):
    from spark_bam_tpu.cli.main import main

    assert main(["lint", "--rules", "no-such-rule"]) == 2


# ------------------------------------------------- surfaced-bug regressions


def _tag(tag, typ, payload):
    return tag + typ + payload


def test_split_tags_round_trip_still_works():
    from spark_bam_tpu.cram.bam_bridge import join_tags, split_tags

    raw = (
        _tag(b"NM", b"i", struct.pack("<i", 3))
        + _tag(b"RG", b"Z", b"grp1\x00")
        + _tag(b"BC", b"B", b"c" + struct.pack("<i", 2) + b"\x01\x02")
    )
    entries = split_tags(raw)
    assert [e[0] for e in entries] == [b"NM", b"RG", b"BC"]
    assert join_tags(entries) == raw


@pytest.mark.parametrize("raw", [
    _tag(b"NM", b"i", b"\x01\x02"),                      # fixed value cut
    _tag(b"RG", b"Z", b"no-terminator"),                 # NUL never comes
    _tag(b"BC", b"B", b"c"),                             # B header cut
    _tag(b"BC", b"B", b"c" + struct.pack("<i", 99)),     # payload missing
])
def test_split_tags_truncation_raises_typed(raw):
    from spark_bam_tpu.core.guard import TruncatedInput
    from spark_bam_tpu.cram.bam_bridge import split_tags

    with pytest.raises(TruncatedInput):
        split_tags(raw)


@pytest.mark.parametrize("raw", [
    _tag(b"BC", b"B", b"q" + struct.pack("<i", 1) + b"\x00"),   # subtype
    _tag(b"BC", b"B", b"c" + struct.pack("<i", -5)),            # negative n
    _tag(b"XX", b"?", b""),                                     # type char
])
def test_split_tags_structural_damage_raises_typed(raw):
    from spark_bam_tpu.core.guard import StructurallyInvalid
    from spark_bam_tpu.cram.bam_bridge import split_tags

    with pytest.raises(StructurallyInvalid):
        split_tags(raw)


class _FakeSteps:
    """Just enough of MeshSteps for a host-only Batcher test."""

    class mesh:
        class devices:
            size = 1

    @staticmethod
    def put(x):
        return x

    def serve_step(self, **kw):
        import numpy as np

        def step(ws, ns, eofs, los, owns, lens, ncs):
            return np.zeros((ws.shape[0], 2), dtype=np.int32)

        return step


def test_batcher_tick_retarget_is_synchronized():
    from spark_bam_tpu.serve.batcher import Batcher, RowTask
    import numpy as np

    b = Batcher(_FakeSteps(), width=32, batch_rows=2, tick_ms=1.0)
    try:
        stop = threading.Event()

        def hammer(lo, hi):
            v = lo
            while not stop.is_set():
                b.set_tick_ms(v)
                v = lo if v >= hi else v + 1

        threads = [threading.Thread(target=hammer, args=(1, 5)),
                   threading.Thread(target=hammer, args=(5, 9))]
        for t in threads:
            t.start()
        futures = []
        for _ in range(16):
            task = RowTask(np.zeros(32, np.uint8), 0, False, 0, 0,
                           np.zeros(4, np.int32), 1)
            futures.append(b.submit(task))
        for f in futures:
            assert f.result(timeout=10) == (0, 0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        final = b.set_tick_ms(7.0)
        assert final == 7.0 and b.tick_s == pytest.approx(0.007)
    finally:
        b.close()


# -------------------------------------------- runtime lock-order harness


@pytest.mark.slow
def test_lock_order_recorder_flags_inversion():
    """The recorder flags an a→b / b→a order cycle even when the run
    never actually interleaved into a deadlock — the threads take the
    inverted orders strictly one after the other."""
    rec = LockOrderRecorder()
    a = rec.wrap(threading.Lock(), "a")
    b = rec.wrap(threading.Lock(), "b")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab, name="t-ab")
    t1.start(); t1.join(10)
    t2 = threading.Thread(target=ba, name="t-ba")
    t2.start(); t2.join(10)
    cycles = rec.cycles()
    assert cycles and any({"a", "b"} <= set(c) for c in cycles)
    assert rec.threads_touching("a") >= {"t-ab", "t-ba"}


@pytest.mark.slow
def test_lock_order_recorder_clean_on_consistent_order():
    rec = LockOrderRecorder()
    outer = rec.wrap(threading.Lock(), "outer")
    inner = rec.wrap(threading.Lock(), "inner")

    def work():
        for _ in range(200):
            with outer:
                with inner:
                    pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert rec.cycles() == []
    assert rec.acquisitions["outer"] == 800


@pytest.mark.slow
def test_batcher_seam_happens_before_under_load(monkeypatch):
    """Observed-evidence twin of the static shared-state pass: wrap the
    Batcher's condition lock and prove both the tick thread and foreign
    mutator threads acquire it (the happens-before edge the PR's
    ``set_tick_ms`` fix introduced)."""
    from spark_bam_tpu.serve.batcher import Batcher

    rec = LockOrderRecorder()
    real_condition = threading.Condition

    def traced_condition(lock=None):
        # Bare Condition() is the Batcher's seam lock; Event/others pass
        # their own lock and stay untraced.
        if lock is None:
            return real_condition(rec.wrap(threading.Lock(), "cond"))
        return real_condition(lock)

    monkeypatch.setattr(threading, "Condition", traced_condition)
    b = Batcher(_FakeSteps(), width=32, batch_rows=2, tick_ms=1.0)
    monkeypatch.undo()
    try:

        def mutate():
            for i in range(50):
                b.set_tick_ms(1.0 + (i % 5))
                b.set_batch_rows(1 + (i % 3))

        threads = [threading.Thread(target=mutate, name=f"mut-{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        time.sleep(0.2)             # a few empty batcher wakeups
        touching = rec.threads_touching("cond")
        assert "serve-batcher" in touching
        assert {f"mut-{i}" for i in range(3)} <= touching
        assert rec.cycles() == []
    finally:
        b.close()
