"""The .bai builder/writer (bam/bai.py build_bai/index_bam — the
samtools-index role, beyond the reference which only consumes .bai):
format round-trip, agreement with the shipped samtools index on real
fixtures, and brute-force-validated interval loads on generated BAMs."""

import shutil

import numpy as np

from spark_bam_tpu.bam.bai import BaiIndex, index_bam
from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bgzf.stream import BlockStream, UncompressedBytes
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.load.api import load_bam_intervals

from conftest import FIXTURES

BAM2 = FIXTURES / "2.bam"


def _names(recs):
    return [(r.read_name, r.flag, r.pos) for r in recs]


def test_matches_shipped_samtools_index(tmp_path):
    bam = tmp_path / "2.bam"
    shutil.copy(BAM2, bam)
    out, idx = index_bam(bam)
    assert BaiIndex.read(out).n_no_coor == idx.n_no_coor

    loci_list = ["1:1-100000", "1:13000-18000", "1:99999-100001", "2:1-50000"]
    ours = {
        loci: _names(load_bam_intervals(bam, loci)) for loci in loci_list
    }
    shutil.copy(str(BAM2) + ".bai", str(bam) + ".bai")  # replace with samtools'
    for loci in loci_list:
        assert ours[loci] == _names(load_bam_intervals(bam, loci)), loci


def test_fuzz_interval_loads_vs_brute_force(tmp_path):
    from tests.bam_factories import random_bam

    rng = np.random.default_rng(99)
    bam = tmp_path / "s.bam"
    # Single contig ⇒ the factory's monotonically increasing pos makes the
    # file coordinate-sorted, as BAI requires.
    random_bam(
        bam, 99, contigs=(("chr1", 2_000_000),), n_records=(300, 301),
        pos_step=(1, 40), read_len=(10, 800), mapped_rate=0.9,
    )
    index_bam(bam)

    stream = RecordStream(UncompressedBytes(BlockStream(open_channel(bam))))
    all_recs = [r for _, r in stream]

    for _ in range(12):
        a = int(rng.integers(1, 20_000))
        b = a + int(rng.integers(1, 5_000))
        loci = f"chr1:{a}-{b}"
        got = _names(load_bam_intervals(bam, loci))
        # Same overlap rule the loader applies (0-based [pos, end_pos)
        # vs the locus' half-open range).
        want = _names([
            r for r in all_recs
            if r.ref_id >= 0 and not r.is_unmapped
            and r.pos < b and r.end_pos() > a - 1
        ])
        assert got == want, loci


def test_unplaced_reads_count_no_coor(tmp_path):
    from tests.bam_factories import random_bam

    bam = tmp_path / "u.bam"
    random_bam(
        bam, 5, contigs=(("chr1", 2_000_000),), n_records=(120, 121),
        mapped_rate=0.5,
    )
    _, idx = index_bam(bam)
    stream = RecordStream(UncompressedBytes(BlockStream(open_channel(bam))))
    unplaced = sum(1 for _, r in stream if r.ref_id < 0)
    assert unplaced > 0
    assert idx.n_no_coor == unplaced


def test_fuzz_multi_contig_sorted(tmp_path):
    from tests.bam_factories import random_bam

    rng = np.random.default_rng(321)
    bam = tmp_path / "m.bam"
    random_bam(
        bam, 321, contigs=(("chr1", 1_000_000), ("chr2", 800_000)),
        n_records=(300, 301), pos_step=(1, 30), read_len=(10, 400),
        mapped_rate=0.85, sort=True,
    )
    index_bam(bam)
    stream = RecordStream(UncompressedBytes(BlockStream(open_channel(bam))))
    all_recs = [r for _, r in stream]

    for contig in ("chr1", "chr2"):
        for _ in range(6):
            a = int(rng.integers(1, 10_000))
            b = a + int(rng.integers(1, 4_000))
            got = _names(load_bam_intervals(bam, f"{contig}:{a}-{b}"))
            ref_idx = 0 if contig == "chr1" else 1
            want = _names([
                r for r in all_recs
                if r.ref_id == ref_idx and not r.is_unmapped
                and r.pos < b and r.end_pos() > a - 1
            ])
            assert got == want, f"{contig}:{a}-{b}"


def test_unsorted_bam_refused(tmp_path):
    """Indexing unsorted input would silently drop records at query time
    (linear-index pruning assumes coordinate order) — it must refuse,
    like samtools."""
    import pytest

    from tests.bam_factories import random_bam

    bam = tmp_path / "unsorted.bam"
    # Two contigs with random interleaving: not coordinate-sorted.
    random_bam(bam, 4, contigs=(("chr1", 1_000_000), ("chr2", 800_000)))
    with pytest.raises(ValueError, match="not coordinate-sorted"):
        index_bam(bam)
    assert not (tmp_path / "unsorted.bam.bai").exists()
