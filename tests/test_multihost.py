"""Multi-host proof: 2 JAX processes × 4 virtual CPU devices, one global
8-way mesh, sharded check step with cross-process psum (Gloo transport —
the DCN stand-in). Each process feeds distinct windows; the reduced
confusion matrix must mix both hosts' contributions exactly.

Launch recipe under test: spark_bam_tpu/parallel/multihost.py docstring.
"""

import json
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_sharded_check(tmp_path):
    port = _free_port()
    args = [
        sys.executable, "-m", "spark_bam_tpu.parallel.multihost",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2", "--local-devices", "4",
    ]
    # File-backed output: a PIPE would deadlock a chatty child (Gloo logs)
    # and we still want diagnostics on failure.
    p1_log = (tmp_path / "p1.log").open("w+")
    p1 = subprocess.Popen(
        [*args, "--process-id", "1"],
        cwd=REPO, stdout=p1_log, stderr=subprocess.STDOUT,
    )
    try:
        p0 = subprocess.run(
            [*args, "--process-id", "0"],
            cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        rc1 = p1.wait(timeout=60)
    finally:
        p1.kill()
        p1_log.seek(0)
        p1_out = p1_log.read()
        p1_log.close()
    assert rc1 == 0, p1_out[-2000:]
    assert p0.returncode == 0, p0.stderr[-2000:]
    stats = json.loads(p0.stdout.strip().splitlines()[-1])
    assert stats["ok"], stats
    assert stats["processes"] == 2
    assert stats["global_devices"] == 8
    # Row r holds 40+r records; trailing noise breaks the last 9 chains.
    assert stats["true_positives"] == sum(40 + r - 9 for r in range(8)) == 276
    assert stats["false_negatives"] == 72
    assert stats["false_positives"] == 0


def test_two_process_bam_count(tmp_path):
    """Real-data multi-host (VERDICT r3 item 5): two processes each inflate
    their own block-range shard of a synthesized BAM (halos stitched from
    the following blocks), and the psum'd global count must equal the
    synthesis manifest exactly."""
    from spark_bam_tpu.benchmarks.synth import synth_bam

    bam = tmp_path / "multi.bam"
    manifest = synth_bam(bam, 4 << 20)

    port = _free_port()
    args = [
        sys.executable, "-m", "spark_bam_tpu.parallel.multihost",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2", "--local-devices", "4",
        "--bam", str(bam),
        # A tiny chunk budget forces several accumulate-psum chunks per
        # process (the O(chunk) host-memory discipline under test).
        "--row-bytes", str(1 << 20), "--halo", str(256 << 10),
        "--chunk-bytes", str(8 << 20),
    ]
    p1_log = (tmp_path / "p1.log").open("w+")
    p1 = subprocess.Popen(
        [*args, "--process-id", "1"],
        cwd=REPO, stdout=p1_log, stderr=subprocess.STDOUT,
    )
    try:
        p0 = subprocess.run(
            [*args, "--process-id", "0"],
            cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        rc1 = p1.wait(timeout=60)
    finally:
        p1.kill()
        p1_log.seek(0)
        p1_out = p1_log.read()
        p1_log.close()
    assert rc1 == 0, p1_out[-2000:]
    assert p0.returncode == 0, p0.stderr[-2000:]
    stats = json.loads(p0.stdout.strip().splitlines()[-1])
    assert stats["ok"], stats
    assert stats["processes"] == 2
    assert stats["global_devices"] == 8
    assert stats["escaped"] == 0
    assert stats["count"] == manifest["reads"]
    # The tiny chunk budget must actually exercise the multi-chunk
    # accumulate-psum loop, not collapse to one chunk.
    assert stats["chunks"] >= 2, stats
