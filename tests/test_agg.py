"""Fused on-device aggregation plane (docs/analytics.md "Aggregation").

The contract under test: the device reduction (agg/kernels.py, running
through the shard_map mesh step over 8 virtual devices — conftest.py)
must produce vectors byte-identical to the numpy record oracle
(agg/host.py) for every metric and every predicate combination, the
result must round-trip the wire schema exactly, and the serve/CLI
surfaces must expose the same numbers.
"""

import json
import os

import numpy as np
import pytest

from spark_bam_tpu.agg import (
    AggConfig,
    aggregate_planes,
    columns_from_records,
    combine,
    decode_result,
    encode_result,
    host_aggregate,
)
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.record import BamRecord, encode_tag
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.load import api
from spark_bam_tpu.load.api import load_bam

from tests.bam_factories import random_bam

pytestmark = pytest.mark.agg

PLAN = AggConfig.parse("")


# ------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("agg") / "plain.bam")
    random_bam(p, seed=7, index=True, sort=True)
    return p


@pytest.fixture(scope="module")
def tagged(tmp_path_factory):
    """A BAM whose records carry a deterministic mix of NM/RG/BC tags
    (every 3rd/5th/7th record), mapped and unmapped, for predicate
    tests. Returns (path, records)."""
    src = str(tmp_path_factory.mktemp("agg_tag_src") / "seed.bam")
    random_bam(src, seed=11, index=False, sort=True)
    header = read_header(src)
    rng = np.random.default_rng(3)
    recs = []
    # Coordinate order (the .bai builder refuses unsorted input): 200
    # mapped reads split across the two contigs, then 40 unmapped.
    for i in range(240):
        n = int(rng.integers(20, 150))
        mapped = i < 200
        tags = b""
        if i % 3 == 0:
            tags += encode_tag(f"NM:i:{int(rng.integers(0, 5))}")
        if i % 5 == 0:
            tags += encode_tag("RG:Z:grp1")
        if i % 7 == 0:
            tags += encode_tag("BC:B:I,1,2,3")
        recs.append(BamRecord(
            ref_id=(i // 100) if mapped else -1,
            pos=5 + 13 * (i % 100) if mapped else -1,
            mapq=int(rng.integers(0, 61)) if mapped else 0, bin=0,
            flag=(16 if i % 2 else 0) if mapped else 4,
            next_ref_id=-1, next_pos=-1,
            tlen=int(rng.integers(-900, 900)),
            read_name=f"r{i}", cigar=[(n, 0)] if mapped else [],
            seq="A" * n, qual=bytes([30] * n), tags=tags,
        ))
    p = str(tmp_path_factory.mktemp("agg_tag") / "tagged.bam")
    write_bam(p, header, recs, block_payload=5000)
    from spark_bam_tpu.bam.bai import index_bam

    index_bam(p)
    return p, recs


def _records(path):
    recs = list(load_bam(path))
    return [r[-1] if isinstance(r, tuple) else r for r in recs]


def _nc(path):
    return len(read_header(path).contig_lengths.lengths_list())


def _assert_equal(metrics, oracle):
    assert set(metrics) == set(oracle)
    for k in oracle:
        got = np.asarray(metrics[k]).reshape(-1)
        assert got.dtype == np.int64
        assert np.array_equal(got, oracle[k]), k


# ------------------------------------------------------------- grammar
def test_parse_default_spec():
    plan = AggConfig.parse("")
    assert plan.canonical() == "count;flagstat;mapq;tlen;coverage"
    assert plan is AggConfig.parse("")          # lru-cached identity
    assert plan.total_length(2) == 3 + 13 + 256 + 2002 + 2 * 512


def test_parse_params_roundtrip():
    plan = AggConfig.parse("coverage:bins=64,bin=500,cap=4 ; count")
    assert plan.canonical() == "coverage:bin=500,bins=64,cap=4;count"
    cov = plan.specs[0]
    assert (cov.get("bin"), cov.get("bins"), cov.get("cap")) == (500, 64, 4)
    assert cov.shape(3) == (3, 64)
    # Canonical form reparses to the same plan.
    assert AggConfig.parse(plan.canonical()).canonical() == plan.canonical()


@pytest.mark.parametrize("bad", [
    "bogus",                      # unknown metric
    "coverage:widths=3",          # unknown param
    "tlen:max=abc",               # non-integer value
    "coverage:bins",              # missing =
    "mapq;mapq",                  # duplicate metric
    "tlen:max=0",                 # below 1
    ";;",                         # empty after split
])
def test_parse_rejects(bad):
    with pytest.raises(ValueError):
        AggConfig.parse(bad)


def test_wire_roundtrip_and_validation():
    plan = AggConfig.parse("count;mapq")
    contigs = [("chr1", 1000), ("chr2", 500)]
    vectors = {
        "count": np.arange(3, dtype=np.int64),
        "mapq": np.arange(256, dtype=np.int64),
    }
    meta, payload = encode_result(plan, 2, contigs, vectors)
    assert meta["agg"] == "count;mapq"
    assert meta["elements"] * 8 == len(payload)
    json.dumps(meta)                              # JSON-able contract
    dec = decode_result(meta, payload)
    _assert_equal(dec, {k: v.reshape(-1) for k, v in vectors.items()})
    with pytest.raises(ValueError):
        decode_result(meta, payload[:-8])         # truncated payload
    with pytest.raises(ValueError):
        encode_result(plan, 2, contigs, {
            "count": np.zeros(4, np.int64),       # wrong length
            "mapq": vectors["mapq"],
        })


# -------------------------------------------- device vs oracle (planes)
def test_device_matches_oracle_whole_file(bam):
    res = api.aggregate(bam)
    oracle = host_aggregate(
        columns_from_records(_records(bam)), PLAN, _nc(bam)
    )
    _assert_equal(res["metrics"], oracle)
    assert res["rows"] == int(oracle["count"][0])
    assert res["agg"] == PLAN.canonical()


def test_device_matches_oracle_small_chunks(bam):
    """Multi-window carry: a tiny chunk forces many device steps with
    int32 carry + int64 flushes; answers must not move."""
    base = api.aggregate(bam)
    small = api.aggregate(bam, chunk=64)
    _assert_equal(small["metrics"], base["metrics"])


def test_device_matches_oracle_filtered(tagged):
    path, recs = tagged
    nc = _nc(path)
    # flags: mapped, reverse-strand only.
    res = api.aggregate(path, flags_required=16, flags_forbidden=4)
    sub = [r for r in recs if (r.flag & 16) and not (r.flag & 4)]
    _assert_equal(res["metrics"], host_aggregate(
        columns_from_records(sub), PLAN, nc))
    # tag presence (single, and conjunction).
    res = api.aggregate(path, tags_required=("NM",))
    sub = [r for i, r in enumerate(recs) if i % 3 == 0]
    assert res["rows"] == len(sub)
    _assert_equal(res["metrics"], host_aggregate(
        columns_from_records(sub), PLAN, nc))
    res = api.aggregate(path, tags_required=("NM", "RG"))
    sub = [r for i, r in enumerate(recs) if i % 3 == 0 and i % 5 == 0]
    assert res["rows"] == len(sub)
    _assert_equal(res["metrics"], host_aggregate(
        columns_from_records(sub), PLAN, nc))


def test_device_empty_selection(tagged):
    path, _ = tagged
    res = api.aggregate(path, agg="count;flagstat", flags_required=2048)
    assert res["rows"] == 0
    assert all(int(v.sum()) == 0 for v in res["metrics"].values())


def test_bad_tag_name_rejected(bam):
    with pytest.raises(ValueError):
        api.aggregate(bam, tags_required=("NMX",))


def test_combine_matches_single_pass(tagged):
    path, recs = tagged
    nc = _nc(path)
    whole = host_aggregate(columns_from_records(recs), PLAN, nc)
    parts = [
        host_aggregate(columns_from_records(recs[:100]), PLAN, nc),
        None,                                     # dead partition
        host_aggregate(columns_from_records(recs[100:]), PLAN, nc),
    ]
    _assert_equal(combine(parts, PLAN, nc), whole)


def test_aggregate_planes_rejects_bad_chunk(bam):
    with pytest.raises(ValueError):
        api.aggregate(bam, chunk=-1)


# --------------------------------------------------- record path (CRAM)
def test_cram_dataset_matches_bam(tagged, tmp_path):
    from spark_bam_tpu.cram import CramWriter

    path, recs = tagged
    header = read_header(path)
    cram = tmp_path / "tagged.cram"
    with CramWriter(cram, header.contig_lengths, header.text) as w:
        w.write_all(recs)
    bam_res = api.aggregate(path)
    cram_res = api.aggregate(str(cram))
    _assert_equal(cram_res["metrics"], {
        k: np.asarray(v).reshape(-1) for k, v in bam_res["metrics"].items()
    })
    assert cram_res["rows"] == bam_res["rows"]


# ----------------------------------------------------------- serve op
def test_serve_aggregate_roundtrip(tagged):
    from spark_bam_tpu.serve.service import ServiceError, SplitService

    path, recs = tagged
    nc = _nc(path)
    svc = SplitService()
    try:
        out = svc._handle_aggregate({"path": path}, None)
        assert out["binary_frames"] == len(out["_binary"]) == 1
        assert out["binary_bytes"] == len(out["_binary"][0])
        dec = decode_result(out["result"], out["_binary"][0])
        _assert_equal(dec, host_aggregate(
            columns_from_records(recs), PLAN, nc))
        # Predicates compose; answers stay oracle-equal.
        out2 = svc._handle_aggregate({
            "path": path, "agg": "count;mapq",
            "flags_forbidden": 4, "tags_required": "NM",
        }, None)
        plan2 = AggConfig.parse("count;mapq")
        sub = [
            r for i, r in enumerate(recs)
            if i % 3 == 0 and not (r.flag & 4)
        ]
        _assert_equal(
            decode_result(out2["result"], out2["_binary"][0]),
            host_aggregate(columns_from_records(sub), plan2, nc),
        )
        assert out2["rows"] == len(sub)
        # Protocol errors, not stack traces.
        with pytest.raises(ServiceError):
            svc._handle_aggregate({"path": path, "agg": "bogus"}, None)
        with pytest.raises(ServiceError):
            svc._handle_aggregate({"path": path, "chunk": 0}, None)
        with pytest.raises(ServiceError):
            svc._handle_aggregate(
                {"path": path, "tags_required": "TOOLONG"}, None
            )
    finally:
        svc.close()


def test_serve_aggregate_deterministic_and_resumable(tagged):
    """Same query ⇒ same bytes (the property the streaming-failover
    resume token and the chaos byte-equality gates rely on)."""
    from spark_bam_tpu.serve.service import ServiceError, SplitService

    path, _ = tagged
    svc = SplitService()
    try:
        a = svc._handle_aggregate({"path": path}, None)
        b = svc._handle_aggregate({"path": path}, None)
        assert a["_binary"] == b["_binary"]
        # The result is a single frame, so the only valid resume token
        # is 0 — out-of-range tokens are protocol errors, same as batch.
        with pytest.raises(ServiceError):
            svc._handle_aggregate({"path": path, "resume_from": 1}, None)
    finally:
        svc.close()


# ---------------------------------------------------------------- CLI
def test_cli_aggregate_tsv_and_json(tagged, tmp_path):
    from spark_bam_tpu.cli.main import main

    path, recs = tagged
    nc = _nc(path)
    out = tmp_path / "agg.tsv"
    assert main(["aggregate", "-a", "count;flagstat", str(path),
                 "-o", str(out)]) == 0
    rows = dict()
    for line in out.read_text().splitlines():
        metric, key, value = line.split("\t")
        rows[(metric, key)] = int(value)
    oracle = host_aggregate(
        columns_from_records(recs), AggConfig.parse("count;flagstat"), nc
    )
    assert rows[("count", "records")] == int(oracle["count"][0])
    assert rows[("count", "mapped")] == int(oracle["count"][1])
    assert rows[("flagstat", "total")] == int(oracle["flagstat"][0])

    out_json = tmp_path / "agg.json"
    assert main(["aggregate", "--format", "json", str(path),
                 "-o", str(out_json)]) == 0
    doc = json.loads(out_json.read_text())
    full = host_aggregate(columns_from_records(recs), PLAN, nc)
    for k, vec in doc["metrics"].items():
        assert vec == [int(x) for x in full[k]], k
    assert doc["agg"] == PLAN.canonical()


def test_cli_aggregate_bad_spec_is_usage_error(tagged, tmp_path):
    from spark_bam_tpu.cli.main import main

    path, _ = tagged
    assert main(["aggregate", "-a", "bogus", str(path),
                 "-o", str(tmp_path / "x")]) == 2
