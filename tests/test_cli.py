"""End-to-end CLI golden-output tests (the reference's MainSuite pattern:
full stdout compared against checked-in goldens, timing lines via regex)."""

import re
from pathlib import Path

import pytest

from spark_bam_tpu.cli.main import main

GOLDEN = Path("/root/reference/cli/src/test/resources/output")


def run_cli(args, tmp_path, name="out.txt") -> str:
    out = tmp_path / name
    assert main(args + ["-o", str(out)]) == 0
    return out.read_text()


def test_check_bam_1bam_golden(bam1, tmp_path):
    got = run_cli(["check-bam", str(bam1)], tmp_path)
    assert got == (GOLDEN / "check-bam" / "1.bam").read_text()


def test_full_check_1bam_golden(bam1, tmp_path):
    got = run_cli(["full-check", str(bam1)], tmp_path)
    assert got == (GOLDEN / "full-check" / "1.bam").read_text()


def test_full_check_2bam_golden(bam2, tmp_path):
    got = run_cli(["full-check", str(bam2)], tmp_path)
    assert got == (GOLDEN / "full-check" / "2.bam").read_text()


def test_check_blocks_1bam_upstream(bam1, tmp_path):
    got = run_cli(["check-blocks", "-u", str(bam1)], tmp_path)
    assert got == (
        "First read-position mismatched in 1 of 25 BGZF blocks\n"
        "\n"
        "25871 of 597482 (0.043300049206503294) compressed positions would lead to bad splits\n"
        "\n"
        "Offsets of blocks' first reads (0 blocks didn't contain a read start):\n"
        "N: 25, μ/σ: 2004/8950, med/mad: 191/110\n"
        " elems: 1 25 28 39 42 45 81 112 136 143 … 268 270 271 287 301 304 311 312 316 45846\n"
        "   5:\t8\n"
        "  10:\t27\n"
        "  25:\t63\n"
        "  50:\t191\n"
        "  75:\t294\n"
        "  90:\t314\n"
        "  95:\t32187\n"
        "\n"
        "1 mismatched blocks:\n"
        "\t239479 (prev block size: 25871):\t239479:312\t239479:311\n"
    )


def test_check_blocks_2bam(bam2, tmp_path):
    got = run_cli(["check-blocks", str(bam2)], tmp_path)
    assert got.startswith(
        "First read-position matched in 25 BGZF blocks totaling 519KB (compressed)\n"
        "\n"
        "Offsets of blocks' first reads (0 blocks didn't contain a read start):\n"
        "N: 25, μ/σ: 604/1049, med/mad: 470/152\n"
    )


def test_compute_splits_eager_230k(bam1, tmp_path):
    got = run_cli(["compute-splits", "-s", "-m", "230k", str(bam1)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"Get spark-bam splits: \d+ms", lines[0])
    assert lines[2:] == [
        "Split-size distribution:",
        "N: 3, μ/σ: 194067/57877.4, med/mad: 224301/20521",
        " elems: 224301 244822 113078",
        "sorted: 113078 224301 244822",
        "",
        "3 splits:",
        "\t0:45846-239479:312",
        "\t239479:312-484396:25",
        "\t484396:25-597482:0",
        "",
    ]


def test_compute_splits_host_plan(bam1, tmp_path, monkeypatch):
    """--plan-hosts renders the per-host sharded-run IO plan (byte ranges
    partitioning the file with a halo seam overlap)."""
    monkeypatch.setenv("SPARK_BAM_WINDOW_SIZE", "256KB")
    monkeypatch.setenv("SPARK_BAM_HALO_SIZE", "64KB")
    got = run_cli(
        ["compute-splits", "-s", "-m", "230k", "--plan-hosts", "2",
         "--devices-per-host", "4", str(bam1)],
        tmp_path,
    )
    assert "2-host plan (4 devices/host):" in got
    lines = [l for l in got.splitlines() if l.startswith("\thost ")]
    assert len(lines) == 2
    assert lines[0].startswith("\thost 0: bytes [0, ")
    assert "owned uncompressed" in lines[0]


def test_compute_splits_seqdoop_230k(bam1, tmp_path):
    got = run_cli(["compute-splits", "-u", "-m", "230k", str(bam1)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"Get hadoop-bam splits: \d+ms", lines[0])
    assert lines[7:] == [
        "3 splits:",
        "\t0:45846-235520:65535",
        "\t239479:311-471040:65535",
        "\t484396:25-597482:65535",
        "",
    ]


def test_compute_splits_compare_230k(bam1, tmp_path):
    got = run_cli(["compute-splits", "-m", "230k", str(bam1)], tmp_path)
    lines = got.splitlines()
    assert lines[3:] == [
        "2 splits differ (totals: 3, 3):",
        "\t\t239479:311-471040:65535",
        "\t239479:312-484396:25",
        "",
    ]


def test_compute_splits_compare_240k_match(bam1, tmp_path):
    got = run_cli(["compute-splits", "-m", "240k", str(bam1)], tmp_path)
    assert "All splits matched!" in got
    assert "N: 3, μ/σ: 194067/74433.1, med/mad: 244941/3497" in got


def test_count_reads_matched(bam1, tmp_path):
    got = run_cli(["count-reads", "-m", "240k", str(bam1)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"spark-bam read-count time: \d+", lines[0])
    assert re.fullmatch(r"hadoop-bam read-count time: \d+", lines[1])
    assert lines[2] == ""
    assert lines[3] == "Read counts matched: 4917"


def _cram_from_bam(bam, tmp_path):
    """Round-trip a fixture BAM into a CRAM for CLI tests."""
    from spark_bam_tpu.bam.iterators import RecordStream
    from spark_bam_tpu.bgzf.stream import BlockStream, UncompressedBytes
    from spark_bam_tpu.core.channel import open_channel
    from spark_bam_tpu.cram import CramWriter

    stream = RecordStream(UncompressedBytes(BlockStream(open_channel(bam))))
    header = stream.header
    recs = [rec for _, rec in stream]
    cram = tmp_path / (Path(bam).stem + ".cram")
    with CramWriter(cram, header.contig_lengths, header.text) as w:
        w.write_all(recs)
    return cram


def test_count_reads_cram(bam2, tmp_path):
    cram = _cram_from_bam(bam2, tmp_path)
    got = run_cli(["count-reads", str(cram)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"spark-bam read-count time: \d+", lines[0])
    assert lines[1] == "Read count: 2500"


def test_count_reads_hadoop_fails(bam1, tmp_path):
    # At 230k the hadoop-bam split start is the 239479:311 false positive;
    # decoding from it must fail SAM validation.
    got = run_cli(["count-reads", "-m", "230k", str(bam1)], tmp_path)
    assert "spark-bam found 4917 reads, hadoop-bam threw exception:" in got
    assert "SAM validation error" in got


def test_time_load(bam1, tmp_path):
    got = run_cli(["time-load", "-m", "240k", str(bam1)], tmp_path)
    assert "All 3 partition-start reads matched" in got
    got = run_cli(["time-load", "-m", "230k", str(bam1)], tmp_path, "out2.txt")
    assert "spark-bam collected 3 partitions' first-reads" in got
    assert "hadoop-bam threw an exception:" in got


def test_compare_splits(bam1, bam2, tmp_path):
    bams = tmp_path / "bams.txt"
    bams.write_text(f"{bam1}\n{bam2}\n")
    got = run_cli(["compare-splits", "-m", "230k", str(bams)], tmp_path)
    lines = got.splitlines()
    assert lines[0] == (
        "1 of 2 BAMs' splits didn't match (totals: 6, 6; 1, 1 unmatched)"
    )
    assert "\t1.bam: 2 splits differ (totals: 3, 3; mismatched: 1, 1):" in lines
    assert "\t\t\t239479:311-471040:65535" in lines
    assert "\t\t239479:312-484396:25" in lines


def test_compare_splits_all_match(bam2, tmp_path):
    bams = tmp_path / "bams.txt"
    bams.write_text(f"{bam2}\n")
    got = run_cli(["compare-splits", "-m", "100k", str(bams)], tmp_path)
    assert got.splitlines()[0] == "All 1 BAMs' splits (totals: 6, 6) matched!"


def test_index_commands(bam2, tmp_path, capsys):
    out_blocks = tmp_path / "b.blocks"
    out_records = tmp_path / "r.records"
    assert main(["index-blocks", "-o", str(out_blocks), str(bam2)]) == 0
    assert main(["index-records", "-o", str(out_records), str(bam2)]) == 0
    assert out_blocks.read_text() == Path(str(bam2) + ".blocks").read_text()
    assert out_records.read_text() == Path(str(bam2) + ".records").read_text()


def test_rewrite_roundtrip(bam2, tmp_path):
    out_bam = tmp_path / "rewritten.bam"
    got = run_cli(
        ["htsjdk-rewrite", "-b", "5000", "-i", str(bam2), str(out_bam)], tmp_path
    )
    assert f"Wrote 2500 reads to {out_bam}" in got
    # The rewritten file loads identically.
    from spark_bam_tpu.load.api import load_bam

    assert load_bam(out_bam, split_size=1_000_000).count() == 2500


def test_cli_knobs(bam2, tmp_path):
    # reads-to-check=1 weakens the chain requirement: more boundary calls
    # than the .records truth (false positives appear), demonstrating the
    # knob reaches the engine.
    got = run_cli(
        ["check-bam", "-s", "--reads-to-check", "1", str(bam2)],
        tmp_path, "knobs.txt",
    )
    assert "false positives" in got or "All calls matched!" in got


def test_full_check_interval_goldens(bam2, tmp_path):
    """The reference's -i golden files (FullCheckTest.scala:34-60)."""
    for name, args in [
        ("2.bam.first", ["-i", "0"]),
        ("2.bam.second", ["-i", "26169"]),
        ("2.bam.200k", ["-i", "0-200k", "-m", "100k"]),
    ]:
        got = run_cli(["full-check", *args, str(bam2)], tmp_path, name + ".txt")
        assert got == (GOLDEN / "full-check" / name).read_text(), name


def test_full_check_noindex_golden(bam1, tmp_path):
    """full-check without .records: no confusion header (golden
    1.noblocks.bam)."""
    import shutil

    bam_copy = tmp_path / "1.noblocks.bam"
    shutil.copyfile(bam1, bam_copy)
    got = run_cli(["full-check", str(bam_copy)], tmp_path)
    assert got == (GOLDEN / "full-check" / "1.noblocks.bam").read_text()


def test_check_blocks_1bam_default_and_spark(bam1, tmp_path):
    # Default (eager vs seqdoop) mismatches exactly like -u; -s (truth vs
    # eager) matches everywhere (CheckBlocksTest.scala:9-53).
    got = run_cli(["check-blocks", str(bam1)], tmp_path, "d.txt")
    assert got.splitlines()[0] == "First read-position mismatched in 1 of 25 BGZF blocks"
    assert "\t239479 (prev block size: 25871):\t239479:312\t239479:311" in got

    got_s = run_cli(["check-blocks", "-s", str(bam1)], tmp_path, "s.txt")
    assert got_s.splitlines()[0] == (
        "First read-position matched in 25 BGZF blocks totaling 583KB (compressed)"
    )


def test_main_help_lists_all_commands(capsys):
    """Reference MainTest analog: the usage text names every subcommand and
    exits cleanly (exit trapped, not raised into the caller)."""
    with pytest.raises(SystemExit) as e:
        main(["--help"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    for cmd in (
        "check-bam", "check-blocks", "full-check", "compute-splits",
        "compare-splits", "count-reads", "time-load", "index-blocks",
        "index-records", "htsjdk-rewrite",
    ):
        assert cmd in out, f"{cmd} missing from usage"


def test_main_unknown_command_fails(capsys):
    with pytest.raises(SystemExit) as e:
        main(["frobnicate"])
    assert e.value.code != 0
    assert "invalid choice" in capsys.readouterr().err


def test_count_reads_sharded(bam2, tmp_path):
    got = run_cli(["count-reads", "--sharded", str(bam2)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"spark-bam read-count time: \d+", lines[0])
    assert lines[1] == "Read count: 2500"


def test_count_reads_resident(bam2, tmp_path):
    """--resident (resident-scan mode: one dispatch per HBM chunk) must
    count exactly, through the CLI surface."""
    got = run_cli(["count-reads", "--resident", str(bam2)], tmp_path)
    lines = got.splitlines()
    assert re.fullmatch(r"spark-bam read-count time: \d+", lines[0])
    assert lines[1] == "Read count: 2500"


def test_check_bam_sharded(bam1, tmp_path):
    got = run_cli(["check-bam", "--sharded", str(bam1)], tmp_path)
    golden = (GOLDEN / "check-bam" / "1.bam").read_text()
    # Header block identical to the golden report's first four lines
    # (eager-vs-truth has no miscalls; the golden's FP lines are the
    # seqdoop comparison's).
    assert got.splitlines() == [
        *golden.splitlines()[:4],
        "checked across 8 device(s)",
        "All calls matched!",
    ]


def test_sharded_flag_conflicts_are_usage_errors(bam1, capsys):
    assert main(["check-bam", "--sharded", "-u", str(bam1)]) == 2
    assert "no sharded path" in capsys.readouterr().err
    assert main(["count-reads", "--sharded", "x.cram"]) == 2
    assert "BAM only" in capsys.readouterr().err


def test_full_check_streaming_matches_golden_sections(bam2, tmp_path):
    """full-check --streaming (the WGS-scale O(window) path): every
    mask-derived section — two-check histogram, per-flag totals, total
    error counts — is byte-identical to the reference golden; the
    position list carries the same positions, unannotated."""
    got = run_cli(["full-check", "--streaming", str(bam2)], tmp_path)
    golden = (GOLDEN / "full-check" / "2.bam").read_text()

    assert got.startswith(
        "No positions where only one check failed\n"
        "\n"
        "10 of 2880 positions where exactly two checks failed:\n"
        "\t0:5649\n"
    )
    hist_start = golden.index("\tHistogram:")
    assert golden[hist_start: golden.index("Total error counts:")] in got
    assert golden[golden.index("Total error counts:"):] in got


def test_full_check_streaming_rejects_intervals(bam2, capsys):
    assert main(["full-check", "--streaming", "-i", "0-100k", str(bam2)]) == 2
    assert "not supported on the streaming path" in capsys.readouterr().err


def test_index_bam_command(bam2, tmp_path, capsys):
    import shutil

    bam = tmp_path / "2.bam"
    shutil.copy(bam2, bam)
    assert main(["index-bam", str(bam)]) == 0
    err = capsys.readouterr().err
    assert "84 references" in err
    from spark_bam_tpu.bam.bai import BaiIndex

    assert len(BaiIndex.read(str(bam) + ".bai").references) == 84


def test_compare_splits_corpus(bam2, tmp_path):
    """The many-BAM cohort shape (BASELINE config: compute-splits over a
    corpus; reference CompareSplits runs one task per BAM): ten repacks of
    2.bam at varied block payloads, every one's splits matching."""
    from spark_bam_tpu.cli import rewrite
    from spark_bam_tpu.cli.output import Printer

    paths = []
    for i, payload in enumerate(range(12_000, 62_000, 5_000)):
        out = tmp_path / f"r{i}.bam"
        rewrite.run(str(bam2), str(out), Printer(), block_payload=payload,
                    reindex=False)
        paths.append(out)
    bams = tmp_path / "bams.txt"
    bams.write_text("".join(f"{p}\n" for p in paths))
    got = run_cli(["compare-splits", "-m", "100k", str(bams)], tmp_path)
    assert got.splitlines()[0] == (
        f"All {len(paths)} BAMs' splits (totals: 60, 60) matched!"
    )


def test_count_reads_resident_sharded_conflict(bam2, capsys):
    """--resident and --sharded are mutually exclusive."""
    assert main(["count-reads", "--resident", "--sharded", str(bam2)]) != 0
    assert "mutually exclusive" in capsys.readouterr().err


def test_count_reads_config_resident_skips_cram(bam2, tmp_path, monkeypatch):
    """A global resident-scan opt-in (env) must not break CRAM counting —
    the mode simply doesn't apply there (review catch: the config-
    triggered branch used to raise '--resident supports BAM only' for a
    flag the user never passed)."""
    cram = _cram_from_bam(bam2, tmp_path)
    monkeypatch.setenv("SPARK_BAM_RESIDENT_SCAN", "1")
    got = run_cli(["count-reads", str(cram)], tmp_path)
    assert "Read count: 2500" in got
