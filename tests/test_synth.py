"""Benchmark big-BAM synthesis: block repetition must preserve record
framing exactly (every repeat starts at a block and record boundary)."""

import json

from spark_bam_tpu.benchmarks.synth import FIXTURE_READS, synth_bam
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
from spark_bam_tpu.load.api import load_bam


def test_synth_bam_counts(tmp_path):
    out = tmp_path / "big.bam"
    manifest = synth_bam(out, 4 << 20)
    assert out.stat().st_size == manifest["compressed_bytes"]
    assert manifest["compressed_bytes"] >= 4 << 20
    assert manifest["reads"] == manifest["reps"] * FIXTURE_READS

    # Header parses and the contig dictionary survives the rewrite
    # (whichever seed fixture this host resolved — reference or synthetic).
    hdr = read_header(out)
    assert hdr.num_contigs == read_header(manifest["fixture"]).num_contigs

    # Block metadata covers exactly the manifest's uncompressed size.
    metas = list(blocks_metadata(out))
    assert sum(m.uncompressed_size for m in metas) == manifest["uncompressed_bytes"]

    # The real proof: loading the file finds every record.
    assert load_bam(out, 2 << 20).count() == manifest["reads"]

    # Manifest round-trips.
    mf = json.loads(out.with_suffix(".manifest.json").read_text())
    assert mf == manifest
