"""Seqdoop (hadoop-bam-semantics) oracle vs its published accuracy.

Goldens: 5 false positives / 0 false negatives on 1.bam
(cli/src/test/resources/output/check-bam/1.bam), the specific FP at
Pos(239479,311) (seqdoop CheckerTest.scala:175-177), zero disagreements on
2.bam (docs/command-line.md:46-53), and the mismatched-block behavior
(CheckBlocksTest.scala:55-82)."""

import numpy as np
import pytest

from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.check.seqdoop import SeqdoopChecker
from spark_bam_tpu.core.pos import Pos

KNOWN_FPS = [
    Pos(39374, 30965),
    Pos(239479, 311),
    Pos(484396, 46507),
    Pos(508565, 56574),
    Pos(533464, 49472),
]


def truth_mask(checker: SeqdoopChecker, path) -> np.ndarray:
    truth = np.zeros(checker.view.size, dtype=bool)
    for p in read_records_index(str(path) + ".records"):
        truth[checker.view.flat_of_pos(p.block_pos, p.offset)] = True
    return truth


def test_seqdoop_1bam_confusion(bam1):
    checker = SeqdoopChecker.open(bam1)
    truth = truth_mask(checker, bam1)
    fp = np.flatnonzero(checker.verdict & ~truth)
    fn = np.flatnonzero(~checker.verdict & truth)
    assert [Pos(*checker.view.pos_of_flat(int(i))) for i in fp] == KNOWN_FPS
    assert len(fn) == 0


def test_seqdoop_2bam_all_match(bam2):
    checker = SeqdoopChecker.open(bam2)
    truth = truth_mask(checker, bam2)
    np.testing.assert_array_equal(checker.verdict, truth)


def test_seqdoop_known_fp_position(bam1):
    checker = SeqdoopChecker.open(bam1)
    assert checker(Pos(239479, 311)) is True   # the TCGA-derived upstream bug
    assert checker(Pos(239479, 312)) is True   # the real record start


def test_seqdoop_next_read_start_mismatch(bam1):
    # CheckBlocksTest: block 239479 is the one block whose first read-start
    # differs between checkers (eager 312 vs seqdoop 311).
    checker = SeqdoopChecker.open(bam1)
    assert checker.next_read_start(Pos(239479, 0)) == Pos(239479, 311)
    assert checker.next_read_start(Pos(0, 0)) == Pos(0, 45846)
