"""Streaming checker: tiled spans must reassemble the whole-file result."""

import numpy as np
import pytest

from spark_bam_tpu.bam.header import contig_lengths
from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.tpu.stream_check import count_reads_streaming, stream_verdicts


def reassemble(path, **kw) -> np.ndarray:
    flat = flatten_file(path)
    out = np.zeros(flat.size, dtype=bool)
    seen = np.zeros(flat.size, dtype=bool)
    for base, verdict in stream_verdicts(path, **kw):
        out[base: base + len(verdict)] |= verdict
        if len(verdict) > 1:
            assert not seen[base: base + len(verdict)].any(), "span overlap"
            seen[base: base + len(verdict)] = True
    assert seen.all(), "spans + pendings must tile the file"
    return out


def test_stream_matches_whole_file(bam2):
    # Small pipeline windows force many stitched buffers (numpy engine for
    # speed; the device path shares check_buffer and is covered elsewhere).
    got = reassemble(
        bam2, window_uncompressed=256 << 10, halo=64 << 10, use_device=False
    )
    flat = flatten_file(bam2)
    lens = np.array(contig_lengths(bam2).lengths_list(), dtype=np.int32)
    want = check_flat(flat.data, lens, at_eof=True).verdict
    np.testing.assert_array_equal(got, want)


def test_stream_longreads_with_pendings(tmp_path):
    """Chains (~10 × ~100 KB records) far exceed the 64 KB halo: pendings
    must carry across windows and still resolve exactly."""
    from tests.test_longreads import longread_bam  # fixture factory reuse

    # Build the same long-read file inline.
    import numpy as np

    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.bam.index_records import index_records
    from spark_bam_tpu.core.pos import Pos

    rng = np.random.default_rng(9)
    path = tmp_path / "long.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 200_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:200000000\n",
    )

    def records():
        pos = 1000
        for i in range(30):
            n = int(rng.integers(60_000, 110_000))
            yield BamRecord(
                ref_id=0, pos=pos, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"lr/{i}", cigar=[(n, 0)],
                seq="A" * n, qual=bytes([30]) * n,
            )
            pos += n + 5

    write_bam(path, header, records())
    index_records(path)

    got = reassemble(
        path, window_uncompressed=256 << 10, halo=64 << 10, use_device=False
    )
    flat = flatten_file(path)
    want = check_flat(
        flat.data, np.array([200_000_000], dtype=np.int32), at_eof=True
    ).verdict
    np.testing.assert_array_equal(got, want)


def test_count_reads_streaming(bam1):
    assert (
        count_reads_streaming(
            bam1, window_uncompressed=256 << 10, halo=64 << 10, use_device=False
        )
        == 4917
    )


def test_count_reads_device_escape_fallback(tmp_path):
    """Device count path with a halo far smaller than the chain span: the
    on-device escape counter must trip and the exact spans fallback must
    still return the true count (ultra-long-read robustness)."""
    import numpy as np

    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.core.pos import Pos
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    rng = np.random.default_rng(11)
    path = tmp_path / "long.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 200_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:200000000\n",
    )

    def records():
        pos = 1000
        for i in range(30):
            n = int(rng.integers(60_000, 110_000))
            yield BamRecord(
                ref_id=0, pos=pos, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"lr/{i}", cigar=[(n, 0)],
                seq="A" * n, qual=bytes([30]) * n,
            )
            pos += n + 5

    write_bam(path, header, records())

    checker = StreamChecker(
        path, Config(), window_uncompressed=256 << 10, halo=64 << 10
    )
    # The fallback must actually run (guard against a future config change
    # silently un-exercising this path).
    calls = []
    orig = StreamChecker._count_via_spans

    def spy(self):
        calls.append(1)
        return orig(self)

    StreamChecker._count_via_spans = spy
    try:
        assert checker.count_reads() == 30
    finally:
        StreamChecker._count_via_spans = orig
    assert calls, "escape fallback was not exercised"


def test_count_reads_flush_chunks(bam1):
    """The chunked device-accumulator flush (int32-overflow guard) must
    partition the stream without losing or double-counting windows."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    checker = StreamChecker(
        bam1, Config(), window_uncompressed=128 << 10, halo=32 << 10
    )
    checker.flush_every = 2  # force many flush boundaries (incl. mid-chunk EOF)
    assert checker.count_reads() == 4917


def test_count_reads_resident_matches_streaming(bam1):
    """The resident-scan count (one dispatch per chunk, checker.count_scan)
    must equal the per-window streaming count across chunk seams, pow2
    bucketing with dummy rows, and the small first chunk."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    checker = StreamChecker(
        bam1, Config(), window_uncompressed=128 << 10, halo=32 << 10
    )
    # chunk_windows=3 is deliberately not a power of two: full chunks pad
    # to a 4-row bucket with a dummy row that must contribute nothing.
    assert checker.count_reads_resident(
        chunk_windows=3, first_chunk_windows=2
    ) == 4917


def test_count_reads_resident_single_chunk(bam2):
    """Default chunking puts the whole small file in one resident chunk."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    checker = StreamChecker(
        bam2, Config(), window_uncompressed=256 << 10, halo=64 << 10
    )
    assert checker.count_reads_resident(first_chunk_windows=64) == 2500


def test_count_reads_resident_escape_falls_back_exact(tmp_path):
    """Reads longer than the halo escape in the first (small) chunk; the
    resident path must abort to the exact spans path and still be right."""
    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.core.pos import Pos
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    rng = np.random.default_rng(13)
    path = tmp_path / "long_resident.bam"
    header = BamHeader(
        ContigLengths({0: ("chr1", 200_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:200000000\n",
    )

    def records():
        pos = 1000
        for i in range(30):
            n = int(rng.integers(60_000, 110_000))
            yield BamRecord(
                ref_id=0, pos=pos, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"lr/{i}", cigar=[(n, 0)],
                seq="A" * n, qual=bytes([30]) * n,
            )
            pos += n + 5

    write_bam(path, header, records())

    checker = StreamChecker(
        path, Config(), window_uncompressed=256 << 10, halo=64 << 10
    )
    calls = []
    orig = StreamChecker._count_via_spans

    def spy(self):
        calls.append(1)
        return orig(self)

    StreamChecker._count_via_spans = spy
    try:
        assert checker.count_reads_resident(chunk_windows=4) == 30
    finally:
        StreamChecker._count_via_spans = orig
    assert calls, "escape fallback was not exercised"


def test_full_spans_match_whole_file(bam1):
    """Streaming full-check spans must reassemble the whole-file fail_mask
    and reads_before exactly (flags for every position, O(window) memory)."""
    from spark_bam_tpu.core.config import Config
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    flat = flatten_file(bam1)
    lens = np.array(contig_lengths(bam1).lengths_list(), dtype=np.int32)

    got_fm = np.full(flat.size, -1, dtype=np.int32)
    got_rb = np.full(flat.size, -1, dtype=np.int32)
    checker = StreamChecker(
        bam1, window_uncompressed=256 << 10, halo=64 << 10
    )
    for base, fm, rb in checker.full_spans():
        got_fm[base: base + len(fm)] = fm
        got_rb[base: base + len(rb)] = rb
    assert (got_fm >= 0).all(), "spans must tile the file"

    want = check_flat(flat.data, lens, at_eof=True)
    np.testing.assert_array_equal(got_fm, want.fail_mask)
    np.testing.assert_array_equal(got_rb, want.reads_before)


def test_full_check_summary_streaming_matches_in_memory(bam1):
    """The streaming full-check aggregations must equal the in-memory
    computation the CLI performs (per-flag totals, critical/two-check
    buckets — reference FullCheck.scala:112-417 semantics)."""
    from spark_bam_tpu.check.flags import BIT, FLAG_NAMES
    from spark_bam_tpu.tpu.stream_check import full_check_summary_streaming

    got = full_check_summary_streaming(
        bam1, window_uncompressed=256 << 10, halo=64 << 10
    )

    flat = flatten_file(bam1)
    lens = np.array(contig_lengths(bam1).lengths_list(), dtype=np.int32)
    res = check_flat(flat.data, lens, at_eof=True)
    bit0 = BIT["tooFewFixedBlockBytes"]
    considered = (res.fail_mask != 0) & ~(
        (res.fail_mask == bit0) & (res.reads_before == 0)
    )
    masked = res.fail_mask[considered]
    for i, name in enumerate(FLAG_NAMES):
        assert got["per_flag"][name] == int(((masked >> i) & 1).sum()), name
    assert got["considered"] == int(considered.sum())

    popcount = np.zeros(flat.size, dtype=np.int32)
    for i in range(len(FLAG_NAMES)):
        popcount += (res.fail_mask >> i) & 1
    nf = popcount + (res.reads_before > 0)
    np.testing.assert_array_equal(
        np.sort(got["critical_positions"]),
        np.flatnonzero(considered & (nf == 1)),
    )
    np.testing.assert_array_equal(
        np.sort(got["two_check_positions"]),
        np.flatnonzero(considered & (nf == 2)),
    )
    assert got["positions"] == flat.size


def test_full_spans_longread_deferrals_exact(tmp_path):
    """full_spans with chains far exceeding the halo: deferred lanes must
    re-emit with COMPLETE masks — a deferral that re-checks the same
    truncated bytes would yield buffer-edge flags instead of the truth."""
    rng = np.random.default_rng(13)
    path = tmp_path / "long.bam"

    from spark_bam_tpu.bam.header import BamHeader, ContigLengths
    from spark_bam_tpu.bam.record import BamRecord
    from spark_bam_tpu.bam.writer import write_bam
    from spark_bam_tpu.core.pos import Pos
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    header = BamHeader(
        ContigLengths({0: ("chr1", 200_000_000)}), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:200000000\n",
    )

    def records():
        pos = 1000
        for i in range(30):
            n = int(rng.integers(60_000, 110_000))
            yield BamRecord(
                ref_id=0, pos=pos, mapq=60, bin=0, flag=0,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"lr/{i}", cigar=[(n, 0)],
                seq="A" * n, qual=bytes([30]) * n,
            )
            pos += n + 5

    write_bam(path, header, records())
    flat = flatten_file(path)
    lens = np.array([200_000_000], dtype=np.int32)

    got_fm = np.full(flat.size, -1, dtype=np.int64)
    got_rb = np.full(flat.size, -1, dtype=np.int64)
    deferrals = 0
    frontier = 0  # window spans tile forward; re-emissions land behind it
    checker = StreamChecker(
        path, window_uncompressed=256 << 10, halo=64 << 10
    )
    for base, fm, rb in checker.full_spans():
        if base < frontier:
            deferrals += 1
        else:
            frontier = base + len(fm)
        got_fm[base: base + len(fm)] = fm
        got_rb[base: base + len(rb)] = rb

    assert deferrals > 0, "scenario must force deferred full-check lanes"
    want = check_flat(flat.data, lens, at_eof=True)
    np.testing.assert_array_equal(got_fm, want.fail_mask)
    np.testing.assert_array_equal(got_rb, want.reads_before)
