"""Big-BAM streaming equality: the product path == native CPU == manifest.

The streaming device path (``count_reads_tpu`` → ``StreamChecker``) is the
same code bench.py measures; this test pins its count against two
independent sources on a multi-window synthesized BAM: the native C++
eager checker over the whole flat file, and the synthesis manifest's exact
read count. Scale via ``SB_BIG_BAM_TEST_BYTES`` (driver/bench runs use
≥1 GB; CI default keeps the CPU-backend kernel affordable).
"""

import os

import numpy as np
import pytest

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.benchmarks.synth import synth_bam
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.load.tpu_load import count_reads_tpu, record_starts_streaming

TARGET = int(os.environ.get("SB_BIG_BAM_TEST_BYTES", str(32 << 20)))
# Small windows force many stitched windows + halo carries.
CFG = Config(window_size=8 << 20, halo_size=1 << 20)


@pytest.fixture(scope="module")
def big_bam(tmp_path_factory):
    out = tmp_path_factory.mktemp("bigbam") / "big.bam"
    manifest = synth_bam(out, TARGET)
    return out, manifest


def test_streaming_count_three_way(big_bam):
    path, manifest = big_bam
    assert count_reads_tpu(path, CFG) == manifest["reads"]

    from spark_bam_tpu.native.build import eager_check_native

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    out = eager_check_native(
        flat.data, np.arange(flat.size, dtype=np.int64), lens
    )
    if out is None:
        pytest.skip("native library unavailable")
    native_count = int(out[hdr.uncompressed_size:].sum())
    assert native_count == manifest["reads"]


def test_streaming_starts_match_native(big_bam):
    path, manifest = big_bam
    from spark_bam_tpu.native.build import eager_check_native

    flat = flatten_file(path)
    hdr = read_header(path)
    lens = np.array(hdr.contig_lengths.lengths_list(), dtype=np.int32)
    out = eager_check_native(
        flat.data, np.arange(flat.size, dtype=np.int64), lens
    )
    if out is None:
        pytest.skip("native library unavailable")
    want = np.flatnonzero(out)
    want = want[want >= hdr.uncompressed_size]

    got = np.sort(np.concatenate(list(record_starts_streaming(path, CFG))))
    np.testing.assert_array_equal(got, want)
    assert len(got) == manifest["reads"]
