"""Shared randomized-BAM generator for the fuzz suites."""

import numpy as np

from spark_bam_tpu.bam.bai import reg2bin
from spark_bam_tpu.bam.header import BamHeader, ContigLengths
from spark_bam_tpu.bam.index_records import index_records
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.core.pos import Pos


def random_bam(
    path,
    seed: int,
    contigs=(("chr1", 10_000_000), ("chr2", 5_000_000)),
    n_records=(150, 400),
    read_len=(10, 3000),
    mapped_rate: float = 0.8,
    dup_rate: float = 0.0,
    pos_step=(1, 900),
    block_payload=(2000, 40000),
    index: bool = False,
    sort: bool = False,
):
    """Write a randomized (but structurally valid) BAM; returns the header
    SAM text's contig count for convenience."""
    rng = np.random.default_rng(seed)
    sam = "@HD\tVN:1.6\n" + "".join(
        f"@SQ\tSN:{name}\tLN:{ln}\n" for name, ln in contigs
    )
    header = BamHeader(
        ContigLengths({i: c for i, c in enumerate(contigs)}), Pos(0, 0), 0, sam
    )

    def records():
        pos = 5
        for i in range(int(rng.integers(*n_records))):
            n = int(rng.integers(*read_len))
            mapped = rng.random() < mapped_rate
            flag = (0 if mapped else 4) | (
                0x400 if rng.random() < dup_rate else 0
            )
            yield BamRecord(
                ref_id=int(rng.integers(0, len(contigs))) if mapped else -1,
                pos=pos if mapped else -1,
                # Canonical values (CRAM derives bin on decode, and MQ is
                # a mapped-only data series in the CRAM spec — a bogus bin
                # or an unmapped MAPQ would fail round-trips vacuously).
                mapq=int(rng.integers(0, 61)) if mapped else 0,
                bin=reg2bin(pos, pos + n) if mapped else 0,
                flag=flag,
                next_ref_id=-1, next_pos=-1, tlen=0,
                read_name=f"f{seed}_{i}",
                cigar=[(n, 0)] if mapped else [],
                seq="".join(rng.choice(list("ACGT"), n)),
                qual=bytes(rng.integers(5, 40, n, dtype=np.uint8)),
            )
            pos += int(rng.integers(*pos_step))

    recs = list(records())
    if sort:
        # Coordinate order (unplaced last) — what BAI indexing requires.
        recs.sort(key=lambda r: (r.ref_id < 0, r.ref_id, r.pos))
    write_bam(
        path, header, recs, block_payload=int(rng.integers(*block_payload))
    )
    if index:
        index_records(path)
