"""Candidate-funnel tier-1 suite.

Two claims keep the funnel honest (docs/design.md, "Candidate funnel"):
the stage-0 prefilter is a provable superset filter (every full-pass
survivor passes it), and every funnel projection is verdict-identical to
the full pass — on factory corpora, on seeded decode-fuzz mutants, and on
adversarial byte soup. Everything here runs on the virtual CPU mesh;
Pallas coverage uses interpret mode.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_bam_tpu.bam.header import contig_lengths
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.tpu import checker as ck
from tests.bam_factories import random_bam

W = 256 << 10  # multiple of the Pallas TILE (32 KiB)

PARITY_KEYS = ("verdict", "escaped", "reads_before", "reads_parsed")


def _window_of(data, w=W):
    padded = np.zeros(w + ck.PAD, dtype=np.uint8)
    n = min(len(data), w)
    padded[:n] = np.asarray(data)[:n]
    return jnp.asarray(padded), jnp.int32(n)


def _lens_of(path):
    arr = np.array(contig_lengths(path).lengths_list(), dtype=np.int32)
    lens = np.zeros(1024, dtype=np.int32)
    lens[: len(arr)] = arr
    return jnp.asarray(lens), jnp.int32(len(arr))


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("funnel")
    paths = []
    for i, kw in enumerate((
        dict(n_records=(150, 400)),
        dict(n_records=(80, 200), mapped_rate=0.3, dup_rate=0.2),
    )):
        p = tmp / f"c{i}.bam"
        random_bam(p, seed=100 + i, **kw)
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def kernels():
    return (
        ck.make_check_window(W, 10, funnel=True),
        ck.make_check_window(W, 10, funnel=False),
    )


def test_check_window_parity_corpora(corpus, kernels):
    """Funnel on/off: identical verdicts (hence identical record starts),
    escapes, and read counts at every position, both at_eof values."""
    on, off = kernels
    for p in corpus:
        pd, n = _window_of(flatten_file(p).data)
        ld, nc = _lens_of(p)
        for at_eof in (True, False):
            a = on(pd, ld, nc, n, jnp.bool_(at_eof))
            b = off(pd, ld, nc, n, jnp.bool_(at_eof))
            for k in PARITY_KEYS:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k]),
                    err_msg=f"{p.name} at_eof={at_eof} key={k}",
                )
            np.testing.assert_array_equal(
                np.flatnonzero(np.asarray(a["verdict"])),
                np.flatnonzero(np.asarray(b["verdict"])),
            )


def test_count_window_parity(corpus):
    on = ck.make_count_window(W, 10, funnel=True)
    off = ck.make_count_window(W, 10, funnel=False)
    p = corpus[0]
    pd, n = _window_of(flatten_file(p).data)
    ld, nc = _lens_of(p)
    spans = ((0, int(n)), (1000, int(n) // 2))
    for at_eof in (True, False):
        for lo, own in spans:
            a = on(pd, ld, nc, n, jnp.bool_(at_eof), jnp.int32(lo), jnp.int32(own))
            b = off(pd, ld, nc, n, jnp.bool_(at_eof), jnp.int32(lo), jnp.int32(own))
            assert int(a["count"]) == int(b["count"]), (at_eof, lo, own)
            assert int(a["esc_count"]) == int(b["esc_count"]), (at_eof, lo, own)


def test_fuzz_mutant_parity(kernels, tmp_path):
    """Seeded decode-fuzz BAM mutants: the funnel must never flip a verdict
    on corrupted input (where the prefilter's screening earns its keep)."""
    from spark_bam_tpu.tools.fuzz_decode import _mutants_for, _Rng

    on, off = kernels
    rng = _Rng(5)
    checked = 0
    for i, blob in enumerate(_mutants_for("bam", tmp_path, rng, 12)):
        p = tmp_path / f"m{i}.bam"
        p.write_bytes(blob)
        try:
            data = flatten_file(p).data
            ld, nc = _lens_of(p)
        except Exception:
            continue  # mutant broke the header/BGZF layer: nothing to scan
        pd, n = _window_of(data)
        for at_eof in (True, False):
            a = on(pd, ld, nc, n, jnp.bool_(at_eof))
            b = off(pd, ld, nc, n, jnp.bool_(at_eof))
            for k in PARITY_KEYS:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k]),
                    err_msg=f"mutant {i} at_eof={at_eof} key={k}",
                )
        checked += 1
    assert checked >= 5, f"only {checked} mutants survived decode"


def _assert_superset(pd, ld, nc, n):
    """Every prefilter bit must also be set by the full pass — hence
    full-pass survivors (F == 0) are a subset of prefilter survivors."""
    pre = np.asarray(ck._prefilter_flags(pd, ld, nc, n))
    full = np.asarray(ck._compute_flags(pd, ld, nc, n))
    stray = pre & ~full
    assert not stray.any(), (
        f"prefilter set bits the full pass did not at "
        f"{np.flatnonzero(stray)[:5]}"
    )
    assert not ((full == 0) & (pre != 0)).any()


def test_superset_on_corpus(corpus):
    for p in corpus:
        pd, n = _window_of(flatten_file(p).data)
        ld, nc = _lens_of(p)
        _assert_superset(pd, ld, nc, n)


def test_superset_on_adversarial_windows(corpus):
    """Byte soup and bit-flipped corpus windows: the superset property is
    structural (prefilter bits are a subset of full-pass bits at every
    position), so it must hold on arbitrary garbage, not just valid BAM."""
    rng = np.random.default_rng(11)
    ld, nc = _lens_of(corpus[0])
    soup = rng.integers(0, 256, size=W, dtype=np.uint8)
    pd, n = _window_of(soup)
    _assert_superset(pd, ld, nc, n)

    data = np.array(flatten_file(corpus[0]).data[:W], dtype=np.uint8, copy=True)
    flips = rng.integers(0, len(data), size=max(1, len(data) // 100))
    data[flips] ^= rng.integers(1, 256, size=len(flips)).astype(np.uint8)
    pd, n = _window_of(data)
    _assert_superset(pd, ld, nc, n)


def test_pallas_prefilter_matches_xla(corpus):
    """The fused Pallas prefilter tile kernel (interpret mode off-TPU) is
    bit-identical to the XLA prefilter."""
    from spark_bam_tpu.tpu.pallas_kernels import prefilter_check_flags

    p = corpus[0]
    pd, n = _window_of(flatten_file(p).data)
    ld, nc = _lens_of(p)
    got = np.asarray(
        prefilter_check_flags(
            pd, ld, nc.reshape(1), n.reshape(1), interpret=True
        )
    )
    want = np.asarray(ck._prefilter_flags(pd, ld, nc, n))
    np.testing.assert_array_equal(got, want)


def test_stream_record_starts_parity(corpus):
    """Whole-stream projection: funnel on vs off yield byte-identical
    record-start positions, and only the funnelled run reports stats."""
    from spark_bam_tpu.tpu.stream_check import StreamChecker

    p = corpus[0]

    def starts(mode):
        checker = StreamChecker(
            p, Config(funnel=mode), window_uncompressed=128 << 10,
            halo=32 << 10,
        )
        got = np.sort(np.concatenate(
            list(checker.record_starts()) or [np.array([], dtype=np.int64)]
        ))
        return got, checker.funnel_stats

    s_on, stats_on = starts("on")
    s_off, stats_off = starts("off")
    np.testing.assert_array_equal(s_on, s_off)
    assert len(s_on) > 0
    assert stats_off is None
    assert stats_on is not None and stats_on["screened"] > 0
    assert stats_on["survivors"] <= stats_on["screened"]


def test_config_funnel_knobs():
    assert Config().funnel == "auto"
    assert Config().funnel_enabled() is True
    assert Config().funnel_enabled(full_masks=True) is False
    assert Config(funnel="off").funnel_enabled() is False
    assert Config(funnel="on").funnel_enabled() is True
    # Explicit "on" still cannot apply where full flag masks are required.
    assert Config(funnel="on").funnel_enabled(full_masks=True) is False
    with pytest.raises(ValueError, match="funnel"):
        Config(funnel="bogus").funnel_enabled()


def test_config_funnel_env_and_dict():
    cfg = Config.from_env({"SPARK_BAM_FUNNEL": "off"})
    assert cfg.funnel == "off"
    cfg = Config.from_dict({"spark.bam.funnel": "on"})
    assert cfg.funnel == "on"


def test_config_flush_every_and_ring_depth():
    kw = 1 << 20
    auto = (1 << 30) // kw
    assert Config().flush_every_for(kw) == auto
    assert Config.from_dict({"spark.bam.flush_every": "auto"}).flush_every is None
    assert Config.from_dict({"spark.bam.flush_every": "8"}).flush_every == 8
    assert Config(flush_every=8).flush_every_for(kw) == 8
    # The int32-overflow cap always wins over a larger operator setting.
    assert Config(flush_every=10 * auto).flush_every_for(kw) == auto
    assert Config(flush_every=0).flush_every_for(kw) == 1
    assert Config(ring_depth=4).ring_depth == 4
    cfg = Config.from_env({"SPARK_BAM_RING_DEPTH": "3"})
    assert cfg.ring_depth == 3
