"""Observability helpers (SURVEY §5 tracing/heartbeat subsystem)."""

import logging
import time

from spark_bam_tpu.utils.timer import Timer, heartbeat, profile_trace


def test_timer_measures_and_echoes():
    lines = []
    with Timer("stage", echo=lines.append) as t:
        time.sleep(0.02)
    assert isinstance(t.seconds, float) and isinstance(t.ms, float)
    assert t.ms >= 15
    assert lines == [f"stage: {t.ms:.3f}ms"]

    # No name ⇒ silent even with an echo sink.
    lines.clear()
    with Timer(echo=lines.append):
        pass
    assert lines == []


def test_timer_sub_millisecond_not_truncated():
    # The old int(ms) truncation erased sub-ms stages; float ms keeps them.
    with Timer("quick") as t:
        time.sleep(0.001)
    assert 0 < t.ms < 1000
    assert t.ms == t.seconds * 1e3


def test_named_timer_feeds_registry():
    from spark_bam_tpu import obs

    obs.shutdown()
    reg = obs.configure()
    try:
        with Timer("stagex"):
            pass
        hists = {h["name"]: h for h in reg.snapshot()["hists"]}
        assert hists["timer.stagex"]["count"] == 1
    finally:
        obs.shutdown()


def test_heartbeat_rate_limits(caplog):
    with caplog.at_level(logging.INFO, logger="spark_bam_tpu.utils.timer"):
        with heartbeat("indexing", interval_seconds=0.05) as beat:
            beat("p0")          # within the first interval: suppressed
            time.sleep(0.06)
            beat("p1")          # logged
            beat("p2")          # suppressed again
    messages = [r.getMessage() for r in caplog.records]
    assert messages == ["indexing: p1"]


def test_profile_trace_noop_and_enabled(tmp_path, monkeypatch):
    monkeypatch.delenv("SPARK_BAM_PROFILE_DIR", raising=False)
    with profile_trace("t"):
        pass  # no-op path

    monkeypatch.setenv("SPARK_BAM_PROFILE_DIR", str(tmp_path))
    import jax.numpy as jnp

    with profile_trace("t"):
        jnp.zeros(8).block_until_ready()
    # A trace directory with profiler artifacts must exist.
    assert any((tmp_path / "t").rglob("*")), "no profiler artifacts written"


def test_heartbeat_progress_shape_and_rate(caplog):
    import logging

    from spark_bam_tpu.utils.timer import heartbeat_progress

    with caplog.at_level(logging.INFO):
        with heartbeat_progress("t", unit="window", interval_seconds=0) as p:
            p(3, 100, 200)
    assert "t: window 3, 100/200 positions" in caplog.text

    # Rate limit: a long interval suppresses the very first beat too.
    with caplog.at_level(logging.INFO):
        caplog.clear()
        with heartbeat_progress("u", interval_seconds=3600) as p:
            p(1, 1, 2)
    assert "u:" not in caplog.text
